"""On-chip profiler evidence for docs/performance.md (VERDICT r4 ask #3).

Two artifacts, both best-effort and window-friendly:

1. **Step breakdown** — traces 3 BERT-Large bench steps with
   ``jax.profiler.trace`` on the real chip, parses the trace-event JSON,
   and aggregates device time by op category (fusions, dots/convs,
   Pallas custom-calls, collectives, copies, host). This replaces the
   design-intent claims about where the step time goes with measurement.

2. **Overlap scheduling proof** — AOT-compiles the data-parallel (dp=8)
   BERT step AND a ZeRO-sharded optimizer step for an 8-chip TPU topology
   (no 8 chips needed — compile only) and scans the optimized HLO for
   async collective pairs (``all-gather-start``/``-done``,
   ``all-reduce-start``/``-done``) with independent compute scheduled
   between start and done: the TPU compiler's own schedule either does or
   does not overlap the ZeRO all-gather / grad all-reduce with compute
   (SURVEY hard part #5). Falls back with an honest note when the
   topology API can't reach the compiler.

Writes ``PROFILE_<tag>.json`` + prints one summary JSON line.
"""

import collections
import glob
import gzip
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _enable_compile_cache():
    import jax

    import bench

    bench._enable_compile_cache(jax)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# 1. trace + parse
# ---------------------------------------------------------------------------

CATEGORIES = [
    ("collective", re.compile(
        r"all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all")),
    ("pallas", re.compile(r"custom-call|tpu_custom_call")),
    ("dot", re.compile(r"dot|conv")),
    ("fusion", re.compile(r"fusion")),
    ("copy", re.compile(r"copy|transpose|reshape|bitcast")),
]


def categorize(name: str) -> str:
    low = name.lower()
    for cat, pat in CATEGORIES:
        if pat.search(low):
            return cat
    return "other"


def parse_trace(logdir):
    paths = sorted(glob.glob(os.path.join(
        logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(f"no trace under {logdir}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device-side events live on pids whose process name mentions TPU/device
    pid_names = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    device_pids = {p for p, n in pid_names.items()
                   if re.search(r"tpu|device|/device:", n, re.I)}
    by_cat = collections.Counter()
    by_name = collections.Counter()
    t_min, t_max = None, None
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        dur = e["dur"]  # microseconds
        name = e.get("name", "")
        by_cat[categorize(name)] += dur
        by_name[re.sub(r"[.\d]+$", "", name)[:60]] += dur
        ts = e.get("ts", 0)
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = max(t_max or 0, ts + dur)
    span = (t_max - t_min) if t_min is not None else 0
    return {"device_time_us_by_category": dict(by_cat),
            "top_ops_us": dict(by_name.most_common(15)),
            "span_us": span,
            "trace_file": os.path.relpath(paths[-1], REPO)}


def run_traced_steps(steps=3):
    """Build the bench train step once, warm it up OUTSIDE the tracer (the
    15-min first compile must not land in the trace), then trace ``steps``
    steady-state steps."""
    import jax
    import numpy as np

    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)
    from apex_tpu.optimizers import FusedLAMB

    devs = jax.devices()
    if devs[0].platform == "cpu":
        log("CPU backend: tracing anyway (smoke), numbers meaningless")
    cfg = bert_large_config()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, 8, 512)
    log("init params...")
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    step = make_pretrain_step(model)
    opt = FusedLAMB(params, lr=1e-4, weight_decay=0.01)

    def train_step(p, i):
        loss, grads = step(p, batch, i)
        return loss, opt.step(grads)

    log("compile + warmup...")
    t0 = time.perf_counter()
    loss, params = train_step(params, 0)
    jax.block_until_ready(params)
    log(f"compiled in {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    loss, params = train_step(params, 1)
    jax.block_until_ready(params)
    step_ms = (time.perf_counter() - t0) * 1e3

    logdir = os.path.join(REPO, "profile_trace")
    with jax.profiler.trace(logdir):
        for i in range(steps):
            loss, params = train_step(params, 2 + i)
        jax.block_until_ready(params)
    parsed = parse_trace(logdir)
    parsed["steps_traced"] = steps
    parsed["step_ms_untraced"] = round(step_ms, 2)
    return parsed


# ---------------------------------------------------------------------------
# 2. AOT overlap-scheduling proof
# ---------------------------------------------------------------------------

def _sync_collective_report(hlo_text: str, max_items: int = 24):
    """Schedulable-overlap evidence for XLA:TPU's SYNC-form HLO.

    This XLA version's TPU pipeline keeps collectives synchronous in the
    final HLO (``all-reduce``, not ``-start/-done``) — asyncification is
    performed later by the backend's latency-hiding scheduler and never
    appears in module text (GPU is where start/done pairs show up). What CAN
    be proven at the HLO level is *schedulability*: for each collective, the
    number of independent ops (and compute ops) between it and its first
    consumer in program order — the window the scheduler can hide the
    collective behind. Also records the backend's chosen collective
    algorithm (ring strategy etc.) when present.
    """
    lines = [ln.strip() for ln in hlo_text.splitlines()]
    kinds = re.compile(
        r"%?([\w.-]+) = \S+ (all-reduce|all-gather|reduce-scatter|"
        r"collective-permute|all-to-all)\(")
    out = []
    for i, ln in enumerate(lines):
        m = kinds.match(ln)
        if not m or "-start" in ln or "-done" in ln:
            continue
        name, kind = m.group(1), m.group(2)
        strat = re.search(r'"strategy":"(\w+)"', ln)
        use_pat = re.compile(r"[(,]\s*%" + re.escape(name) + r"[),]")
        first_use = None
        between, compute = 0, 0
        for j in range(i + 1, len(lines)):
            if use_pat.search(lines[j]):
                first_use = j
                break
            if re.search(r" = ", lines[j]) and not re.search(
                    r"parameter|constant", lines[j]):
                between += 1
                if re.search(r"fusion|dot|convolution|custom-call",
                             lines[j]):
                    compute += 1
        out.append({"kind": kind,
                    "algorithm": strat.group(1) if strat else None,
                    "ops_to_first_use": between if first_use else None,
                    "compute_to_first_use": compute if first_use else None})
        if len(out) >= max_items:
            break
    return out


def _async_overlap_report(hlo_text: str):
    """For each async collective pair, count non-trivial ops scheduled
    between start and done in the entry computation's program order."""
    lines = [ln.strip() for ln in hlo_text.splitlines()]
    starts = {}
    pairs = []
    for i, ln in enumerate(lines):
        m = re.match(r"%?([\w.-]+) = .*(all-gather-start|all-reduce-start|"
                     r"reduce-scatter-start|collective-permute-start|"
                     r"async-start)", ln)
        if m:
            starts[m.group(1)] = (i, m.group(2))
            continue
        m2 = re.search(r"(all-gather-done|all-reduce-done|"
                       r"reduce-scatter-done|collective-permute-done|"
                       r"async-done)[(]%?([\w.-]+)", ln)
        if m2 and m2.group(2) in starts:
            s_line, kind = starts.pop(m2.group(2))
            between = [x for x in lines[s_line + 1:i]
                       if re.search(r" = ", x)
                       and not re.search(r"-(start|done)|parameter|constant",
                                         x)]
            compute = [x for x in between
                       if re.search(r"fusion|dot|convolution|custom-call", x)]
            pairs.append({"kind": kind.replace("-start", ""),
                          "ops_between": len(between),
                          "compute_between": len(compute)})
    return pairs


def aot_overlap_check():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # NB: deliberately no jax.devices() here — this path is tunnel-
    # independent (device-less topology AOT) and a dead tunnel makes any
    # backend touch hang >400 s. Candidate names live in tpu_aot (shared).
    try:
        from tpu_aot import _topology

        _, topo = _topology()
    except Exception as e:  # noqa: BLE001
        return {"available": False,
                "errors": [f"{type(e).__name__}: {str(e)[:300]}"]}

    mesh = topologies.make_mesh(topo, (8,), ("data",))
    out = {"available": True, "topology": str(topo)}
    try:
        out["dp8_grad_allreduce_pairs"] = _dp8_overlap_hlo(mesh)
    except Exception as e:  # noqa: BLE001
        out["dp8_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    try:
        out["zero_shard_step_pairs"] = _zero_overlap_hlo(mesh)
    except Exception as e:  # noqa: BLE001
        out["zero_shard_step_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    return out


def _dp8_overlap_hlo(mesh):
    """AOT-compile the dp=8 BERT-Large grad step (shard_map with an explicit
    grad pmean — plain jit cannot auto-partition the Mosaic kernels) and
    report whether the compiler overlaps the grad all-reduce with backward
    compute (SURVEY hard part #5)."""
    import os

    import jax
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    os.environ.setdefault("APEX_TPU_FORCE_MOSAIC", "1")
    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)

    cfg = bert_large_config()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, 8, 512)
    step = make_pretrain_step(model)
    abstract_params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), batch["input_ids"],
                           batch["token_type_ids"],
                           batch["attention_mask"])["params"])

    def dp_step(p, b, i):
        loss, grads = step(p, b, i)
        grads = jax.tree.map(lambda g: lax.pmean(g, "data"), grads)
        return lax.pmean(loss, "data"), grads

    fn = jax.shard_map(dp_step, mesh=mesh, in_specs=(P(), P("data"), P()),
                       out_specs=(P(), P()), check_vma=False)

    repl = NamedSharding(mesh, P())
    data_sh = {k: NamedSharding(mesh, P("data", *[None] * (v.ndim - 1)))
               for k, v in batch.items()}
    params_in = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=repl),
        abstract_params)
    batch_in = {k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype,
                                        sharding=data_sh[k])
                for k, v in batch.items()}
    i_in = jax.ShapeDtypeStruct((), np.int32, sharding=repl)
    hlo = jax.jit(fn).lower(params_in, batch_in, i_in).compile().as_text()
    return {"async_pairs": _async_overlap_report(hlo),
            "sync_collectives": _sync_collective_report(hlo)}


def _zero_overlap_hlo(mesh):
    """AOT-compile the ZeRO shard_step (psum_scatter -> local update ->
    param all-gather) for the 8-chip topology and report whether the TPU
    scheduler overlaps the param all-gather with independent work
    (docs/performance.md's ZeRO claim; SURVEY hard part #5)."""
    import unittest.mock as mock

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w1": np.zeros((1024, 1024), np.float32),
              "w2": np.zeros((4096, 1024), np.float32),
              "emb": np.zeros((8192, 1024), np.float32),
              "b": np.zeros((1024,), np.float32)}
    # the ctor device_puts master/state onto the mesh — impossible on a
    # device-less topology; shapes are all the lowering needs
    with mock.patch.object(jax, "device_put", lambda x, s=None: x):
        opt = DistributedFusedAdam(params, lr=1e-3, weight_decay=0.01,
                                   mesh=mesh, dp_axis="data")
    row = P("data", None)
    state_specs = {k: row for k in opt.state}

    def body(g, master, state, step):
        p, m2, s2, st2, _ = opt.shard_step(g, master, state, step)
        return p, m2, s2, st2

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(), row, state_specs, P()),
                       out_specs=(P(), row, state_specs, P()),
                       check_vma=False)

    def spec(shape, sh):
        return jax.ShapeDtypeStruct(shape, np.float32,
                                    sharding=NamedSharding(mesh, sh))

    g_in = jax.tree.map(lambda a: spec(a.shape, P()), params)
    master_in = spec(opt.master.shape, row)
    state_in = {k: spec(v.shape, row) for k, v in opt.state.items()}
    step_in = jax.ShapeDtypeStruct((), np.int32,
                                   sharding=NamedSharding(mesh, P()))
    hlo = jax.jit(fn).lower(g_in, master_in, state_in,
                            step_in).compile().as_text()
    return {"async_pairs": _async_overlap_report(hlo),
            "sync_collectives": _sync_collective_report(hlo)}


def main():
    _enable_compile_cache()
    tag = os.environ.get("APEX_TPU_TAG", "session")
    out = {"metric": "tpu_profile", "tag": tag}
    try:
        out["step_breakdown"] = run_traced_steps()
    except Exception as e:  # noqa: BLE001
        import traceback

        log(traceback.format_exc())
        out["step_breakdown_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    try:
        out["aot_overlap"] = aot_overlap_check()
    except Exception as e:  # noqa: BLE001
        import traceback

        log(traceback.format_exc())
        out["aot_overlap_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    path = os.path.join(REPO, f"PROFILE_{tag}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"wrote {path}")
    print(json.dumps({k: v for k, v in out.items()
                      if not isinstance(v, dict)} |
                     {"wrote": os.path.basename(path),
                      "ok": "step_breakdown" in out}))


if __name__ == "__main__":
    main()
