"""On-chip profiler evidence for docs/performance.md (VERDICT r4 ask #3).

Two artifacts, both best-effort and window-friendly:

1. **Step breakdown** — traces 3 BERT-Large bench steps with
   ``jax.profiler.trace`` on the real chip, parses the trace-event JSON,
   and aggregates device time by op category (fusions, dots/convs,
   Pallas custom-calls, collectives, copies, host). This replaces the
   design-intent claims about where the step time goes with measurement.

2. **Overlap scheduling proof** — AOT-compiles the data-parallel (dp=8)
   BERT step AND a ZeRO-sharded optimizer step for an 8-chip TPU topology
   (no 8 chips needed — compile only) and scans the optimized HLO for
   async collective pairs (``all-gather-start``/``-done``,
   ``all-reduce-start``/``-done``) with independent compute scheduled
   between start and done: the TPU compiler's own schedule either does or
   does not overlap the ZeRO all-gather / grad all-reduce with compute
   (SURVEY hard part #5). Falls back with an honest note when the
   topology API can't reach the compiler.

Writes ``PROFILE_<tag>.json`` + prints one summary JSON line.
"""

import collections
import glob
import gzip
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _enable_compile_cache():
    import jax

    import bench

    bench._enable_compile_cache(jax)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# 1. trace + parse
# ---------------------------------------------------------------------------

CATEGORIES = [
    ("collective", re.compile(
        r"all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all")),
    ("pallas", re.compile(r"custom-call|tpu_custom_call")),
    ("dot", re.compile(r"dot|conv")),
    ("fusion", re.compile(r"fusion")),
    ("copy", re.compile(r"copy|transpose|reshape|bitcast")),
]


def categorize(name: str) -> str:
    low = name.lower()
    for cat, pat in CATEGORIES:
        if pat.search(low):
            return cat
    return "other"


def parse_trace(logdir):
    paths = sorted(glob.glob(os.path.join(
        logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(f"no trace under {logdir}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device-side events live on pids whose process name mentions TPU/device
    pid_names = {e["pid"]: e.get("args", {}).get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    device_pids = {p for p, n in pid_names.items()
                   if re.search(r"tpu|device|/device:", n, re.I)}
    by_cat = collections.Counter()
    by_name = collections.Counter()
    t_min, t_max = None, None
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        dur = e["dur"]  # microseconds
        name = e.get("name", "")
        by_cat[categorize(name)] += dur
        by_name[re.sub(r"[.\d]+$", "", name)[:60]] += dur
        ts = e.get("ts", 0)
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = max(t_max or 0, ts + dur)
    span = (t_max - t_min) if t_min is not None else 0
    return {"device_time_us_by_category": dict(by_cat),
            "top_ops_us": dict(by_name.most_common(15)),
            "span_us": span,
            "trace_file": os.path.relpath(paths[-1], REPO)}


def run_traced_steps(steps=3):
    """Build the bench train step once, warm it up OUTSIDE the tracer (the
    15-min first compile must not land in the trace), then trace ``steps``
    steady-state steps."""
    import jax
    import numpy as np

    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)
    from apex_tpu.optimizers import FusedLAMB

    devs = jax.devices()
    if devs[0].platform == "cpu":
        log("CPU backend: tracing anyway (smoke), numbers meaningless")
    cfg = bert_large_config()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, 8, 512)
    log("init params...")
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    step = make_pretrain_step(model)
    opt = FusedLAMB(params, lr=1e-4, weight_decay=0.01)

    def train_step(p, i):
        loss, grads = step(p, batch, i)
        return loss, opt.step(grads)

    log("compile + warmup...")
    t0 = time.perf_counter()
    loss, params = train_step(params, 0)
    jax.block_until_ready(params)
    log(f"compiled in {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    loss, params = train_step(params, 1)
    jax.block_until_ready(params)
    step_ms = (time.perf_counter() - t0) * 1e3

    logdir = os.path.join(REPO, "profile_trace")
    with jax.profiler.trace(logdir):
        for i in range(steps):
            loss, params = train_step(params, 2 + i)
        jax.block_until_ready(params)
    parsed = parse_trace(logdir)
    parsed["steps_traced"] = steps
    parsed["step_ms_untraced"] = round(step_ms, 2)
    return parsed


# ---------------------------------------------------------------------------
# 2. AOT overlap-scheduling proof
# ---------------------------------------------------------------------------

def _async_overlap_report(hlo_text: str):
    """For each async collective pair, count non-trivial ops scheduled
    between start and done in the entry computation's program order."""
    lines = [ln.strip() for ln in hlo_text.splitlines()]
    starts = {}
    pairs = []
    for i, ln in enumerate(lines):
        m = re.match(r"%?([\w.-]+) = .*(all-gather-start|all-reduce-start|"
                     r"reduce-scatter-start|collective-permute-start|"
                     r"async-start)", ln)
        if m:
            starts[m.group(1)] = (i, m.group(2))
            continue
        m2 = re.search(r"(all-gather-done|all-reduce-done|"
                       r"reduce-scatter-done|collective-permute-done|"
                       r"async-done)[(]%?([\w.-]+)", ln)
        if m2 and m2.group(2) in starts:
            s_line, kind = starts.pop(m2.group(2))
            between = [x for x in lines[s_line + 1:i]
                       if re.search(r" = ", x)
                       and not re.search(r"-(start|done)|parameter|constant",
                                         x)]
            compute = [x for x in between
                       if re.search(r"fusion|dot|convolution|custom-call", x)]
            pairs.append({"kind": kind.replace("-start", ""),
                          "ops_between": len(between),
                          "compute_between": len(compute)})
    return pairs


def aot_overlap_check():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    kind = jax.devices()[0].device_kind
    topo_names = ["v5e:2x4", "v5litepod-8", "v5e-8"]
    topo = None
    errs = []
    for name in topo_names:
        try:
            topo = topologies.get_topology_desc(name, platform="tpu")
            break
        except Exception as e:  # noqa: BLE001
            errs.append(f"{name}: {type(e).__name__}: {str(e)[:80]}")
    if topo is None:
        return {"available": False, "device_kind": kind, "errors": errs}

    mesh = topologies.make_mesh(topo, (8,), ("data",))

    # dp-8 grad step: does the grad all-reduce overlap the backward?
    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)

    cfg = bert_large_config()
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, 8, 512)
    import functools

    step = make_pretrain_step(model)
    abstract_params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), batch["input_ids"],
                           batch["token_type_ids"],
                           batch["attention_mask"])["params"])
    repl = NamedSharding(mesh, P())
    data_sh = {k: NamedSharding(mesh, P("data", *[None] * (v.ndim - 1)))
               for k, v in batch.items()}
    p_sh = jax.tree.map(lambda _: repl, abstract_params)

    def spec(v, sh):
        return jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=sh)

    params_in = jax.tree.map(
        lambda a, s: spec(a, s), abstract_params, p_sh)
    batch_in = {k: spec(np.asarray(v), data_sh[k]) for k, v in batch.items()}

    lowered = jax.jit(functools.partial(step), out_shardings=None).lower(
        params_in, batch_in, 0)
    compiled = lowered.compile()
    hlo = compiled.as_text()
    out = {"available": True, "topology": str(topo),
           "dp8_grad_allreduce_pairs": _async_overlap_report(hlo)}
    try:
        out["zero_shard_step_pairs"] = _zero_overlap_hlo(mesh)
    except Exception as e:  # noqa: BLE001
        out["zero_shard_step_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    return out


def _zero_overlap_hlo(mesh):
    """AOT-compile the ZeRO shard_step (psum_scatter -> local update ->
    param all-gather) for the 8-chip topology and report whether the TPU
    scheduler overlaps the param all-gather with independent work
    (docs/performance.md's ZeRO claim; SURVEY hard part #5)."""
    import unittest.mock as mock

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w1": np.zeros((1024, 1024), np.float32),
              "w2": np.zeros((4096, 1024), np.float32),
              "emb": np.zeros((8192, 1024), np.float32),
              "b": np.zeros((1024,), np.float32)}
    # the ctor device_puts master/state onto the mesh — impossible on a
    # device-less topology; shapes are all the lowering needs
    with mock.patch.object(jax, "device_put", lambda x, s=None: x):
        opt = DistributedFusedAdam(params, lr=1e-3, weight_decay=0.01,
                                   mesh=mesh, dp_axis="data")
    row = P("data", None)
    state_specs = {k: row for k in opt.state}

    def body(g, master, state, step):
        p, m2, s2, st2, _ = opt.shard_step(g, master, state, step)
        return p, m2, s2, st2

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(), row, state_specs, P()),
                       out_specs=(P(), row, state_specs, P()),
                       check_vma=False)

    def spec(shape, sh):
        return jax.ShapeDtypeStruct(shape, np.float32,
                                    sharding=NamedSharding(mesh, sh))

    g_in = jax.tree.map(lambda a: spec(a.shape, P()), params)
    master_in = spec(opt.master.shape, row)
    state_in = {k: spec(v.shape, row) for k, v in opt.state.items()}
    step_in = jax.ShapeDtypeStruct((), np.int32,
                                   sharding=NamedSharding(mesh, P()))
    hlo = jax.jit(fn).lower(g_in, master_in, state_in,
                            step_in).compile().as_text()
    return _async_overlap_report(hlo)


def main():
    _enable_compile_cache()
    tag = os.environ.get("APEX_TPU_TAG", "session")
    out = {"metric": "tpu_profile", "tag": tag}
    try:
        out["step_breakdown"] = run_traced_steps()
    except Exception as e:  # noqa: BLE001
        import traceback

        log(traceback.format_exc())
        out["step_breakdown_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    try:
        out["aot_overlap"] = aot_overlap_check()
    except Exception as e:  # noqa: BLE001
        import traceback

        log(traceback.format_exc())
        out["aot_overlap_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    path = os.path.join(REPO, f"PROFILE_{tag}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"wrote {path}")
    print(json.dumps({k: v for k, v in out.items()
                      if not isinstance(v, dict)} |
                     {"wrote": os.path.basename(path),
                      "ok": "step_breakdown" in out}))


if __name__ == "__main__":
    main()
