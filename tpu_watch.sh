#!/bin/bash
# Retry run_tpu_round.sh until it succeeds once (TPU tunnel is flaky and
# may return at any time). Stops after a successful bench artifact or when
# the deadline (seconds, default 8h) passes.
set -u
TAG="${1:-r03}"
DEADLINE="${2:-28800}"
START=$(date +%s)
cd "$(dirname "$0")"
while true; do
  now=$(date +%s)
  if [ $((now - START)) -ge "$DEADLINE" ]; then
    echo "[watch] deadline reached"; exit 1
  fi
  bash run_tpu_round.sh "$TAG" && {
    echo "[watch] TPU round completed"; exit 0; }
  sleep 900
done
