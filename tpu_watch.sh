#!/bin/bash
# Retry run_tpu_round.sh until it succeeds once (TPU tunnel is flaky and
# may return at any time). Stops after a successful bench artifact or when
# the deadline (seconds, default 8h) passes.
set -u
TAG="${1:-r03}"
DEADLINE="${2:-28800}"
START=$(date +%s)
cd "$(dirname "$0")"
bench_ok() {
  python - <<'EOF'
import json, sys
try:
    with open("BENCH_r03.json.local") as f:
        sys.exit(0 if json.load(f).get("value", 0) > 0 else 1)
except Exception:
    sys.exit(1)
EOF
}

while true; do
  now=$(date +%s)
  if [ $((now - START)) -ge "$DEADLINE" ]; then
    echo "[watch] deadline reached"; exit 1
  fi
  if bench_ok; then echo "[watch] bench nonzero; done"; exit 0; fi
  bash run_tpu_round.sh "$TAG" && {
    echo "[watch] TPU round completed"; exit 0; }
  # each attempt already spends ~15 min probing; short gap keeps the duty
  # cycle high against a tunnel that comes back on minute timescales
  sleep 240
done
