#!/bin/bash
# Retry run_tpu_round.sh until it succeeds once (TPU tunnel is flaky and
# may return at any time). Stops after a successful bench artifact or when
# the deadline (seconds, default 8h) passes.
set -u
TAG="${1:-r04}"
DEADLINE="${2:-28800}"
START=$(date +%s)
cd "$(dirname "$0")"
bench_ok() { python bench_ok.py "BENCH_${TAG}.json.local"; }
suite_ok() {
  # complete run with zero failures (a truncated run keeps no summary line)
  tail -3 "TPU_TESTS_${TAG}.log" 2>/dev/null \
    | grep -qE "[0-9]+ passed" \
    && ! tail -3 "TPU_TESTS_${TAG}.log" | grep -qE "[0-9]+ (failed|error)"
}

while true; do
  now=$(date +%s)
  if [ $((now - START)) -ge "$DEADLINE" ]; then
    echo "[watch] deadline reached"; exit 1
  fi
  if bench_ok && suite_ok; then
    echo "[watch] bench nonzero AND suite clean; done"; exit 0
  fi
  bash run_tpu_round.sh "$TAG"
  if bench_ok && suite_ok; then
    echo "[watch] TPU round completed with both artifacts"; exit 0
  fi
  # each attempt already spends ~15 min probing; short gap keeps the duty
  # cycle high against a tunnel that comes back on minute timescales
  sleep 240
done
