"""On-chip decode-throughput harvest: GPT-2 small autoregressive generation.

Run inside a healthy tunnel window (run_tpu_round.sh calls it after the
gate artifacts exist). Measures steady-state single-token decode steps/s
of `apex_tpu.models.generation.generate` on BASELINE config #4's GPT-2
small (beyond-reference: apex has no inference path, so this metric has
no reference analog — it documents the KV-cache design's throughput).

Method: jit two generate programs at the same prompt — one with
`max_new_tokens=1` (prefill + 1 step) and one with `N` steps — and take
``(N-1) * batch / (t_N - t_1)``: pure decode-step throughput with the
prefill and sampling epilogue differenced out. Greedy decode (argmax),
bf16 model, batch 8, prompt 128, N=128.

Emits one JSON line: {"metric": "gpt2_decode_tokens_per_sec_per_chip", ...}.

Also measures the SERVING path (apex_tpu/serving): a mixed-length request
set through the paged-KV continuous-batching engine, emitting a second
line {"metric": "gpt2_paged_decode_tokens_per_sec_per_chip", ...} with
the engine's decode-step count next to the steps lock-step generate would
have padded to — the Orca/vLLM win this harness exists to document.

The serving workloads are no longer inline generators: the mixed-length
and shared-system-prompt request sets are the scenario library's
``bench-mixed-length`` / ``bench-shared-prefix`` catalog entries
(apex_tpu/serving/scenarios, docs/scenarios.md), materialized from a
fixed seed — the bench keeps only the measurement loops and asserts.

After the paged line: the QUANTIZED KV-PAGE engine — the same workload
with ``kv_dtype='int8'`` (int8 pages + per-(page, kv_head) f32 scales,
dequant inside the kernel), emitting
{"metric": "gpt2_int8kv_paged_decode_tokens_per_sec_per_chip", ...}
with slot-capacity telemetry (``kv_pool.max_slots_for_pool_bytes`` at a
fixed pool-byte budget: int8 admits ~2x the slots); the smoke run
asserts per-request shapes and first tokens match the fp engine (full
token-level parity is tolerance-pinned in tests/test_quantized_kv.py)
and the >= 1.9x capacity ratio.

After the int8-KV line: the QUANTIZED WEIGHT-STREAMING engine — the
same workload with the int8 ``WeightPrecisionPolicy`` model (block
linears int8 + per-channel f32 scales, fused in-kernel dequant;
docs/serving.md "Quantized weight streaming"), emitting
{"metric": "gpt2_w8_paged_decode_tokens_per_sec_per_chip", ...} with
TTFT/TPOT percentiles and the weight-tree byte split; the smoke run
asserts per-request shapes, first-token identity vs the fp paged engine
(fixed-seed pin — prefill runs the quantized weights), and that the
quantized tree's bytes genuinely drop below the fp tree's.

Between the paged and prefix-cached lines: the TENSOR-PARALLEL paged
engine (serving/tp.py, docs/tp_serving.md) — the same mixed-length
workload through a tp=2 ``TensorParallelPagedEngine`` (head-sharded
pool + Megatron weight shards over a 2-device mesh), emitting
{"metric": "gpt2_tp2_paged_decode_tokens_per_sec_per_chip", ...} with
TTFT/TPOT percentiles; the smoke run asserts greedy token identity
against the single-chip engine. On a 1-device window the record lands
with value 0.0 (zero baselines never gate in the perf ledger).

Third line: the PREFIX-CACHED serving path — a shared-system-prompt
workload (every request = one common header + a private tail, the
dominant multi-user pattern) through the engine with
``prefix_cache=True``, emitting
{"metric": "gpt2_prefix_cached_decode_tokens_per_sec_per_chip", ...}
with the radix-cache hit rate and prefill-tokens-skipped counters next to
the total. The smoke run asserts the reduction: every request past the
first concurrent wave must skip the full shared-header prefill.

After it: the TIERED KV POOL (docs/serving.md "Tiered KV pool") — the
catalogued ``host-tier-churn`` workload (more cacheable header pages
than the thrash-sized pool holds) through the engine with
``host_tier_bytes`` set, emitting
{"metric": "gpt2_host_tier_decode_tokens_per_sec_per_chip", ...} with
the demote/promote counters and promote-hit rate next to the total. The
smoke run asserts promotes > 0, strictly more prefix hits than the
tier-off engine at the same pool, and token identity vs tier-off.

Fourth line: the ASYNC FRONT-END (docs/frontend.md) — an open-loop
Poisson arrival stream with mixed priorities and TTFT deadlines through
``ServingFrontend``, closed by an adversarial burst that forces the
preemption/spill/resume path, emitting
{"metric": "gpt2_frontend_decode_tokens_per_sec_per_chip", ...} with
``gpt2_frontend_ttft/tpot`` percentiles and deadline-miss counts from
the metrics registry plus preemption/resume counters. The smoke run
asserts preemptions > 0 and resumes > 0 under the burst.

Last two lines (the s>1 paged query block, docs/serving.md): the
IN-ENGINE SPECULATIVE path — the mixed-length workload with a
self-draft (acceptance ceiling k), emitting
{"metric": "gpt2_spec_decode_tokens_per_sec_per_chip", ...} with
round/acceptance telemetry, smoke-asserted token-identical to the plain
paged engine — and the CHUNKED-PREFILL TTFT A/B — one long prompt plus
short traffic through monolithic vs ``prefill_chunk`` admission,
emitting {"metric": "gpt2_frontend_chunked_ttft_ms_p95", ...} with both
variants' TTFT percentiles so the ledger banks the tail reduction.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def time_best(fn, repeats=3):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import functools
    import os

    from apex_tpu.models.generation import generate
    from apex_tpu.models.gpt import GPTModel, gpt2_small_config, gpt_tiny_config

    if os.environ.get("APEX_TPU_DECODE_SMOKE") == "1":
        # CPU smoke: interpret-mode flash prefill at GPT-2 shapes is far
        # too slow; prove the harness mechanics on the tiny model instead
        # (jax.config, not env — sitecustomize imports jax before us).
        # n_new=16 keeps the differenced step window wide enough that
        # scheduler noise can't zero the speedup ratio
        jax.config.update("jax_platforms", "cpu")
        batch, prompt_len, n_new = 2, 8, 16
        cfg = gpt_tiny_config()
    else:
        batch, prompt_len, n_new = 8, 128, 128
        cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt_len)),
                         jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt[:, :8])

    def measure(m, variables):
        gen_1 = jax.jit(functools.partial(generate, m, max_new_tokens=1,
                                          max_len=prompt_len + n_new,
                                          axis_name="unbound"))
        gen_n = jax.jit(functools.partial(generate, m,
                                          max_new_tokens=n_new,
                                          max_len=prompt_len + n_new,
                                          axis_name="unbound"))
        jax.block_until_ready(gen_1(variables, prompt))   # compile
        jax.block_until_ready(gen_n(variables, prompt))
        t1 = time_best(lambda: gen_1(variables, prompt))
        tn = time_best(lambda: gen_n(variables, prompt))
        steps = n_new - 1
        return steps * batch / max(tn - t1, 1e-9), t1, tn, steps

    toks_per_s, t1, tn, steps = measure(model, v)

    # int8 W8A8 serving pass (docs/quantization.md): same weights,
    # post-training-quantized — decode is weight-fetch bound, so this
    # measures the HBM-bandwidth story directly
    import dataclasses

    from apex_tpu.models.quantize import quantize_model_params

    qmodel = GPTModel(dataclasses.replace(cfg, quantize_int8=True))
    qparams = quantize_model_params(qmodel, v, prompt[:, :8])
    q_toks_per_s, _, _, _ = measure(qmodel, {"params": qparams})

    dev = jax.devices()[0]
    rec = {
        "metric": "gpt2_decode_tokens_per_sec_per_chip",
        "value": round(toks_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "batch": batch, "prompt_len": prompt_len, "new_tokens": n_new,
        "step_ms": round(1e3 * (tn - t1) / steps, 3),
        "prefill_plus_one_s": round(t1, 3),
        "int8_tokens_per_sec": round(q_toks_per_s, 1),
        "int8_speedup": round(q_toks_per_s / max(toks_per_s, 1e-9), 3),
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(rec), flush=True)

    # --- paged continuous-batching serving metric ---------------------------
    # the workload DEFINITION lives in the scenario library
    # (apex_tpu/serving/scenarios, docs/scenarios.md): the bench
    # materializes the catalogued ``bench-mixed-length`` trace (seeded —
    # reproducible request set) and keeps only the measurement loop here
    import dataclasses as _dc

    from apex_tpu.serving import PagedDecodeEngine, Request
    from apex_tpu.serving.scenarios import (Lengths, materialize,
                                            scenario_spec,
                                            trace_requests)

    smoke = os.environ.get("APEX_TPU_DECODE_SMOKE") == "1"
    if smoke:
        ml_spec = scenario_spec("bench-mixed-length", seed=1)
    else:
        base = scenario_spec("bench-mixed-length", seed=1)
        ml_spec = _dc.replace(
            base, n_requests=3 * batch,
            prompt_lens=Lengths(kind="uniform", lo=32, hi=128),
            output_lens=Lengths(kind="uniform", lo=32, hi=128),
            engine=_dc.replace(base.engine, model="gpt2-small",
                               num_slots=batch, page_size=16))
    num_slots, page_size = ml_spec.engine.num_slots, \
        ml_spec.engine.page_size
    ml_trace = materialize(ml_spec)
    requests = trace_requests(ml_trace)
    n_req = len(requests)
    prompt_lens = [len(e.prompt) for e in ml_trace.events]
    new_tokens = [e.max_new_tokens for e in ml_trace.events]

    engine = PagedDecodeEngine(model, v, num_slots=num_slots,
                               page_size=page_size)
    engine.run(requests)                                 # compile + warm
    t0 = time.perf_counter()
    outs, stats = engine.run(requests)
    elapsed = time.perf_counter() - t0
    gen_tokens = int(sum(o.shape[0] for o in outs))
    # lock-step at the same slot capacity pads every batch of num_slots
    # requests to the batch's longest token budget
    order = sorted(range(n_req), key=lambda i: -int(new_tokens[i]))
    lockstep_steps = sum(
        max(int(new_tokens[i]) for i in order[g:g + num_slots])
        for g in range(0, n_req, num_slots))
    if smoke and stats["decode_steps"] >= lockstep_steps:
        raise SystemExit(
            f"continuous batching regressed: {stats['decode_steps']} engine "
            f"steps vs {lockstep_steps} lock-step steps")
    prec = {
        "metric": "gpt2_paged_decode_tokens_per_sec_per_chip",
        "value": round(gen_tokens / max(elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_req, "num_slots": num_slots, "page_size": page_size,
        "prompt_lens": [int(x) for x in prompt_lens],
        "new_tokens": [int(x) for x in new_tokens],
        "generated_tokens": gen_tokens,
        "decode_steps": stats["decode_steps"],
        "lockstep_steps": lockstep_steps,
        "step_savings": round(1.0 - stats["decode_steps"]
                              / max(lockstep_steps, 1), 3),
        # per-request latency percentiles from the engine's span tracer
        # (docs/observability.md): TTFT = enqueue -> first token,
        # decode-step = per-step wall time at the sync boundary
        "gpt2_paged_decode_ttft_ms_p50": round(stats["ttft_ms_p50"], 3),
        "gpt2_paged_decode_ttft_ms_p95": round(stats["ttft_ms_p95"], 3),
        "decode_step_ms_p50": round(stats["decode_step_ms_p50"], 3),
        "decode_step_ms_p95": round(stats["decode_step_ms_p95"], 3),
        "queue_wait_ms_p50": round(stats["queue_wait_ms_p50"], 3),
        "tpot_ms_p50": round(stats["tpot_ms_p50"], 3),
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(prec), flush=True)

    # --- quantized (int8) KV-page serving metric ----------------------------
    # the SAME mixed-length workload through the engine with
    # ``kv_dtype='int8'`` (docs/serving.md "Quantized KV pages"): K/V
    # pages live in the pool as int8 with per-(page, kv_head) f32 scales
    # and dequantize inside the paged-attention kernel. The headline
    # rides next to the slot-capacity telemetry — at a FIXED pool-byte
    # budget the int8 pool admits ~2x the slots of the bf16 pool
    # (kv_pool.max_slots_for_pool_bytes), which is the actual win:
    # more concurrent sequences per chip, not a faster single step.
    from apex_tpu.serving import kv_pool as _kvp

    q_engine = PagedDecodeEngine(model, v, num_slots=num_slots,
                                 page_size=page_size, kv_dtype="int8")
    q_engine.run(requests)                               # compile + warm
    t0 = time.perf_counter()
    q_outs, q_stats = q_engine.run(requests)
    q_elapsed = time.perf_counter() - t0
    q_tokens = int(sum(o.shape[0] for o in q_outs))
    if smoke:
        # NOT exact token identity: quantization legitimately perturbs
        # logits by more than a tiny random-init model's argmax gaps
        # (the tolerance-pinned parity lives in
        # tests/test_quantized_kv.py). What IS exact: request shapes,
        # and each request's FIRST token — it comes off the prefill
        # forward pass's own logits, before any quantized-pool read
        for i, (a, b) in enumerate(zip(outs, q_outs)):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape:
                raise SystemExit(
                    f"int8-kv engine changed request {i}'s output shape: "
                    f"{a.shape} vs fp {b.shape}")
            if a.shape[0] and a[0] != b[0]:
                raise SystemExit(
                    f"int8-kv engine flipped request {i}'s FIRST token "
                    f"({b[0]} vs fp {a[0]}) — prefill logits never touch "
                    f"the quantized pool, so this is a real bug")
    # slot capacity at a fixed budget: what one fp pool's bytes would
    # buy in each dtype (pages_per_slot from the bench's own shapes)
    pps = max((max(prompt_lens) + max(new_tokens) + page_size - 1)
              // page_size, 1)
    fp_pool_bytes = _kvp.page_bytes(cfg, page_size) * (
        num_slots * pps + 1)
    fp_cap = _kvp.max_slots_for_pool_bytes(cfg, fp_pool_bytes,
                                           pages_per_slot=pps,
                                           page_size=page_size)
    q_cap = _kvp.max_slots_for_pool_bytes(cfg, fp_pool_bytes,
                                          pages_per_slot=pps,
                                          page_size=page_size,
                                          kv_dtype="int8")
    if smoke and q_cap < 1.9 * fp_cap:
        raise SystemExit(
            f"int8-kv slot capacity regressed: {q_cap} slots vs "
            f"{fp_cap} fp slots at a fixed pool budget (< 1.9x)")
    q_rec = {
        "metric": "gpt2_int8kv_paged_decode_tokens_per_sec_per_chip",
        "value": round(q_tokens / max(q_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_req, "num_slots": num_slots, "page_size": page_size,
        "kv_dtype": "int8",
        "generated_tokens": q_tokens,
        "decode_steps": q_stats["decode_steps"],
        "fp_tokens_per_sec": prec["value"],
        # capacity telemetry: slots a fixed pool-byte budget admits
        "pool_bytes_budget": int(fp_pool_bytes),
        "pages_per_slot": int(pps),
        "fp_slot_capacity": int(fp_cap),
        "int8_slot_capacity": int(q_cap),
        "slot_capacity_ratio": round(q_cap / max(fp_cap, 1), 3),
        "page_bytes_fp": int(_kvp.page_bytes(cfg, page_size)),
        "page_bytes_int8": int(_kvp.page_bytes(cfg, page_size,
                                               kv_dtype="int8")),
        "gpt2_int8kv_paged_decode_ttft_ms_p50": round(
            q_stats["ttft_ms_p50"], 3),
        "gpt2_int8kv_paged_decode_ttft_ms_p95": round(
            q_stats["ttft_ms_p95"], 3),
        "tpot_ms_p50": round(q_stats["tpot_ms_p50"], 3),
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(q_rec), flush=True)

    # --- quantized WEIGHT streaming serving metric --------------------------
    # the SAME mixed-length workload through the paged engine over the
    # int8-policy model (docs/serving.md "Quantized weight streaming"):
    # every block linear's weight lives in HBM as int8 with a per-channel
    # f32 scale and dequantizes inside the fused dequant-matmul kernel,
    # next to the contraction — decode is weight-fetch bound, so the
    # per-step weight stream roughly halves (cost.decode.w8.*). Unlike
    # the KV record above, prefill itself runs the quantized weights, so
    # the first-token identity asserted here is an empirical fixed-seed
    # pin (deterministic per build), not a structural guarantee; the
    # tolerance-pinned parity lives in tests/test_quantized_weights.py.
    def _tree_bytes(tree):
        return int(sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree)))

    w8_engine = PagedDecodeEngine(qmodel, {"params": qparams},
                                  num_slots=num_slots, page_size=page_size)
    w8_engine.run(requests)                              # compile + warm
    t0 = time.perf_counter()
    w8_outs, w8_stats = w8_engine.run(requests)
    w8_elapsed = time.perf_counter() - t0
    w8_tokens = int(sum(o.shape[0] for o in w8_outs))
    fp_weight_bytes = _tree_bytes(v["params"])
    w8_weight_bytes = _tree_bytes(qparams)
    if smoke:
        for i, (a, b) in enumerate(zip(outs, w8_outs)):
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape:
                raise SystemExit(
                    f"w8 engine changed request {i}'s output shape: "
                    f"{b.shape} vs fp {a.shape}")
            if a.shape[0] and a[0] != b[0]:
                raise SystemExit(
                    f"w8 engine flipped request {i}'s FIRST token "
                    f"({b[0]} vs fp {a[0]}) — the fixed-seed first-token "
                    f"pin regressed (tests/test_quantized_weights.py "
                    f"holds the tolerance parity)")
        if w8_weight_bytes >= fp_weight_bytes:
            raise SystemExit(
                f"w8 weight stream regressed: {w8_weight_bytes} quantized "
                f"tree bytes >= {fp_weight_bytes} fp bytes")
    w8_rec = {
        "metric": "gpt2_w8_paged_decode_tokens_per_sec_per_chip",
        "value": round(w8_tokens / max(w8_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_req, "num_slots": num_slots, "page_size": page_size,
        "weight_dtype": "int8",
        "generated_tokens": w8_tokens,
        "decode_steps": w8_stats["decode_steps"],
        "fp_tokens_per_sec": prec["value"],
        # streaming telemetry: each tree's bytes at its ACTUAL leaf
        # dtypes (scales included) — the gpt2s ratio is pinned exactly
        # by the cost model (cost.decode.w8.weight_bytes_ratio_vs_bf16)
        "fp_weight_bytes": fp_weight_bytes,
        "w8_weight_bytes": w8_weight_bytes,
        "weight_bytes_ratio_vs_fp": round(
            w8_weight_bytes / max(fp_weight_bytes, 1), 3),
        "gpt2_w8_paged_decode_ttft_ms_p50": round(
            w8_stats["ttft_ms_p50"], 3),
        "gpt2_w8_paged_decode_ttft_ms_p95": round(
            w8_stats["ttft_ms_p95"], 3),
        "tpot_ms_p50": round(w8_stats["tpot_ms_p50"], 3),
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(w8_rec), flush=True)

    # --- tensor-parallel paged serving metric -------------------------------
    # the SAME mixed-length workload through a tp=2
    # TensorParallelPagedEngine (serving/tp.py, docs/tp_serving.md): the
    # pool's kv heads and the Megatron weight shards split over a
    # 2-device mesh, the scheduler/block tables stay replicated, and
    # greedy outputs must be token-identical to the single-chip engine
    # above (asserted in smoke). The headline divides by tp — per-CHIP
    # throughput, comparable against the single-chip paged number
    # (aggregate bandwidth scales with the mesh; per-chip should hold
    # roughly steady once the model is big enough to stream).
    if len(jax.devices()) >= 2:
        from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                         shard_model_variables, tp_mesh)

        tp = 2
        tp_cfg = dataclasses.replace(cfg, tensor_parallel_size=tp)
        tp_model = GPTModel(tp_cfg)
        tp_m = tp_mesh(tp)
        tp_vars, _ = shard_model_variables(tp_model, v, tp_m)
        tp_engine = TensorParallelPagedEngine(
            tp_model, tp_vars, mesh=tp_m, num_slots=num_slots,
            page_size=page_size)
        tp_engine.run(requests)                          # compile + warm
        t0 = time.perf_counter()
        tp_outs, tp_stats = tp_engine.run(requests)
        tp_elapsed = time.perf_counter() - t0
        tp_tokens = int(sum(o.shape[0] for o in tp_outs))
        if smoke:
            for i, (a, b) in enumerate(zip(outs, tp_outs)):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    raise SystemExit(
                        f"tp=2 greedy decode diverged from the "
                        f"single-chip engine on request {i}: "
                        f"{np.asarray(a)[:8]}... vs {np.asarray(b)[:8]}...")
        tp_rec = {
            "metric": "gpt2_tp2_paged_decode_tokens_per_sec_per_chip",
            "value": round(tp_tokens / max(tp_elapsed, 1e-9) / tp, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "tp_world": tp_stats["tp_world"],
            "requests": n_req, "num_slots": num_slots,
            "page_size": page_size,
            "generated_tokens": tp_tokens,
            "decode_steps": tp_stats["decode_steps"],
            "aggregate_tokens_per_sec": round(
                tp_tokens / max(tp_elapsed, 1e-9), 1),
            "gpt2_tp2_paged_decode_ttft_ms_p50": round(
                tp_stats["ttft_ms_p50"], 3),
            "gpt2_tp2_paged_decode_ttft_ms_p95": round(
                tp_stats["ttft_ms_p95"], 3),
            "gpt2_tp2_paged_decode_tpot_ms_p50": round(
                tp_stats["tpot_ms_p50"], 3),
            "gpt2_tp2_paged_decode_tpot_ms_p95": round(
                tp_stats["tpot_ms_p95"], 3),
            "decode_step_ms_p50": round(
                tp_stats["decode_step_ms_p50"], 3),
            "device": dev.device_kind, "platform": dev.platform,
        }
        print(json.dumps(tp_rec), flush=True)
    else:
        # a 1-device window cannot run the tp=2 engine; emit the record
        # with a dead value (zero baselines never gate in the ledger)
        print(json.dumps({
            "metric": "gpt2_tp2_paged_decode_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "skipped": "needs >= 2 devices",
            "device": dev.device_kind, "platform": dev.platform,
        }), flush=True)

    # --- shared-prefix (radix) cached serving metric ------------------------
    # every request: one shared system header + a private tail (the
    # catalogued ``bench-shared-prefix`` scenario — one tenant whose
    # deterministic system prompt every request shares). Requests
    # admitted after the first concurrent wave point their block tables
    # at the header's cached pages and prefill only the tail.
    from apex_tpu.serving.scenarios import Tenant

    if smoke:
        pc_spec = scenario_spec("bench-shared-prefix", seed=2)
    else:
        pc_base = scenario_spec("bench-shared-prefix", seed=2)
        pc_spec = _dc.replace(
            pc_base, n_requests=3 * batch,
            prompt_lens=Lengths(kind="uniform", lo=16, hi=64),
            output_lens=Lengths(kind="uniform", lo=32, hi=128),
            tenants=(Tenant("shared",
                            system_prompt_tokens=16 * 16),),
            engine=_dc.replace(pc_base.engine, model="gpt2-small",
                               num_slots=num_slots, page_size=16))
    pc_slots = pc_spec.engine.num_slots
    sys_len = pc_spec.tenants[0].system_prompt_tokens
    pc_trace = materialize(pc_spec)
    pc_requests = trace_requests(pc_trace)
    n_pc = len(pc_requests)
    pc_tails = [len(e.prompt) - sys_len for e in pc_trace.events]
    pc_new = [e.max_new_tokens for e in pc_trace.events]

    pc_engine = PagedDecodeEngine(model, v, num_slots=pc_slots,
                                  page_size=pc_spec.engine.page_size,
                                  prefix_cache=True)
    pc_engine.run(pc_requests)          # cold: populate the radix cache
    pc_engine.run(pc_requests)          # warm: compile the hit-depth
    #                                     admission programs the timed
    #                                     (steady-state) run replays
    t0 = time.perf_counter()
    pc_outs, pc_stats = pc_engine.run(pc_requests)
    pc_elapsed = time.perf_counter() - t0
    pc_tokens = int(sum(o.shape[0] for o in pc_outs))
    if smoke:
        # warm-cache floor (pc_stats is the third run): EVERY request's
        # full prompt is already cached, so every one must hit and at
        # least skip the shared header. (The cold-run floor is weaker:
        # inserts happen at retirement, so the first pc_slots-wide
        # concurrent wave misses — (n_pc - pc_slots) * sys_len.)
        floor = n_pc * sys_len
        if pc_stats["prefill_tokens_skipped"] < floor:
            raise SystemExit(
                f"prefix cache regressed: skipped "
                f"{pc_stats['prefill_tokens_skipped']} prefill tokens < "
                f"the {floor} the warm shared header guarantees")
        if pc_stats["prefix_hits"] < n_pc:
            raise SystemExit(
                f"prefix cache regressed: {pc_stats['prefix_hits']}/{n_pc} "
                f"hits on a warm shared-system-prompt workload")
    pc_rec = {
        "metric": "gpt2_prefix_cached_decode_tokens_per_sec_per_chip",
        "value": round(pc_tokens / max(pc_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_pc, "num_slots": pc_slots, "page_size": page_size,
        "shared_prefix_tokens": sys_len,
        "tail_lens": [int(x) for x in pc_tails],
        "new_tokens": [int(x) for x in pc_new],
        "generated_tokens": pc_tokens,
        # engine counters (the serving-observability tier): the third —
        # timed, warm-cache — run's stats, i.e. steady-state hit behavior
        **{k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in pc_stats.items()},
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(pc_rec), flush=True)

    # --- tiered (host-RAM spill) KV pool serving metric ---------------------
    # the catalogued ``host-tier-churn`` workload (docs/serving.md
    # "Tiered KV pool"): more cacheable header pages than the pool
    # holds, so tier-off every revisited header re-prefills while
    # tier-on demotes it to host RAM and promotes it back over the host
    # link. The smoke run asserts promotes actually fired AND that the
    # tier changed no output token vs the tier-off engine at the same
    # thrash-sized pool.
    from apex_tpu.serving.scenarios.tenants import churn_tenants

    if smoke:
        ht_spec = scenario_spec("host-tier-churn", seed=3)
    else:
        ht_base = scenario_spec("host-tier-churn", seed=3)
        ht_spec = _dc.replace(
            ht_base, n_requests=3 * batch,
            output_lens=Lengths(kind="uniform", lo=16, hi=64),
            tenants=churn_tenants(8, 4, 16),
            engine=_dc.replace(ht_base.engine, model="gpt2-small",
                               num_slots=num_slots, page_size=16,
                               num_pages=24, host_tier_bytes=1 << 30))
    ht_es = ht_spec.engine
    ht_trace = materialize(ht_spec)
    ht_requests = trace_requests(ht_trace)
    n_ht = len(ht_requests)

    ht_engine = PagedDecodeEngine(model, v, num_slots=ht_es.num_slots,
                                  page_size=ht_es.page_size,
                                  num_pages=ht_es.num_pages,
                                  prefix_cache=True,
                                  host_tier_bytes=ht_es.host_tier_bytes)
    ht_engine.run(ht_requests)          # compile + populate tier
    t0 = time.perf_counter()
    ht_outs, ht_stats = ht_engine.run(ht_requests)
    ht_elapsed = time.perf_counter() - t0
    ht_tokens = int(sum(o.shape[0] for o in ht_outs))
    tier = ht_engine.host_tier.stats()
    if smoke:
        if tier["host_tier_promotes"] < 1:
            raise SystemExit(
                "host tier regressed: the churn workload never promoted "
                f"a demoted page ({tier})")
        off_engine = PagedDecodeEngine(model, v,
                                       num_slots=ht_es.num_slots,
                                       page_size=ht_es.page_size,
                                       num_pages=ht_es.num_pages,
                                       prefix_cache=True)
        off_engine.run(ht_requests)
        off_outs, off_stats = off_engine.run(ht_requests)
        for i, (a, b) in enumerate(zip(ht_outs, off_outs)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise SystemExit(
                    f"host tier regressed: request {i} diverged from the "
                    "tier-off engine (promote must be bit-stable)")
        if ht_stats["prefix_hits"] <= off_stats["prefix_hits"]:
            raise SystemExit(
                f"host tier regressed: {ht_stats['prefix_hits']} hits "
                f"tier-on <= {off_stats['prefix_hits']} tier-off on the "
                "churn workload")
    ht_rec = {
        "metric": "gpt2_host_tier_decode_tokens_per_sec_per_chip",
        "value": round(ht_tokens / max(ht_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_ht, "num_slots": ht_es.num_slots,
        "page_size": ht_es.page_size, "num_pages": ht_es.num_pages,
        "host_tier_budget_bytes": ht_es.host_tier_bytes,
        "generated_tokens": ht_tokens,
        # lifetime tier counters (both runs): the churn evidence
        "host_tier_demotes": tier["host_tier_demotes"],
        "host_tier_promotes": tier["host_tier_promotes"],
        "host_tier_promote_hit_rate":
            round(tier["host_tier_promote_hit_rate"], 3),
        "host_tier_resident_bytes": tier["host_tier_resident_bytes"],
        **{k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in ht_stats.items()},
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(ht_rec), flush=True)

    # --- open-loop async frontend workload (Poisson arrivals) ---------------
    # the serving FRONT-END under an open arrival stream (docs/frontend.md):
    # requests are submitted at Poisson arrival times regardless of
    # completion (open loop — queueing is real, unlike the closed run()
    # batches above), with mixed priorities and TTFT deadlines, followed
    # by an adversarial burst (slots pinned by low-priority work, then a
    # high-priority arrival) that FORCES the preemption/spill/resume
    # path. Emits gpt2_frontend_* TTFT/TPOT/deadline-miss fields from the
    # metrics registry; the smoke run asserts preemptions actually fired.
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.policy import PriorityDeadlinePolicy

    wl3 = np.random.default_rng(3)
    if smoke:
        fe_slots, n_fe = 2, 8
        fe_prompts = wl3.integers(8, 49, n_fe)
        fe_new = wl3.integers(6, 15, n_fe)
        mean_gap_s, fe_deadline_ms = 0.004, 2000.0
        burst_prompt, burst_new = 24, 20
    else:
        fe_slots, n_fe = num_slots, 3 * batch
        fe_prompts = wl3.integers(32, 129, n_fe)
        fe_new = wl3.integers(32, 129, n_fe)
        mean_gap_s, fe_deadline_ms = 0.01, 500.0
        burst_prompt, burst_new = 128, 96
    arrivals = np.cumsum(wl3.exponential(mean_gap_s, n_fe))
    fe_priorities = wl3.integers(0, 3, n_fe)
    fe_reqs = [
        Request(prompt=wl3.integers(0, cfg.vocab_size, int(L)).astype(
            np.int32), max_new_tokens=int(m), priority=int(p),
            deadline_ms=fe_deadline_ms if p == 2 else None)
        for L, m, p in zip(fe_prompts, fe_new, fe_priorities)]

    fe_engine = PagedDecodeEngine(model, v, num_slots=fe_slots,
                                  page_size=page_size, prefix_cache=True)
    fe_engine.run(fe_reqs)      # warm: compile buckets, seed the cache
    fe = ServingFrontend(fe_engine, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    handles = []
    t0 = time.perf_counter()
    i = 0
    while i < n_fe:
        now = time.perf_counter() - t0
        while i < n_fe and arrivals[i] <= now:
            handles.append(fe.submit(fe_reqs[i], request_id=i))
            i += 1
        if not fe.pump() and i < n_fe:
            # idle before the next arrival — nap up to it (bounded so a
            # late-arriving burst still sees a responsive pump)
            time.sleep(min(max(arrivals[i] - (time.perf_counter() - t0),
                               0.0), 0.002))
    fe.drain()
    # adversarial burst: pin every slot with low-priority long work,
    # give it a little progress, then land a high-priority deadline
    # arrival — with no vacancy the policy MUST preempt-and-spill
    burst_low = [
        Request(prompt=wl3.integers(0, cfg.vocab_size, burst_prompt
                                    ).astype(np.int32),
                max_new_tokens=burst_new, priority=0)
        for _ in range(fe_slots)]
    for j, r in enumerate(burst_low):
        handles.append(fe.submit(r, request_id=n_fe + j))
    while fe.queue_depth:
        fe.pump()
    for _ in range(3):
        fe.pump()
    handles.append(fe.submit(
        Request(prompt=wl3.integers(0, cfg.vocab_size, burst_prompt
                                    ).astype(np.int32),
                max_new_tokens=max(burst_new // 4, 2), priority=9,
                deadline_ms=fe_deadline_ms),
        request_id=n_fe + fe_slots))
    fe.drain()
    fe_elapsed = time.perf_counter() - t0
    fe_stats = fe.stats()
    fe_tokens = int(sum(h.result().shape[0] for h in handles))
    n_deadlined = sum(1 for r in fe_reqs if r.deadline_ms is not None) + 1
    if smoke and fe_stats["preemptions"] < 1:
        raise SystemExit(
            "frontend preemption regressed: the adversarial burst (all "
            "slots pinned low-priority, high-priority arrival, "
            "preempt_on_priority policy) produced 0 preemptions")
    if smoke and fe_stats["resumes"] < 1:
        raise SystemExit("frontend resume regressed: preempted work was "
                         "never resumed")
    fe_rec = {
        "metric": "gpt2_frontend_decode_tokens_per_sec_per_chip",
        "value": round(fe_tokens / max(fe_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_fe, "num_slots": fe_slots, "page_size": page_size,
        "open_loop_mean_gap_ms": round(mean_gap_s * 1e3, 3),
        "deadline_ms": fe_deadline_ms,
        "deadlined_requests": n_deadlined,
        "generated_tokens": fe_tokens,
        # TTFT/TPOT percentiles + deadline misses, from the instrument
        # registry (serving.* histograms/counters, docs/observability.md)
        "gpt2_frontend_ttft_ms_p50": round(fe_stats["ttft_ms_p50"], 3),
        "gpt2_frontend_ttft_ms_p95": round(fe_stats["ttft_ms_p95"], 3),
        "gpt2_frontend_tpot_ms_p50": round(fe_stats["tpot_ms_p50"], 3),
        "gpt2_frontend_tpot_ms_p95": round(fe_stats["tpot_ms_p95"], 3),
        "gpt2_frontend_deadline_misses": fe_stats["deadline_misses"],
        "gpt2_frontend_deadline_miss_rate": round(
            fe_stats["deadline_misses"] / max(n_deadlined, 1), 3),
        "preemptions": fe_stats["preemptions"],
        "resumes": fe_stats["resumes"],
        "peak_queue_depth": fe_stats["peak_queue_depth"],
        "prefix_hits": fe_stats["prefix_hits"],
        "prefill_tokens_skipped": fe_stats["prefill_tokens_skipped"],
        # pump pipeline attribution + recompile window (PR 8,
        # docs/observability.md): bubble_ms ≈ 0 means the double-buffered
        # host work is actually hidden behind the decode chunks;
        # jit.compiles during the measured window should be ~0 after the
        # warm run (a recompile storm here is a served-latency cliff)
        "pump.bubble_ms": round(fe_stats["pump.bubble_ms"], 3),
        "pump.host_work_ms_p50": round(
            fe_stats.get("pump.host_work_ms_p50", 0.0), 3),
        "pump.dispatch_ready_ms_p50": round(
            fe_stats.get("pump.dispatch_ready_ms_p50", 0.0), 3),
        "jit.compiles": fe_stats["jit.compiles"],
        "jit.trace_cache_misses": fe_stats["jit.trace_cache_misses"],
        "tpot_slo_misses": fe_stats["tpot_slo_misses"],
        "slo_burn": round(fe_stats["slo_burn"], 3),
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(fe_rec), flush=True)

    # --- in-engine speculative decode metric --------------------------------
    # the SAME mixed-length workload through the engine's speculative
    # mode (docs/serving.md): every step drafts ``draft_len`` tokens per
    # slot through a draft pool and verifies the block in ONE
    # s = draft_len + 1 paged target step. SELF-DRAFT here (draft =
    # target): acceptance hits the ceiling k = draft_len + 1, so this
    # measures the mechanism's best case — a real small draft lands
    # mean acceptance somewhere in 1..k and scales the win by the
    # cost model's per-acceptance split (cost.spec_decode.*). The smoke
    # run asserts greedy token identity against the non-speculative
    # paged engine and that acceptance telemetry actually exceeds 1.
    spec_draft_len = 3
    spec_engine = PagedDecodeEngine(model, v, num_slots=num_slots,
                                    page_size=page_size,
                                    draft_model=model, draft_variables=v,
                                    draft_len=spec_draft_len)
    spec_engine.run(requests)                            # compile + warm
    t0 = time.perf_counter()
    spec_outs, spec_stats = spec_engine.run(requests)
    spec_elapsed = time.perf_counter() - t0
    spec_gen = int(sum(o.shape[0] for o in spec_outs))
    if smoke:
        for i, (a, b) in enumerate(zip(outs, spec_outs)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise SystemExit(
                    f"speculative decode diverged from the greedy paged "
                    f"engine on request {i}: {np.asarray(a)[:8]}... vs "
                    f"{np.asarray(b)[:8]}...")
        if spec_stats["mean_acceptance_len"] <= 1.0:
            raise SystemExit(
                f"speculative acceptance regressed: self-draft mean "
                f"acceptance {spec_stats['mean_acceptance_len']} <= 1.0 "
                f"(every round should accept the whole block)")
    spec_rec = {
        "metric": "gpt2_spec_decode_tokens_per_sec_per_chip",
        "value": round(spec_gen / max(spec_elapsed, 1e-9), 1),
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": n_req, "num_slots": num_slots, "page_size": page_size,
        "draft_len": spec_draft_len, "self_draft": True,
        "generated_tokens": spec_gen,
        "decode_steps": spec_stats["decode_steps"],
        "spec_rounds": spec_stats["spec_rounds"],
        "spec_tokens": spec_stats["spec_tokens"],
        "mean_acceptance_len": round(spec_stats["mean_acceptance_len"], 3),
        "paged_tokens_per_sec": prec["value"],
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(spec_rec), flush=True)

    # --- chunked-prefill TTFT re-measure ------------------------------------
    # the frontend-TTFT claim of docs/frontend.md as an A/B: one long
    # prompt plus a tail of short ones through two otherwise-identical
    # engines — monolithic admission (the long prefill runs whole
    # between two decode chunks) vs ``prefill_chunk=page_size``
    # (Sarathi-style: the long prompt enters in page-sized pieces
    # interleaved with everyone else's decode). Chunking bounds the
    # pause any single admission can inject, so the SHORT requests'
    # TTFT tail (p95) is the number that moves. The smoke run asserts
    # the chunk path actually engaged and that both runs are
    # greedy token-identical; the p95 reduction itself is only
    # meaningful on-chip (CPU smoke timing is scheduler noise).
    wl4 = np.random.default_rng(4)
    if smoke:
        cp_slots, n_short = 2, 6
        cp_long, cp_short, cp_new = 61, 6, 8
    else:
        cp_slots, n_short = num_slots, 3 * batch
        cp_long, cp_short, cp_new = 512, 24, 32
    cp_reqs = [Request(prompt=wl4.integers(0, cfg.vocab_size, cp_long
                                           ).astype(np.int32),
                       max_new_tokens=cp_new)]
    cp_reqs += [Request(prompt=wl4.integers(0, cfg.vocab_size, cp_short
                                            ).astype(np.int32),
                        max_new_tokens=cp_new) for _ in range(n_short)]

    def ttft_ab(chunk):
        eng = PagedDecodeEngine(
            model, v, num_slots=cp_slots, page_size=page_size,
            prefill_chunk=page_size if chunk else None)
        eng.run(cp_reqs)                                 # compile + warm
        ab = ServingFrontend(eng)
        hs = [ab.submit(r, request_id=j)
              for j, r in enumerate(cp_reqs)]           # all arrive at t0
        ab.drain()
        return [np.asarray(h.result()) for h in hs], ab.stats()

    mono_outs, mono_stats = ttft_ab(chunk=False)
    ck_outs, ck_stats = ttft_ab(chunk=True)
    if smoke:
        for i, (a, b) in enumerate(zip(mono_outs, ck_outs)):
            if not np.array_equal(a, b):
                raise SystemExit(
                    f"chunked prefill diverged from monolithic admission "
                    f"on request {i}: {a[:8]}... vs {b[:8]}...")
        if ck_stats["chunked_prefills"] < 1:
            raise SystemExit(
                "chunked prefill never engaged: the long prompt should "
                "have been admitted through the chunk path")
        if ck_stats["prefill_chunks"] <= ck_stats["chunked_prefills"]:
            raise SystemExit(
                f"chunked prefill degenerate: {ck_stats['prefill_chunks']} "
                f"chunks for {ck_stats['chunked_prefills']} chunked "
                f"admissions — the long prompt should span many chunks")
    cp_rec = {
        "metric": "gpt2_frontend_chunked_ttft_ms_p95",
        "value": round(ck_stats["ttft_ms_p95"], 3),
        "unit": "ms",
        "vs_baseline": 0.0,  # no reference analog (apex ships no inference)
        "requests": len(cp_reqs), "num_slots": cp_slots,
        "page_size": page_size, "prefill_chunk": page_size,
        "long_prompt": cp_long, "short_prompt": cp_short,
        "gpt2_frontend_chunked_ttft_ms_p50": round(
            ck_stats["ttft_ms_p50"], 3),
        "gpt2_frontend_chunked_ttft_ms_p95": round(
            ck_stats["ttft_ms_p95"], 3),
        "gpt2_frontend_monolithic_ttft_ms_p50": round(
            mono_stats["ttft_ms_p50"], 3),
        "gpt2_frontend_monolithic_ttft_ms_p95": round(
            mono_stats["ttft_ms_p95"], 3),
        "ttft_p95_reduction": round(
            1.0 - ck_stats["ttft_ms_p95"]
            / max(mono_stats["ttft_ms_p95"], 1e-9), 3),
        "chunked_prefills": ck_stats["chunked_prefills"],
        "prefill_chunks": ck_stats["prefill_chunks"],
        "device": dev.device_kind, "platform": dev.platform,
    }
    print(json.dumps(cp_rec), flush=True)

    # --- metrics snapshot artifact (docs/observability.md) ------------------
    # run_tpu_round.sh sets APEX_TPU_METRICS_OUT so every round banks the
    # full instrument registry (serving histograms + pool gauges) next to
    # the bench JSON — the postmortem counterpart of the headline numbers
    out_path = os.environ.get("APEX_TPU_METRICS_OUT")
    if out_path:
        from apex_tpu.obs import export
        export.write_snapshot(out_path, extra={"source": "tpu_decode_bench"})
        print(f"[metrics] snapshot written to {out_path}", flush=True)


if __name__ == "__main__":
    main()
