"""GPT-MoE training with expert parallelism over the ``data`` axis.

Beyond the reference: apex has no mixture-of-experts. This example trains
the GPT decoder with every second MLP routed across ``num_experts``
experts (`apex_tpu.transformer.moe.MoEMLP`), sharded expert-parallel over
the data-parallel ranks — token dispatch rides a tiled ``all_to_all``
(ICI on hardware), and each rank stores only ``num_experts/ep`` expert
FFNs. The full expert stack lives host-side as one param tree; each rank
dynamic-slices its shard inside ``shard_map`` (the slice transpose
scatters grads back, and ``pmean`` over ``data`` is the exact combine —
see the gradient note in examples/long_context/train_ring_attention.py).

Run:  python examples/moe/train_moe_ep.py
(CPU-mesh friendly: forces an 8-virtual-device CPU backend when no
multi-device platform is present.)
"""

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import slice_expert_shards


def make_step_fn(model, mesh, e_local):
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)), out_specs=(P(), P()),
        check_vma=False)
    def step(full_params, ii, ll):
        def f(p):
            local = slice_expert_shards(p, e_local)
            return gpt_loss(model, {"params": local}, ii, ll)

        loss, grads = jax.value_and_grad(f)(full_params)
        # exact combine for every leaf class (expert shards, router,
        # dense): mean over the data/EP axis — see module docstring
        return (lax.pmean(loss, DATA_AXIS), lax.pmean(grads, DATA_AXIS))

    return jax.jit(step)


def run_training(steps: int = 8, num_experts: int = 8, verbose=print):
    mesh = parallel_state.initialize_model_parallel(1, 1)
    dp = int(mesh.shape[DATA_AXIS])  # EP world == the data axis
    assert num_experts % dp == 0, (num_experts, dp)

    cfg = gpt_tiny_config(
        num_experts=num_experts, moe_layer_freq=2, moe_k=2,
        moe_capacity_factor=float(num_experts) / 2 + 1.0,  # dropless
        expert_parallel=True)
    model = GPTModel(cfg)
    e_local = num_experts // dp

    rng = np.random.default_rng(0)
    batch, seq = 2 * dp, 32
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    opt = FusedAdam(params, lr=3e-3, weight_decay=0.0)

    step_fn = make_step_fn(model, mesh, e_local)
    losses = []
    for step in range(steps):
        loss, grads = step_fn(params, ids, labels)
        params = opt.step(grads)
        losses.append(float(loss))
        verbose(f"step {step}: loss {losses[-1]:.4f}  "
                f"({num_experts} experts over ep={dp}, "
                f"{e_local}/rank, all_to_all dispatch)")
    return losses


if __name__ == "__main__":
    import os

    if os.environ.get("APEX_TPU_EXAMPLE_REAL") != "1":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    ls = run_training()
    assert ls[-1] < ls[0], ls
    print(f"MoE expert-parallel training converges: {ls[0]:.3f} -> {ls[-1]:.3f}")
