"""DCGAN with amp: two models + two optimizers + two losses under one
``amp.initialize`` (the multi-loss pattern).

Reference: examples/dcgan/main_amp.py — generator/discriminator each with
its own optimizer and ``amp.scale_loss(..., loss_id=...)``; the point of the
example is the per-loss scaler bookkeeping (apex/amp/handle.py multi-loss
support). Synthetic data; tiny MLP G/D keep it runnable anywhere — the amp
plumbing, not the model, is the exercised surface.

Run:  python examples/dcgan/main_amp.py --steps 20
"""

from __future__ import annotations

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.optimizers import FusedAdam


class Generator(nn.Module):
    latent: int = 32
    out_dim: int = 64

    @nn.compact
    def __call__(self, z):
        dt = resolve_compute_dtype(z.dtype)
        z = z.astype(dt)
        h = nn.relu(nn.Dense(128, dtype=dt)(z))
        return jnp.tanh(nn.Dense(self.out_dim, dtype=dt)(h))


class Discriminator(nn.Module):
    @nn.compact
    def __call__(self, x):
        dt = resolve_compute_dtype(x.dtype)
        x = x.astype(dt)
        h = nn.leaky_relu(nn.Dense(128, dtype=dt)(x), 0.2)
        return nn.Dense(1, dtype=dt)(h)[..., 0].astype(jnp.float32)


def bce_with_logits(logits, target):
    return jnp.mean(jnp.maximum(logits, 0) - logits * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def run_training(*, steps: int = 20, batch: int = 32, latent: int = 32,
                 opt_level: str = "O1", half_dtype=jnp.bfloat16,
                 seed: int = 0, verbose=print):
    rng = np.random.default_rng(seed)
    g_model, d_model = Generator(latent=latent), Discriminator()

    z0 = jnp.asarray(rng.standard_normal((batch, latent)), jnp.float32)
    x0 = jnp.asarray(rng.standard_normal((batch, 64)), jnp.float32)
    g_params = g_model.init(jax.random.PRNGKey(seed), z0)["params"]
    d_params = d_model.init(jax.random.PRNGKey(seed + 1), x0)["params"]

    g_opt = FusedAdam(g_params, lr=2e-4, betas=(0.5, 0.999))
    d_opt = FusedAdam(d_params, lr=2e-4, betas=(0.5, 0.999))
    # THE pattern: one initialize, N models, N optimizers, N losses
    (g_params, d_params), (g_opt, d_opt) = amp.initialize(
        [g_params, d_params], [g_opt, d_opt], opt_level=opt_level,
        half_dtype=half_dtype, num_losses=2)

    def d_loss_fn(dp, gp, z, real):
        fake = g_model.apply({"params": gp}, z)
        lr_ = bce_with_logits(d_model.apply({"params": dp}, real), 1.0)
        lf = bce_with_logits(
            d_model.apply({"params": dp}, jax.lax.stop_gradient(fake)), 0.0)
        loss = lr_ + lf
        with amp.scale_loss(loss, d_opt, loss_id=1) as scaled:
            return scaled

    def g_loss_fn(gp, dp, z):
        fake = g_model.apply({"params": gp}, z)
        loss = bce_with_logits(d_model.apply({"params": dp}, fake), 1.0)
        with amp.scale_loss(loss, g_opt, loss_id=0) as scaled:
            return scaled

    d_step = jax.jit(jax.value_and_grad(d_loss_fn))
    g_step = jax.jit(jax.value_and_grad(g_loss_fn))

    d_losses, g_losses = [], []
    for step in range(steps):
        z = jnp.asarray(rng.standard_normal((batch, latent)), jnp.float32)
        real = jnp.asarray(
            np.tanh(rng.standard_normal((batch, 64)) * 0.5), jnp.float32)
        dl, d_grads = d_step(d_params, g_params, z, real)
        d_params = d_opt.step(d_grads)
        gl, g_grads = g_step(g_params, d_params, z)
        g_params = g_opt.step(g_grads)
        d_losses.append(float(dl))
        g_losses.append(float(gl))
        if step % 10 == 0:
            verbose(f"step {step:4d}  D {dl:.4f}  G {gl:.4f}")
    return d_losses, g_losses


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--opt-level", default="O1")
    args = p.parse_args()
    d, g = run_training(steps=args.steps, opt_level=args.opt_level)
    print(f"final D {d[-1]:.4f}  G {g[-1]:.4f}")


if __name__ == "__main__":
    main()
