"""ImageNet-style ResNet-50 training with amp + SyncBatchNorm + DDP
(BASELINE.md config #1).

Reference: examples/imagenet/main_amp.py (~550 LoC) — ResNet-50 through
``amp.initialize(opt_level=O0..O3)``, apex DDP, ``convert_syncbn_model``,
a data prefetcher with loss-scale-aware stream sync, and AverageMeter
logging. TPU restatement: the prefetcher's stream plumbing disappears
(device transfers are async under jit by default); DP comes from sharding
the batch over the ``data`` mesh axis; SyncBatchNorm psums stats over the
same axis. Synthetic data by default (the reference's tests/L1 mode).

Run:  python examples/imagenet/main_amp.py --steps 20 --opt-level O1
"""

from __future__ import annotations

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import argparse
import time
from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import DATA_AXIS
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel, SyncBatchNorm


class Bottleneck(nn.Module):
    """ResNet bottleneck (1x1 -> 3x3 -> 1x1 + residual), NHWC.

    Reference model: torchvision resnet50 as driven by
    examples/imagenet/main_amp.py; BNs are SyncBatchNorm when --sync_bn
    (the config BASELINE names).
    """

    features: int
    stride: int = 1
    sync_axis: Any = DATA_AXIS

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = resolve_compute_dtype(x.dtype)
        bn = partial(SyncBatchNorm, axis_name=self.sync_axis, dtype=dt)
        conv = partial(nn.Conv, use_bias=False, param_dtype=jnp.float32,
                       dtype=dt)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(bn(name="bn1")(y, use_running_average=not train))
        y = conv(self.features, (3, 3), strides=(self.stride, self.stride),
                 padding=((1, 1), (1, 1)))(y)
        y = nn.relu(bn(name="bn2")(y, use_running_average=not train))
        y = conv(self.features * 4, (1, 1))(y)
        y = bn(name="bn3")(y, use_running_average=not train)
        if residual.shape[-1] != self.features * 4 or self.stride != 1:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.stride, self.stride),
                            name="downsample_conv")(x)
            residual = bn(name="downsample_bn")(
                residual, use_running_average=not train)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    """ResNet-v1 with bottleneck blocks (50 = [3,4,6,3])."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    sync_axis: Any = DATA_AXIS

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = resolve_compute_dtype(x.dtype)
        x = x.astype(dt)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                    padding=((3, 3), (3, 3)), use_bias=False,
                    param_dtype=jnp.float32, dtype=dt, name="conv1")(x)
        x = nn.relu(SyncBatchNorm(axis_name=self.sync_axis, dtype=dt,
                                  name="bn1")(x, use_running_average=not train))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for b in range(n_blocks):
                stride = 2 if (i > 0 and b == 0) else 1
                x = Bottleneck(self.width * 2 ** i, stride=stride,
                               sync_axis=self.sync_axis,
                               name=f"stage{i}_block{b}")(x, train=train)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(self.num_classes, param_dtype=jnp.float32,
                     dtype=dt, name="fc")(x)
        return x.astype(jnp.float32)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)


def resnet_tiny(num_classes: int = 10, **kw) -> ResNet:
    """Small variant for CPU-mesh example tests."""
    return ResNet(stage_sizes=(1, 1), num_classes=num_classes, width=16, **kw)


class AverageMeter:
    """Reference: examples/imagenet/main_amp.py AverageMeter."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = self.sum = self.count = 0.0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n

    @property
    def avg(self):
        return self.sum / max(self.count, 1)


def synthetic_batch(rng, batch_size: int, image_size: int, num_classes: int):
    return (jnp.asarray(rng.standard_normal(
        (batch_size, image_size, image_size, 3)), jnp.float32),
        jnp.asarray(rng.integers(0, num_classes, (batch_size,)), jnp.int32))


def run_training(model: ResNet, *, steps: int = 10, batch_size: int = 8,
                 image_size: int = 32, opt_level: str = "O1",
                 lr: float = 0.1, seed: int = 0, mesh=None, verbose=print):
    """The example's train loop, importable for tests. Returns losses."""
    rng = np.random.default_rng(seed)
    images, labels = synthetic_batch(rng, batch_size, image_size,
                                     model.num_classes)
    variables = model.init(jax.random.PRNGKey(seed), images, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt = FusedSGD(params, lr=lr, momentum=0.9, weight_decay=1e-4)
    # amp: O1 flips module compute dtypes; O2/O3 also cast params
    params, opt = amp.initialize(params, opt, opt_level=opt_level)
    # DDP facade: XLA owns bucketing/overlap; kept for reference API parity
    ddp = DistributedDataParallel(model)

    def loss_fn(p, bs, x, y):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return nll, updates["batch_stats"]

    grad_step = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

    losses, meter, t0 = [], AverageMeter(), time.perf_counter()
    for step in range(steps):
        # synthetic mode reuses one batch (the reference's --prof/synthetic
        # path does the same): random labels on fresh data have no signal
        x, y = images, labels
        (loss, batch_stats), grads = grad_step(params, batch_stats, x, y)
        params = opt.step(grads)
        losses.append(float(loss))
        meter.update(float(loss))
        if step % 5 == 0:
            verbose(f"step {step:4d}  loss {meter.val:.4f} "
                    f"(avg {meter.avg:.4f})  "
                    f"{(time.perf_counter()-t0):.1f}s")
    return losses


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet50", "resnet_tiny"])
    args = p.parse_args()
    model = (resnet50() if args.arch == "resnet50"
             else resnet_tiny())
    losses = run_training(model, steps=args.steps,
                          batch_size=args.batch_size,
                          image_size=args.image_size,
                          opt_level=args.opt_level, lr=args.lr)
    print(f"final loss {losses[-1]:.4f} (start {losses[0]:.4f})")


if __name__ == "__main__":
    main()
