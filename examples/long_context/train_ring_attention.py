"""Long-context GPT training with ring-attention context parallelism.

Beyond the reference: apex's longest-sequence story is Megatron sequence
parallelism with an fmha kernel capped at seqlen 512 (SURVEY.md §5
long-context row). Here the sequence is sharded over the ``context`` mesh
axis and K/V chunks rotate around the ring (`apex_tpu.ops.ring_attention`),
so the per-device activation AND attention memory scale with S/cp — the
context length a pod can train on grows linearly with the ring size.

Run:  python examples/long_context/train_ring_attention.py
(CPU-mesh friendly: forces an 8-virtual-device CPU backend when no
multi-device platform is present.)
"""

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS, DATA_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state


def make_loss_and_grad_fn(model, mesh):
    """(params, ids, labels) -> (loss, grads) with the sequence sharded
    over ``context`` and the batch over ``data``."""
    seq_sh = P(DATA_AXIS, CONTEXT_AXIS)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), seq_sh, seq_sh), out_specs=P(), check_vma=False)
    def loss_and_grad_fn(p, ii, ll):
        def f(p):
            return gpt_loss(model, {"params": p}, ii, ll)

        loss, grads = jax.value_and_grad(f)(p)
        # grads taken INSIDE shard_map on replicated params are per-device
        # contributions whose cotangent carries the full (not 1/N) loss
        # weight — the in-shard pmean's transpose replicates the cotangent
        # instead of splitting it — so the exact combine is the MEAN over
        # every participating axis (verified against the unsharded
        # jax.value_and_grad in tests/test_examples.py)
        grads = jax.lax.pmean(grads, CONTEXT_AXIS)
        grads = jax.lax.pmean(grads, DATA_AXIS)
        loss = jax.lax.pmean(loss, DATA_AXIS)
        return loss, grads

    return loss_and_grad_fn


def run_training(steps: int = 8, seq_len: int = 128, cp: int = 4,
                 layout: str = "ring", verbose=print):
    """``layout='zigzag'`` uses the causal load-balanced layout: the data
    pipeline permutes the sequence with ``to_zigzag`` (each device gets one
    early + one late half-chunk) and the model's position embeddings follow
    automatically (``context_parallel_zigzag``)."""
    if layout not in ("ring", "zigzag"):
        raise ValueError(f"layout must be 'ring' or 'zigzag', got {layout!r}")
    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=cp)
    dp = int(mesh.shape[DATA_AXIS])

    cfg = gpt_tiny_config(context_parallel=True,
                          context_parallel_zigzag=layout == "zigzag",
                          max_position_embeddings=seq_len)
    model = GPTModel(cfg)
    rng = np.random.default_rng(0)
    batch = 2 * dp
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq_len)),
                      jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    if layout == "zigzag":
        from apex_tpu.ops import to_zigzag

        ids = to_zigzag(ids, cp, axis=1)
        labels = to_zigzag(labels, cp, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids[:, : seq_len // cp])[
        "params"]
    opt = FusedAdam(params, lr=3e-3, weight_decay=0.0)
    loss_and_grad_fn = make_loss_and_grad_fn(model, mesh)

    step_fn = jax.jit(loss_and_grad_fn)
    losses = []
    for step in range(steps):
        loss, grads = step_fn(params, ids, labels)
        params = opt.step(grads)
        losses.append(float(loss))
        verbose(f"step {step}: loss {losses[-1]:.4f}  "
                f"(seq {seq_len} over cp={cp} {layout})")
    return losses


if __name__ == "__main__":
    import os

    # decide the platform BEFORE any jax.devices() call initializes the
    # backends (jax_num_cpu_devices cannot be changed afterwards); probing
    # device count via env avoids that init
    if os.environ.get("APEX_TPU_EXAMPLE_REAL") != "1":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    ls = run_training()
    assert ls[-1] < ls[0], ls
    print(f"ring-attention CP training converges: {ls[0]:.3f} -> {ls[-1]:.3f}")
