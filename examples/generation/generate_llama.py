"""Autoregressive generation with the static KV cache — greedy, sampled,
and tensor-parallel decode on one model.

Beyond the reference: apex ships no inference path (it is a training
library); `apex_tpu.models.generation` is the TPU-first decode design —
flash-kernel prefill, `lax.scan` decode over a static
`(b, kv_local, max_len, d)` cache, vocab-gathered sampling under TP
(docs/generation.md).

Run:  python examples/generation/generate_llama.py
(CPU-mesh friendly: forces an 8-virtual-device CPU backend when no
multi-device platform is present.)
"""

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import generate
from apex_tpu.models.llama import LlamaModel, llama_tiny_config


def run_generation(*, prompt_len=6, new_tokens=12, tp=1, temperature=0.0,
                   top_k=None, seed=0, verbose=print):
    """Greedy or sampled decode on a tiny Llama (GQA + SwiGLU); with tp>1,
    head-/vocab-sharded decode inside shard_map on the ``model`` axis.
    Returns the generated (batch, prompt+new) token array."""
    rng = np.random.default_rng(seed)
    cfg = llama_tiny_config(tensor_parallel_size=tp)
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, prompt_len)),
                         jnp.int32)
    sample_kw = dict(temperature=temperature, top_k=top_k,
                     rng=jax.random.PRNGKey(seed)) if temperature else {}

    if tp == 1:
        v = model.init(jax.random.PRNGKey(0), prompt)
        out = generate(model, v, prompt, new_tokens, axis_name="unbound",
                       **sample_kw)
    else:
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel(tp)

        @functools.partial(jax.shard_map, mesh=mesh,
                           in_specs=P(), out_specs=P(), check_vma=False)
        def sharded_generate(ii):
            # each rank initializes its OWN param shard (same seed ->
            # consistent sharded init via the TP layers' rank folding)
            v = model.init(jax.random.PRNGKey(0), ii)
            return generate(model, v, ii, new_tokens, **sample_kw)

        with mesh:
            out = jax.jit(sharded_generate)(prompt)

    out = np.asarray(out)
    mode = f"sampled(T={temperature}, top_k={top_k})" if temperature \
        else "greedy"
    verbose(f"[generation] tp={tp} {mode}: prompt {prompt_len} tokens -> "
            f"{out.shape[1]} tokens")
    for row in out:
        verbose(f"  {row.tolist()}")
    return out


def run_speculative(*, prompt_len=6, new_tokens=10, k=4, seed=0,
                    verbose=print):
    """Greedy speculative decoding: a differently-seeded tiny draft
    proposes k-1 tokens/round; output must equal plain greedy."""
    from apex_tpu.models.generation import speculative_generate

    rng = np.random.default_rng(seed)
    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, prompt_len)),
                         jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    draft = LlamaModel(cfg)
    dv = draft.init(jax.random.PRNGKey(99), prompt)

    ref = np.asarray(generate(model, v, prompt, new_tokens,
                              axis_name="unbound"))
    out = np.asarray(speculative_generate(model, v, draft, dv, prompt,
                                          new_tokens, k=k,
                                          axis_name="unbound"))
    assert (out == ref).all(), "speculative must equal greedy"
    verbose(f"[speculative] k={k}: exact greedy parity over "
            f"{new_tokens} tokens")
    return out


def run_beam(*, prompt_len=6, new_tokens=8, beams=4, seed=0, verbose=print):
    from apex_tpu.models.generation import generate_beam

    rng = np.random.default_rng(seed)
    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, prompt_len)),
                         jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    seqs, scores = generate_beam(model, v, prompt, new_tokens,
                                 num_beams=beams, length_penalty=0.0,
                                 axis_name="unbound")
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    verbose(f"[beam] {beams} beams, best scores: "
            f"{np.round(scores[:, 0], 2).tolist()}")
    return seqs, scores


if __name__ == "__main__":
    import os

    # decide the platform BEFORE any jax.devices() call initializes the
    # backends (examples contract: CPU mesh unless opted onto real TPU)
    if os.environ.get("APEX_TPU_EXAMPLE_REAL") != "1":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    run_generation()                                   # greedy single-device
    run_generation(temperature=0.9, top_k=8, seed=3)   # sampled
    run_generation(tp=2)                               # tensor-parallel decode
    run_speculative()                                  # draft-accelerated
    run_beam()                                         # beam search
