"""Minimal data-parallel training loop.

Reference: examples/simple/distributed/distributed_data_parallel.py (~60
LoC): DDP wrapper + allreduce'd grads on a toy linear model. TPU
restatement: the batch is sharded over the ``data`` mesh axis under jit and
XLA inserts (and overlaps) the grad all-reduce; the DDP facade records the
reference knobs.

Run:  python examples/simple/distributed/distributed_data_parallel.py
"""

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.transformer import parallel_state


def run_training(steps: int = 10, verbose=print):
    mesh = parallel_state.initialize_model_parallel(1, 1)
    rng = np.random.default_rng(0)
    w_true = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
    params = {"w": jnp.zeros((16, 4), jnp.float32)}
    opt = FusedSGD(params, lr=0.2)
    ddp = DistributedDataParallel(None)  # facade: records reference knobs

    def loss_fn(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    x_sh = NamedSharding(mesh, P(DATA_AXIS, None))
    grad_step = jax.jit(jax.value_and_grad(loss_fn),
                        in_shardings=(None, x_sh, x_sh))

    losses = []
    with mesh:
        for step in range(steps):
            x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
            y = x @ w_true
            loss, grads = grad_step(params, x, y)
            params = opt.step(grads)
            losses.append(float(loss))
            verbose(f"step {step} loss {loss:.5f}")
    return losses


if __name__ == "__main__":
    run_training()
