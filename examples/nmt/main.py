"""Transformer NMT exercising contrib.multihead_attn + softmax-xentropy
(BASELINE.md config #3).

Reference: the apex components come from MLPerf/fairseq-style NMT training —
``SelfMultiheadAttn``/``EncdecMultiheadAttn`` (apex/contrib/multihead_attn/)
inside a pre-LN encoder-decoder, with the memory-saving label-smoothed
``SoftmaxCrossEntropyLoss`` (apex/contrib/xentropy/). Apex itself ships no
NMT example; this fills BASELINE config #3 with a runnable synthetic-copy
task (the loss must fall toward copying the source).

Run:  python examples/nmt/main.py --steps 30
"""

from __future__ import annotations

import os as _os
import sys as _sys

# runnable without installation: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(_os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


import argparse
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.multihead_attn import (EncdecMultiheadAttn,
                                             SelfMultiheadAttn)
from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.optimizers import FusedAdam


class EncoderLayer(nn.Module):
    embed_dim: int
    num_heads: int
    ffn_dim: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool):
        # pre-LN + residual fused into the attention module (norm_add —
        # the reference's self_multihead_attn_norm_add variant)
        attn, _ = SelfMultiheadAttn(
            self.embed_dim, self.num_heads, dropout=self.dropout,
            include_norm_add=True, impl="fast", name="self_attn")(
                x, is_training=train)
        x = attn  # norm_add returns out + residual
        h = FusedLayerNorm(self.embed_dim, name="ffn_norm")(x)
        h = nn.Dense(self.ffn_dim, name="fc1")(h)
        h = nn.relu(h)
        h = nn.Dense(self.embed_dim, name="fc2")(h)
        return x + h


class DecoderLayer(nn.Module):
    embed_dim: int
    num_heads: int
    ffn_dim: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, y, memory, *, train: bool):
        sq = y.shape[0]
        causal = jnp.where(
            jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :], 0.0, -1e9
        ).astype(jnp.float32)
        attn, _ = SelfMultiheadAttn(
            self.embed_dim, self.num_heads, dropout=self.dropout,
            include_norm_add=True, mask_additive=True, impl="fast",
            name="self_attn")(y, attn_mask=causal, is_training=train)
        y = attn
        cross, _ = EncdecMultiheadAttn(
            self.embed_dim, self.num_heads, dropout=self.dropout,
            include_norm_add=True, impl="fast", name="cross_attn")(
                y, memory, memory, is_training=train)
        y = cross
        h = FusedLayerNorm(self.embed_dim, name="ffn_norm")(y)
        h = nn.Dense(self.ffn_dim, name="fc1")(h)
        h = nn.relu(h)
        h = nn.Dense(self.embed_dim, name="fc2")(h)
        return y + h


class NMTTransformer(nn.Module):
    """Tiny pre-LN encoder-decoder over [seq, batch, embed] activations
    (the reference modules' native layout)."""

    vocab_size: int = 1024
    embed_dim: int = 128
    num_heads: int = 4
    ffn_dim: int = 256
    num_layers: int = 2
    dropout: float = 0.0

    @nn.compact
    def __call__(self, src_ids, tgt_ids, *, train: bool = True):
        emb = self.param("embed", nn.initializers.normal(0.02),
                         (self.vocab_size, self.embed_dim), jnp.float32)
        pos = self.param("pos", nn.initializers.normal(0.02),
                         (512, self.embed_dim), jnp.float32)

        def embed(ids):  # [B, S] -> [S, B, E]
            x = jnp.take(emb, ids, axis=0) + pos[None, :ids.shape[1], :]
            return x.transpose(1, 0, 2)

        x = embed(src_ids)
        for i in range(self.num_layers):
            x = EncoderLayer(self.embed_dim, self.num_heads, self.ffn_dim,
                             self.dropout, name=f"enc_{i}")(x, train=train)
        x = FusedLayerNorm(self.embed_dim, name="enc_norm")(x)

        y = embed(tgt_ids)
        for i in range(self.num_layers):
            y = DecoderLayer(self.embed_dim, self.num_heads, self.ffn_dim,
                             self.dropout, name=f"dec_{i}")(y, x, train=train)
        y = FusedLayerNorm(self.embed_dim, name="dec_norm")(y)
        # tied output projection -> [B, S, V]
        return (y @ emb.T).transpose(1, 0, 2)


def synthetic_copy_batch(rng, batch, seq, vocab):
    """Copy task: target = source shifted (teacher forcing)."""
    src = rng.integers(2, vocab, (batch, seq))
    tgt_in = np.concatenate([np.ones((batch, 1), np.int64), src[:, :-1]], 1)
    return (jnp.asarray(src, jnp.int32), jnp.asarray(tgt_in, jnp.int32),
            jnp.asarray(src, jnp.int32))


def run_training(*, steps: int = 30, batch: int = 8, seq: int = 16,
                 vocab: int = 256, label_smoothing: float = 0.1,
                 lr: float = 3e-4, seed: int = 0, verbose=print):
    model = NMTTransformer(vocab_size=vocab)
    rng = np.random.default_rng(seed)
    src, tgt_in, tgt_out = synthetic_copy_batch(rng, batch, seq, vocab)
    params = model.init(jax.random.PRNGKey(seed), src, tgt_in)["params"]
    opt = FusedAdam(params, lr=lr)
    criterion = SoftmaxCrossEntropyLoss()

    def loss_fn(p, src, tgt_in, tgt_out):
        logits = model.apply({"params": p}, src, tgt_in, train=True)
        per_tok = criterion(logits.reshape(-1, vocab).astype(jnp.float32),
                            tgt_out.reshape(-1), smoothing=label_smoothing)
        return per_tok.mean()

    grad_step = jax.jit(jax.value_and_grad(loss_fn))

    losses = []
    for step in range(steps):
        src, tgt_in, tgt_out = synthetic_copy_batch(rng, batch, seq, vocab)
        loss, grads = grad_step(params, src, tgt_in, tgt_out)
        params = opt.step(grads)
        losses.append(float(loss))
        if step % 10 == 0:
            verbose(f"step {step:4d}  loss {losses[-1]:.4f}")
    return losses


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=32)
    args = p.parse_args()
    losses = run_training(steps=args.steps, batch=args.batch, seq=args.seq)
    print(f"final loss {losses[-1]:.4f} (start {losses[0]:.4f})")


if __name__ == "__main__":
    main()
