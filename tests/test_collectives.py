"""Collectives wrappers vs numpy ground truth (the NCCL-equivalent layer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import collectives as coll
from apex_tpu.transformer import parallel_state


def _smap(fn, mesh, in_spec, out_spec):
    # check_vma=False: JAX's varying-manual-axes inference is conservative
    # about all_gather/ppermute replication; numerics are asserted instead.
    return jax.shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec, check_vma=False)


def test_all_reduce_sum(mesh8):
    x = jnp.arange(8.0)
    out = _smap(lambda v: coll.all_reduce(v, "data"), mesh8, P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_all_reduce_max(mesh8):
    x = jnp.arange(8.0)
    out = _smap(lambda v: coll.all_reduce(v, "data", op="max"), mesh8, P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))


def test_all_gather(mesh8):
    x = jnp.arange(8.0)
    out = _smap(
        lambda v: coll.all_gather(v, "data", axis=0), mesh8, P("data"), P(None)
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_reduce_scatter(mesh8):
    # each rank holds a replicated (8, 4) of ones; reduce-scatter over the
    # 8 ranks leaves each rank a (1, 4) slice summed across ranks.
    x = jnp.ones((8, 4))
    out = _smap(
        lambda v: coll.reduce_scatter(v, "data", axis=0), mesh8, P(None), P("data")
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))


def test_broadcast(mesh8):
    x = jnp.arange(8.0)
    out = _smap(lambda v: coll.broadcast(v, "data", 3), mesh8, P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_shift_right_no_wrap(mesh8):
    x = jnp.arange(1.0, 9.0)
    out = _smap(lambda v: coll.shift_right(v, "data"), mesh8, P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), [0, 1, 2, 3, 4, 5, 6, 7])


def test_shift_left_wrap(mesh8):
    x = jnp.arange(8.0)
    out = _smap(
        lambda v: coll.shift_left(v, "data", wrap=True), mesh8, P("data"), P("data")
    )(x)
    np.testing.assert_allclose(np.asarray(out), [1, 2, 3, 4, 5, 6, 7, 0])


def test_all_to_all(mesh8):
    # 8 devices, each with a row of 8 values; all_to_all transposes blocks.
    x = jnp.arange(64.0).reshape(8, 8)
    out = _smap(
        lambda v: coll.all_to_all(v, "data", split_axis=1, concat_axis=0),
        mesh8,
        P("data", None),
        P(None, "data"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(64.0).reshape(8, 8).T.reshape(8, 8).T)


def test_tp_mappings_roundtrip(mesh_tp2_pp2_dp2):
    """Mirrors tests/L0/run_transformer/test_mapping.py: collective region
    fwd numerics — gather(scatter(x)) == x."""
    from apex_tpu.transformer import tensor_parallel as tp

    mesh = mesh_tp2_pp2_dp2
    x = jnp.arange(8.0).reshape(2, 4)

    def roundtrip(v):
        s = tp.scatter_to_tensor_model_parallel_region(v, "model")
        return tp.gather_from_tensor_model_parallel_region(s, "model")

    out = _smap(roundtrip, mesh, P(None, None), P(None, None))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_copy_and_reduce_regions_megatron_linear(mesh_tp2_pp2_dp2):
    """The canonical Megatron TP pattern: copy-in, column/row-split matmuls,
    reduce-out — fwd AND grads must match the single-device ground truth.
    Uses check_vma=True (default) which is what makes grads correct."""
    from apex_tpu.transformer import tensor_parallel as tp

    mesh = mesh_tp2_pp2_dp2
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    w1 = jax.random.normal(jax.random.PRNGKey(1), (16, 32))
    w2 = jax.random.normal(jax.random.PRNGKey(2), (32, 16))

    def tp_forward(a, b, c):
        ai = tp.copy_to_tensor_model_parallel_region(a, "model")
        h = ai @ b  # b column-sharded → local (16, 16)
        y = h @ c  # c row-sharded → local (16, 16)
        return tp.reduce_from_tensor_model_parallel_region(y, "model")

    f = jax.shard_map(
        lambda a, b, c: tp_forward(a, b, c).sum(),
        mesh=mesh,
        in_specs=(P(), P(None, "model"), P("model", None)),
        out_specs=P(),
    )
    expected = ((x @ w1) @ w2).sum()
    np.testing.assert_allclose(float(f(x, w1, w2)), float(expected), rtol=1e-5)

    g = jax.grad(lambda w: f(x, w, w2))(w1)
    g_ref = jax.grad(lambda w: ((x @ w) @ w2).sum())(w1)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-5)


def test_sequence_parallel_regions(mesh_tp2_pp2_dp2):
    from apex_tpu.transformer import tensor_parallel as tp

    mesh = mesh_tp2_pp2_dp2
    x = jnp.arange(16.0).reshape(8, 2)

    def f(v):
        shard = tp.scatter_to_sequence_parallel_region(v, "model")  # (4, 2)
        full = tp.gather_from_sequence_parallel_region(shard, "model")  # (8, 2)
        return tp.reduce_scatter_to_sequence_parallel_region(full, "model")  # (4,2)*2

    out = _smap(
        lambda v: tp.gather_from_sequence_parallel_region(f(v), "model"),
        mesh,
        P(None, None),
        P(None, None),
    )(x)
    np.testing.assert_allclose(np.asarray(out), 2 * np.asarray(x))
