"""Paged-attention decode kernel (ops/paged_attention.py).

Parity contract: the Pallas kernel (run through the interpreter on CPU —
the same code Mosaic compiles on chip) must match (a) the pure-jnp
gather-based reference and (b) the dense ``cached_attention`` decode path
it replaces, across MHA/GQA, page-boundary lengths, and scattered
(non-contiguous, permuted) page assignments."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import cached_attention
from apex_tpu.ops.paged_attention import (paged_attention,
                                          paged_attention_reference)

TOL = dict(rtol=2e-5, atol=2e-5)


def _pool(rng, num_pages, kv, ps, d, dtype=jnp.float32):
    k = jnp.asarray(rng.standard_normal((num_pages, kv, ps, d)), dtype)
    v = jnp.asarray(rng.standard_normal((num_pages, kv, ps, d)), dtype)
    return k, v


def _tables(rng, b, max_pages, num_pages):
    """Disjoint, scrambled page assignments (pages 1..num_pages-1)."""
    perm = rng.permutation(np.arange(1, num_pages))[:b * max_pages]
    return jnp.asarray(perm.reshape(b, max_pages), jnp.int32)


def test_matches_reference_mha(rng):
    P, kv, ps, d, b, mp = 24, 4, 8, 16, 3, 4
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    q = jnp.asarray(rng.standard_normal((b, kv, 1, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    lens = jnp.asarray([5, 17, 32], jnp.int32)
    out = paged_attention(q, k_pages, v_pages, bt, lens)
    ref = paged_attention_reference(q, k_pages, v_pages, bt, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)


def test_matches_reference_gqa(rng):
    """kv=2 < h=6 (rep=3): grouped queries contract against the
    unexpanded kv-head pages."""
    P, kv, h, ps, d, b, mp = 20, 2, 6, 8, 32, 2, 3
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    lens = jnp.asarray([9, 24], jnp.int32)
    out = paged_attention(q, k_pages, v_pages, bt, lens)
    ref = paged_attention_reference(q, k_pages, v_pages, bt, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)


def test_page_boundary_lengths(rng):
    """Exact-multiple, one-past, one-short, single-token, and zero
    lengths: the per-position mask and the dead-page skip must agree at
    every boundary."""
    P, kv, ps, d, mp = 40, 2, 8, 16, 4
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    lens = jnp.asarray([ps, ps + 1, ps - 1, 1, 0, mp * ps], jnp.int32)
    b = lens.shape[0]
    q = jnp.asarray(rng.standard_normal((b, 4, 1, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    out = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens))
    ref = np.asarray(paged_attention_reference(q, k_pages, v_pages, bt,
                                               lens))
    np.testing.assert_allclose(out, ref, **TOL)
    assert (out[4] == 0).all()          # length 0 -> exactly zero output


def test_matches_dense_cached_attention(rng):
    """Cross-validation against the lock-step decode path: scatter a
    contiguous cache into pages, then the paged kernel at length t+1 must
    equal cached_attention at offset t over the contiguous buffer."""
    b, kv, h, t_max, d, ps = 2, 2, 4, 24, 16, 8
    t = 19                                        # mid-page position
    k = jnp.asarray(rng.standard_normal((b, kv, t_max, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kv, t_max, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.float32)

    dense = cached_attention(q, {"k": k, "v": v, "len": jnp.int32(t)})

    mp = t_max // ps
    P = 1 + b * mp
    bt = jnp.arange(1, P, dtype=jnp.int32).reshape(b, mp)
    # pages[(bt[b, j]), :, o, :] = contiguous[b, :, j*ps + o, :]
    contig = k.transpose(0, 2, 1, 3).reshape(b * mp, ps, kv, d)
    k_pages = jnp.zeros((P, kv, ps, d)).at[bt.reshape(-1)].set(
        contig.transpose(0, 2, 1, 3))
    contig_v = v.transpose(0, 2, 1, 3).reshape(b * mp, ps, kv, d)
    v_pages = jnp.zeros((P, kv, ps, d)).at[bt.reshape(-1)].set(
        contig_v.transpose(0, 2, 1, 3))

    lens = jnp.full((b,), t + 1, jnp.int32)
    paged = paged_attention(q, k_pages, v_pages, bt, lens)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense), **TOL)


def test_kernel_is_jittable(rng):
    P, kv, ps, d, b, mp = 12, 2, 8, 16, 2, 2
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    q = jnp.asarray(rng.standard_normal((b, kv, 1, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    lens = jnp.asarray([3, 12], jnp.int32)
    out = np.asarray(jax.jit(paged_attention)(q, k_pages, v_pages, bt, lens))
    ref = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens))
    np.testing.assert_array_equal(out, ref)


def test_validation_errors(rng):
    P, kv, ps, d = 8, 2, 8, 16
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    bt = jnp.zeros((2, 2), jnp.int32)
    lens = jnp.zeros((2,), jnp.int32)
    good_q = jnp.zeros((2, 2, 1, d))
    with pytest.raises(ValueError):      # query block wider than a page
        paged_attention(jnp.zeros((2, 2, ps + 1, d)), k_pages, v_pages,
                        bt, lens)
    with pytest.raises(ValueError):      # heads not a kv multiple
        paged_attention(jnp.zeros((2, 3, 1, d)), k_pages, v_pages, bt, lens)
    with pytest.raises(ValueError):      # head_dim mismatch
        paged_attention(jnp.zeros((2, 2, 1, d * 2)), k_pages, v_pages, bt,
                        lens)
    with pytest.raises(ValueError):      # lengths shape
        paged_attention(good_q, k_pages, v_pages, bt, jnp.zeros((3,),
                                                               jnp.int32))
    with pytest.raises(ValueError):      # non-sublane page size
        paged_attention(good_q, jnp.zeros((P, kv, 12, d)),
                        jnp.zeros((P, kv, 12, d)), bt, lens)


def test_windowed_matches_reference_and_rolling_band(rng):
    """ISSUE 9: `window=` bands the kernel to the exact rolling-cache
    attention set — kernel vs reference vs the dense window mask, across
    boundary-page offsets (window straddling a page edge) and lengths
    shorter than the window."""
    P, kv, ps, d, mp = 40, 2, 8, 16, 4
    W = 11                               # deliberately page-misaligned
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    lens = jnp.asarray([5, W, W + 1, 2 * ps, mp * ps, 0], jnp.int32)
    b = lens.shape[0]
    q = jnp.asarray(rng.standard_normal((b, 4, 1, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    out = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens,
                                     window=W))
    ref = np.asarray(paged_attention_reference(q, k_pages, v_pages, bt,
                                               lens, window=W))
    np.testing.assert_allclose(out, ref, **TOL)
    assert (out[5] == 0).all()           # idle slot stays exactly zero
    # against the dense cached band: gather the pages contiguous and run
    # cached_attention with the same window at offset len-1
    for i in range(b - 1):
        t1 = int(lens[i])
        if t1 < 1:
            continue
        kc = jnp.take(k_pages, bt[i], axis=0).transpose(
            1, 0, 2, 3).reshape(1, kv, mp * ps, d)
        vc = jnp.take(v_pages, bt[i], axis=0).transpose(
            1, 0, 2, 3).reshape(1, kv, mp * ps, d)
        dense = cached_attention(q[i:i + 1], {"k": kc, "v": vc,
                                              "len": jnp.int32(t1 - 1)},
                                 window=W)
        np.testing.assert_allclose(out[i], np.asarray(dense)[0], **TOL)


def test_windowed_dropped_pages_leave_the_result_unchanged(rng):
    """The engine's page-drop contract: nulling a block-table entry whose
    page sits fully below the band (and even poisoning the null page's
    contents) must not change the output — dead pages are skipped, not
    masked-after-read."""
    P, kv, ps, d, mp = 24, 2, 8, 16, 4
    W = 10
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    lens = jnp.asarray([4 * ps], jnp.int32)      # band covers pages 2..3
    q = jnp.asarray(rng.standard_normal((1, 4, 1, d)), jnp.float32)
    bt = _tables(rng, 1, mp, P)
    ref = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens,
                                     window=W))
    # drop pages 0 and 1 (fully below the band floor 32-1-10=21 ... page
    # 1 ends at 15 <= 21) and poison the null page
    bt_dropped = bt.at[0, 0].set(0).at[0, 1].set(0)
    k_bad = k_pages.at[0].set(1e9)
    v_bad = v_pages.at[0].set(-1e9)
    out = np.asarray(paged_attention(q, k_bad, v_bad, bt_dropped, lens,
                                     window=W))
    np.testing.assert_array_equal(out, ref)
    with pytest.raises(ValueError):      # non-positive window
        paged_attention(q, k_pages, v_pages, bt, lens, window=0)
    with pytest.raises(ValueError):      # non-static (array) window
        paged_attention(q, k_pages, v_pages, bt, lens,
                        window=jnp.int32(W))


# --------------------------------------------------------------------------
# s > 1 query blocks (ISSUE 13: speculative verify / chunked prefill)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("s_q", [2, 4, 8])
def test_query_block_matches_reference(rng, s_q):
    """The kernel generalized to a static query block: position ``i`` of
    the block attends causally up to ``lengths[b] - s_q + i`` — parity
    against the reference at every s, over boundary lengths including
    ``len < s_q`` (admission never produces it, but the mask must stay
    sane) and ``len = 0``."""
    P, kv, ps, d, mp = 40, 2, 8, 16, 4
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    lens = jnp.asarray([s_q, ps, ps + 1, 2 * ps - 1, mp * ps,
                        max(s_q - 1, 0), 0], jnp.int32)
    b = lens.shape[0]
    q = jnp.asarray(rng.standard_normal((b, 4, s_q, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    out = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens))
    ref = np.asarray(paged_attention_reference(q, k_pages, v_pages, bt,
                                               lens))
    np.testing.assert_allclose(out, ref, **TOL)
    assert out.shape == (b, 4, s_q, d)
    assert (out[6] == 0).all()           # length 0 -> exactly zero block


def test_query_block_gqa_matches_reference(rng):
    """GQA grouping under an s=4 block: each kv head serves rep=3 query
    heads at every block position."""
    P, kv, h, ps, d, b, mp, s_q = 20, 2, 6, 8, 32, 2, 3, 4
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    q = jnp.asarray(rng.standard_normal((b, h, s_q, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    lens = jnp.asarray([9, 24], jnp.int32)
    out = paged_attention(q, k_pages, v_pages, bt, lens)
    ref = paged_attention_reference(q, k_pages, v_pages, bt, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), **TOL)


def test_query_block_last_row_matches_s1(rng):
    """Consistency across block widths: the LAST row of an s-block at
    length t equals the s=1 call at length t (same query, same visible
    set ``<= t - 1``) — the property that makes a chunked prefill's
    final logit interchangeable with a decode step's."""
    P, kv, ps, d, b, mp, s_q = 24, 2, 8, 16, 2, 3, 4
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    q = jnp.asarray(rng.standard_normal((b, 4, s_q, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    lens = jnp.asarray([13, 2 * ps], jnp.int32)
    block = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens))
    single = np.asarray(paged_attention(q[:, :, -1:], k_pages, v_pages,
                                        bt, lens))
    np.testing.assert_allclose(block[:, :, -1:], single, **TOL)


def test_query_block_windowed_matches_reference(rng):
    """The window band composes with s>1: block position ``i`` sees
    exactly ``(qpos_i - W, qpos_i]`` — parity at a page-misaligned
    window, including lengths inside the first window."""
    P, kv, ps, d, mp, s_q = 40, 2, 8, 16, 4, 4
    W = 11
    k_pages, v_pages = _pool(rng, P, kv, ps, d)
    lens = jnp.asarray([s_q, W, W + s_q, 2 * ps, mp * ps], jnp.int32)
    b = lens.shape[0]
    q = jnp.asarray(rng.standard_normal((b, 4, s_q, d)), jnp.float32)
    bt = _tables(rng, b, mp, P)
    out = np.asarray(paged_attention(q, k_pages, v_pages, bt, lens,
                                     window=W))
    ref = np.asarray(paged_attention_reference(q, k_pages, v_pages, bt,
                                               lens, window=W))
    np.testing.assert_allclose(out, ref, **TOL)
