"""Llama family: GQA/RoPE/SwiGLU correctness + TP parity + train smoke.

No reference analog (apex ships no models); the TP parity harness mirrors
tests/test_gpt_model.py, and the RoPE check pins the rotate-half convention
against a from-scratch complex-rotation reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.llama import (LlamaModel, llama_loss, llama_tiny_config,
                                   _rope_cos_sin)


def test_rope_matches_complex_rotation(rng):
    """cos/sin tables + rotate-half == complex rotation e^{i*pos*theta_j} on
    (x_j, x_{j+d/2}) pairs (the NeoX/Llama pairing)."""
    cfg = llama_tiny_config()
    s, d = 8, cfg.head_dim
    cos_, sin_ = _rope_cos_sin(cfg, s, 0)
    x = rng.standard_normal((s, 1, 1, d)).astype(np.float32)

    from apex_tpu.transformer.functional.fused_rope import (
        fused_apply_rotary_pos_emb_cached)
    y = np.asarray(fused_apply_rotary_pos_emb_cached(jnp.asarray(x),
                                                     cos_, sin_))

    half = d // 2
    inv = 1.0 / (cfg.rope_theta ** (np.arange(0, d, 2) / d))
    for p in range(s):
        zr = x[p, 0, 0, :half] + 1j * x[p, 0, 0, half:]
        zr = zr * np.exp(1j * p * inv)
        expect = np.concatenate([zr.real, zr.imag])
        np.testing.assert_allclose(y[p, 0, 0], expect, rtol=1e-5, atol=1e-5)


def test_gqa_matches_repeated_dense_attention(rng):
    """num_kv_heads=2 < num_heads=4: model output == manually computed
    attention with kv heads repeated."""
    from apex_tpu.ops import flash_attention

    b, h, kvh, s, d = 2, 4, 2, 16, 16
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    kr = jnp.repeat(k, h // kvh, axis=1)
    vr = jnp.repeat(v, h // kvh, axis=1)
    out = flash_attention(q, kr, vr, causal=True)
    # per-head dense reference
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr) / np.sqrt(d)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_llama_train_smoke(rng):
    from apex_tpu.optimizers import FusedAdam

    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)
    params = v["params"]
    assert "lm_head" in params            # untied head (Llama convention)
    assert "kv_proj" in params["layer_0"]  # GQA projections present
    opt = FusedAdam(params, lr=3e-3)
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: llama_loss(model, {"params": p}, ids, labels)))
    losses = []
    for _ in range(6):
        loss, g = grad_fn(params)
        params = opt.step(g)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def _shard_tree(params1, params_tp_shape, rank, tp):
    """Slice a tp=1 Llama tree into rank's tp shard (no fused-qkv special
    case: q/kv/gate/up are column-split, o/down row-split, vocab dims split
    — all inferred by which dim shrank)."""

    def slice_leaf(path, full, shard):
        if full.shape == shard.shape:
            return full
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if "kv_proj" in name or "gate_up_proj" in name:
            # fused 2-part projections: local layout is [A_r | B_r], so
            # slice per-half, not contiguously
            per = shard.shape[0] // 2
            t = full.reshape(2, full.shape[0] // 2, *full.shape[1:])
            return t[:, rank * per:(rank + 1) * per].reshape(shard.shape)
        for ax in range(full.ndim):
            if full.shape[ax] == shard.shape[ax] * tp:
                size = shard.shape[ax]
                idx = [slice(None)] * full.ndim
                idx[ax] = slice(rank * size, (rank + 1) * size)
                return full[tuple(idx)]
        raise AssertionError(f"unsliceable {name}: {full.shape} -> {shard.shape}")

    return jax.tree_util.tree_map_with_path(slice_leaf, params1,
                                            params_tp_shape)


@pytest.mark.slow
def test_llama_tp2_matches_tp1(rng):
    from apex_tpu.transformer import parallel_state

    tp = 2
    mesh = parallel_state.initialize_model_parallel(tp)
    cfg1 = llama_tiny_config(tensor_parallel_size=1)
    cfgt = llama_tiny_config(tensor_parallel_size=tp)
    ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)

    m1 = LlamaModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), ids)
    loss1 = float(llama_loss(m1, v1, ids, labels, axis_name="unbound"))

    mt = LlamaModel(cfgt)
    vt_shape = jax.eval_shape(lambda: mt.init(jax.random.PRNGKey(0), ids))
    shards = [_shard_tree(v1["params"], vt_shape["params"], r, tp)
              for r in range(tp)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(MODEL_AXIS), P(), P()), out_specs=P(MODEL_AXIS),
        check_vma=False)
    def run(vs, ii, ll):
        v = jax.tree.map(lambda t: t[0], vs)
        return llama_loss(mt, {"params": v}, ii, ll).reshape(1)

    losst = run(stacked, ids, labels)
    np.testing.assert_allclose(np.asarray(losst), loss1, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_llama_cp2_matches_single_device(rng):
    """Sequence sharded over ``context`` (ring attention rotating the
    UNEXPANDED GQA K/V + RoPE offsets) == the single-device model."""
    import dataclasses

    from apex_tpu.transformer import parallel_state

    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)
    loss_ref = float(llama_loss(model, v, ids, labels))

    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=2)
    cfg_cp = dataclasses.replace(cfg, context_parallel=True)
    m_cp = LlamaModel(cfg_cp)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(None, "context"), P(None, "context")),
        out_specs=P(), check_vma=False)
    def cp_loss(p, ii, ll):
        return llama_loss(m_cp, {"params": p}, ii, ll)

    with mesh:
        loss_cp = float(jax.jit(cp_loss)(v["params"], ids, labels))
    np.testing.assert_allclose(loss_cp, loss_ref, rtol=2e-5, atol=2e-5)


def test_llama_rejects_overlong_sequence(rng):
    import dataclasses

    cfg = dataclasses.replace(llama_tiny_config(), max_position_embeddings=16)
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        model.init(jax.random.PRNGKey(0), ids)


@pytest.mark.slow
def test_llama_sliding_window_trains_and_differs(rng):
    """sliding_window wires through to the kernel: output differs from the
    full-causal model (long-range key cut off) and still trains."""
    import dataclasses

    cfg_full = llama_tiny_config()
    cfg_win = dataclasses.replace(cfg_full, sliding_window=8)
    ids = jnp.asarray(rng.integers(0, cfg_full.vocab_size, (2, 64)),
                      jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    m_full, m_win = LlamaModel(cfg_full), LlamaModel(cfg_win)
    v = m_full.init(jax.random.PRNGKey(0), ids)
    l_full = float(llama_loss(m_full, v, ids, labels))
    l_win = float(llama_loss(m_win, v, ids, labels))
    assert abs(l_full - l_win) > 1e-6  # the window actually bites
    g = jax.grad(lambda p: llama_loss(m_win, {"params": p}, ids, labels))(
        v["params"])
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["ring", "zigzag"])
def test_llama_sliding_window_cp_matches_single_device(rng, layout):
    """sliding_window composes with context_parallel on BOTH layouts: the
    window-aware sequence-ordered ring AND the causal load-balanced zigzag
    (VERDICT r3 weak #5 — windows and zigzag were mutually exclusive)."""
    import dataclasses

    from apex_tpu.ops import to_zigzag
    from apex_tpu.transformer import parallel_state

    cfg = dataclasses.replace(llama_tiny_config(), sliding_window=24)
    model = LlamaModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 64)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)
    loss_ref = float(llama_loss(model, v, ids, labels))

    cp = 2
    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=cp)
    m_cp = LlamaModel(dataclasses.replace(
        cfg, context_parallel=True,
        context_parallel_zigzag=layout == "zigzag"))
    if layout == "zigzag":
        # the model consumes the zigzag-permuted sequence; the mean loss is
        # permutation-invariant so it still matches the unpermuted oracle
        ids = to_zigzag(ids, cp, axis=1)
        labels = to_zigzag(labels, cp, axis=1)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(None, "context"), P(None, "context")),
        out_specs=P(), check_vma=False)
    def cp_loss(p, ii, ll):
        return llama_loss(m_cp, {"params": p}, ii, ll)

    with mesh:
        loss_cp = float(jax.jit(cp_loss)(v["params"], ids, labels))
    np.testing.assert_allclose(loss_cp, loss_ref, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_mixtral_style_moe_llama_trains(rng):
    """Mixtral family = GQA + sliding window + SwiGLU MoE experts: routed
    layers get router+expert grads, aux in the loss, loss decreases."""
    import dataclasses

    from apex_tpu.optimizers import FusedAdam

    cfg = dataclasses.replace(
        llama_tiny_config(), num_experts=4, moe_layer_freq=2, moe_k=2,
        moe_capacity_factor=3.0, sliding_window=16,
        moe_aux_loss_coeff=1e-2, moe_z_loss_coeff=1e-3)
    model = LlamaModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)
    params = v["params"]
    # layer_1 routed with swiglu experts: w1 carries [gate|up] fused cols
    moe = params["layer_1"]["moe_mlp"]
    assert moe["w1"].shape == (4, cfg.hidden_size,
                               2 * cfg.intermediate_size)
    assert "gate_up_proj" in params["layer_0"]  # dense block untouched

    def loss(p):
        return llama_loss(model, {"params": p}, ids, labels)

    g = jax.grad(loss)(params)
    assert float(jnp.sum(jnp.abs(g["layer_1"]["moe_mlp"]["router"]["weight"]
                                 ))) > 0.0
    opt = FusedAdam(params, lr=3e-3)
    grad_fn = jax.jit(jax.value_and_grad(loss))
    losses = []
    for _ in range(6):
        l, g = grad_fn(params)
        params = opt.step(g)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_llama_moe_pipeline_matches_dense(rng):
    """Mixtral + PP: SwiGLU MoE blocks through the pipeline (aux rides the
    payload, autodiff schedule) == the non-pipelined model."""
    import dataclasses
    import functools

    from jax.sharding import PartitionSpec as P

    from apex_tpu.mesh import STAGE_AXIS
    from apex_tpu.models.llama_pipeline import (
        make_llama_pipeline_fns, merge_pipeline_grads_to_llama,
        split_llama_params_for_pipeline)
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    pp, n_layers, m, b, s = 2, 4, 4, 2, 16
    cfg = dataclasses.replace(
        llama_tiny_config(num_layers=n_layers), num_experts=4,
        moe_capacity_factor=3.0, sliding_window=8)
    mesh = parallel_state.initialize_model_parallel(1, pp)

    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.roll(mbs, -1, axis=-1)
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), mbs[0])["params"]

    def ref_loss(p):
        per = jax.vmap(lambda ii, ll: llama_loss(
            model, {"params": p}, ii, ll, axis_name="unbound"))(mbs, labels)
        return per.mean()

    ref_l, ref_g = jax.value_and_grad(ref_loss)(v)

    stacked = split_llama_params_for_pipeline(cfg, v, pp)
    first_fn, stage_fn, loss_fn = make_llama_pipeline_fns(cfg)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)), check_vma=False)
    def run(p, mb, lb):
        local = jax.tree.map(lambda t: t[0], p)
        loss, g = fwd_bwd(stage_fn, loss_fn, local, mb, loss_aux=lb,
                          first_fn=first_fn, loss_with_params=True)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], g)

    loss_pp, g_pp = jax.jit(run)(stacked, mbs, labels)
    np.testing.assert_allclose(np.asarray(loss_pp), float(ref_l),
                               rtol=2e-5, atol=2e-5)
    merged = merge_pipeline_grads_to_llama(cfg, g_pp, pp)
    for a, r in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-3, atol=1e-4)


def test_llama_remat_same_loss_and_grads(rng):
    """remat only changes the backward — grads must match, not just loss
    (cos_/sin_ extra args + variable lifting ride through the recompute)."""
    import dataclasses

    cfg = llama_tiny_config()
    m = LlamaModel(cfg)
    mr = LlamaModel(dataclasses.replace(cfg, remat=True))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = m.init(jax.random.PRNGKey(0), ids)
    l0, g0 = jax.value_and_grad(
        lambda p: llama_loss(m, {"params": p}, ids, labels))(v["params"])
    l1, g1 = jax.value_and_grad(
        lambda p: llama_loss(mr, {"params": p}, ids, labels))(v["params"])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
