"""Cost model (apex_tpu/obs/costs.py) — closed-form validation.

The ledger gates on these numbers EXACTLY, so the counting conventions
must be provably implemented: matmul / attention / layer-norm FLOPs
match hand formulas, scan multiplies by length, pallas kernels price by
grid, the liveness sweep matches a hand-traced peak, and the decode
chunk's weight-byte count equals parameter-count x dtype width. The
registry coverage test is the acceptance bar: the CLI report covers
EVERY ``analysis_cases()`` program with source anchors.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from apex_tpu.obs import costs

PROF = costs.PROFILES["v5e"]


def _cost(fn, *args, **kw):
    closed = jax.make_jaxpr(fn)(*args)
    return costs.cost_of_jaxpr(closed, PROF, **kw)


def sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# --------------------------------------------------------------------------
# closed forms
# --------------------------------------------------------------------------

def test_matmul_flops_and_bytes_closed_form():
    M, K, N = 48, 96, 32
    c = _cost(lambda x, w: x @ w, sds((M, K)), sds((K, N)))
    assert c.flops == 2 * M * N * K
    assert c.hbm_bytes == 4 * (M * K + K * N + M * N)
    assert c.by_primitive["dot_general"]["count"] == 1


def test_batched_matmul_counts_batch_dims():
    B, M, K, N = 3, 8, 16, 4
    c = _cost(lambda x, w: jnp.einsum("bmk,bkn->bmn", x, w),
              sds((B, M, K)), sds((B, K, N)))
    assert c.by_primitive["dot_general"]["flops"] == 2 * B * M * N * K


def test_attention_flops_closed_form():
    """softmax(q k^T / sqrt(d)) v — the two matmuls carry the closed
    form 2·b·h·s²·d each; the softmax adds its elementwise/reduce terms
    on top (convention: 1 FLOP per element per op)."""
    b, h, s, d = 2, 4, 32, 16

    def attn(q, k, v):
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    shape = (b, h, s, d)
    c = _cost(attn, sds(shape), sds(shape), sds(shape))
    bp = c.by_primitive
    scores = b * h * s * s
    # the two matmuls carry the canonical 2·b·h·s²·d each
    assert bp["dot_general"]["flops"] == 2 * (2 * b * h * s * s * d)
    # softmax closed forms, op by op over the (b,h,s,s) score tensor
    assert bp["reduce_max"]["flops"] == scores
    assert bp["reduce_sum"]["flops"] == scores
    assert bp["sub"]["flops"] == scores
    assert bp["exp"]["flops"] == scores
    # two divs: the 1/sqrt(d) scale and the softmax normalizer
    assert bp["div"]["flops"] == 2 * scores
    # nothing under the hood beyond softmax's -inf guard (b·h·s elems)
    assert c.flops == bp["dot_general"]["flops"] + 6 * scores + b * h * s


def test_layer_norm_flops_closed_form():
    B, D = 16, 64
    eps = 1e-5

    def ln(x, g, b):
        mu = jnp.sum(x, -1, keepdims=True) / D
        xc = x - mu
        var = jnp.sum(xc * xc, -1, keepdims=True) / D
        inv = lax.rsqrt(var + eps)
        return xc * inv * g + b

    c = _cost(ln, sds((B, D)), sds((D,)), sds((D,)))
    # sum(B·D) + div(B) + sub(B·D) + mul(B·D) + sum(B·D) + div(B)
    # + add(B) + rsqrt(B) + mul(B·D) + mul(B·D) + add(B·D)
    assert c.flops == 7 * B * D + 4 * B
    assert c.bound == "memory"           # AI << v5e ridge point


def test_scan_multiplies_body_by_length():
    N, L = 8, 7

    def f(c0, w):
        def body(c, _):
            return jnp.tanh(c @ w), ()
        c1, _ = lax.scan(body, c0, None, length=L)
        return c1

    c = _cost(f, sds((N, N)), sds((N, N)))
    assert c.by_primitive["dot_general"]["flops"] == L * 2 * N * N * N
    assert c.by_primitive["dot_general"]["count"] == L
    assert c.by_primitive["tanh"]["flops"] == L * N * N
    # the closed-over weight streams once per iteration — the HBM model
    # behind the weight-bound decode claim
    assert c.by_primitive["dot_general"]["bytes"] \
        == L * 4 * (3 * N * N)


def test_peak_live_bytes_hand_traced():
    N = 10

    def f(a, b):
        c = a + b          # a, b, c live -> 3N floats
        d = c * a          # b dead; a, c, d live -> 3N
        return d

    c = _cost(f, sds((N,)), sds((N,)))
    assert c.peak_live_bytes == 3 * N * 4


def test_pallas_call_priced_by_grid():
    pl = pytest.importorskip("jax.experimental.pallas")

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    grid = 4
    block = 8

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((grid * block,), jnp.float32),
            grid=(grid,),
            in_specs=[pl.BlockSpec((block,), lambda i: (i,))],
            out_specs=pl.BlockSpec((block,), lambda i: (i,)),
            interpret=True)(x)

    c = _cost(f, sds((grid * block,)))
    pallas = c.by_primitive["pallas_call"]
    # kernel mul = block elements, once per grid step; bytes = the
    # operand + result crossing HBM once
    assert pallas["flops"] == grid * block
    assert pallas["bytes"] == 2 * 4 * grid * block


def test_cond_charges_most_expensive_branch():
    N = 16

    def f(p, x, w):
        return lax.cond(p, lambda: x @ w @ w, lambda: x + 1.0)

    c = _cost(f, sds((), jnp.bool_), sds((N, N)), sds((N, N)))
    assert c.by_primitive["dot_general"]["flops"] == 2 * 2 * N * N * N
    assert "add" not in c.by_primitive


def test_profiles_change_predicted_time_not_counts():
    M = 256
    f = lambda x, w: x @ w                               # noqa: E731
    closed = jax.make_jaxpr(f)(sds((M, M), jnp.bfloat16),
                               sds((M, M), jnp.bfloat16))
    v5e = costs.cost_of_jaxpr(closed, costs.PROFILES["v5e"])
    v5p = costs.cost_of_jaxpr(closed, costs.PROFILES["v5p"])
    assert v5e.flops == v5p.flops and v5e.hbm_bytes == v5p.hbm_bytes
    assert v5e.predicted_ms > v5p.predicted_ms           # more HBM BW


# --------------------------------------------------------------------------
# the registry report (acceptance)
# --------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def registry_report():
    return costs.cost_report(REPO)


def test_report_covers_every_registered_case(registry_report):
    """Acceptance: the roofline report prices EVERY analysis_cases()
    program, with no trace errors at HEAD."""
    from apex_tpu.analysis.ir.harness import analysis_cases

    expected = {c.name for c in analysis_cases(REPO)}
    priced = {c["name"] for c in registry_report["cases"]}
    assert registry_report["errors"] == []
    assert priced == expected and len(priced) >= 25


def test_report_has_source_anchors_and_rollups(registry_report):
    anchored = [e for c in registry_report["cases"] for e in c["top_eqns"]
                if e["file"]]
    assert anchored, "no top equation resolved to an in-repo source line"
    assert all(e["file"].endswith(".py") and e["line"] >= 1
               for e in anchored)
    t = registry_report["totals"]
    assert t["flops"] > 0 and t["hbm_bytes"] > 0
    assert set(registry_report["by_domain"]) \
        >= {"serving", "ops", "optimizers"}


def test_decode_split_weight_bytes_match_param_count(registry_report):
    """The docs/serving.md claim as a number: the decode chunk's
    per-step weight stream equals parameter count x dtype width, and it
    dominates the KV reads (weight-bound decode)."""
    import jax

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config

    split = registry_report["decode_split"]
    assert split is not None
    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    expected = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                   for l in jax.tree.leaves(dvars))
    assert split["weight_bytes_per_step"] == expected
    assert split["weight_fraction"] > 0.5
    assert split["kv_bytes_per_step_max"] > 0


def test_ledger_metrics_flatten(registry_report):
    m = costs.ledger_metrics(registry_report)
    assert m["cost.total_flops"] == float(
        registry_report["totals"]["flops"])
    assert any(k.startswith("cost.case.") for k in m)
    assert "cost.decode.weight_fraction" in m
    # every value JSON-serializable float (the ledger line contract)
    assert all(isinstance(v, float) for v in m.values())


def test_spec_decode_split_beats_decode_at_acceptance_two(
        registry_report):
    """ISSUE 13 acceptance: the speculative round's per-ACCEPTED-token
    weight stream — (W_target + k * W_draft) / a off the registered
    spec case's meta — drops below the non-speculative decode stream
    (``cost.decode.weight_bytes_per_step``) at every acceptance length
    a >= 2, and the exact/banded ledger metric pair is emitted."""
    ssplit = registry_report["spec_decode_split"]
    assert ssplit is not None
    k = ssplit["k"]
    assert k >= 2
    non_spec = registry_report["decode_split"]["weight_bytes_per_step"]
    assert ssplit["target_weight_bytes"] == non_spec
    # the round streams the target once + the draft k times, exactly
    assert ssplit["round_weight_bytes"] == (
        ssplit["target_weight_bytes"] + k * ssplit["draft_weight_bytes"])
    a1 = ssplit["per_acceptance"]["1"]
    assert a1["weight_bytes_per_accepted_token"] > non_spec
    for a in range(2, k + 1):
        slot = ssplit["per_acceptance"][str(a)]
        assert slot["weight_bytes_per_accepted_token"] < non_spec
        assert slot["predicted_step_ms"] < a1["predicted_step_ms"]
    assert ssplit["breakeven_acceptance"] == 2
    m = costs.ledger_metrics(registry_report)
    assert m["cost.spec_decode.weight_bytes_per_token_a2"] \
        < m["cost.decode.weight_bytes_per_step"]
    assert f"spec_decode.predicted_step_ms_a{k}" in m


def test_host_tier_split_prices_the_dma_chunk(registry_report):
    """ISSUE 17 acceptance: the tiered pool's demote/promote chunk is
    priced against the chip's HOST LINK, not HBM — the chunk bytes are
    the registered gather case's output tree (HOST_COPY_CHUNK pages'
    K/V tiles), and the exact/banded ledger metric pair is emitted."""
    from apex_tpu.serving import kv_pool

    hsplit = registry_report["host_tier_split"]
    assert hsplit is not None
    assert hsplit["chunk_pages"] == kv_pool.HOST_COPY_CHUNK
    assert hsplit["chunk_bytes"] == \
        hsplit["bytes_per_page"] * kv_pool.HOST_COPY_CHUNK
    assert hsplit["host_link_bytes_per_sec"] == \
        PROF.host_link_bytes_per_sec
    # the reason the tier exists as a *spill* tier and not a peer: the
    # host link is far under HBM bandwidth on every profile
    for prof in costs.PROFILES.values():
        assert prof.host_link_bytes_per_sec \
            < 0.1 * prof.hbm_bytes_per_sec
    assert hsplit["predicted_chunk_dma_ms"] == pytest.approx(
        hsplit["chunk_bytes"] / PROF.host_link_bytes_per_sec * 1e3)
    m = costs.ledger_metrics(registry_report)
    assert m["cost.decode.host_tier.chunk_bytes"] == \
        float(hsplit["chunk_bytes"])
    assert m["cost.decode.host_tier.bytes_per_page"] == \
        float(hsplit["bytes_per_page"])
    assert "host_tier.promote_chunk_predicted_ms" in m


def test_cli_single_case_and_text_report(tmp_path, capsys):
    rc = costs.main(["--case", "layer_norm_fwd",
                     "--json", str(tmp_path / "r.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "layer_norm_fwd" in out and "profile v5e" in out
    import json
    with open(tmp_path / "r.json") as f:
        doc = json.load(f)
    assert [c["name"] for c in doc["cases"]] == ["layer_norm_fwd"]
