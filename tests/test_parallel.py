"""apex_tpu.parallel: SyncBatchNorm, DDP facade, LARC, clip_grad.

Mirrors the reference suites: tests/distributed/synced_batchnorm (SyncBN vs
BatchNorm on the gathered batch), ddp_race_condition_test's role (grad
averaging correctness), tests/L0/run_amp/test_larc.py, and
apex/contrib/test/clip_grad.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS


# --- SyncBatchNorm -----------------------------------------------------------

@pytest.mark.slow
def test_syncbn_matches_batchnorm_on_gathered_batch(mesh8, rng):
    """The canonical reference check (two_gpu_unit_test.py): SyncBN over N
    shards == plain BN over the concatenated batch."""
    from apex_tpu.parallel import SyncBatchNorm

    x = rng.standard_normal((16, 6, 6, 8), dtype=np.float32)
    bn = SyncBatchNorm(num_features=8, axis_name=DATA_AXIS)
    variables = bn.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]))

    # ground truth: local-only stats over the FULL batch
    ref = SyncBatchNorm(num_features=8, axis_name=None)
    y_ref, ref_state = ref.apply(variables, jnp.asarray(x),
                                 mutable=["batch_stats"])

    @functools.partial(
        jax.shard_map, mesh=mesh8,
        in_specs=(P(), P(DATA_AXIS)), out_specs=(P(DATA_AXIS), P()))
    def sharded(vars_, xs):
        y, st = bn.apply(vars_, xs, mutable=["batch_stats"])
        return y, st

    y, st = sharded(variables, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(st["batch_stats"]["mean"]),
        np.asarray(ref_state["batch_stats"]["mean"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(st["batch_stats"]["var"]),
        np.asarray(ref_state["batch_stats"]["var"]), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_syncbn_backward_matches_gathered(mesh8, rng):
    from apex_tpu.parallel import SyncBatchNorm

    x = rng.standard_normal((16, 8), dtype=np.float32)
    bn_sync = SyncBatchNorm(num_features=8, axis_name=DATA_AXIS,
                            track_running_stats=False)
    bn_local = SyncBatchNorm(num_features=8, axis_name=None,
                             track_running_stats=False)
    variables = bn_local.init(jax.random.PRNGKey(0), jnp.asarray(x))

    def loss_ref(v, xs):
        return jnp.sum(bn_local.apply(v, xs) ** 2)

    g_ref = jax.grad(loss_ref)(variables, jnp.asarray(x))

    @functools.partial(jax.shard_map, mesh=mesh8,
                       in_specs=(P(), P(DATA_AXIS)), out_specs=P())
    def sharded_grad(v, xs):
        # the transpose of the replicated-param broadcast (pvary) already
        # psums the per-shard cotangents — no explicit collective needed
        return jax.grad(lambda vv: jnp.sum(bn_sync.apply(vv, xs) ** 2))(v)

    g = sharded_grad(variables, jnp.asarray(x))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-4),
        g, g_ref)


def test_syncbn_running_average_inference(rng):
    from apex_tpu.parallel import SyncBatchNorm

    bn = SyncBatchNorm(num_features=4, axis_name=None)
    x = jnp.asarray(rng.standard_normal((32, 4), dtype=np.float32)) * 3 + 1
    variables = bn.init(jax.random.PRNGKey(0), x)
    _, st = bn.apply(variables, x, mutable=["batch_stats"])
    y = bn.apply({**variables, **st}, x, use_running_average=True)
    assert np.isfinite(np.asarray(y)).all()


def test_convert_syncbn_model(rng):
    """A real flax nn.BatchNorm field is rewritten to SyncBatchNorm and
    produces the same (local) normalization (reference:
    apex/parallel/__init__.py convert_syncbn_model walking named_children)."""
    import flax.linen as nn

    from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

    class Net(nn.Module):
        bn: nn.Module

        @nn.compact
        def __call__(self, x):
            return self.bn(x)

    x = jnp.asarray(rng.standard_normal((16, 4), dtype=np.float32)) * 2 + 3
    ref_net = Net(bn=nn.BatchNorm(use_running_average=False, momentum=0.9))
    ref_vars = ref_net.init(jax.random.PRNGKey(0), x)
    y_ref, _ = ref_net.apply(ref_vars, x, mutable=["batch_stats"])

    net = convert_syncbn_model(ref_net)
    assert isinstance(net.bn, SyncBatchNorm)
    v = net.init(jax.random.PRNGKey(0), x)
    y, st = net.apply(v, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    # torch-momentum conversion: running mean moved by flax-momentum 0.9
    # -> torch momentum 0.1 of the batch mean
    np.testing.assert_allclose(np.asarray(st["batch_stats"]["bn"]["mean"]),
                               0.1 * np.asarray(x).mean(0), rtol=1e-4)


# --- DDP facade --------------------------------------------------------------

def test_ddp_allreduce_gradients(mesh8, rng):
    from apex_tpu.parallel import DistributedDataParallel

    ddp = DistributedDataParallel(lambda x: x)
    g_local = rng.standard_normal((8, 4), dtype=np.float32)

    @functools.partial(jax.shard_map, mesh=mesh8,
                       in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    def avg(g):
        return ddp.allreduce_gradients({"w": g})["w"]

    out = avg(jnp.asarray(g_local))
    expect = np.broadcast_to(g_local.reshape(8, 1, 4).mean(0), (8, 1, 4)
                             ).reshape(8, 4)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_ddp_call_passthrough():
    from apex_tpu.parallel import DistributedDataParallel

    ddp = DistributedDataParallel(lambda x: x * 2, message_size=123,
                                  delay_allreduce=True)
    assert ddp(3) == 6
    assert ddp.message_size == 123


# --- LARC --------------------------------------------------------------------

def test_larc_scales_large_grads(rng):
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import LARC

    params = {"w": jnp.ones((64, 64)) * 0.1, "b": jnp.zeros((64,))}
    opt = FusedSGD(params, lr=0.1, weight_decay=0.0)
    larc = LARC(opt, trust_coefficient=0.02, clip=True)
    grads = {"w": jnp.ones((64, 64)) * 100.0, "b": jnp.zeros((64,))}
    new_params = larc.step(grads)
    # without LARC: w - 0.1*100 = -9.99; with LARC the update is clipped to
    # local_lr*g where local_lr = 0.02*||p||/||g|| << lr
    delta = np.abs(np.asarray(new_params["w"]) - 0.1).max()
    assert delta < 0.01, delta
    # zero-norm grads pass through unscaled
    np.testing.assert_allclose(np.asarray(new_params["b"]), 0.0)


def test_larc_no_clip_is_lars(rng):
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import LARC

    params = {"w": jnp.ones((8, 8))}
    g = rng.standard_normal((8, 8), dtype=np.float32)
    opt = FusedSGD(params, lr=1.0, weight_decay=0.0)
    larc = LARC(opt, trust_coefficient=0.5, clip=False)
    new_params = larc.step({"w": jnp.asarray(g)})
    pn = np.linalg.norm(np.ones((8, 8)))
    gn = np.linalg.norm(g)
    expect = 1.0 - (0.5 * pn / (gn + 1e-8)) * g
    np.testing.assert_allclose(np.asarray(new_params["w"]), expect, rtol=1e-4)


# --- clip_grad ---------------------------------------------------------------

def test_clip_grad_norm_matches_reference(rng):
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    grads = {"a": jnp.asarray(rng.standard_normal((33, 17), dtype=np.float32)),
             "b": jnp.asarray(rng.standard_normal((129,), dtype=np.float32))}
    flat = np.concatenate([np.asarray(g).ravel() for g in jax.tree.leaves(grads)])
    expect_norm = np.linalg.norm(flat)

    clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
    np.testing.assert_allclose(float(norm), expect_norm, rtol=1e-5)
    scale = 1.0 / (expect_norm + 1e-6)
    np.testing.assert_allclose(
        np.asarray(clipped["a"]), np.asarray(grads["a"]) * scale, rtol=1e-5)

    # under the max -> unchanged
    clipped2, _ = clip_grad_norm_(grads, max_norm=1e9)
    np.testing.assert_allclose(np.asarray(clipped2["b"]),
                               np.asarray(grads["b"]), rtol=1e-6)


def test_clip_grad_norm_inf(rng):
    from apex_tpu.contrib.clip_grad import clip_grad_norm_

    grads = {"a": jnp.asarray(rng.standard_normal((5, 5), dtype=np.float32))}
    _, norm = clip_grad_norm_(grads, max_norm=1.0, norm_type=float("inf"))
    np.testing.assert_allclose(float(norm),
                               np.abs(np.asarray(grads["a"])).max(), rtol=1e-6)
