"""GPT with MoE blocks: training signal + expert-parallel loss parity.

No reference analog (apex has no MoE); same strategy as the other parallelism
suites — sharded execution on the CPU mesh must match a single-device ground
truth. The load-balance aux is per-device-batch by construction (GShard
convention), so the EP parity test zeroes the aux coefficients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _moe_cfg(**over):
    from apex_tpu.models.gpt import gpt_tiny_config

    base = dict(num_experts=4, moe_layer_freq=2, moe_k=2,
                moe_capacity_factor=3.0)  # >= E/k: dropless
    base.update(over)
    return gpt_tiny_config(**base)


@pytest.mark.slow
def test_gpt_moe_has_routed_layers_and_grads_flow(rng):
    from apex_tpu.models.gpt import GPTModel, gpt_loss

    cfg = _moe_cfg()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)

    p = v["params"]
    # layer_freq=2 with 2 layers: layer_1 is MoE, layer_0 dense
    assert "moe_mlp" in p["layer_1"] and "mlp_in" in p["layer_0"]

    loss, g = jax.value_and_grad(
        lambda pp: gpt_loss(model, {"params": pp}, ids, labels))(p)
    assert np.isfinite(float(loss))
    router_g = g["layer_1"]["moe_mlp"]["router"]["weight"]
    assert float(jnp.sum(jnp.abs(router_g))) > 0.0
    assert float(jnp.sum(jnp.abs(g["layer_1"]["moe_mlp"]["w1"]))) > 0.0


def test_gpt_moe_aux_loss_included(rng):
    """aux coeff changes the loss value (sown intermediates are collected)."""
    from apex_tpu.models.gpt import GPTModel, gpt_loss
    import dataclasses

    cfg0 = _moe_cfg(moe_aux_loss_coeff=0.0)
    cfg1 = dataclasses.replace(cfg0, moe_aux_loss_coeff=1.0)
    ids = jnp.asarray(rng.integers(0, cfg0.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    m0, m1 = GPTModel(cfg0), GPTModel(cfg1)
    v = m0.init(jax.random.PRNGKey(0), ids)
    l0 = float(gpt_loss(m0, v, ids, labels))
    l1 = float(gpt_loss(m1, v, ids, labels))
    # balance loss >= 1 at any routing, so coeff=1 must add at least ~1
    assert l1 > l0 + 0.5


@pytest.mark.slow
def test_gpt_moe_pipeline_matches_dense(rng):
    """MoE through the pipeline: the aux loss rides the activation payload
    (pytree payload -> autodiff schedule), heterogeneous per-position
    block layout; loss + merged grads match the non-pipelined GPT-MoE."""
    from apex_tpu.mesh import STAGE_AXIS
    from apex_tpu.models.gpt import GPTModel, gpt_loss
    from apex_tpu.models.gpt_pipeline import (
        make_gpt_pipeline_fns, merge_pipeline_grads_to_gpt,
        split_gpt_params_for_pipeline)
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    pp, n_layers, m, b, s = 2, 4, 4, 2, 16
    cfg = _moe_cfg(num_layers=n_layers)
    mesh = parallel_state.initialize_model_parallel(1, pp)

    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.roll(mbs, -1, axis=-1)
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), mbs[0])["params"]

    def ref_loss(p):
        per = jax.vmap(lambda ii, ll: gpt_loss(
            model, {"params": p}, ii, ll, axis_name="unbound"))(mbs, labels)
        return per.mean()

    ref_l, ref_g = jax.value_and_grad(ref_loss)(v)

    stacked = split_gpt_params_for_pipeline(v, pp, n_layers)
    first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)), check_vma=False)
    def run(p, mb, lb):
        local = jax.tree.map(lambda t: t[0], p)
        loss, g = fwd_bwd(stage_fn, loss_fn, local, mb, loss_aux=lb,
                          first_fn=first_fn, loss_with_params=True)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], g)

    loss_pp, g_pp = jax.jit(run)(stacked, mbs, labels)
    np.testing.assert_allclose(np.asarray(loss_pp), float(ref_l),
                               rtol=2e-5, atol=2e-5)
    merged = merge_pipeline_grads_to_gpt(g_pp, pp, n_layers)
    for a, r in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-3, atol=1e-4)


def test_gpt_moe_pipeline_rejects_bad_stride():
    """MoE stride must divide layers-per-stage (SPMD needs a stage-uniform
    block pattern) — fail loud at split time."""
    from apex_tpu.models.gpt import GPTModel
    from apex_tpu.models.gpt_pipeline import split_gpt_params_for_pipeline

    cfg = _moe_cfg(num_layers=6, moe_layer_freq=4)  # 3 layers/stage, freq 4
    ids = jnp.zeros((1, 8), jnp.int32)
    v = GPTModel(cfg).init(jax.random.PRNGKey(0), ids)["params"]
    with pytest.raises(NotImplementedError, match="stride"):
        split_gpt_params_for_pipeline(v, 2, 6)


@pytest.mark.slow
def test_gpt_moe_expert_parallel_matches_dense(rng):
    """ep=2 over ``data`` (tokens sharded, experts sliced per rank) == the
    single-device dense-dispatch model, aux coeffs zeroed (per-device-batch
    balance loss is intentionally local)."""
    import dataclasses

    from apex_tpu.models.gpt import GPTModel, gpt_loss

    cfg = _moe_cfg(moe_aux_loss_coeff=0.0)
    ep = 2
    e_loc = cfg.num_experts // ep
    dense = GPTModel(cfg)
    par = GPTModel(dataclasses.replace(cfg, expert_parallel=True))

    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = dense.init(jax.random.PRNGKey(0), ids)
    loss_ref = float(gpt_loss(dense, v, ids, labels))

    mesh = Mesh(np.asarray(jax.devices()[:ep]).reshape(ep, 1, 1, 1),
                ("data", "stage", "context", "model"))

    def slice_experts(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if "moe_mlp" in names and names[-1] in ("w1", "b1", "w2", "b2"):
            r = lax.axis_index("data")
            return lax.dynamic_slice_in_dim(leaf, r * e_loc, e_loc, axis=0)
        return leaf

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=P(), check_vma=False)
    def ep_loss(full_params, ii, ll):
        local = jax.tree_util.tree_map_with_path(slice_experts, full_params)
        loss = gpt_loss(par, {"params": local}, ii, ll)
        return lax.pmean(loss, "data")

    loss_ep = float(jax.jit(ep_loss)(v["params"], ids, labels))
    np.testing.assert_allclose(loss_ep, loss_ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_moe_pipeline_freq1_all_routed(rng):
    """moe_layer_freq=1 (every block MoE): layers stay structurally
    homogeneous, so the split keeps the scanned layout and stage_fn carries
    the aux through the scan — parity vs the dense model."""
    from apex_tpu.mesh import STAGE_AXIS
    from apex_tpu.models.gpt import GPTModel, gpt_loss
    from apex_tpu.models.gpt_pipeline import (
        make_gpt_pipeline_fns, split_gpt_params_for_pipeline)
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    pp, n_layers, m, b, s = 2, 4, 2, 2, 16
    cfg = _moe_cfg(num_layers=n_layers, moe_layer_freq=1)
    mesh = parallel_state.initialize_model_parallel(1, pp)

    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.roll(mbs, -1, axis=-1)
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), mbs[0])["params"]

    ref = float(jax.vmap(lambda ii, ll: gpt_loss(
        model, {"params": v}, ii, ll, axis_name="unbound"))(
        mbs, labels).mean())

    stacked = split_gpt_params_for_pipeline(v, pp, n_layers)
    # homogeneous layers -> scanned layout, NOT the per-position dict
    assert "k0" not in stacked["blocks"]
    first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=P(STAGE_AXIS), check_vma=False)
    def run(p, mb, lb):
        local = jax.tree.map(lambda t: t[0], p)
        # scanned layout carries the V=1 chunk axis — drop it (the
        # heterogeneous k-dict layout has none)
        sched = {"blocks": jax.tree.map(lambda t: t[0], local["blocks"]),
                 "shared": local["shared"]}
        loss, _ = fwd_bwd(stage_fn, loss_fn, sched, mb, loss_aux=lb,
                          first_fn=first_fn, loss_with_params=True)
        return loss.reshape(1)

    loss_pp = jax.jit(run)(stacked, mbs, labels)
    np.testing.assert_allclose(np.asarray(loss_pp), ref,
                               rtol=2e-5, atol=2e-5)
