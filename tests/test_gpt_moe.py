"""GPT with MoE blocks: training signal + expert-parallel loss parity.

No reference analog (apex has no MoE); same strategy as the other parallelism
suites — sharded execution on the CPU mesh must match a single-device ground
truth. The load-balance aux is per-device-batch by construction (GShard
convention), so the EP parity test zeroes the aux coefficients.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _moe_cfg(**over):
    from apex_tpu.models.gpt import gpt_tiny_config

    base = dict(num_experts=4, moe_layer_freq=2, moe_k=2,
                moe_capacity_factor=3.0)  # >= E/k: dropless
    base.update(over)
    return gpt_tiny_config(**base)


@pytest.mark.slow
def test_gpt_moe_has_routed_layers_and_grads_flow(rng):
    from apex_tpu.models.gpt import GPTModel, gpt_loss

    cfg = _moe_cfg()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)

    p = v["params"]
    # layer_freq=2 with 2 layers: layer_1 is MoE, layer_0 dense
    assert "moe_mlp" in p["layer_1"] and "mlp_in" in p["layer_0"]

    loss, g = jax.value_and_grad(
        lambda pp: gpt_loss(model, {"params": pp}, ids, labels))(p)
    assert np.isfinite(float(loss))
    router_g = g["layer_1"]["moe_mlp"]["router"]["weight"]
    assert float(jnp.sum(jnp.abs(router_g))) > 0.0
    assert float(jnp.sum(jnp.abs(g["layer_1"]["moe_mlp"]["w1"]))) > 0.0


def test_gpt_moe_aux_loss_included(rng):
    """aux coeff changes the loss value (sown intermediates are collected)."""
    from apex_tpu.models.gpt import GPTModel, gpt_loss
    import dataclasses

    cfg0 = _moe_cfg(moe_aux_loss_coeff=0.0)
    cfg1 = dataclasses.replace(cfg0, moe_aux_loss_coeff=1.0)
    ids = jnp.asarray(rng.integers(0, cfg0.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    m0, m1 = GPTModel(cfg0), GPTModel(cfg1)
    v = m0.init(jax.random.PRNGKey(0), ids)
    l0 = float(gpt_loss(m0, v, ids, labels))
    l1 = float(gpt_loss(m1, v, ids, labels))
    # balance loss >= 1 at any routing, so coeff=1 must add at least ~1
    assert l1 > l0 + 0.5


def test_gpt_moe_pipeline_rejected():
    """Pipeline stages can't express MoE yet — must fail loud, not train
    silently without the aux loss."""
    from apex_tpu.models.gpt_pipeline import make_gpt_pipeline_fns

    with pytest.raises(NotImplementedError, match="MoE"):
        make_gpt_pipeline_fns(_moe_cfg())


@pytest.mark.slow
def test_gpt_moe_expert_parallel_matches_dense(rng):
    """ep=2 over ``data`` (tokens sharded, experts sliced per rank) == the
    single-device dense-dispatch model, aux coeffs zeroed (per-device-batch
    balance loss is intentionally local)."""
    import dataclasses

    from apex_tpu.models.gpt import GPTModel, gpt_loss

    cfg = _moe_cfg(moe_aux_loss_coeff=0.0)
    ep = 2
    e_loc = cfg.num_experts // ep
    dense = GPTModel(cfg)
    par = GPTModel(dataclasses.replace(cfg, expert_parallel=True))

    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = dense.init(jax.random.PRNGKey(0), ids)
    loss_ref = float(gpt_loss(dense, v, ids, labels))

    mesh = Mesh(np.asarray(jax.devices()[:ep]).reshape(ep, 1, 1, 1),
                ("data", "stage", "context", "model"))

    def slice_experts(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if "moe_mlp" in names and names[-1] in ("w1", "b1", "w2", "b2"):
            r = lax.axis_index("data")
            return lax.dynamic_slice_in_dim(leaf, r * e_loc, e_loc, axis=0)
        return leaf

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=P(), check_vma=False)
    def ep_loss(full_params, ii, ll):
        local = jax.tree_util.tree_map_with_path(slice_experts, full_params)
        loss = gpt_loss(par, {"params": local}, ii, ll)
        return lax.pmean(loss, "data")

    loss_ep = float(jax.jit(ep_loss)(v["params"], ids, labels))
    np.testing.assert_allclose(loss_ep, loss_ref, rtol=2e-4, atol=2e-4)
