"""§5 tail: donation/aliasing regression + the metrics registry.

SURVEY.md §5 race-detection row: XLA owns device-side ordering, but
host-side donation bugs (reusing a buffer the jitted step consumed via
``donate_argnums``/``input_output_aliases``) are the one async failure mode
left — keep a regression test for them. The fused optimizers donate their
flat master/state buffers every step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam
from apex_tpu.utils import metrics


def _params():
    return {"w": jnp.ones((8, 8)), "b": jnp.zeros((8,))}


def test_donated_master_buffer_is_dead_after_step():
    """step() donates the flat master/state buffers; a caller that kept a
    reference must get a loud RuntimeError, not silently stale data."""
    opt = FusedAdam(_params(), lr=1e-2)
    master_before = opt.master
    state_before = opt.state["m"]
    opt.step(jax.tree.map(jnp.ones_like, _params()))
    assert master_before.is_deleted()
    assert state_before.is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(master_before)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(state_before)


def test_three_donated_steps_match_undonated_oracle():
    """Repeated donation must not corrupt state: 3 fused steps == 3 steps of
    a plain undonated jnp adam on the same schedule."""
    opt = FusedAdam(_params(), lr=1e-2, weight_decay=0.0)
    g = {"w": jnp.full((8, 8), 0.3), "b": jnp.full((8,), -0.1)}
    for _ in range(3):
        out = opt.step(g)

    def oracle():
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-2
        p = {k: np.asarray(v, np.float64) for k, v in _params().items()}
        m = {k: np.zeros_like(v) for k, v in p.items()}
        v = {k: np.zeros_like(x) for k, x in p.items()}
        for t in range(1, 4):
            for k in p:
                gk = np.asarray(g[k], np.float64)
                m[k] = b1 * m[k] + (1 - b1) * gk
                v[k] = b2 * v[k] + (1 - b2) * gk * gk
                mhat = m[k] / (1 - b1 ** t)
                vhat = v[k] / (1 - b2 ** t)
                p[k] = p[k] - lr * mhat / (np.sqrt(vhat) + eps)
        return p

    want = oracle()
    for k in want:
        np.testing.assert_allclose(np.asarray(out[k], np.float64), want[k],
                                   rtol=1e-5, atol=1e-7)


def test_metrics_record_inside_jit():
    metrics.clear()

    @jax.jit
    def step(x):
        y = (x ** 2).sum()
        metrics.record("loss", y)
        return y

    for i in range(3):
        step(jnp.full((4,), float(i))).block_until_ready()
    jax.effects_barrier()
    vals = metrics.get("loss")
    assert vals == [0.0, 4.0, 16.0], vals
    assert metrics.mean("loss") == pytest.approx(20.0 / 3)
    s = metrics.summary()["loss"]
    assert s["count"] == 3 and s["last"] == 16.0
    metrics.clear("loss")
    assert metrics.get("loss") == []


def test_average_meter_and_step_timer():
    m = metrics.AverageMeter("acc")
    m.update(1.0, n=2)
    m.update(4.0)
    assert m.count == 3 and m.avg == pytest.approx(2.0) and m.val == 4.0

    metrics.clear()
    t = metrics.StepTimer("t_ms")
    t.start()
    out = jax.jit(lambda x: x * 2)(jnp.ones((16,)))
    dt = t.observe(out)
    assert dt > 0 and metrics.get("t_ms") == [dt]
    with pytest.raises(RuntimeError):
        t.observe()
