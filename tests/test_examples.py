"""Examples train with decreasing loss on the CPU mesh (VERDICT round-1
item 7: BASELINE configs #1 and #3 as first real consumers of SyncBN and
Encdec MHA; plus the DCGAN multi-loss amp pattern and the simple DDP loop).
"""

import importlib.util
import os

import pytest

pytestmark = [pytest.mark.example, pytest.mark.slow]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    import sys

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # flax dataclass processing looks the module up
    spec.loader.exec_module(mod)
    return mod


def _quiet(*a, **k):
    pass


def test_imagenet_resnet_amp_syncbn_trains():
    imagenet = _load("example_imagenet", "examples/imagenet/main_amp.py")
    model = imagenet.resnet_tiny()
    losses = imagenet.run_training(model, steps=8, batch_size=8,
                                   image_size=16, opt_level="O1", lr=0.05,
                                   verbose=_quiet)
    assert losses[-1] < losses[0], losses


def test_nmt_transformer_trains():
    nmt = _load("example_nmt", "examples/nmt/main.py")
    losses = nmt.run_training(steps=12, batch=8, seq=12, vocab=64,
                              verbose=_quiet)
    assert losses[-1] < losses[0], losses


def test_dcgan_multi_loss_amp():
    dcgan = _load("example_dcgan", "examples/dcgan/main_amp.py")
    d_losses, g_losses = dcgan.run_training(steps=6, verbose=_quiet)
    assert len(d_losses) == 6 and len(g_losses) == 6
    import numpy as np

    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()


def test_simple_ddp_loop():
    mod = _load("example_simple_ddp",
                "examples/simple/distributed/distributed_data_parallel.py")
    losses = mod.run_training(steps=6, verbose=_quiet)
    assert losses[-1] < losses[0]
