"""Examples train with decreasing loss on the CPU mesh (VERDICT round-1
item 7: BASELINE configs #1 and #3 as first real consumers of SyncBN and
Encdec MHA; plus the DCGAN multi-loss amp pattern and the simple DDP loop).
"""

import importlib.util
import os

import pytest

pytestmark = [pytest.mark.example, pytest.mark.slow]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    import sys

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # flax dataclass processing looks the module up
    spec.loader.exec_module(mod)
    return mod


def _quiet(*a, **k):
    pass


def test_imagenet_resnet_amp_syncbn_trains():
    imagenet = _load("example_imagenet", "examples/imagenet/main_amp.py")
    model = imagenet.resnet_tiny()
    losses = imagenet.run_training(model, steps=8, batch_size=8,
                                   image_size=16, opt_level="O1", lr=0.05,
                                   verbose=_quiet)
    assert losses[-1] < losses[0], losses


def test_nmt_transformer_trains():
    nmt = _load("example_nmt", "examples/nmt/main.py")
    losses = nmt.run_training(steps=12, batch=8, seq=12, vocab=64,
                              verbose=_quiet)
    assert losses[-1] < losses[0], losses


def test_dcgan_multi_loss_amp():
    dcgan = _load("example_dcgan", "examples/dcgan/main_amp.py")
    d_losses, g_losses = dcgan.run_training(steps=6, verbose=_quiet)
    assert len(d_losses) == 6 and len(g_losses) == 6
    import numpy as np

    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()


def test_generation_example_decodes():
    mod = _load("example_generation",
                "examples/generation/generate_llama.py")
    out = mod.run_generation(new_tokens=6, verbose=_quiet)
    assert out.shape == (2, 12)
    sampled = mod.run_generation(new_tokens=6, temperature=0.9, top_k=8,
                                 verbose=_quiet)
    assert sampled.shape == (2, 12)
    tp = mod.run_generation(new_tokens=4, tp=2, verbose=_quiet)
    assert tp.shape == (2, 10)
    mod.run_speculative(new_tokens=6, k=3, verbose=_quiet)  # asserts parity
    seqs, scores = mod.run_beam(new_tokens=5, beams=3, verbose=_quiet)
    assert seqs.shape == (2, 3, 11)


def test_simple_ddp_loop():
    mod = _load("example_simple_ddp",
                "examples/simple/distributed/distributed_data_parallel.py")
    losses = mod.run_training(steps=6, verbose=_quiet)
    assert losses[-1] < losses[0]


def test_long_context_ring_attention_trains():
    import jax
    import jax.numpy as jnp
    import numpy as np

    lc = _load("example_long_context",
               "examples/long_context/train_ring_attention.py")
    losses = lc.run_training(steps=6, seq_len=64, cp=4, verbose=_quiet)
    assert losses[-1] < losses[0], losses

    # zigzag layout variant (round-4): same pipeline, load-balanced chunks
    z_losses = lc.run_training(steps=6, seq_len=64, cp=4, layout="zigzag",
                               verbose=_quiet)
    assert z_losses[-1] < z_losses[0], z_losses

    # the in-shard_map grads (psum over context + pmean over data) must
    # equal the plain value_and_grad of the unsharded model — review r3
    # caught the example shipping partial per-chunk grads
    from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=4)
    cfg = gpt_tiny_config(context_parallel=True, max_position_embeddings=64)
    model = GPTModel(cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 64)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids[:, :16])["params"]
    fn = lc.make_loss_and_grad_fn(model, mesh)
    loss, grads = jax.jit(fn)(params, ids, labels)

    cfg1 = gpt_tiny_config(max_position_embeddings=64)
    m1 = GPTModel(cfg1)
    ref_l, ref_g = jax.value_and_grad(
        lambda p: gpt_loss(m1, {"params": p}, ids, labels,
                           axis_name="unbound"))(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5), grads, ref_g)


def test_moe_expert_parallel_trains():
    mod = _load("example_moe_ep", "examples/moe/train_moe_ep.py")
    losses = mod.run_training(steps=6, verbose=_quiet)
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_amp_opt_level_cross_consistency():
    """L1-tier analog (reference tests/L1/common/run_test.sh + compare.py):
    the SAME model/data trained under O0 / O1 / O2 must produce close loss
    curves — bf16 compute (O1) and bf16 params (O2) may drift only within
    half-precision tolerance of the fp32 run."""
    imagenet = _load("example_imagenet_xc", "examples/imagenet/main_amp.py")

    curves = {}
    for lvl in ("O0", "O1", "O2"):
        model = imagenet.resnet_tiny()
        curves[lvl] = imagenet.run_training(
            model, steps=6, batch_size=8, image_size=16, opt_level=lvl,
            lr=0.05, verbose=_quiet)
    import numpy as np
    o0 = np.asarray(curves["O0"])
    for lvl in ("O1", "O2"):
        drift = np.max(np.abs(np.asarray(curves[lvl]) - o0))
        assert drift < 0.25, (lvl, curves[lvl], curves["O0"])
        assert curves[lvl][-1] < curves[lvl][0]
