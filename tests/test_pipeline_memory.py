"""True-1F1B schedule: parity with the autodiff oracle + O(S) memory.

VERDICT r2 next-round #3: peak pipeline activation memory must scale with
the stage count S, not the microbatch count M. The explicit 1F1B
implementation keeps a [2(S-1)+1, act] ring of in-flight stage inputs and
never differentiates through the tick scan, so XLA's reported peak for the
whole fwd+bwd step must stay ~flat as M grows 8 -> 32; the autodiff
formulation retains one stage-input residual per tick (O(M)) and is the
contrast case.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import STAGE_AXIS

from tests.test_pipeline_parallel import (
    D, loss_fn, make_params, reference_loss_and_grads, stage_fn)

pytestmark = pytest.mark.slow


@pytest.fixture
def pp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(1, 4)


def build_run(mesh, implementation):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        p = jax.tree.map(lambda t: t[0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, p, mb, loss_aux=lb,
                              implementation=implementation)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)

    return run


def test_1f1b_matches_autodiff_and_reference(pp4_mesh, rng):
    m = 8
    params4 = make_params(rng, 4)
    mbs = jnp.asarray(rng.standard_normal((m, 4, D), np.float32))
    labels = jnp.asarray(rng.standard_normal((m, 4, D), np.float32))

    ref_loss, ref_grads = reference_loss_and_grads(params4, mbs, labels)
    loss_e, grads_e = build_run(pp4_mesh, "1f1b")(params4, mbs, labels)
    loss_a, grads_a = build_run(pp4_mesh, "autodiff")(params4, mbs, labels)

    np.testing.assert_allclose(np.asarray(loss_e), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads_e, ref_grads)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads_e, grads_a)


def _peak_temp_bytes(mesh, implementation, m, width=256):
    """XLA-reported temp allocation for one pipelined fwd+bwd step."""
    run = build_run(mesh, implementation)
    params4 = {
        "w": jnp.zeros((4, width, width), jnp.float32),
        "b": jnp.zeros((4, width), jnp.float32),
    }
    mbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    lbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    compiled = (jax.jit(run)
                .lower(jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params4),
                    mbs, lbs)
                .compile())
    ma = compiled.memory_analysis()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend does not report memory analysis")
    return ma.temp_size_in_bytes


@pytest.mark.parametrize("width", [256])
def test_1f1b_memory_flat_in_microbatch_count(pp4_mesh, width):
    """The reference 1F1B contract: activations in flight ~ S, not M."""
    small = _peak_temp_bytes(pp4_mesh, "1f1b", m=8, width=width)
    big = _peak_temp_bytes(pp4_mesh, "1f1b", m=32, width=width)
    # 4x the microbatches must not cost meaningfully more temp memory
    assert big <= small * 1.35 + (1 << 20), (small, big)

    # contrast: the autodiff formulation's residuals grow ~linearly with M
    a_small = _peak_temp_bytes(pp4_mesh, "autodiff", m=8, width=width)
    a_big = _peak_temp_bytes(pp4_mesh, "autodiff", m=32, width=width)
    assert a_big >= a_small * 1.7, (a_small, a_big)
    # and at M=32 the 1F1B peak undercuts autodiff
    assert big < a_big, (big, a_big)
