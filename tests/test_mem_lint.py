"""tpu-lint mem tier (apex_tpu.analysis.mem) coverage.

Mirrors the IR tier's load-bearing pattern (tests/test_ir_lint.py) for
the fourth tier, per ISSUE 18:

1. per-rule fixture pairs — a bad PROGRAM whose static memory estimate
   triggers EXACTLY its rule (and passes with the rule deselected), and
   a good twin that is clean;
2. machinery — case anchoring, inline suppression, the trace-error
   path, tier-partitioned ``--write-baseline``, ``--diff --mem``;
3. a seeded-mutation pin: shrinking a REAL registered case's declared
   HBM budget makes the fit proof fail (and between the two peaks, the
   scan-carry rule — the two HBM rules are disjoint by construction);
4. end-to-end — ``--mem`` over the repo itself exits 0 at HEAD: the
   tier-1 twin of the ``run_tpu_round.sh`` mem gate.
"""

import dataclasses
import json
import os
import sys
import textwrap
from pathlib import Path

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
from jax import lax                                            # noqa: E402
from jax.experimental import pallas as pl                      # noqa: E402
from jax.sharding import PartitionSpec as P                    # noqa: E402

from apex_tpu.analysis import cli                              # noqa: E402
from apex_tpu.analysis.ir.harness import (AnalysisCase,        # noqa: E402
                                          CaseProgram,
                                          analysis_cases,
                                          build_case_ir)
from apex_tpu.analysis.mem import (MEM_RULES, analyze_mem,     # noqa: E402
                                   estimate_case)
from apex_tpu.analysis.mem.mem_report import (                 # noqa: E402
    findings_for_mem_case)
from apex_tpu.analysis.tiers import tier_of                    # noqa: E402

f32, i32 = jnp.float32, jnp.int32

MIB = 1024 ** 2


def _sds(shape, dtype=f32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _mesh2():
    from apex_tpu.serving.tp import abstract_tp_mesh

    return abstract_tp_mesh(2)


def _fired(ir, select=None):
    return [f.rule for f in findings_for_mem_case(ir, Path(REPO),
                                                  select=select)]


# --------------------------------------------------------------------------
# per-rule program fixture pairs
# --------------------------------------------------------------------------
# Each entry: rule -> (bad CaseProgram builder, good CaseProgram builder).
# Builders are lazy so a broken fixture fails its own test, not import.

def _hbm_bad():
    # 1 MiB input + 1 MiB matmul result = 2 MiB peak vs a 1.5 MiB budget
    def f(x):
        return x @ x
    return CaseProgram(fn=f, args=(_sds((512, 512)),),
                       meta={"hbm_budget_bytes": int(1.5 * MIB)})


def _hbm_good():
    def f(x):
        return x @ x
    return CaseProgram(fn=f, args=(_sds((512, 512)),),
                       meta={"hbm_budget_bytes": 4 * MIB})


def _scan_carry_bad():
    # the donated 1 MiB carry updates in place (peak 1 MiB) — but XLA
    # double-buffers the scan carry, so the true peak is 2 MiB; a
    # 1.5 MiB budget passes the naive sweep and fails the real one.
    # This is docs/tp_serving.md's pool-sizing lesson at lint scale.
    def f(x):
        def body(c, _):
            return c + 1.0, ()
        c, _ = lax.scan(body, x, None, length=3)
        return c
    return CaseProgram(fn=f, args=(_sds((512, 512)),), donate=(0,),
                       meta={"hbm_budget_bytes": int(1.5 * MIB)})


def _scan_carry_good():
    def f(x):
        def body(c, _):
            return c + 1.0, ()
        c, _ = lax.scan(body, x, None, length=3)
        return c
    # sized for BOTH copies of the carry — the rule's prescribed fix
    return CaseProgram(fn=f, args=(_sds((512, 512)),), donate=(0,),
                       meta={"hbm_budget_bytes": 3 * MIB})


def _vmem_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _vmem_bad():
    # one (2048, 2080) f32 block pads to ~17.8 MiB > the 16 MiB stack
    def f(x):
        return pl.pallas_call(
            _vmem_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)
    return CaseProgram(fn=f, args=(_sds((2048, 2080)),))


def _vmem_good():
    def f(x):
        return pl.pallas_call(
            _vmem_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)
    return CaseProgram(fn=f, args=(_sds((1024, 128)),))


def _padding_bad():
    # minor dim 64 pads to 128: 128 MiB logical occupies 256 MiB (2.0x,
    # 128 MiB wasted) — the PR 10 d=64 pool lesson at fixture scale
    def f(x):
        return x + 1.0
    return CaseProgram(fn=f, args=(_sds((256, 2048, 64)),))


def _padding_good():
    def f(x):
        return x + 1.0
    return CaseProgram(fn=f, args=(_sds((256, 1024, 128)),))


def _indivisible_bad():
    def f(x):
        return x * 2.0
    return CaseProgram(fn=f, args=(_sds((6, 128)),),
                       meta={"mesh_axes": {"model": 4},
                             "arg_specs": (P("model", None),)})


def _indivisible_good():
    def f(x):
        return x * 2.0
    return CaseProgram(fn=f, args=(_sds((8, 128)),),
                       meta={"mesh_axes": {"model": 4},
                             "arg_specs": (P("model", None),)})


def _replicated_bad():
    # out_specs P() promises every chip the same value, but the body
    # reduces a SHARDED operand with no psum — check_vma=False (the
    # production seam, serving/tp.py) asserts nothing
    fn = jax.shard_map(lambda v: v.sum(), mesh=_mesh2(),
                       in_specs=P("model"), out_specs=P(),
                       check_vma=False)
    return CaseProgram(fn=fn, args=(_sds((2, 128)),))


def _replicated_good():
    fn = jax.shard_map(lambda v: lax.psum(v.sum(), "model"),
                       mesh=_mesh2(), in_specs=P("model"), out_specs=P(),
                       check_vma=False)
    return CaseProgram(fn=fn, args=(_sds((2, 128)),))


def _donation_spec_bad():
    # donated buffer sharded on dim 0, only output sharded on dim 1:
    # no same-shape+dtype+spec output, the aliasing cannot happen
    fn = jax.shard_map(lambda p: p * 2.0, mesh=_mesh2(),
                       in_specs=P("model", None),
                       out_specs=P(None, "model"), check_vma=False)
    return CaseProgram(fn=fn, args=(_sds((8, 128)),), donate=(0,))


def _donation_spec_good():
    fn = jax.shard_map(lambda p: p + 1.0, mesh=_mesh2(),
                       in_specs=P("model", None),
                       out_specs=P("model", None), check_vma=False)
    return CaseProgram(fn=fn, args=(_sds((8, 128)),), donate=(0,))


def _scale_drift_prog(scale_spec):
    fn = jax.shard_map(
        lambda d: lax.psum(d["weight"].sum() * d["scale"].sum(),
                           "model"),
        mesh=_mesh2(),
        in_specs=({"scale": scale_spec, "weight": P("model", None)},),
        out_specs=P(), check_vma=False)
    args = ({"scale": _sds((256,)), "weight": _sds((256, 128))},)
    return CaseProgram(fn=fn, args=args)


def _scale_drift_bad():
    # the weight shards its 256 output channels over 'model'; its
    # per-out-channel scale replicates — each chip would scale its
    # shard with the wrong rows (the PR 16 invariant)
    return _scale_drift_prog(P())


def _scale_drift_good():
    return _scale_drift_prog(P("model"))


MEM_FIXTURES = {
    "mem-hbm-over-budget": (_hbm_bad, _hbm_good),
    "mem-scan-carry-double-buffer": (_scan_carry_bad, _scan_carry_good),
    "mem-vmem-over-budget": (_vmem_bad, _vmem_good),
    "mem-padding-blowup": (_padding_bad, _padding_good),
    "mem-spec-indivisible": (_indivisible_bad, _indivisible_good),
    "mem-replicated-no-collective": (_replicated_bad, _replicated_good),
    "mem-donation-spec-mismatch": (_donation_spec_bad,
                                   _donation_spec_good),
    "mem-scale-shard-drift": (_scale_drift_bad, _scale_drift_good),
}


def _ir_for(builder, name):
    return build_case_ir(AnalysisCase(name, "test", builder))


@pytest.mark.parametrize("rule", sorted(MEM_FIXTURES))
def test_bad_program_triggers_exactly_its_rule(rule):
    ir = _ir_for(MEM_FIXTURES[rule][0], f"bad_{rule}")
    fired = _fired(ir)
    assert fired, f"bad program for {rule} produced no findings"
    assert set(fired) == {rule}, fired


@pytest.mark.parametrize("rule", sorted(MEM_FIXTURES))
def test_good_program_is_clean(rule):
    ir = _ir_for(MEM_FIXTURES[rule][1], f"good_{rule}")
    assert not _fired(ir)


@pytest.mark.parametrize("rule", sorted(MEM_FIXTURES))
def test_mem_rules_individually_load_bearing(rule):
    """With the rule deselected (≈ its check deleted), its bad program
    passes: no other mem rule shadows it."""
    ir = _ir_for(MEM_FIXTURES[rule][0], f"bad_{rule}")
    others = [r for r in MEM_RULES if r != rule]
    assert not _fired(ir, select=others)


def test_every_mem_rule_has_a_fixture():
    assert set(MEM_RULES) == set(MEM_FIXTURES)


def test_mem_rules_are_in_the_mem_tier():
    for name in MEM_RULES:
        assert tier_of(name) == "mem", name


# --------------------------------------------------------------------------
# the estimator's model, pinned at fixture scale
# --------------------------------------------------------------------------

def test_scan_carry_peaks_are_disjoint_evidence():
    """The two HBM rules partition on (peak_no_db, peak): the donated
    in-place carry costs 1 MiB until double buffering doubles it."""
    ir = _ir_for(_scan_carry_bad, "peaks_case")
    est = estimate_case(ir)
    assert est.peak_no_db_bytes == 1 * MIB
    assert est.peak_bytes == 2 * MIB
    assert est.scan_carry_extra_bytes == 1 * MIB
    assert est.alias_bytes == 1 * MIB          # the in-place credit


def test_undonated_scan_carry_gets_no_inplace_credit():
    """Without donation the program input is not writable: both copies
    count even before double buffering (donation-ineffective at the
    memory level)."""
    prog = _scan_carry_bad()
    undonated = dataclasses.replace(prog, donate=())
    ir = build_case_ir(AnalysisCase("no_donate", "test",
                                    lambda: undonated))
    est = estimate_case(ir)
    assert est.peak_no_db_bytes == 2 * MIB
    assert est.alias_bytes == 0


def test_per_chip_scope_on_shard_map_programs():
    ir = _ir_for(_donation_spec_good, "scope_case")
    est = estimate_case(ir)
    assert est.scope == "per-chip"
    # boundary arrays carry LOCAL shard shapes: (8,128) over 2 chips
    shapes = {b.shape for b in est.boundary}
    assert (4, 128) in shapes, est.boundary


# --------------------------------------------------------------------------
# seeded mutation: shrink a REAL case's declared budget
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tp2_decode_ir():
    (case,) = [c for c in analysis_cases(REPO)
               if c.name == "tp2_engine_decode_chunk"]
    return build_case_ir(case)


def _with_budget(ir, budget):
    meta = dict(ir.prog.meta or {})
    meta["hbm_budget_bytes"] = budget
    return dataclasses.replace(ir, prog=dataclasses.replace(
        ir.prog, meta=meta))


def test_shrunk_budget_fails_the_fit_proof(tp2_decode_ir):
    """The registered tp2 decode case fits a v5e; declare a budget
    below its static peak and the fit proof must fail — proof the gate
    would catch a pool/model growth that outruns the chip."""
    est = estimate_case(tp2_decode_ir)
    assert not _fired(tp2_decode_ir), "case should be clean as shipped"
    mutated = _with_budget(tp2_decode_ir, est.peak_no_db_bytes - 1)
    assert "mem-hbm-over-budget" in _fired(mutated)


def test_budget_between_peaks_names_the_double_buffer(tp2_decode_ir):
    """A budget that fits the naive sweep but not the double-buffered
    carry blames the SCAN rule, not the generic over-budget one — each
    failure names the lesson to apply."""
    est = estimate_case(tp2_decode_ir)
    assert est.peak_no_db_bytes < est.peak_bytes, (
        "decode chunk lost its scan double-buffer charge")
    between = (est.peak_no_db_bytes + est.peak_bytes) // 2
    fired = _fired(_with_budget(tp2_decode_ir, between))
    assert "mem-scan-carry-double-buffer" in fired
    assert "mem-hbm-over-budget" not in fired


# --------------------------------------------------------------------------
# machinery: anchoring, suppression, trace errors
# --------------------------------------------------------------------------

def test_findings_anchor_into_this_file():
    """Estimate-level findings anchor at the case's def site in this
    test file; equation-level ones (vmem) at the pallas_call eqn."""
    ir = _ir_for(_hbm_bad, "anchor_case")
    findings = findings_for_mem_case(ir, Path(REPO))
    assert findings
    for f in findings:
        assert f.path == "tests/test_mem_lint.py"
        assert f.scope == "anchor_case"
        assert "[case anchor_case]" in f.message


def test_mem_finding_is_inline_suppressible(tmp_path):
    """The ordinary disable pragma at the ANCHORED line silences a mem
    finding through the same suppression cache the other tiers use."""
    from apex_tpu.analysis.ir import ir_report

    mod = tmp_path / "memprog.py"
    mod.write_text(textwrap.dedent("""\
        def hungry(x):  # tpu-lint: disable=mem-hbm-over-budget -- test
            return x @ x
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        import memprog

        def build():
            return CaseProgram(fn=memprog.hungry,
                               args=(_sds((512, 512)),),
                               meta={"hbm_budget_bytes": MIB})
        ir = build_case_ir(AnalysisCase("supp_case", "test", build))
        findings = findings_for_mem_case(ir, tmp_path)
        assert [f.rule for f in findings] == ["mem-hbm-over-budget"]
        supp = ir_report._SuppressionCache(tmp_path)
        assert supp.get(findings[0].path).covers(findings[0])
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("memprog", None)


def test_trace_error_is_a_finding_not_a_crash(monkeypatch):
    import apex_tpu.analysis.mem.mem_report as mem_report

    def boom():
        raise RuntimeError("fixture exploded")

    monkeypatch.setattr(
        mem_report, "mem_cases",
        lambda root: [AnalysisCase("boom_case", "test", boom)])
    findings, suppressed, n = analyze_mem(REPO)
    assert n == 1
    assert [f.rule for f in findings] == ["mem-trace-error"]
    assert "boom_case" in findings[0].message
    assert "fixture exploded" in findings[0].message


def test_registry_build_failure_is_a_finding(monkeypatch):
    import apex_tpu.analysis.mem.mem_report as mem_report

    def boom_registry(root):
        raise RuntimeError("tpu_aot import exploded")

    monkeypatch.setattr(mem_report, "mem_cases", boom_registry)
    findings, suppressed, n = analyze_mem(REPO)
    assert n == 0 and suppressed == 0
    assert [f.rule for f in findings] == ["mem-trace-error"]
    assert "registry" in findings[0].message
    assert "tpu_aot import exploded" in findings[0].message


def test_registry_covers_ir_cases_plus_acceptance():
    from apex_tpu.analysis.mem import ACCEPTANCE_TO_AOT, mem_cases

    names = [c.name for c in mem_cases(REPO)]
    assert len(names) == len(set(names)), "duplicate case names"
    ir_names = {c.name for c in analysis_cases(REPO)}
    assert ir_names <= set(names), "mem tier dropped IR cases"
    for acc in ACCEPTANCE_TO_AOT:
        assert acc in names, f"acceptance case {acc} missing"


# --------------------------------------------------------------------------
# CLI: usage errors, baseline partitioning, --diff
# --------------------------------------------------------------------------

def test_unknown_mem_case_and_rule_are_usage_errors(capsys):
    assert cli.main(["--root", REPO, "--mem-case", "no-such-case"]) == 2
    assert cli.main(["--root", REPO, "--mem",
                     "--select", "no-such-mem-rule"]) == 2
    # rule names from other tiers are not valid in mem mode
    assert cli.main(["--root", REPO, "--mem",
                     "--select", "ir-dead-output"]) == 2


def test_mem_rejects_paths_and_other_tiers(capsys):
    assert cli.main(["apex_tpu", "--root", REPO, "--mem"]) == 2
    assert cli.main(["--root", REPO, "--mem", "--ir"]) == 2
    assert cli.main(["--root", REPO, "--mem", "--conc"]) == 2


def test_mem_diff_refuses_baseline_flags(capsys):
    assert cli.main(["--root", REPO, "--mem", "--diff", "HEAD",
                     "--write-baseline"]) == 2
    assert cli.main(["--root", REPO, "--mem", "--diff", "HEAD",
                     "--baseline", "x.json"]) == 2


def test_mem_case_scoped_write_baseline_keeps_other_entries(tmp_path,
                                                            monkeypatch):
    """--mem-case A --write-baseline replaces only case A's mem
    entries; other mem cases' and other tiers' debt survives."""
    from apex_tpu.analysis.walker import Finding

    baseline = tmp_path / "tpu_lint_baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": {
        "x.py::mem-hbm-over-budget::case_a": 1,
        "y.py::mem-padding-blowup::case_b": 2,
        "z.py::ir-dead-output::case_c": 3,
        "w.py::host-sync-in-jit::fn": 4,
    }}))
    fresh_a = Finding(rule="mem-vmem-over-budget", severity="error",
                      path="x.py", line=1, col=1, message="m",
                      scope="case_a")
    import apex_tpu.analysis.mem as mem_pkg
    monkeypatch.setattr(mem_pkg, "analyze_mem",
                        lambda root, select=None, case=None:
                        ([fresh_a], 0, 1))
    assert cli.main(["--root", str(tmp_path), "--mem-case", "case_a",
                     "--write-baseline"]) == 0
    counts = json.loads(baseline.read_text())["findings"]
    assert counts == {
        "x.py::mem-vmem-over-budget::case_a": 1,   # case A replaced
        "y.py::mem-padding-blowup::case_b": 2,     # other mem case kept
        "z.py::ir-dead-output::case_c": 3,         # IR tier kept
        "w.py::host-sync-in-jit::fn": 4,           # AST tier kept
    }


def test_mem_diff_splits_on_base_findings(tmp_path, monkeypatch,
                                          capsys):
    """--diff BASE --mem: base-side keys absorb matching current
    findings; the remainder fails the run."""
    from collections import Counter

    from apex_tpu.analysis.walker import Finding

    old = Finding(rule="mem-hbm-over-budget", severity="error",
                  path="a.py", line=3, col=1, message="old",
                  scope="case_x")
    new = Finding(rule="mem-padding-blowup", severity="warning",
                  path="b.py", line=7, col=1, message="new",
                  scope="case_y")
    import apex_tpu.analysis.mem as mem_pkg
    monkeypatch.setattr(mem_pkg, "analyze_mem",
                        lambda root, select=None, case=None:
                        ([old, new], 0, 2))
    monkeypatch.setattr(
        cli, "_mem_base_findings",
        lambda root, rev: Counter({old.baseline_key(): 1}))
    assert cli.main(["--root", REPO, "--mem", "--diff", "BASE",
                     "--format", "json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in data["findings"]] == \
        ["mem-padding-blowup"]
    assert [f["rule"] for f in data["baselined"]] == \
        ["mem-hbm-over-budget"]
    # base side covering everything -> clean exit
    monkeypatch.setattr(
        cli, "_mem_base_findings",
        lambda root, rev: Counter({old.baseline_key(): 1,
                                   new.baseline_key(): 1}))
    assert cli.main(["--root", REPO, "--mem", "--diff", "BASE"]) == 0


@pytest.mark.slow       # a second full --mem run, in the worktree
def test_mem_diff_base_side_runs_in_a_worktree():
    """The real base-side runner materializes HEAD in a worktree and
    runs its --mem there. HEAD ships this very tier, and the repo is
    clean at HEAD, so the base side must come back empty — this also
    proves the worktree run actually executes (a crash would raise)."""
    counts = cli._mem_base_findings(Path(REPO), "HEAD")
    assert sum(counts.values()) == 0, counts


def test_mem_diff_base_rev_without_tier_is_empty(capsys):
    """A base rev that predates --mem contributes no findings (its CLI
    exits 2 on the unknown flag); the diff then degrades to the
    absolute gate instead of crashing."""
    # the growth seed commit has no apex_tpu.analysis at all
    import subprocess

    seed = subprocess.run(
        ["git", "-C", REPO, "rev-list", "--max-parents=0", "HEAD"],
        capture_output=True, text=True).stdout.split()[0]
    counts = cli._mem_base_findings(Path(REPO), seed)
    assert sum(counts.values()) == 0


# --------------------------------------------------------------------------
# end-to-end: the repo's programs fit their chips (the mem gate)
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_repo_mem_is_clean_at_head(capsys):
    """The full-registry mem gate (~85 s: every case re-traced). Slow
    tier to hold the tier-1 verify wall; run_tpu_round.sh runs the same
    gate on every round, and test_mem_gate_case_is_clean_at_head below
    is the fast tier-1 twin."""
    rc = cli.main(["--root", REPO, "--mem"])
    out = capsys.readouterr().out
    assert rc == 0, f"tpu-lint --mem found new issues in the repo:\n{out}"


def test_mem_gate_case_is_clean_at_head(capsys):
    """Tier-1 twin of the full gate: one real registry case through the
    whole pipeline — trace, estimate, all 8 rules, baseline, exit code.
    tp2_engine_decode_chunk is the load-bearing choice: a shard_map
    program with mesh_axes/arg_specs meta, so the sharding-contract
    rules run against real engine specs, not just fixtures."""
    rc = cli.main(["--root", REPO, "--mem-case", "tp2_engine_decode_chunk"])
    out = capsys.readouterr().out
    assert rc == 0, f"tpu-lint --mem-case found new issues:\n{out}"
    assert "0 finding(s)" in out
