"""Paged KV pool + continuous-batching engine (apex_tpu/serving).

Invariant tier (no model): block-table alloc/free/defrag keep the pool
consistent — disjoint ownership, exact free counts, null page never
handed out, defrag preserves page contents under remapping.

Engine tier (tiny GPT): greedy outputs are token-identical to per-request
lock-step ``generate`` on a mixed-length workload with more requests than
slots; EOS retirement frees slots early; and the whole set completes in
FEWER decode steps than lock-step padding to the longest request (the
acceptance bar for the continuous-batching design)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import (PagedDecodeEngine, Request, alloc_slot, defrag,
                              free_page_count, free_slot, init_paged_cache,
                              pages_for, prefill_into_pages)


def _owned_pages(cache, slot):
    n = int(cache["alloc_pages"][slot])
    return set(np.asarray(cache["block_tables"][slot][:n]).tolist())


def test_alloc_free_invariants():
    cfg = gpt_tiny_config()
    cache = init_paged_cache(cfg, num_slots=3, num_pages=12, page_size=8)
    assert int(free_page_count(cache)) == 11      # page 0 reserved

    cache = alloc_slot(cache, 0, 3)
    cache = alloc_slot(cache, 1, 4)
    cache = alloc_slot(cache, 2, 2)
    assert int(free_page_count(cache)) == 11 - 9
    own = [_owned_pages(cache, s) for s in range(3)]
    assert all(0 not in o for o in own)           # null page never allocated
    assert len(own[0] | own[1] | own[2]) == 9     # disjoint ownership
    # free stack + owned pages partition pages 1..11
    free = set(np.asarray(
        cache["free_stack"][:int(cache["free_top"])]).tolist())
    assert free | own[0] | own[1] | own[2] == set(range(1, 12))

    cache["len"] = cache["len"].at[1].set(13)     # slot 1 wrote 13 tokens
    cache = free_slot(cache, 1)
    assert int(free_page_count(cache)) == 11 - 9 + 4   # ALL owned pages back
    assert int(cache["len"][1]) == 0
    assert int(cache["alloc_pages"][1]) == 0
    assert (np.asarray(cache["block_tables"][1]) == 0).all()
    # freed pages are re-allocatable and still disjoint from survivors
    cache = alloc_slot(cache, 1, 4)
    own = [_owned_pages(cache, s) for s in range(3)]
    assert len(own[0] | own[1] | own[2]) == 9


def test_alloc_free_jittable():
    cfg = gpt_tiny_config()
    cache = init_paged_cache(cfg, num_slots=2, num_pages=8, page_size=8)
    cache = jax.jit(alloc_slot)(cache, jnp.int32(0), jnp.int32(3))
    assert int(free_page_count(cache)) == 4
    cache = jax.jit(free_slot)(cache, jnp.int32(0))
    assert int(free_page_count(cache)) == 7


def test_defrag_preserves_contents_and_collects(rng):
    cfg = gpt_tiny_config()
    cache = init_paged_cache(cfg, num_slots=2, num_pages=16, page_size=8)
    # fill the pool with recognizable per-page values
    shape = cache["layers"][0]["k_pages"].shape
    marks = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    cache["layers"] = [{"k_pages": marks, "v_pages": -marks}
                       for _ in cache["layers"]]
    cache = alloc_slot(cache, 0, 3)
    cache = alloc_slot(cache, 1, 2)      # then free -> fragmentation holes
    cache["len"] = cache["len"].at[0].set(20)
    cache = free_slot(cache, 1)
    cache = alloc_slot(cache, 1, 4)
    cache["len"] = cache["len"].at[1].set(9)

    def gather(cache, slot, layer=0):
        n = int(cache["alloc_pages"][slot])
        bt = np.asarray(cache["block_tables"][slot][:n])
        return np.asarray(cache["layers"][layer]["k_pages"])[bt]

    before = [gather(cache, s) for s in range(2)]
    free_before = int(free_page_count(cache))
    cache = defrag(cache)
    after = [gather(cache, s) for s in range(2)]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(a, b)       # contents follow the remap
    assert int(free_page_count(cache)) == free_before
    # compaction: live pages (null + 7 owned) occupy the low ids
    own = _owned_pages(cache, 0) | _owned_pages(cache, 1)
    assert own == set(range(1, 8))
    # defrag is jittable (pure index ops)
    cache2 = jax.jit(defrag)(cache)
    np.testing.assert_array_equal(np.asarray(cache2["block_tables"]),
                                  np.asarray(cache["block_tables"]))


def test_prefill_scatter_roundtrip(rng):
    """prefill_into_pages places position p at table entry p//ps, offset
    p%ps — gathering the pages back must reproduce the contiguous K/V."""
    cfg = gpt_tiny_config()
    ps, s0, bucket = 8, 13, 16
    cache = init_paged_cache(cfg, num_slots=1, num_pages=8, page_size=ps)
    cache = alloc_slot(cache, 0, pages_for(s0, ps))
    kv = cache["layers"][0]["k_pages"].shape[1]
    d = cache["layers"][0]["k_pages"].shape[3]
    contig = [{"k": jnp.asarray(rng.standard_normal((1, kv, bucket, d)),
                                jnp.float32),
               "v": jnp.asarray(rng.standard_normal((1, kv, bucket, d)),
                                jnp.float32)}
              for _ in range(cfg.num_layers)]
    cache = prefill_into_pages(cache, 0, contig, jnp.int32(s0))
    assert int(cache["len"][0]) == s0
    bt = np.asarray(cache["block_tables"][0])
    for li in range(cfg.num_layers):
        pages = np.asarray(cache["layers"][li]["k_pages"])
        want = np.asarray(contig[li]["k"][0])     # (kv, bucket, d)
        for p in range(s0):
            np.testing.assert_array_equal(pages[bt[p // ps], :, p % ps, :],
                                          want[:, p, :])


def test_engine_matches_lockstep_mixed_lengths(rng):
    """The acceptance bar: mixed-length prompts, more requests than
    slots — greedy outputs token-identical to per-request lock-step
    generate, AND fewer engine decode steps than lock-step padding."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    init_ids = jnp.zeros((1, 8), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), init_ids)

    lengths = [5, 16, 9, 23, 12]
    max_new = [6, 3, 8, 4, 7]
    reqs = [Request(prompt=np.asarray(
                rng.integers(0, cfg.vocab_size, (L,)), np.int32),
                max_new_tokens=m)
            for L, m in zip(lengths, max_new)]

    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8)
    outs, stats = engine.run(reqs)

    for req, out in zip(reqs, outs):
        ref = np.asarray(generate(model, v, np.asarray(req.prompt)[None],
                                  max_new_tokens=req.max_new_tokens))
        np.testing.assert_array_equal(out, ref[0, req.prompt.shape[0]:])

    # lock-step at the same 2-slot capacity pads every batch to the
    # longest member's budget: 3 batches x max(max_new) worst case; even
    # the best static grouping can't beat per-slot retirement + refill
    lockstep_steps = int(np.ceil(len(reqs) / 2)) * max(max_new)
    assert stats["decode_steps"] < lockstep_steps
    assert stats["peak_slots_in_use"] == 2
    # every page returned after the queue drains
    assert int(free_page_count(engine.cache)) == \
        engine.cache["free_stack"].shape[0] - 1


def test_engine_eos_retirement_and_refill(rng):
    """A request whose first greedy token is EOS retires at admission (0
    decode steps) and its slot/pages immediately serve the next request."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    free = np.asarray(generate(model, v, prompt, max_new_tokens=4))
    eos = int(free[0, 8])

    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                               eos_token_id=eos)
    other = np.asarray(rng.integers(0, cfg.vocab_size, (6,)), np.int32)
    outs, stats = engine.run([
        Request(prompt=np.asarray(prompt[0]), max_new_tokens=4),
        Request(prompt=other, max_new_tokens=3),
    ])
    assert outs[0].tolist() == [eos]
    ref = np.asarray(generate(model, v, other[None], max_new_tokens=3,
                              eos_token_id=eos))[0, 6:]
    first = np.where(ref == eos)[0]
    want = ref[:first[0] + 1] if first.size else ref
    np.testing.assert_array_equal(outs[1], want)
    assert int(free_page_count(engine.cache)) == \
        engine.cache["free_stack"].shape[0] - 1


@pytest.mark.slow
def test_generate_paged_rectangular_matches_generate(rng):
    """generate(paged=True) on a rectangular batch returns the exact
    lock-step array (prompt + tokens, EOS padding semantics)."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (3, 16)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=6))
    out = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                              paged=True, page_size=8))
    np.testing.assert_array_equal(out, ref)

    # and with EOS: lock-step pads EOS rows; the engine retires them —
    # same output array either way
    eos = int(ref[0, 17])
    ref_e = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                                eos_token_id=eos))
    out_e = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                                eos_token_id=eos, paged=True, page_size=8))
    np.testing.assert_array_equal(out_e, ref_e)


@pytest.mark.slow
def test_engine_sync_every_and_sampling_invariance(rng):
    """sync_every > 1 batches steps between host syncs without changing
    greedy output; sampled decode keys derive from the request index, so
    outputs are invariant to slot count / scheduling."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    init_ids = jnp.zeros((1, 8), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), init_ids)
    reqs = [Request(prompt=np.asarray(
                rng.integers(0, cfg.vocab_size, (L,)), np.int32),
                max_new_tokens=5)
            for L in (6, 9, 14)]

    e_sync = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                               sync_every=4)
    outs, _ = e_sync.run(reqs)
    for req, out in zip(reqs, outs):
        ref = np.asarray(generate(model, v, np.asarray(req.prompt)[None],
                                  max_new_tokens=5))
        np.testing.assert_array_equal(out, ref[0, req.prompt.shape[0]:])

    key = jax.random.PRNGKey(3)
    kw = dict(page_size=8, temperature=1.0, top_k=8, rng=key)
    o1, _ = PagedDecodeEngine(model, v, num_slots=1, **kw).run(reqs)
    o2, _ = PagedDecodeEngine(model, v, num_slots=3, **kw).run(reqs)
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)


def test_engine_validates_requests(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    init_ids = jnp.zeros((1, 8), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), init_ids)
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8)
    with pytest.raises(ValueError):      # position cap
        engine.run([Request(prompt=np.zeros((8,), np.int32),
                            max_new_tokens=cfg.max_position_embeddings)])
    with pytest.raises(ValueError):
        engine.run([Request(prompt=np.zeros((8,), np.int32),
                            max_new_tokens=0)])
    # a request whose page demand exceeds the whole pool deadlocks loudly
    small = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                              num_pages=3)
    with pytest.raises(RuntimeError):
        small.run([Request(prompt=np.zeros((30,), np.int32),
                           max_new_tokens=10)])