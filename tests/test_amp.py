"""amp policy/scaler tests — mirrors tests/L0/run_amp from the reference
(cast behavior, dynamic loss scaling incl. inf-skip and scale growth/backoff,
checkpointing of scaler state)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def params():
    return {
        "dense": {"kernel": jnp.ones((8, 8)), "bias": jnp.ones((8,))},
        "layernorm": {"weight": jnp.ones((8,)), "bias": jnp.zeros((8,))},
    }


def test_opt_levels_policy():
    for lvl, pd, cd in [("O0", jnp.float32, jnp.float32),
                        ("O1", jnp.float32, jnp.bfloat16),
                        ("O2", jnp.bfloat16, jnp.bfloat16),
                        ("O3", jnp.bfloat16, jnp.bfloat16)]:
        pol = amp.make_policy(lvl)
        assert pol.param_dtype == pd and pol.compute_dtype == cd, lvl
    with pytest.raises(ValueError):
        amp.make_policy("O4")


def test_initialize_o2_casts_but_keeps_norm_fp32():
    p = params()
    opt = FusedAdam(p, lr=1e-3)
    cast_p, opt2 = amp.initialize(p, opt, opt_level="O2")
    assert cast_p["dense"]["kernel"].dtype == jnp.bfloat16
    assert cast_p["layernorm"]["weight"].dtype == jnp.float32  # keep_batchnorm_fp32
    # bf16 static scale 1.0: no scaler attached (no per-step stats pass), but
    # output dtypes are registered so step() keeps the model half
    assert opt2 is opt and opt._amp_scaler is None
    assert opt._out_dtypes is not None


def test_o2_step_returns_cast_dtypes():
    """After O2 initialize, step() must hand back HALF params (master->model
    copy), not the fp32 dtypes the optimizer was built with."""
    p = params()
    opt = FusedAdam(p, lr=1e-3)
    cast_p, opt = amp.initialize(p, opt, opt_level="O2")
    out = opt.step(jax.tree.map(jnp.ones_like, cast_p))
    assert out["dense"]["kernel"].dtype == jnp.bfloat16
    assert out["layernorm"]["weight"].dtype == jnp.float32


def test_noop_does_not_advance_step_count():
    """Skipped (overflow) steps must not advance Adam bias correction —
    the reference skips optimizer.step() entirely."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2)
    amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                   loss_scale="dynamic")
    opt.step({"w": jnp.full((4, 4), jnp.inf, jnp.float16)})
    assert int(opt.step_count) == 0
    opt.step({"w": jnp.full((4, 4), 0.5, jnp.float16)})
    assert int(opt.step_count) == 1


def test_enabled_false_passthrough_shapes():
    p = params()
    assert amp.initialize(p, enabled=False) is p
    opt = FusedAdam(p, lr=1e-3)
    m, o = amp.initialize(p, opt, enabled=False)
    assert m is p and o is opt


def test_scale_clamped_at_max():
    from apex_tpu.amp.scaler import LossScaler

    s = LossScaler("dynamic", init_scale=2.0 ** 23, scale_window=1,
                   max_loss_scale=2.0 ** 24)
    st = s.state
    z = jnp.zeros(())
    st = s.update(st, z)
    assert float(st.scale) == 2.0 ** 24
    st = s.update(st, z)
    assert float(st.scale) == 2.0 ** 24  # capped (reference max_loss_scale)


def test_o2_master_params_roundtrip():
    p = params()
    opt = FusedAdam(p, lr=1e-3)
    amp.initialize(p, opt, opt_level="O2")
    masters = amp.master_params(opt)
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(masters))
    np.testing.assert_allclose(np.asarray(masters["dense"]["kernel"]), 1.0)


def test_scale_loss_and_fused_unscale_fp16_dynamic():
    """fp16 + dynamic scaling: grads of the scaled loss are unscaled inside
    step; master update matches the unscaled-gradient update."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2, weight_decay=0.0)
    _, opt = amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                            loss_scale="dynamic")
    scale0 = float(opt._amp_scaler.state.scale)
    assert scale0 == 2.0 ** 16

    with amp.scale_loss(jnp.float32(1.0), opt) as sl:
        assert float(sl) == scale0

    # grads as if computed from a scaled loss
    g_unscaled = jnp.full((4, 4), 0.5)
    out = opt.step({"w": g_unscaled * scale0})
    # reference: one unscaled adam step from ones with g=0.5
    m = 0.1 * 0.5
    v = 0.001 * 0.25
    want = 1.0 - 1e-2 * (m / (1 - 0.9)) / (np.sqrt(v / (1 - 0.999)) + 1e-8)
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), want, rtol=1e-3)


def test_dynamic_scaler_backoff_on_inf():
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2)
    amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                   loss_scale="dynamic")
    scale0 = float(opt._amp_scaler.state.scale)
    out = opt.step({"w": jnp.full((4, 4), jnp.inf)})
    # step skipped, scale halved
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), 1.0)
    assert float(opt._amp_scaler.state.scale) == scale0 / 2
    np.testing.assert_allclose(np.asarray(opt.state["m"]), 0.0)


def test_dynamic_scaler_growth():
    from apex_tpu.amp.scaler import LossScaler

    s = LossScaler("dynamic", init_scale=4.0, scale_window=3)
    st = s.state
    zero = jnp.zeros(())
    for _ in range(3):
        st = s.update(st, zero)
    assert float(st.scale) == 8.0 and int(st.growth_tracker) == 0
    st = s.update(st, jnp.ones(()))
    assert float(st.scale) == 4.0


def test_scaler_hysteresis():
    """Reference: csrc/update_scale_hysteresis.cu — tolerate hysteresis-1
    overflows before halving; the budget refills only when the scale grows
    (the .cu kernel resets the tracker inside the growth branch), so
    intermittent overflows accumulate; continued overflow past zero keeps
    halving every overflowing step."""
    from apex_tpu.amp.scaler import LossScaler

    s = LossScaler("dynamic", init_scale=16.0, hysteresis=2, scale_window=2)
    st = s.state
    inf, zero = jnp.ones(()), jnp.zeros(())

    st = s.update(st, inf)            # 1st overflow: tolerated
    assert float(st.scale) == 16.0 and int(st.hysteresis_tracker) == 1
    st = s.update(st, inf)            # 2nd: budget hits 0 -> halves
    assert float(st.scale) == 8.0
    st = s.update(st, inf)            # still overflowing: halves again
    assert float(st.scale) == 4.0

    st = s.update(st, zero)           # clean step does NOT refill
    assert int(st.hysteresis_tracker) == 0
    st = s.update(st, inf)            # intermittent overflow still halves
    assert float(st.scale) == 2.0

    st = s.update(st, zero)           # two clean steps -> growth fires
    st = s.update(st, zero)
    assert float(st.scale) == 4.0
    assert int(st.hysteresis_tracker) == 2   # budget refilled on growth
    st = s.update(st, inf)            # tolerated again
    assert float(st.scale) == 4.0 and int(st.hysteresis_tracker) == 1

    # default hysteresis=1 is the classic halve-on-every-overflow
    s1 = LossScaler("dynamic", init_scale=16.0)
    st1 = s1.update(s1.state, inf)
    assert float(st1.scale) == 8.0


def test_amp_state_dict_roundtrip():
    p = {"w": jnp.ones((2, 2))}
    opt = FusedAdam(p, lr=1e-3)
    amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                   loss_scale="dynamic")
    opt.step({"w": jnp.full((2, 2), jnp.inf)})  # halves the scale
    sd = amp.state_dict()
    assert float(sd["loss_scaler0"]["scale"]) == 2.0 ** 15
    amp.load_state_dict(sd)


def test_static_loss_scale_bf16_noop():
    """bf16 default: loss_scale 1.0, scale_loss is identity."""
    p = {"w": jnp.ones((2, 2))}
    opt = FusedAdam(p, lr=1e-3)
    amp.initialize(p, opt, opt_level="O2")  # bf16
    with amp.scale_loss(jnp.float32(3.5), opt) as sl:
        assert float(sl) == 3.5


def test_fp16_utils():
    from apex_tpu import fp16_utils

    p = params()
    h = fp16_utils.network_to_half(p)
    assert h["dense"]["kernel"].dtype == jnp.bfloat16
    h2 = fp16_utils.BN_convert_float(h)
    assert h2["layernorm"]["weight"].dtype == jnp.float32
    assert h2["dense"]["kernel"].dtype == jnp.bfloat16

    opt = FusedAdam(p, lr=1e-3)
    fo = fp16_utils.FP16_Optimizer(opt, dynamic_loss_scale=True)
    assert fo.loss_scale == 2.0 ** 16
    out = fo.step(jax.tree.map(jnp.ones_like, p))
    assert jax.tree.structure(out) == jax.tree.structure(p)


def test_multi_loss_single_optimizer_dynamic():
    """Reference: handle.py scale_loss(loss, opt, loss_id=i) with
    num_losses=2 on ONE optimizer — per-loss scalers diverge (one overflows
    and halves, the other grows), the step skips on the union found-inf, and
    a clean combined step matches the plain two-loss update."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2, weight_decay=0.0)
    _, opt = amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                            loss_scale="dynamic", num_losses=2)
    # multi-loss path: no scaler fused into the step
    assert opt._amp_scaler is None
    s0, s1 = amp._loss_scalers
    scale0, scale1 = float(s0.state.scale), float(s1.state.scale)

    with amp.scale_loss(jnp.float32(1.0), opt, loss_id=1) as sl:
        assert float(sl) == scale1

    # ---- clean step: grads of each SCALED loss, combined ----
    g0 = {"w": jnp.full((4, 4), 0.25) * scale0}
    g1 = {"w": jnp.full((4, 4), 0.25) * scale1}
    grads, noop = amp.unscale_and_combine([g0, g1])
    assert float(noop) == 0.0
    np.testing.assert_allclose(np.asarray(grads["w"], np.float32), 0.5,
                               rtol=1e-6)
    out = opt.step(grads, noop=noop)
    m, v = 0.1 * 0.5, 0.001 * 0.25
    want = 1.0 - 1e-2 * (m / (1 - 0.9)) / (np.sqrt(v / (1 - 0.999)) + 1e-8)
    np.testing.assert_allclose(np.asarray(out["w"], np.float32), want,
                               rtol=1e-3)

    # ---- loss 0 overflows: ITS scaler halves, loss 1's grows; step skips ----
    g0_inf = {"w": jnp.full((4, 4), jnp.inf)}
    g1_ok = {"w": jnp.full((4, 4), 0.1) * float(s1.state.scale)}
    step_before = int(opt.step_count)
    master_before = np.asarray(opt.master)
    grads, noop = amp.unscale_and_combine([g0_inf, g1_ok])
    assert float(noop) == 1.0
    out = opt.step(grads, noop=noop)
    assert float(s0.state.scale) == scale0 / 2          # overflow backoff
    assert float(s1.state.scale) == float(scale1)       # clean: unchanged
    assert int(s1.state.growth_tracker) == 2            # two clean steps
    assert int(opt.step_count) == step_before           # skipped
    np.testing.assert_allclose(np.asarray(opt.master), master_before)


def test_multi_loss_scaler_growth_divergence():
    """After scale_window clean steps on loss 1 only, its scale doubles
    while loss 0's (halved by an earlier inf) stays put."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2)
    _, opt = amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                            loss_scale="dynamic", num_losses=2)
    s0, s1 = amp._loss_scalers
    s0.state = s0.state._replace(scale=jnp.float32(1024.0))
    s1.state = s1.state._replace(
        scale=jnp.float32(2048.0),
        growth_tracker=jnp.int32(s1._scale_window - 1))
    g = {"w": jnp.ones((4, 4))}
    _, noop = amp.unscale_and_combine(
        [{"w": g["w"] * 1024.0}, {"w": g["w"] * 2048.0}])
    assert float(noop) == 0.0
    assert float(s0.state.scale) == 1024.0
    assert float(s1.state.scale) == 4096.0   # grew on its own window


def test_multi_loss_static_scale_rejects_unscale_and_combine():
    """Static-scale multi-loss keeps the fused in-step unscale (the scaler
    stays attached); unscale_and_combine must refuse rather than silently
    unscale twice."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2)
    _, opt = amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                            loss_scale=256.0, num_losses=2)
    assert opt._amp_scaler is not None   # fused unscale stays attached
    with pytest.raises(RuntimeError, match="static"):
        amp.unscale_and_combine([{"w": jnp.ones((4, 4))},
                                 {"w": jnp.ones((4, 4))}])


def test_multi_loss_dynamic_step_without_noop_raises():
    """A caller skipping the unscale_and_combine protocol must fail loudly,
    not silently apply 2**16-scaled grads."""
    p = {"w": jnp.ones((4, 4))}
    opt = FusedAdam(p, lr=1e-2)
    _, opt = amp.initialize(p, opt, opt_level="O2", half_dtype=jnp.float16,
                            loss_scale="dynamic", num_losses=2)
    with pytest.raises(RuntimeError, match="unscale_and_combine"):
        opt.step({"w": jnp.ones((4, 4))})


def test_unscale_and_combine_graceful_when_amp_disabled(monkeypatch):
    monkeypatch.setattr(amp, "_loss_scalers", [])
    g, noop = amp.unscale_and_combine([{"w": jnp.ones((2,))},
                                       {"w": jnp.full((2,), 2.0)}])
    np.testing.assert_allclose(np.asarray(g["w"]), 3.0)
    assert float(noop) == 0.0


@pytest.mark.slow
def test_fp16_bert_end_to_end_overflow_skip_halve_refill(rng):
    """VERDICT r4 stretch #7: a TRUE-fp16 (half_dtype=float16) BERT step
    where the overflow arises inside the real scaled backward — not from
    injected inf grads — and the fused scaler path is observed doing the
    reference's full dance: tolerate (hysteresis=2), halve, skip without
    advancing step_count, recover, grow after scale_window clean steps,
    and refill the hysteresis budget ONLY on growth
    (csrc/update_scale_hysteresis.cu semantics)."""
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.models import (BertForPreTraining, bert_tiny_config,
                                 synthetic_batch)
    from apex_tpu.models.bert import bert_pretrain_loss

    cfg = bert_tiny_config()
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, 2, 32)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    opt = FusedAdam(params, lr=1e-3, weight_decay=0.0)
    params, opt = amp.initialize(params, opt, opt_level="O2",
                                 half_dtype=jnp.float16)
    # non-norm params really are fp16 (the cotangents live there too)
    assert params["word_embeddings"].dtype == jnp.float16
    # scale forced to the cap so the fp16 backward MUST overflow; short
    # growth window + hysteresis 2 make every phase observable in a few
    # steps (attach_amp_scaler is the public rewiring hook)
    scaler = LossScaler("dynamic", init_scale=2.0 ** 24, hysteresis=2,
                        scale_window=3)
    opt.attach_amp_scaler(scaler)

    positions = batch.get("mlm_positions")
    labels = (batch["mlm_gathered_labels"] if positions is not None
              else batch["mlm_labels"])

    def scaled_loss(p, scale):
        mlm_logits, nsp_logits = model.apply(
            {"params": p}, batch["input_ids"], batch["token_type_ids"],
            batch["attention_mask"], deterministic=True,
            masked_positions=positions)
        return bert_pretrain_loss(mlm_logits, nsp_logits, labels,
                                  batch["nsp_labels"]) * scale

    grad_fn = jax.jit(jax.grad(scaled_loss))

    events = []   # (scale_before, hyst_before, applied_count_after)
    p_cur = params
    grew = False
    for _ in range(60):
        scale_before = float(scaler.state.scale)
        hyst_before = int(scaler.state.hysteresis_tracker)
        grads = grad_fn(p_cur, scaler.state.scale)
        p_cur = opt.step(grads)
        events.append((scale_before, hyst_before,
                       int(opt.step_count), float(scaler.state.scale)))
        if float(scaler.state.scale) > scale_before:
            grew = True
            break

    scales = [e[0] for e in events]
    applied = [e[2] for e in events]
    # 1. the first step overflowed in the real backward: tolerated by
    # hysteresis (scale unchanged, budget 2 -> 1, step NOT applied)
    assert applied[0] == 0, "first step at scale 2^24 must be skipped"
    assert events[0][3] == scales[0], "hysteresis must absorb overflow #1"
    # 2. the second overflow exhausts the budget and halves the scale
    assert events[1][3] == scales[1] / 2, "overflow #2 must halve"
    # 3. halving continues until the backward stops overflowing, then
    # steps apply (step_count advances only on applied steps)
    assert grew, "scale never grew — no clean-step recovery observed"
    n_applied = applied[-1]
    assert n_applied >= 3, "need scale_window clean steps before growth"
    first_applied = next(i for i, a in enumerate(applied) if a > 0)
    assert scales[first_applied] < 2.0 ** 24, (
        "recovery must follow at least one halve")
    # 4. growth doubled the scale and REFILLED the hysteresis budget
    assert float(scaler.state.scale) == scales[-1] * 2
    assert int(scaler.state.hysteresis_tracker) == 2, (
        "hysteresis budget must refill on growth (refill-on-growth rule)")
    # 5. the skipped steps really left the master untouched: total applied
    # steps << total loop steps yet the final loss is finite and the
    # master buffer is finite
    assert np.isfinite(np.asarray(opt.master)).all()
    assert n_applied < len(events)
