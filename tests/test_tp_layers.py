"""TP layers vs single-device ground truth.

Mirrors the reference suites tests/L0/run_transformer/test_layers.py (TP
linears vs nn.Linear), test_cross_entropy.py (vocab-parallel CE vs plain CE),
test_random.py (RNG tracker), test_data.py (broadcast_data) — on the 8-device
CPU mesh instead of multi-process NCCL.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS


@pytest.fixture
def tp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(4)


def _gather_shards(arrs, axis):
    return np.concatenate([np.asarray(a) for a in arrs], axis=axis)


# --- ColumnParallelLinear ----------------------------------------------------

def test_column_parallel_linear_matches_dense(tp4_mesh, rng):
    from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

    layer = ColumnParallelLinear(16, 32, gather_output=True)
    x = jnp.asarray(rng.standard_normal((6, 16), dtype=np.float32))

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh, in_specs=P(),
        out_specs=(P(), P(MODEL_AXIS)), check_vma=False)
    def init_and_run(xx):
        v = layer.init(jax.random.PRNGKey(7), xx)
        y = layer.apply(v, xx)
        return y, v["params"]["weight"]

    y, w_shards = init_and_run(x)
    # reconstruct the full weight from the shards; output must equal x @ W^T
    w_full = np.asarray(w_shards).reshape(32, 16)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ w_full.T,
                               rtol=1e-5, atol=1e-5)
    # shards must be decorrelated (per-rank init)
    w4 = np.asarray(w_shards)
    assert not np.allclose(w4[0], w4[1])


@pytest.mark.slow
def test_column_parallel_linear_grad_matches_dense(tp4_mesh, rng):
    from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

    layer = ColumnParallelLinear(8, 16, gather_output=True, bias=True,
                                 world_size=4)
    x = jnp.asarray(rng.standard_normal((4, 8), dtype=np.float32))
    dense = ColumnParallelLinear(8, 16, gather_output=False, bias=True,
                                 world_size=1, axis_name="nope")
    v_dense = dense.init(jax.random.PRNGKey(0), x)
    w_full = np.asarray(v_dense["params"]["weight"])   # (16, 8)
    b_full = np.asarray(v_dense["params"]["bias"])

    def ref_loss(v, xx):
        y = xx @ jnp.asarray(w_full).T + jnp.asarray(b_full)
        del v
        return jnp.sum(y * y)

    # build sharded variables holding the SAME weight values
    w_shards = w_full.reshape(4, 4, 8)
    b_shards = b_full.reshape(4, 4)

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh,
        in_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P()),
        out_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS)))
    def sharded_loss_and_grad(ws, bs, xx):
        v = {"params": {"weight": ws.reshape(4, 8), "bias": bs.reshape(4)}}

        def loss(vv):
            y = layer.apply(vv, xx)
            return jnp.sum(y * y)

        l, g = jax.value_and_grad(loss)(v)
        # loss is numerically identical on every rank (y was gathered) but
        # VMA can't prove it — emit per-rank and take shard 0 outside
        return l.reshape(1), g["params"]["weight"][None], g["params"]["bias"][None]

    l4, gw_sh, gb_sh = sharded_loss_and_grad(
        jnp.asarray(w_shards.reshape(16, 8)), jnp.asarray(b_shards.reshape(16)), x)
    l = l4[0]
    np.testing.assert_allclose(np.asarray(l4), float(l), rtol=1e-6)

    # dense reference grads
    def dense_loss(w, b):
        y = x @ w.T + b
        return jnp.sum(y * y)

    gw_ref, gb_ref = jax.grad(dense_loss, argnums=(0, 1))(
        jnp.asarray(w_full), jnp.asarray(b_full))
    np.testing.assert_allclose(float(l), float(dense_loss(
        jnp.asarray(w_full), jnp.asarray(b_full))), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_sh).reshape(16, 8),
                               np.asarray(gw_ref), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb_sh).reshape(16),
                               np.asarray(gb_ref), rtol=1e-4, atol=1e-4)


# --- RowParallelLinear -------------------------------------------------------

@pytest.mark.slow
def test_row_parallel_linear_matches_dense(tp4_mesh, rng):
    from apex_tpu.transformer.tensor_parallel import RowParallelLinear

    layer = RowParallelLinear(16, 8, input_is_parallel=False, bias=True)
    x = jnp.asarray(rng.standard_normal((6, 16), dtype=np.float32))

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh, in_specs=P(),
        out_specs=(P(), P(MODEL_AXIS), P()), check_vma=False)
    def run(xx):
        v = layer.init(jax.random.PRNGKey(3), xx)
        return layer.apply(v, xx), v["params"]["weight"][None], v["params"]["bias"]

    y, w_shards, b = run(x)
    # full weight: shards are (8, 4) along input dim
    w_full = np.concatenate(list(np.asarray(w_shards)), axis=1)  # (8, 16)
    expect = np.asarray(x) @ w_full.T + np.asarray(b)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_column_into_row_matches_mlp(tp4_mesh, rng):
    """The Megatron pair: CPL(gather_output=False) -> RPL(input_is_parallel)
    == dense 2-layer MLP (the reference's canonical usage)."""
    from apex_tpu.transformer.tensor_parallel import (ColumnParallelLinear,
                                                      RowParallelLinear)

    cpl = ColumnParallelLinear(8, 16, gather_output=False, bias=False)
    rpl = RowParallelLinear(16, 8, input_is_parallel=True, bias=False)
    x = jnp.asarray(rng.standard_normal((4, 8), dtype=np.float32))

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh, in_specs=P(),
        out_specs=(P(), P(MODEL_AXIS), P(MODEL_AXIS)), check_vma=False)
    def run(xx):
        v1 = cpl.init(jax.random.PRNGKey(1), xx)
        h = cpl.apply(v1, xx)
        v2 = rpl.init(jax.random.PRNGKey(2), h)
        y = rpl.apply(v2, jax.nn.gelu(h))
        return y, v1["params"]["weight"][None], v2["params"]["weight"][None]

    y, w1_sh, w2_sh = run(x)
    w1 = np.concatenate(list(np.asarray(w1_sh)), axis=0)   # (16, 8)
    w2 = np.concatenate(list(np.asarray(w2_sh)), axis=1)   # (8, 16)
    h = np.asarray(x) @ w1.T
    expect = np.asarray(jax.nn.gelu(jnp.asarray(h))) @ w2.T
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_sequence_parallel_roundtrip(tp4_mesh, rng):
    """CPL(sequence_parallel) -> RPL(sequence_parallel): activations enter
    and leave sharded over sequence; result == dense."""
    from apex_tpu.transformer.tensor_parallel import (ColumnParallelLinear,
                                                      RowParallelLinear)

    cpl = ColumnParallelLinear(8, 16, gather_output=False, bias=False,
                               sequence_parallel_enabled=True)
    rpl = RowParallelLinear(16, 8, input_is_parallel=True, bias=False,
                            sequence_parallel_enabled=True)
    x = jnp.asarray(rng.standard_normal((8, 8), dtype=np.float32))  # [S, E]

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh, in_specs=P(MODEL_AXIS),
        out_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS)))
    def run(xs):
        v1 = cpl.init(jax.random.PRNGKey(1), xs)
        h = cpl.apply(v1, xs)
        v2 = rpl.init(jax.random.PRNGKey(2), h)
        return rpl.apply(v2, h), v1["params"]["weight"][None], v2["params"]["weight"][None]

    y, w1_sh, w2_sh = run(x)
    w1 = np.concatenate(list(np.asarray(w1_sh)), axis=0)
    w2 = np.concatenate(list(np.asarray(w2_sh)), axis=1)
    expect = (np.asarray(x) @ w1.T) @ w2.T
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)


# --- VocabParallelEmbedding --------------------------------------------------

@pytest.mark.slow
def test_vocab_parallel_embedding(tp4_mesh, rng):
    from apex_tpu.transformer.tensor_parallel import VocabParallelEmbedding

    emb = VocabParallelEmbedding(32, 8)
    ids = jnp.asarray(rng.integers(0, 32, size=(5, 7)), jnp.int32)

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh, in_specs=P(),
        out_specs=(P(), P(MODEL_AXIS)), check_vma=False)
    def run(ii):
        v = emb.init(jax.random.PRNGKey(5), ii)
        return emb.apply(v, ii), v["params"]["weight"][None]

    y, w_sh = run(ids)
    w_full = np.concatenate(list(np.asarray(w_sh)), axis=0)  # (32, 8)
    np.testing.assert_allclose(np.asarray(y), w_full[np.asarray(ids)],
                               rtol=1e-6)


# --- vocab-parallel cross entropy --------------------------------------------

@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy(tp4_mesh, rng, smoothing):
    from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy

    logits = jnp.asarray(rng.standard_normal((6, 32), dtype=np.float32)) * 3
    target = jnp.asarray(rng.integers(0, 32, size=(6,)), jnp.int32)

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh,
        in_specs=(P(None, MODEL_AXIS), P()), out_specs=P())
    def run(lg, tg):
        return vocab_parallel_cross_entropy(lg, tg, smoothing)

    loss = run(logits, target)
    # plain CE reference with the reference's smoothing rescale:
    # smoothing' = smoothing * vocab/(vocab-1) (apex _VocabParallelCrossEntropy)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -np.asarray(jnp.take_along_axis(logp, target[:, None], axis=1))[:, 0]
    adj = smoothing * 32 / 31
    ref = (1 - adj) * nll - adj * np.asarray(logp).mean(-1)
    np.testing.assert_allclose(np.asarray(loss), ref, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_vocab_parallel_cross_entropy_grad(tp4_mesh, rng):
    from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy

    logits = jnp.asarray(rng.standard_normal((4, 16), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, 16, size=(4,)), jnp.int32)

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh,
        in_specs=(P(None, MODEL_AXIS), P()), out_specs=P(None, MODEL_AXIS))
    def grad_sharded(lg, tg):
        return jax.grad(
            lambda l: jnp.sum(vocab_parallel_cross_entropy(l, tg)))(lg)

    g = grad_sharded(logits, target)
    ref = jax.grad(lambda l: jnp.sum(
        -jnp.take_along_axis(jax.nn.log_softmax(l), target[:, None], 1)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# --- RNG tracker -------------------------------------------------------------

def test_rng_tracker_decorrelates_tp_ranks(tp4_mesh):
    from apex_tpu.transformer.tensor_parallel import (
        get_rng_state_tracker, model_parallel_seed)

    model_parallel_seed(123)
    tracker = get_rng_state_tracker()

    @functools.partial(jax.shard_map, mesh=tp4_mesh, in_specs=(),
                       out_specs=(P(MODEL_AXIS), P(MODEL_AXIS)))
    def draw():
        with tracker.fork():
            a = jax.random.uniform(tracker.get_key(), (1, 4))
        b = jax.random.uniform(jax.random.PRNGKey(123), (1, 4))
        return a, b

    model_parallel_seed(123)
    a, b = draw()
    a = np.asarray(a)
    # model-parallel stream: all 4 rank rows differ
    assert len({tuple(r) for r in a.round(6).tolist()}) == 4
    # default (data) stream: identical across ranks
    b = np.asarray(b)
    assert all(np.allclose(b[0], b[i]) for i in range(4))


def test_rng_tracker_state_roundtrip():
    from apex_tpu.transformer.tensor_parallel import (
        get_rng_state_tracker, model_parallel_seed)

    model_parallel_seed(9)
    tr = get_rng_state_tracker()
    st = tr.get_states()
    with tr.fork():
        k1 = tr.get_key()
    tr.set_states(st)
    with tr.fork():
        k2 = tr.get_key()
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


def test_checkpoint_recompute_matches():
    from apex_tpu.transformer.tensor_parallel import checkpoint

    def f(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    x = jnp.arange(8.0)
    g_ref = jax.grad(f)(x)
    g_ckpt = jax.grad(lambda xx: checkpoint(f, False, xx))(x)
    np.testing.assert_allclose(np.asarray(g_ckpt), np.asarray(g_ref), rtol=1e-6)


# --- broadcast_data ----------------------------------------------------------

def test_broadcast_data(tp4_mesh):
    from apex_tpu.transformer.tensor_parallel import broadcast_data

    @functools.partial(jax.shard_map, mesh=tp4_mesh,
                       in_specs=P(MODEL_AXIS), out_specs=P(MODEL_AXIS))
    def run(x):
        out = broadcast_data(["x"], {"x": x})
        return out["x"]

    x = jnp.arange(8.0).reshape(4, 2)  # rank i holds row i
    y = run(x)
    # every rank must end with rank 0's shard
    expect = np.tile(np.asarray(x[:1]), (4, 1))
    np.testing.assert_allclose(np.asarray(y), expect)
