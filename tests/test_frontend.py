"""Serving front-end (apex_tpu/serving/frontend.py + policy.py).

Policy tier (no model): queue ordering (priority desc, EDF inside a
class, FIFO tiebreak), victim selection (strictly-lower priority only,
most recent first), preemption arming (margin/deadline semantics).

Frontend tier (tiny GPT): the acceptance bars for preemption-by-spill —
greedy outputs token-identical with preemption forced on vs off, the
resumed request's re-admission skipping its FULL-page prefix via the
radix cache, priority inversion bounded (a low-priority flood cannot
starve a high-priority arrival past its deadline), streaming handles
delivering tokens in order and terminating on EOS/cancel, and sampled
decode staying scheduling-invariant ACROSS a preemption (the resume
continues the request's fold_in key stream)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import (FaultPlan, FaultSpec, PagedDecodeEngine,
                              PriorityDeadlinePolicy, Request,
                              ServingError, free_page_count)
from apex_tpu.serving.frontend import ServingFrontend
from apex_tpu.utils import metrics


def _model():
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, v


def _refs(model, v, reqs, **kw):
    return [np.asarray(generate(model, v, np.asarray(r.prompt)[None],
                                max_new_tokens=r.max_new_tokens, **kw)
                       )[0, np.asarray(r.prompt).shape[0]:]
            for r in reqs]


# --------------------------------------------------------------------------
# policy (pure host logic)
# --------------------------------------------------------------------------

class _E:
    """Minimal entry stand-in for policy unit tests."""

    def __init__(self, priority=0, deadline_at=None, arrival=0.0, seq=0):
        self.priority = priority
        self.deadline_at = deadline_at
        self.arrival = arrival
        self.seq = seq


def test_request_backcompat_defaults():
    """The pre-frontend constructor shape still works; the scheduling
    fields default to plain FIFO traffic."""
    r = Request(prompt=np.zeros((4,), np.int32), max_new_tokens=3)
    assert (r.priority, r.deadline_ms, r.arrival_time) == (0, None, None)
    r2 = Request(np.zeros((4,), np.int32), 3)         # positional form
    assert r2.max_new_tokens == 3 and r2.priority == 0


def test_policy_ordering():
    pol = PriorityDeadlinePolicy()
    hi = _E(priority=2, arrival=3.0, seq=3)
    edf = _E(priority=0, deadline_at=5.0, arrival=2.0, seq=2)
    old = _E(priority=0, arrival=0.0, seq=0)
    new = _E(priority=0, arrival=1.0, seq=1)
    ordered = sorted([new, old, edf, hi],
                     key=lambda e: pol.sort_key(e, now=0.0))
    # priority first, then earliest deadline, then arrival FIFO
    assert ordered == [hi, edf, old, new]


def test_policy_victim_selection_and_arming():
    pol = PriorityDeadlinePolicy(preempt_margin_ms=100.0)
    active = {0: _E(priority=1, seq=0), 1: _E(priority=0, seq=1),
              2: _E(priority=0, seq=2)}
    cand = _E(priority=2, deadline_at=1.0)
    # lowest priority wins; inside the class, the most recent admission
    assert pol.select_victim(cand, active, now=0.0) == 2
    # equal-or-higher priority never qualifies (no ping-pong)
    assert pol.select_victim(_E(priority=0), active, now=0.0) is None
    assert pol.select_victim(_E(priority=1), active,
                             now=0.0) in (1, 2)       # only the 0s
    # arming: inside the margin of the deadline, or past it
    assert not pol.at_risk(_E(deadline_at=10.0), now=0.0)
    assert pol.at_risk(_E(deadline_at=10.0), now=9.95)
    assert pol.at_risk(_E(deadline_at=10.0), now=11.0)
    assert not pol.wants_preempt(_E(), now=0.0)       # no deadline
    assert PriorityDeadlinePolicy(preempt_on_priority=True).wants_preempt(
        _E(), now=0.0)
    assert not PriorityDeadlinePolicy(preemption=False).wants_preempt(
        _E(deadline_at=0.0), now=1.0)


# --------------------------------------------------------------------------
# streaming handles
# --------------------------------------------------------------------------

def test_streaming_tokens_in_order_and_eos_termination(rng):
    """Tokens arrive on the handle in generation order as the pump runs
    and the stream terminates; a request ending at EOS includes it and
    stops."""
    import queue as queue_mod

    cfg, model, v = _model()
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    ref = np.asarray(generate(model, v, prompt[None], max_new_tokens=6))
    eos = int(ref[0, 10])                 # forces an EOS mid-budget
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                               eos_token_id=eos)
    fe = ServingFrontend(engine)
    h = fe.submit(Request(prompt=prompt, max_new_tokens=6))
    streamed = []
    while fe.pump():                      # consume between boundaries
        try:
            while (tok := h.get(timeout=0)) is not None:
                streamed.append(tok)
        except queue_mod.Empty:
            pass
    streamed.extend(list(h))              # whatever the last chunk left
    out = h.result()
    assert h.done
    assert streamed == list(out)          # in order, nothing dropped
    assert h.tokens_so_far() == list(out)
    assert int(out[-1]) == eos or out.shape[0] == 6
    assert list(h) == []                  # the stream stays terminated


def test_streaming_cancel_stops_stream_and_frees_pages(rng):
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8)
    fe = ServingFrontend(engine)
    prompt = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
    h = fe.submit(Request(prompt=prompt, max_new_tokens=30))
    for _ in range(4):
        fe.pump()
    h.cancel()
    fe.drain()
    out = h.result()
    assert h.done
    assert 1 <= out.shape[0] < 30         # truncated at the cancel point
    # the prefix of an uncancelled run matches (cancel loses no tokens)
    ref = np.asarray(generate(model, v, prompt[None], max_new_tokens=30)
                     )[0, 9:]
    np.testing.assert_array_equal(out, ref[:out.shape[0]])
    # pages all returned (no prefix cache: everything frees)
    assert int(free_page_count(engine.cache)) == \
        engine.cache["free_stack"].shape[0] - 1
    # a cancelled PENDING request never admits and finishes empty
    fe2 = ServingFrontend(engine)
    h2 = fe2.submit(Request(prompt=prompt, max_new_tokens=4))
    h2.cancel()
    fe2.drain()
    assert h2.result().shape[0] == 0


def test_background_pump_thread(rng):
    """start()/stop(): submissions stream results without the caller
    driving the pump."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8)
    fe = ServingFrontend(engine)
    fe.start()
    try:
        prompt = rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32)
        h = fe.submit(Request(prompt=prompt, max_new_tokens=5))
        out = h.result(timeout=120.0)
    finally:
        fe.stop()
    ref = np.asarray(generate(model, v, prompt[None], max_new_tokens=5)
                     )[0, 10:]
    np.testing.assert_array_equal(out, ref)


# --------------------------------------------------------------------------
# preemption / resume
# --------------------------------------------------------------------------

def _forced_preemption_run(model, v, cfg, low, hi, *, engine_kw=None,
                           warm_pumps=3):
    """Admit the low-priority requests, let them decode a few chunks,
    then submit the high-priority one under an aggressive policy — with
    every slot busy it MUST preempt. Returns (frontend, handles)."""
    engine = PagedDecodeEngine(model, v, num_slots=len(low), page_size=8,
                               prefix_cache=True, **(engine_kw or {}))
    fe = ServingFrontend(
        engine, policy=PriorityDeadlinePolicy(preempt_on_priority=True))
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(low)]
    while fe.queue_depth:
        fe.pump()
    for _ in range(warm_pumps):           # give the victims some progress
        fe.pump()
    handles.append(fe.submit(hi, request_id=len(low)))
    fe.drain()
    return fe, handles


def test_forced_preemption_token_identity_and_full_prefix_resume(rng):
    """THE acceptance bar: a high-priority arrival that must evict a
    low-priority slot produces greedy output token-identical to the
    unconstrained run for every request, and the resumed request's
    re-admission skips its ENTIRE full-page written prefix via the
    radix cache."""
    cfg, model, v = _model()
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                       ).astype(np.int32),
                   max_new_tokens=16, priority=0) for _ in range(2)]
    hi = Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                     ).astype(np.int32),
                 max_new_tokens=8, priority=5)
    fe, handles = _forced_preemption_run(model, v, cfg, low, hi)
    stats = fe.stats()
    assert stats["preemptions"] >= 1
    assert stats["resumes"] >= 1

    # token identity: every request matches its unconstrained lock-step
    # run — the preempt/spill/resume cycle changed nothing
    for h, ref in zip(handles, _refs(model, v, low + [hi])):
        np.testing.assert_array_equal(h.result(), ref)

    # the resume hit the cache for its FULL written full-page prefix:
    # ample pages mean the spilled pages survived until the resume, and
    # resumes skip the power-of-two match flooring
    ring = fe.engine.events.tail()
    preempts = {e["request"]: e for e in ring if e["kind"] == "preempt"}
    resumes = [e for e in ring if e["kind"] == "resume"]
    assert resumes, ring
    for ev in resumes:
        generated = preempts[ev["request"]]["generated"]
        s0 = 24                           # every prompt here is 24 tokens
        full_pages = (s0 + generated - 1) // 8
        assert ev["cached_pages"] == full_pages, (ev, generated)
    assert stats["prefill_tokens_skipped"] >= 8

    # pool hygiene: every non-cached page returned after the drain
    usable = fe.engine.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(fe.engine.cache)) == \
        usable - len(fe.engine.prefix)


def test_preemption_on_off_identical_via_run(rng):
    """engine.run() outputs are identical whether the policy may preempt
    or not (same requests, same engine config)."""
    cfg, model, v = _model()
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (int(s),)
                                        ).astype(np.int32),
                    max_new_tokens=int(m), priority=int(p))
            for s, m, p in zip((16, 24, 9), (10, 6, 12), (0, 3, 1))]
    e1 = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                           prefix_cache=True)
    outs_off, _ = e1.run(reqs, policy=PriorityDeadlinePolicy(
        preemption=False))
    e2 = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                           prefix_cache=True)
    outs_on, _ = e2.run(reqs, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    for a, b in zip(outs_off, outs_on):
        np.testing.assert_array_equal(a, b)


def test_priority_inversion_bounded(rng):
    """A flood of low-priority work cannot starve a high-priority
    deadline request: the policy preempts the running victim and the
    high-priority request completes before any further low-priority
    request is even admitted."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8)
    # a huge margin arms preemption the moment the request is blocked —
    # long before the (comfortable) deadline could be missed
    fe = ServingFrontend(engine, policy=PriorityDeadlinePolicy(
        preempt_margin_ms=1e7))
    lows = [fe.submit(Request(
        prompt=rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32),
        max_new_tokens=12), request_id=i) for i in range(3)]
    while fe.queue_depth == 3:            # let the first low admit
        fe.pump()
    fe.pump()
    h_hi = fe.submit(Request(
        prompt=rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32),
        max_new_tokens=4, priority=9, deadline_ms=600000.0),
        request_id=9)
    fe.drain()
    stats = fe.stats()
    assert stats["preemptions"] >= 1
    assert stats["deadline_misses"] == 0
    ring = fe.engine.events.tail()
    hi_retire = next(e["seq"] for e in ring
                     if e["kind"] == "retire" and e["request"] == 9)
    later_low_admits = [e["seq"] for e in ring
                        if e["kind"] == "admit" and e["request"] in (1, 2)]
    assert all(hi_retire < s for s in later_low_admits), ring
    for h in lows:                        # the flood still completes
        assert h.result().shape[0] == 12


@pytest.mark.slow
def test_sampled_preemption_scheduling_invariance(rng):
    """Sampled decode draws the SAME tokens with and without a
    preemption in the middle: the resume admission continues the
    request's fold_in key stream at its token index (samp0)."""
    cfg, model, v = _model()
    key = jax.random.PRNGKey(3)
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                       ).astype(np.int32),
                   max_new_tokens=12, priority=0) for _ in range(2)]
    hi = Request(prompt=rng.integers(0, cfg.vocab_size, (16,)
                                     ).astype(np.int32),
                 max_new_tokens=6, priority=5)
    kw = dict(temperature=1.0, top_k=8, rng=key)

    # undisturbed: plain run, no preemption possible (FIFO, no deadlines)
    e_plain = PagedDecodeEngine(model, v, num_slots=3, page_size=8,
                                **kw)
    outs_plain, stats_plain = e_plain.run(low + [hi])
    assert stats_plain.get("preemptions", 0) == 0

    # forced preemption mid-decode; prefix_cache on for the spill path
    e_pre = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                              prefix_cache=True, **kw)
    fe = ServingFrontend(e_pre, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(low)]
    while fe.queue_depth:
        fe.pump()
    for _ in range(3):
        fe.pump()
    handles.append(fe.submit(hi, request_id=2))
    fe.drain()
    assert fe.stats()["preemptions"] >= 1
    for h, ref in zip(handles, outs_plain):
        np.testing.assert_array_equal(h.result(), np.asarray(ref))


def test_deadline_miss_counted_and_queue_metrics(rng):
    """An already-expired deadline is counted exactly once at first
    token; the queue-depth gauge tracks ingest and the preemption
    counters carry the engine label."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8)
    fe = ServingFrontend(engine, policy=PriorityDeadlinePolicy(
        preemption=False))
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                        ).astype(np.int32),
                    max_new_tokens=3,
                    deadline_ms=0.0 if i == 0 else None,
                    arrival_time=time.perf_counter() - 1.0)
            for i in range(3)]
    for i, r in enumerate(reqs):
        fe.submit(r, request_id=i)
    assert fe.queue_depth == 3
    assert metrics.gauge("serving.queue_depth",
                         labels=engine.obs_labels).value == 3
    fe.drain()
    stats = fe.stats()
    assert stats["deadline_misses"] == 1
    assert stats["peak_queue_depth"] >= 3
    assert stats["preemptions"] == 0 and stats["resumes"] == 0
    assert metrics.counter("serving.deadline_misses",
                           labels=engine.obs_labels).value >= 1
    assert metrics.gauge("serving.queue_depth",
                         labels=engine.obs_labels).value == 0


def test_lifecycle_reports_time_in_preempted(rng):
    """The span tracer's lifecycle sums decode segments across a
    preemption and reports preempted_ms/preemptions; TTFT anchors on
    the ORIGINAL first token, not the resume's."""
    cfg, model, v = _model()
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                       ).astype(np.int32),
                   max_new_tokens=10, priority=0) for _ in range(2)]
    hi = Request(prompt=rng.integers(0, cfg.vocab_size, (16,)
                                     ).astype(np.int32),
                 max_new_tokens=4, priority=5)
    fe, handles = _forced_preemption_run(model, v, cfg, low, hi)
    ring = fe.engine.events.tail()
    victim = next(e["request"] for e in ring if e["kind"] == "preempt")
    life = fe.tracer.lifecycle(victim)
    assert life["preemptions"] >= 1
    assert life["preempted_ms"] > 0.0
    assert life["new_tokens"] == handles[victim].result().shape[0]
    assert life["ttft_ms"] >= 0.0
    assert life["tpot_ms"] >= 0.0
    # an unpreempted request reports no preemption keys
    untouched = next(i for i in (0, 1) if i != victim)
    assert "preemptions" not in fe.tracer.lifecycle(untouched)


def test_pump_timing_fields_present_and_sane(rng):
    """ISSUE 8 acceptance: a frontend run's stats carry the pump
    pipeline attribution (`pump.bubble_ms`, dispatch-ready/host-work
    percentiles) and the recompile window (`jit.compiles`), and the
    engine-labeled pump instruments exist in the registry."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                               sync_every=2)
    fe = ServingFrontend(engine)
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (9,)
                                        ).astype(np.int32),
                    max_new_tokens=6) for _ in range(4)]
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(reqs)]
    fe.drain()
    for h in handles:
        h.result(timeout=0)
    stats = fe.stats()
    assert stats["pump.bubble_ms"] >= 0.0
    assert stats["pump.dispatch_ready_ms_p50"] > 0.0
    assert (stats["pump.dispatch_ready_ms_p95"]
            >= stats["pump.dispatch_ready_ms_p50"])
    assert (0.0 <= stats["pump.host_work_ms_p50"]
            <= stats["pump.host_work_ms_p95"])
    assert stats["jit.compiles"] >= 0
    assert stats["jit.trace_cache_misses"] >= stats["jit.compiles"]
    labels = dict(engine.obs_labels, phase="steady")
    assert metrics.histogram("pump.dispatch_ready_ms",
                             labels=labels).count > 0
    assert metrics.histogram("pump.host_work_ms",
                             labels=engine.obs_labels).count > 0
    assert metrics.gauge("pump.bubble_ms",
                         labels=engine.obs_labels).value >= 0.0


def test_preempt_flush_chunks_labeled_separately(rng):
    """A preemption flush harvests the in-flight chunk synchronously;
    its device time lands under phase="preempt", not in the
    steady-state distribution."""
    cfg, model, v = _model()
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                       ).astype(np.int32),
                   max_new_tokens=10, priority=0) for _ in range(2)]
    hi = Request(prompt=rng.integers(0, cfg.vocab_size, (16,)
                                     ).astype(np.int32),
                 max_new_tokens=4, priority=5)
    fe, _ = _forced_preemption_run(model, v, cfg, low, hi)
    eng_labels = fe.engine.obs_labels
    preempt = metrics.histogram(
        "pump.dispatch_ready_ms", labels=dict(eng_labels,
                                              phase="preempt"))
    assert fe.stats()["preemptions"] >= 1
    assert preempt.count >= 1


def test_tpot_slo_miss_counted_and_burn_gauge(rng):
    """ISSUE 8 satellite: a request with an impossible TPOT SLO is
    counted once (`serving.tpot_slo_misses`, engine-labeled) and the
    rolling `serving.slo_burn` gauge reports the miss rate over
    SLO-carrying retirements; a generous SLO records no miss."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8)
    fe = ServingFrontend(engine)
    reqs = [
        Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                    ).astype(np.int32),
                max_new_tokens=4, tpot_slo_ms=0.0),       # must miss
        Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                    ).astype(np.int32),
                max_new_tokens=4, tpot_slo_ms=1e9),       # cannot miss
        Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                    ).astype(np.int32),
                max_new_tokens=4),                        # no SLO
    ]
    for i, r in enumerate(reqs):
        fe.submit(r, request_id=i)
    fe.drain()
    stats = fe.stats()
    assert stats["tpot_slo_misses"] == 1
    # burn = misses / SLO-carrying retirements in the window (the
    # no-SLO request does not dilute it)
    assert stats["slo_burn"] == pytest.approx(0.5)
    assert metrics.counter("serving.tpot_slo_misses",
                           labels=engine.obs_labels).value == 1
    assert metrics.gauge("serving.slo_burn",
                         labels=engine.obs_labels).value \
        == pytest.approx(0.5)
    ring = engine.events.tail()
    misses = [e for e in ring if e["kind"] == "tpot_slo_miss"]
    assert len(misses) == 1 and misses[0]["request"] == 0
    assert fe.tracer.lifecycle(0)["tpot_ms"] > 0.0


def test_slo_window_prunes_by_policy_horizon(rng):
    """The burn gauge forgets misses older than the policy's
    slo_window_s (injected clock)."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8)
    t = [0.0]
    fe = ServingFrontend(
        engine, policy=PriorityDeadlinePolicy(slo_window_s=10.0),
        clock=lambda: t[0])
    miss = Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                       ).astype(np.int32),
                   max_new_tokens=4, tpot_slo_ms=0.0)
    fe.submit(miss, request_id=0)
    fe.drain()
    assert fe.stats()["slo_burn"] == 1.0
    # 60 fake seconds later, a healthy retirement: the old miss has
    # aged out of the 10 s window
    t[0] = 60.0
    ok = Request(prompt=rng.integers(0, cfg.vocab_size, (8,)
                                     ).astype(np.int32),
                 max_new_tokens=4, tpot_slo_ms=1e9)
    fe.submit(ok, request_id=1)
    fe.drain()
    assert metrics.gauge("serving.slo_burn",
                         labels=engine.obs_labels).value == 0.0


def test_deadlock_still_raises_and_fails_handles(rng):
    """A request the pool can never hold dies loudly through the
    frontend too (the engine's original deadlock contract)."""
    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                               num_pages=3)
    fe = ServingFrontend(engine)
    fe.submit(Request(prompt=np.zeros((30,), np.int32),
                      max_new_tokens=10))
    with pytest.raises(RuntimeError, match="deadlock"):
        fe.drain()


# --------------------------------------------------------------------------
# pump death (ISSUE 11 satellite: a dead engine must never hang a handle)
# --------------------------------------------------------------------------

def _killed_frontend(model, v, *, at=2, start=True):
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8)
    plan = FaultPlan(specs=(FaultSpec(kind="kill_replica", at=at),))
    fe = ServingFrontend(engine, fault_hook=plan.injector(0))
    if start:
        fe.start()
    return fe


def test_pump_death_mid_decode_raises_serving_error_bounded(rng):
    """ISSUE 11 satellite (the pump-death hang): an engine that dies
    mid-decode must surface a terminal ServingError from result() AND
    from blocked iteration within a bounded time — before this PR the
    synchronous pump path left handles un-finished and iteration ended
    silently instead of raising."""
    import queue as queue_mod

    cfg, model, v = _model()
    fe = _killed_frontend(model, v, at=2)
    try:
        prompt = rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32)
        h = fe.submit(Request(prompt=prompt, max_new_tokens=40))
        # consumer 1: blocked in result() on another thread
        res: dict = {}

        def consume_result():
            try:
                res["out"] = h.result(timeout=300)
            except BaseException as exc:     # noqa: BLE001
                res["exc"] = exc

        import threading
        t = threading.Thread(target=consume_result, daemon=True)
        t.start()
        t.join(timeout=300)
        assert not t.is_alive(), "result() hung on a dead engine"
        assert isinstance(res.get("exc"), ServingError)
        # consumer 2: blocked iteration raises too (never silent-ends)
        with pytest.raises(ServingError):
            for _ in h:
                pass
        with pytest.raises(ServingError):
            while h.get(timeout=10) is not None:
                pass
        assert h.error is not None
        # the frontend is terminally failed: late submits raise, the
        # failure is observable (the /healthz surface)
        assert fe.failure is not None
        with pytest.raises(ServingError, match="pump has failed"):
            fe.submit(Request(prompt=prompt, max_new_tokens=4))
        del queue_mod
    finally:
        fe.stop()


def test_pump_death_sync_path_fails_handles(rng):
    """The SYNCHRONOUS pump driver takes the same terminal path: the
    exception propagates to the driving caller AND every live handle
    (active + pending) fails — nothing dangles for a streaming
    consumer on another thread to block on."""
    cfg, model, v = _model()
    fe = _killed_frontend(model, v, at=3, start=False)
    prompts = [rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
               for _ in range(4)]
    handles = [fe.submit(Request(prompt=p, max_new_tokens=30))
               for p in prompts]        # 2 active + 2 pending (2 slots)
    from apex_tpu.serving.faults import InjectedFault

    with pytest.raises(InjectedFault):
        fe.drain()
    for h in handles:
        assert h.done
        with pytest.raises(ServingError):
            h.result(timeout=0)


# --------------------------------------------------------------------------
# shutdown under load (ISSUE 11 satellite: stop() must not strand work)
# --------------------------------------------------------------------------

def _loaded_frontend(model, v, cfg, rng, *, n=6, max_new=16):
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                               prefix_cache=True)
    fe = ServingFrontend(engine)
    handles = [fe.submit(Request(
        prompt=rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32),
        max_new_tokens=max_new), request_id=i) for i in range(n)]
    return fe, handles


@pytest.mark.parametrize("mode", ["drain", "cancel"])
def test_shutdown_under_load_resolves_deterministically(rng, mode):
    """shutdown() with queued + active + mid-stream requests: every
    handle reaches done (full output under drain, truncated under
    cancel), zero pool-page leaks, zero dangling threads, and late
    submits raise."""
    import threading

    cfg, model, v = _model()
    fe, handles = _loaded_frontend(model, v, cfg, rng)
    fe.start()
    try:
        handles[0].get(timeout=120)      # at least one token streamed
        fe.shutdown(deadline_s=300.0, mode=mode)
    finally:
        fe.stop()
    for h in handles:
        assert h.done
        out = h.result(timeout=0)        # never raises: resolved, not
        if mode == "drain":              # stranded
            assert out.shape[0] == 16
        else:
            assert out.shape[0] <= 16
    with pytest.raises(ServingError, match="shutting down"):
        fe.submit(Request(prompt=np.zeros((4,), np.int32),
                          max_new_tokens=2))
    # zero dangling threads, zero leaked pages
    assert not fe.pump_alive
    assert "serving-frontend-pump" not in {
        t.name for t in threading.enumerate()}
    engine = fe.engine
    usable = engine.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(engine.cache)) == \
        usable - len(engine.prefix)
    assert int(np.asarray(engine.cache["page_ref"]).sum()) == 0


def test_shutdown_sync_and_deadline_expiry(rng):
    """A synchronously driven frontend shuts down the same way, and an
    already-expired drain deadline degrades to cancellation — bounded,
    never an infinite pump loop."""
    cfg, model, v = _model()
    fe, handles = _loaded_frontend(model, v, cfg, rng, n=4, max_new=24)
    for _ in range(3):
        fe.pump()
    fe.shutdown(deadline_s=0.0, mode="drain")   # expires immediately
    for h in handles:
        assert h.done
        assert h.result(timeout=0).shape[0] <= 24   # truncated is fine
    engine = fe.engine
    usable = engine.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(engine.cache)) == \
        usable - len(engine.prefix)
    with pytest.raises(ValueError, match="mode"):
        fe.shutdown(mode="nope")


# --------------------------------------------------------------------------
# host-concurrency stress (ISSUE 7: the dynamic counterpart of --conc)
# --------------------------------------------------------------------------

def test_concurrent_submit_cancel_stress(rng):
    """N producer threads concurrently submit()/cancel()/iterate handles
    against the background pump under a watchdog: no lost or duplicated
    tokens (each handle's streamed sequence equals its final output), no
    deadlock (every thread finishes inside the timeout), and the pool's
    free-page count returns to baseline after the drain (leak check —
    preemption spill/resume and cancellation paths all release)."""
    import threading

    cfg, model, v = _model()
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                               prefix_cache=True)
    fe = ServingFrontend(engine, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    fe.start()
    n_threads, n_req = 3, 3
    errors: list = []
    results: dict = {}

    def producer(tid: int) -> None:
        try:
            local = np.random.default_rng(tid)
            for i in range(n_req):
                s0 = 8 + 2 * ((tid + i) % 3)
                prompt = local.integers(0, cfg.vocab_size, (s0,)
                                        ).astype(np.int32)
                h = fe.submit(Request(prompt=prompt, max_new_tokens=5,
                                      priority=(tid + i) % 3),
                              request_id=tid * 10 + i)
                streamed: list = []
                if (tid + i) % 4 == 3:
                    # consume one token, then cancel mid-stream
                    tok = h.get(timeout=120)
                    if tok is not None:
                        streamed.append(tok)
                    h.cancel()
                for tok in h:            # live-stream the rest
                    streamed.append(tok)
                out = h.result(timeout=120)
                results[(tid, i)] = (streamed, list(out), h.cancelled)
        except BaseException as exc:     # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=producer, args=(t,), daemon=True)
               for t in range(n_threads)]
    try:
        for t in threads:
            t.start()
        for t in threads:                # watchdog: a hang fails, not wedges
            t.join(timeout=300)
        stuck = [t.name for t in threads if t.is_alive()]
        assert not stuck, f"deadlocked producer threads: {stuck}"
    finally:
        fe.stop()
    assert not errors, errors
    assert len(results) == n_threads * n_req
    for (tid, i), (streamed, out, cancelled) in results.items():
        # in-order, nothing dropped, nothing pushed twice
        assert streamed == out, (tid, i, streamed, out)
        if not cancelled:
            assert len(out) == 5 or (
                engine.eos_token_id is not None)
    # pool hygiene: every non-cached page returned after the drain
    usable = engine.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(engine.cache)) == \
        usable - len(engine.prefix)
    # the cached pages are all refcount-0 (no dangling prefix refs)
    assert int(np.asarray(engine.cache["page_ref"]).sum()) == 0
    assert fe.stats()["retired"] == n_threads * n_req
