"""Mirrors tests/L0/run_transformer/test_parallel_state.py from the reference."""

import jax
import pytest

from apex_tpu import mesh as mesh_lib
from apex_tpu.transformer import parallel_state


def test_initialize_and_query():
    m = parallel_state.initialize_model_parallel(2, 2)
    assert parallel_state.model_parallel_is_initialized()
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_data_parallel_world_size() == 2
    assert parallel_state.get_world_size() == 8
    assert m.axis_names == ("data", "stage", "context", "model")


def test_invalid_sizes_raise():
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(3, 1)  # 8 % 3 != 0


def test_destroy():
    parallel_state.initialize_model_parallel(1, 1)
    parallel_state.destroy_model_parallel()
    assert not parallel_state.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        parallel_state.get_tensor_model_parallel_world_size()


def test_virtual_pipeline_state():
    parallel_state.initialize_model_parallel(1, 2, virtual_pipeline_model_parallel_size_=2)
    assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 2
    parallel_state.set_virtual_pipeline_model_parallel_rank(1)
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 1


def test_ranks_inside_shard_map():
    import jax.numpy as jnp
    import numpy as np

    m = parallel_state.initialize_model_parallel(2, 1)

    def f(x):
        tp_rank = parallel_state.get_tensor_model_parallel_rank()
        return x + tp_rank

    from jax.sharding import PartitionSpec as P

    out = jax.shard_map(
        f,
        mesh=m,
        in_specs=P("model"),
        out_specs=P("model"),
    )(jnp.zeros((2,), jnp.int32))
    np.testing.assert_array_equal(np.asarray(out), [0, 1])
