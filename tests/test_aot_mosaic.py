"""CI tier of the offline AOT-Mosaic sweep (VERDICT r4 next-round #1).

Compiles a representative subset of the on-chip kernel configurations
against the device-less v5e topology — Mosaic block rules, layouts, and
scoped-VMEM limits all enforced with no TPU attached. Pins the r5 Adam
regression: at the BERT-Large buffer shape the 7-buffer Adam kernel
overflowed Mosaic's 16 MB scoped-VMEM stack at block 256 (caught by this
path, fixed via the n_bufs-aware ``_row_block``).

The full sweep (every config + the BERT-Large train step + the autotune
candidate set) is ``python tpu_aot.py`` -> ``AOT_<tag>.json``.
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

CASE_NAMES = [
    "layer_norm_bwd",
    "flash_bwd_seq512",
    "flash_causal_dropout_bwd",
    "xentropy_bwd",
    "scaled_upper_triang_softmax",
    "optim_adam_bert_large_buffer",   # r5 scoped-VMEM regression pin
    "optim_lamb_bert_large_buffer",
    "group_norm_bwd_fp32",
    "flash_lse_bwd_with_lse_cotangent",
    "flash_window128_bwd",
    "gpt2_small_decode128_int8",      # serving path: scan decode + W8A8
    "paged_attention_gpt2s_decode",   # paged serving: scalar-prefetch gather
    "gpt2s_prefix_cached_admit",      # prefix cache: tail-only admission
    "gpt2s_paged_spec_verify",        # s=4 query block: spec verify step
    "gpt2s_chunked_prefill_step",     # chunked prefill through the s>1 path
    "gpt2s_paged_decode_int8kv",      # quantized pool: in-kernel dequant
    "gpt2s_paged_decode_w8",          # w8 policy: fused dequant-matmul
    "gpt2s_fused_dequant_w4",         # int4 nibbles + grouped scales
    "gpt2s_host_tier_gather",         # tiered pool: demote-side page read
    "gpt2s_host_tier_promote",        # tiered pool: promote-side scatter
]

#: ISSUE 17: the tiered pool's copy programs are plain XLA data movers
#: by design — the pin is INVERTED (zero tpu_custom_call sites). A
#: Mosaic kernel appearing here must be acknowledged by moving the name
#: out of this set.
NO_MOSAIC_CASES = {"gpt2s_host_tier_gather", "gpt2s_host_tier_promote"}


@pytest.fixture(scope="module", autouse=True)
def _force_mosaic():
    os.environ["APEX_TPU_FORCE_MOSAIC"] = "1"
    # another process (tpu_aot.py sweep, tunnel watcher) may hold the
    # libtpu lockfile; topology-only use is safe concurrently
    os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "1")
    yield
    os.environ.pop("APEX_TPU_FORCE_MOSAIC", None)


@pytest.fixture(scope="module")
def topo():
    import tpu_aot

    try:
        _, t = tpu_aot._topology()
    except RuntimeError as e:
        pytest.skip(f"no TPU topology support in this jaxlib: {e}")
    return t


@pytest.fixture(scope="module")
def mesh(topo):
    import tpu_aot

    return tpu_aot._mesh(topo)


@pytest.fixture(scope="module")
def cases():
    import tpu_aot

    return {name: (fn, structs, rest[0] if rest else ())
            for name, fn, structs, *rest in tpu_aot.kernel_cases()}


@pytest.mark.parametrize("name", CASE_NAMES)
def test_kernel_compiles_to_mosaic_under_budget(name, mesh, cases):
    import tpu_aot

    fn, structs, donate = cases[name]
    r = tpu_aot.case_result(mesh, fn, structs, donate)
    assert r["ok"]
    if name in NO_MOSAIC_CASES:
        assert r["tpu_custom_call_sites"] == 0, (
            "a Mosaic kernel appeared in a plain-XLA copy program — "
            "move the name out of NO_MOSAIC_CASES to acknowledge it")
    else:
        assert r["tpu_custom_call_sites"] >= 1, (
            "kernel lowered without a tpu_custom_call — interpret-mode "
            "leak?")
    assert r["under_16gib_budget"], r
    # static perf-lint: no copy/transpose result over 256 MiB (the r3
    # 86 GB relayout class is visible in compiled text)
    assert not r["giant_copy_flags"], r["giant_copy_flags"]


def test_multichip_ring_cp_compiles_for_tpu(topo):
    """The context-parallel path has only ever RUN on the virtual CPU mesh
    (interpret mode); this pins that the same sharded program — ring
    attention rotating K/V by ppermute around Mosaic flash kernels —
    COMPILES for the real v5e topology."""
    import tpu_aot

    r = tpu_aot.multichip_aot(topo, only=["cp2_ring_attention_grad"])
    r = r["cp2_ring_attention_grad"]
    assert r["ok"], r
    assert r["tpu_custom_call_sites"] >= 2, "flash kernels missing"
    assert r["collective_permutes"] >= 1, "ring rotation missing"


def test_multichip_tp_paged_serving_compiles_for_tpu(topo):
    """ISSUE 10 acceptance: the tensor-parallel sharded admit + decode
    programs (serving/tp.py) AOT-compile for the deviceless v5e:2x4
    topology with per-chip argument+output+temp bytes under the 16 GiB
    budget, at a shape where the UNSHARDED pool does NOT fit one chip —
    the model-size-ceiling claim of docs/tp_serving.md as a compile
    artifact. (tp=4 over the topology's 8 chips: the decode scan
    double-buffers the pool carry, so a chip needs ~2x its shard —
    tpu_aot.py's shape comment records both compile-failure lessons.)
    Also requires the Megatron all-reduces and the Mosaic kernels
    (paged attention / flash prefill) to actually be present in the
    lowered program.

    ISSUE 18: the byte assertions are no longer hand-typed pins — the
    mem lint tier's STATIC per-chip estimate (traced on CPU, tiled-
    padded liveness sweep) must land within +/-20% of what the compiler
    measures, per case, in BOTH directions. If the model drifts (a new
    resident buffer the sweep misses) or the program drifts (a buffer
    the sweep still charges but the compiler elided), this fails and
    whichever side regressed has to be fixed — the lint tier's fit
    proofs are only worth trusting while this band holds."""
    import tpu_aot

    from apex_tpu.analysis.mem import ACCEPTANCE_TO_AOT, acceptance_estimates

    # the acceptance inequality's first half: one chip cannot hold the
    # unsharded pool (lane-exact tiles, so these bytes are physical)
    assert tpu_aot.tp_serving_pool_bytes() > tpu_aot.HBM_BUDGET

    est = acceptance_estimates(REPO)
    names = sorted(ACCEPTANCE_TO_AOT.values())
    assert sorted(est) == names
    r = tpu_aot.multichip_aot(topo, only=names)
    pool_shard = tpu_aot.tp_serving_pool_bytes() // tpu_aot.TP_SERVING_TP
    for name in names:
        c, e = r[name], est[name]
        assert c["ok"], c
        assert c["all_reduces"] >= 1, "Megatron TP collectives missing"
        assert c["tpu_custom_call_sites"] >= 1, (
            "Mosaic kernels missing — interpret-mode leak?")
        # static-vs-measured peak band (the mem tier's calibration pin)
        measured = c["peak_estimate_bytes"]
        assert e.scope == "per-chip", e
        assert 0.8 * measured <= e.peak_bytes <= 1.2 * measured, (
            f"{name}: static {e.peak_bytes:,} B vs AOT-measured "
            f"{measured:,} B drifted past +/-20%")
        # the budget verdict must agree on both sides, and the static
        # side's input working set carries at least this chip's pool
        # shard — the sharded pool is genuinely in the program
        static_under = e.peak_bytes <= tpu_aot.HBM_BUDGET
        assert static_under == bool(c["under_16gib_budget"]), (c, e)
        assert static_under, c
        static_in = sum(b.padded_bytes for b in e.boundary
                        if b.kind == "in")
        assert static_in >= pool_shard, (static_in, pool_shard)
        assert c["argument_bytes"] >= pool_shard, c
    # quantized weight streaming (docs/serving.md): the w8 decode chunk
    # carries the SAME sharded pool but int8 block-linear weights — the
    # per-chip footprint must genuinely drop vs the bf16 program, and
    # the static model must see the same ordering
    fp, w8 = r["tp4_paged_engine_decode_chunk"], r["tp4_paged_engine_decode_w8"]
    assert w8["argument_bytes"] < fp["argument_bytes"], (fp, w8)
    assert w8["peak_estimate_bytes"] < fp["peak_estimate_bytes"], (fp, w8)
    assert est["tp4_paged_engine_decode_w8"].peak_bytes < \
        est["tp4_paged_engine_decode_chunk"].peak_bytes, est


def test_tight_headdim_compiles(mesh):
    """Compile half of the tight-head-dim gate: the unpadded d=64 layout
    must stay legal under Mosaic (runtime parity is the on-chip test)."""
    import tpu_aot

    fa_impl, tcases = tpu_aot.tight_headdim_cases()
    orig = fa_impl._TIGHT_HEADDIM
    fa_impl._TIGHT_HEADDIM = True
    try:
        for name, fn, structs in tcases:
            r = tpu_aot.case_result(mesh, fn, structs)
            assert r["ok"] and r["tpu_custom_call_sites"] >= 1, (name, r)
    finally:
        fa_impl._TIGHT_HEADDIM = orig
