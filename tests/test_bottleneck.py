"""contrib.bottleneck: fused bottleneck + spatial-parallel halo variant vs
the single-device result (reference: apex/contrib/bottleneck tests)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS


@pytest.mark.parametrize("stride,cin,cout", [(1, 32, 32), (2, 32, 64)])
@pytest.mark.slow
def test_spatial_bottleneck_matches_dense(rng, stride, cin, cout):
    from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=8)
    n, h, w = 2, 32, 8
    x = jnp.asarray(rng.standard_normal((n, h, w, cin)), jnp.float32)

    dense = Bottleneck(cin, 16, cout, stride=stride)
    spatial = SpatialBottleneck(cin, 16, cout, stride=stride,
                                spatial_axis=CONTEXT_AXIS)
    params = dense.init(jax.random.PRNGKey(0), x)
    y_ref = dense.apply(params, x)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(None, CONTEXT_AXIS)),
        out_specs=P(None, CONTEXT_AXIS), check_vma=False)
    def run(p, x_slab):
        return spatial.apply(p, x_slab)

    y = jax.jit(run)(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_bottleneck_residual_paths(rng):
    from apex_tpu.contrib.bottleneck import Bottleneck

    x = jnp.asarray(rng.standard_normal((2, 8, 8, 32)), jnp.float32)
    # identity residual (cin == cout, stride 1) must have no downsample
    b1 = Bottleneck(32, 8, 32)
    p1 = b1.init(jax.random.PRNGKey(0), x)
    assert "downsample_weight" not in p1["params"]
    # projection residual
    b2 = Bottleneck(32, 8, 64, stride=2)
    p2 = b2.init(jax.random.PRNGKey(0), x)
    assert "downsample_weight" in p2["params"]
    y = b2.apply(p2, x)
    assert y.shape == (2, 4, 4, 64)
    assert (np.asarray(y) >= 0).all()
