"""Native C++ data loader vs the numpy fallback (identical PCG32 stream).

Reference analog: the apex examples' input pipelines are native (DALI /
torch DataLoader workers); parity here is bit-exact batch equality between
the C++ prefetcher and the pure-numpy path given the same seed.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def shard(tmp_path_factory):
    from apex_tpu.data import write_token_shard

    rng = np.random.default_rng(0)
    path = str(tmp_path_factory.mktemp("data") / "tokens.bin")
    tokens = rng.integers(0, 50000, 4096, dtype=np.int32)
    write_token_shard(path, tokens)
    return path, tokens


def test_numpy_fallback_shapes_and_content(shard):
    from apex_tpu.data import FastLoader

    path, tokens = shard
    ld = FastLoader(path, batch=4, seq_len=64, seed=7, native=False)
    batch = next(ld)
    assert batch.shape == (4, 64) and batch.dtype == np.int32
    # every row must be a contiguous window of the source stream
    for row in batch:
        starts = np.where(tokens == row[0])[0]
        assert any(np.array_equal(tokens[s:s + 64], row) for s in starts
                   if s + 64 <= tokens.size)


def test_native_builds_and_matches_numpy_bit_exact(shard):
    from apex_tpu.data import FastLoader
    from apex_tpu.data.loader import _build_native

    if _build_native() is None:
        pytest.skip("no C++ toolchain in this environment")
    path, _ = shard
    a = FastLoader(path, batch=8, seq_len=32, seed=123, native=True)
    b = FastLoader(path, batch=8, seq_len=32, seed=123, native=False)
    assert a.is_native and not b.is_native
    for _ in range(5):
        np.testing.assert_array_equal(next(a), next(b))


def test_native_prefetch_many_batches(shard):
    from apex_tpu.data import FastLoader
    from apex_tpu.data.loader import _build_native

    if _build_native() is None:
        pytest.skip("no C++ toolchain in this environment")
    path, tokens = shard
    ld = FastLoader(path, batch=16, seq_len=128, seed=5)
    seen = [next(ld) for _ in range(20)]
    assert all(s.shape == (16, 128) for s in seen)
    # prefetch stream must not repeat the same batch
    assert not np.array_equal(seen[0], seen[1])
    # values must come from the shard's vocabulary range
    assert all(int(s.max()) < 50000 and int(s.min()) >= 0 for s in seen)


def test_shard_too_small_raises(tmp_path):
    from apex_tpu.data import FastLoader, write_token_shard

    path = str(tmp_path / "tiny.bin")
    write_token_shard(path, np.arange(16, dtype=np.int32))
    with pytest.raises((ValueError, RuntimeError)):
        FastLoader(path, batch=2, seq_len=64, native=False)


def test_batches_are_writable_on_both_paths(shard):
    """In-place mutation (pad masking etc.) must work identically whether
    the native extension built or not."""
    from apex_tpu.data import FastLoader
    from apex_tpu.data.loader import _build_native

    path, _ = shard
    loaders = [FastLoader(path, batch=2, seq_len=16, seed=1, native=False)]
    if _build_native() is not None:
        loaders.append(FastLoader(path, batch=2, seq_len=16, seed=1,
                                  native=True))
    for ld in loaders:
        b = next(ld)
        b[0, 0] = -1  # must not raise
        assert b[0, 0] == -1


def test_last_token_is_reachable(tmp_path):
    """Window sampling includes the final window (off-by-one regression)."""
    from apex_tpu.data import FastLoader, write_token_shard

    path = str(tmp_path / "edge.bin")
    write_token_shard(path, np.arange(17, dtype=np.int32))
    ld = FastLoader(path, batch=64, seq_len=16, seed=3, native=False)
    seen_last = any(int(next(ld).max()) == 16 for _ in range(20))
    assert seen_last


def test_invalid_batch_raises_not_aborts(shard):
    from apex_tpu.data import FastLoader

    path, _ = shard
    for native in (False, None):
        with pytest.raises(ValueError, match="positive"):
            FastLoader(path, batch=-1, seq_len=32, native=native)


def test_corrupt_shard_rejected_on_both_paths(tmp_path):
    from apex_tpu.data import FastLoader
    from apex_tpu.data.loader import _build_native

    path = str(tmp_path / "corrupt.bin")
    with open(path, "wb") as f:
        f.write(b"\x00" * 4097)  # not a multiple of int32
    with pytest.raises(ValueError):
        FastLoader(path, batch=2, seq_len=16, native=False)
    if _build_native() is not None:
        with pytest.raises(ValueError):
            FastLoader(path, batch=2, seq_len=16, native=True)
