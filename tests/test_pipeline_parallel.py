"""Pipeline-parallel schedules vs single-device ground truth.

Mirrors the reference tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py
(toy MyModel through the schedules, compared against the unpipelined run) and
test_microbatches.py — on the CPU mesh with the stage axis.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import STAGE_AXIS


@pytest.fixture
def pp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(1, 4)


D = 16


def stage_fn(p, x):
    """One toy stage: Linear + tanh, activation shape preserved."""
    return jnp.tanh(x @ p["w"] + p["b"])


def loss_fn(y, labels):
    return jnp.mean((y - labels) ** 2)


def make_params(rng, n_stages):
    return {
        "w": jnp.asarray(rng.standard_normal((n_stages, D, D), np.float32)) / np.sqrt(D),
        "b": jnp.asarray(rng.standard_normal((n_stages, D), np.float32)) * 0.1,
    }


def reference_loss_and_grads(params4, microbatches, labels):
    """Unpipelined: chain the 4 stages, mean loss over microbatches."""

    def full_loss(p4):
        def per_mb(mb, lb):
            x = mb
            for i in range(4):
                x = stage_fn({"w": p4["w"][i], "b": p4["b"][i]}, x)
            return loss_fn(x, lb)

        return jax.vmap(per_mb)(microbatches, labels).mean()

    return jax.value_and_grad(full_loss)(params4)


@pytest.mark.slow
def test_pipeline_matches_sequential(pp4_mesh, rng):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    m = 8
    params4 = make_params(rng, 4)
    mbs = jnp.asarray(rng.standard_normal((m, 4, D), np.float32))
    labels = jnp.asarray(rng.standard_normal((m, 4, D), np.float32))

    ref_loss, ref_grads = reference_loss_and_grads(params4, mbs, labels)

    @functools.partial(
        jax.shard_map, mesh=pp4_mesh,
        in_specs=(P(STAGE_AXIS), P(), P()), out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        p = jax.tree.map(lambda t: t[0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, p, mb, loss_aux=lb)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)

    losses, grads = run(params4, mbs, labels)
    # every stage sees the same broadcast loss
    np.testing.assert_allclose(np.asarray(losses), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    # stage s's grads == reference grads for stage s's slice
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_pipeline_forward_only(pp4_mesh, rng):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    m = 6
    params4 = make_params(rng, 4)
    mbs = jnp.asarray(rng.standard_normal((m, 2, D), np.float32))
    labels = jnp.asarray(rng.standard_normal((m, 2, D), np.float32))
    ref_loss, _ = reference_loss_and_grads(params4, mbs, labels)

    @functools.partial(
        jax.shard_map, mesh=pp4_mesh,
        in_specs=(P(STAGE_AXIS), P(), P()), out_specs=P(STAGE_AXIS),
        check_vma=False)
    def run(p_stacked, mb, lb):
        p = jax.tree.map(lambda t: t[0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, p, mb, loss_aux=lb,
                              forward_only=True)
        assert grads is None
        return loss.reshape(1)

    losses = run(params4, mbs, labels)
    np.testing.assert_allclose(np.asarray(losses), float(ref_loss),
                               rtol=1e-5, atol=1e-6)


def test_no_pipelining_schedule(rng):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining)

    m = 4
    params = {"w": jnp.asarray(rng.standard_normal((D, D), np.float32)),
              "b": jnp.zeros((D,))}
    mbs = jnp.asarray(rng.standard_normal((m, 2, D), np.float32))
    labels = jnp.asarray(rng.standard_normal((m, 2, D), np.float32))

    loss, grads = forward_backward_no_pipelining(
        stage_fn, loss_fn, params, mbs, loss_aux=labels)

    def ref(p):
        return jax.vmap(
            lambda mb, lb: loss_fn(stage_fn(p, mb), lb))(mbs, labels).mean()

    ref_loss, ref_grads = jax.value_and_grad(ref)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), grads, ref_grads)


def test_get_forward_backward_func_dispatch():
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining,
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
        get_forward_backward_func)

    assert (get_forward_backward_func(None, 1)
            is forward_backward_no_pipelining)
    assert (get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving)
    assert (get_forward_backward_func(2, 4)
            is forward_backward_pipelining_with_interleaving)


def test_microbatch_calculators():
    from apex_tpu.transformer.pipeline_parallel import (
        ConstantNumMicroBatchesCalculator,
        RampupBatchsizeNumMicroBatchesCalculator,
        build_num_microbatches_calculator)

    c = build_num_microbatches_calculator(
        global_batch_size=64, micro_batch_size=4, data_parallel_size=2)
    assert isinstance(c, ConstantNumMicroBatchesCalculator)
    assert c.get() == 8
    assert c.get_current_global_batch_size() == 64

    r = build_num_microbatches_calculator(
        rampup_batch_size=[16, 16, 1000], global_batch_size=64,
        micro_batch_size=4, data_parallel_size=2)
    assert isinstance(r, RampupBatchsizeNumMicroBatchesCalculator)
    assert r.get() == 2                      # start 16 / (4*2)
    r.update(500, True)
    # 1000 ramp samples / 3 increments = 333.3 per step; 500 -> 1 step
    assert r.get_current_global_batch_size() == 32
    r.update(2000, True)
    assert r.get() == 8                      # fully ramped

    with pytest.raises(RuntimeError):
        ConstantNumMicroBatchesCalculator(63, 4, 2)


def test_p2p_shift(pp4_mesh):
    from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

    x = jnp.arange(4.0).reshape(4, 1)

    @functools.partial(jax.shard_map, mesh=pp4_mesh,
                       in_specs=P(STAGE_AXIS), out_specs=P(STAGE_AXIS))
    def fwd(v):
        return p2p.send_forward_recv_forward(v)

    @functools.partial(jax.shard_map, mesh=pp4_mesh,
                       in_specs=P(STAGE_AXIS), out_specs=P(STAGE_AXIS))
    def bwd(v):
        return p2p.send_backward_recv_backward(v)

    np.testing.assert_allclose(np.asarray(fwd(x)).ravel(), [0, 0, 1, 2])
    np.testing.assert_allclose(np.asarray(bwd(x)).ravel(), [1, 2, 3, 0])
