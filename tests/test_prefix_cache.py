"""Shared-prefix KV cache (apex_tpu/serving/prefix_cache.py).

Invariant tier (no model): radix-tree match/insert/evict semantics
(page-granular keys, LRU leaf-only eviction, refcount pinning, duplicate
dedup) and the kv_pool sharing ops (``alloc_slot_shared`` /
``release_slot`` / ``evict_pages`` refcount + free-stack bookkeeping).

Engine tier (tiny GPT / Llama): greedy outputs are TOKEN-IDENTICAL with
``prefix_cache`` on vs off — including partial-match, hit-after-evict,
and post-defrag-remap admissions — while the hit/skip counters prove the
prefill actually shrank. Plus the two safety valves: pool exhaustion
defers admission (free stack intact, request completes after a
retirement), and a free-page leak provokes ``defrag`` at the sync
boundary (stack rebuilt from liveness, radix tree remapped)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import (PagedDecodeEngine, PrefixCache, Request,
                              alloc_slot_shared, free_page_count, free_slot,
                              init_paged_cache, release_slot)
from apex_tpu.utils import metrics

PS = 8


def _lockstep(model, v, req, eos=None):
    ref = np.asarray(generate(model, v, np.asarray(req.prompt)[None],
                              max_new_tokens=req.max_new_tokens,
                              eos_token_id=eos))[0, req.prompt.shape[0]:]
    if eos is not None:
        hit = np.where(ref == eos)[0]
        if hit.size:
            ref = ref[:hit[0] + 1]
    return ref


def _req(rng, prefix, tail_len, max_new):
    tail = rng.integers(0, 100, (tail_len,)).astype(np.int32)
    return Request(prompt=np.concatenate([prefix, tail]).astype(np.int32),
                   max_new_tokens=max_new)


# --- invariant tier ----------------------------------------------------------


def test_radix_match_insert_dedup_evict():
    pc = PrefixCache(page_size=4)
    toks = np.arange(14, dtype=np.int32)          # 3 full pages + 2 tail
    row = np.asarray([11, 12, 13, 14, 0, 0], np.int32)

    # cold: no match; retirement inserts the full-page prefix only
    assert pc.match(toks) == []
    keep = pc.release_and_insert(toks, 14, [], row)
    assert keep.tolist() == [True, True, True, False, False, False]
    assert len(pc) == 3 and sorted(pc.pages()) == [11, 12, 13]

    # match is capped at (len-1)//ps so >= 1 token always prefills
    assert [n.page for n in pc.match(toks)] == [11, 12, 13]
    assert [n.page for n in pc.match(toks[:12])] == [11, 12]  # exact-page cap
    assert [n.page for n in pc.match(toks[:5])] == [11]
    # divergence inside a page: no match for that page
    div = toks.copy()
    div[5] = 99
    assert [n.page for n in pc.match(div)] == [11]

    # duplicate insert (a concurrent twin): existing nodes win, our
    # copies free
    keep2 = pc.release_and_insert(toks, 14, [], np.asarray(
        [21, 22, 23, 24, 0, 0], np.int32))
    assert not keep2.any()
    assert len(pc) == 3

    # refs pin; eviction is LRU and leaf-only
    nodes = pc.match(toks)
    pc.acquire(nodes)
    assert pc.evict(3) == []                      # everything pinned
    pc.release(nodes)
    pc.match(toks[:9])                            # bump page 11's chain
    assert pc.evict(1) == [13]                    # deepest leaf, LRU
    assert pc.evict(5) == [12, 11]                # parent exposed next
    assert len(pc) == 0


def test_kv_pool_shared_ops_refcounts():
    cfg = gpt_tiny_config()
    cache = init_paged_cache(cfg, num_slots=2, num_pages=12, page_size=PS)
    cache = free_slot(cache, 0)                   # no-op on an empty slot
    assert int(free_page_count(cache)) == 11

    # pretend pages [1, 2] are cache-held: share them into slot 0 + 2
    # private pages
    shared_row = jnp.zeros((cache["block_tables"].shape[1],), jnp.int32)
    shared_row = shared_row.at[0].set(1).at[1].set(2)
    cache["free_stack"] = jnp.asarray(
        [3, 4, 5, 6, 7, 8, 9, 10, 11, 0, 0, 0], jnp.int32)
    cache["free_top"] = jnp.asarray(9, jnp.int32)
    cache = alloc_slot_shared(cache, 0, shared_row, 2, 2)
    assert int(free_page_count(cache)) == 7
    assert int(cache["shared_pages"][0]) == 2
    assert int(cache["alloc_pages"][0]) == 2
    assert cache["page_ref"][jnp.asarray([1, 2])].tolist() == [1, 1]
    row = np.asarray(cache["block_tables"][0])
    assert row[:2].tolist() == [1, 2] and (row[2:4] > 2).all()

    # a second reader of the same shared pages
    cache = alloc_slot_shared(cache, 1, shared_row, 2, 1)
    assert cache["page_ref"][jnp.asarray([1, 2])].tolist() == [2, 2]

    # free_slot: owned pages return, shared only drop their refcount
    cache = free_slot(cache, 1)
    assert cache["page_ref"][jnp.asarray([1, 2])].tolist() == [1, 1]
    assert int(free_page_count(cache)) == 7      # 1 owned back, none shared

    # release_slot with a keep mask: entry 2 (first private page)
    # transfers to the cache, entry 3 frees, shared entries decref
    keep = np.zeros((row.shape[0],), bool)
    keep[:3] = True
    cache = release_slot(cache, 0, jnp.asarray(keep))
    assert cache["page_ref"][jnp.asarray([1, 2])].tolist() == [0, 0]
    assert int(free_page_count(cache)) == 8      # only entry 3's page back
    assert int(cache["shared_pages"][0]) == 0
    free = set(np.asarray(
        cache["free_stack"][:int(cache["free_top"])]).tolist())
    assert row[2] not in free                    # kept page stayed out
    assert row[3] in free


# --- engine tier -------------------------------------------------------------


def test_prefix_cache_token_identical_and_skips(rng):
    """The acceptance bar: a shared-system-prompt workload decodes
    token-identically with prefix caching on vs off, skipping the shared
    pages' prefill for every request past the first concurrent wave."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    # 4 pages: a power-of-two header, so the admission's match-depth
    # bucketing (compile-count bound) never drops below the full header
    sys_p = rng.integers(0, cfg.vocab_size, (4 * PS,)).astype(np.int32)
    reqs = [_req(rng, sys_p, int(t), int(m))
            for t, m in zip(rng.integers(3, 12, 6), rng.integers(3, 8, 6))]

    e_off = PagedDecodeEngine(model, v, num_slots=2, page_size=PS)
    o_off, s_off = e_off.run(reqs)
    e_on = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                             prefix_cache=True)
    o_on, s_on = e_on.run(reqs)
    for a, b in zip(o_off, o_on):
        np.testing.assert_array_equal(a, b)

    assert not s_off["prefix_cache_enabled"]
    assert s_off["prefill_tokens_skipped"] == 0
    # the first wave (2 slots) prefills cold; everyone after shares the
    # 4 system-prompt pages at minimum
    assert s_on["prefix_hits"] >= len(reqs) - 2
    assert s_on["prefill_tokens_skipped"] >= (len(reqs) - 2) * 4 * PS
    assert (s_on["prefill_tokens_computed"]
            + s_on["prefill_tokens_skipped"]) == s_on["prefill_tokens_total"]
    # pool bookkeeping after the drain: no active readers, and the free
    # stack + cached pages partition the usable pool
    assert int(e_on.cache["page_ref"].sum()) == 0
    usable = e_on.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(e_on.cache)) == usable - len(e_on.prefix)

    # a warm second run: every request hits
    o2, s2 = e_on.run(reqs)
    for a, b in zip(o_off, o2):
        np.testing.assert_array_equal(a, b)
    assert s2["prefix_hits"] == len(reqs)


def test_prefix_cache_partial_match(rng):
    """A prompt diverging inside the cached prefix shares only the pages
    before the divergence — mid-page divergence drops that whole page
    (copy-on-write at page granularity) — and still decodes identically."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    base = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)

    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               prefix_cache=True)
    warm = Request(prompt=np.concatenate(
        [base, rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)]),
        max_new_tokens=4)
    engine.run([warm])

    # diverges at token 12 (inside page 1): only page 0 can match
    part = warm.prompt.copy()
    part[PS + 4] = (part[PS + 4] + 1) % cfg.vocab_size
    partial = Request(prompt=part, max_new_tokens=4)
    # diverges at token 2 (inside page 0): no match at all
    miss = warm.prompt.copy()
    miss[2] = (miss[2] + 1) % cfg.vocab_size
    miss_req = Request(prompt=miss, max_new_tokens=4)

    outs, stats = engine.run([partial, miss_req])
    np.testing.assert_array_equal(outs[0], _lockstep(model, v, partial))
    np.testing.assert_array_equal(outs[1], _lockstep(model, v, miss_req))
    assert stats["prefix_hits"] == 1
    assert stats["prefill_tokens_skipped"] == PS   # page 0 only


def test_prefix_cache_hit_after_evict(rng):
    """Pool pressure evicts LRU refcount-0 cached pages to replenish the
    free stack; a later request re-populates the prefix and hits again —
    token-identical throughout."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    sys_p = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)

    # usable pool of 7 pages: request A (3 pages) caches 2-3 pages; the
    # fat request B (6 pages, distinct prefix) must evict to fit
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               num_pages=8, prefix_cache=True)
    req_a = _req(rng, sys_p, 3, 4)
    (out_a,), _ = engine.run([req_a])
    np.testing.assert_array_equal(out_a, _lockstep(model, v, req_a))
    cached_before = len(engine.prefix)
    assert cached_before >= 2

    fat = Request(prompt=rng.integers(0, cfg.vocab_size,
                                      (5 * PS,)).astype(np.int32),
                  max_new_tokens=PS)
    (out_f,), s_fat = engine.run([fat])
    np.testing.assert_array_equal(out_f, _lockstep(model, v, fat))
    assert s_fat["evicted_pages"] >= 1

    # the shared prefix was (at least partly) evicted: re-run the
    # A-shaped request twice — first re-populates, second hits again
    req_c = _req(rng, sys_p, 4, 4)
    (out_c,), s_c = engine.run([req_c])
    np.testing.assert_array_equal(out_c, _lockstep(model, v, req_c))
    req_d = _req(rng, sys_p, 6, 4)
    (out_d,), s_d = engine.run([req_d])
    np.testing.assert_array_equal(out_d, _lockstep(model, v, req_d))
    assert s_d["prefix_hits"] == 1
    assert s_d["prefill_tokens_skipped"] >= 2 * PS


def test_pool_exhaustion_defers_until_retirement(rng):
    """Admission with insufficient free pages DEFERS the request (free
    stack untouched) and admits it once a retirement returns pages —
    with and without the prefix cache."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size,
                                        (2 * PS,)).astype(np.int32),
                    max_new_tokens=PS) for _ in range(2)]  # 3 pages each

    for prefix_cache in (False, True):
        engine = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                                   num_pages=6, prefix_cache=prefix_cache)
        outs, stats = engine.run(reqs)           # 5 usable pages: one at
        for req, out in zip(reqs, outs):         # a time
            np.testing.assert_array_equal(out, _lockstep(model, v, req))
        assert stats["deferred_admissions"] >= 1
        assert stats["peak_slots_in_use"] == 1
        assert stats["retired"] == 2
        assert int(engine.cache["page_ref"].sum()) == 0
        cached = len(engine.prefix) if prefix_cache else 0
        assert int(free_page_count(engine.cache)) == 5 - cached


def test_defrag_provoked_by_leak(rng):
    """A free-page leak (free stack shorter than liveness implies) makes
    admission invoke ``defrag`` at the sync boundary: the stack rebuilds
    from actual liveness and the deferred request completes."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               num_pages=9)
    req1 = Request(prompt=rng.integers(0, cfg.vocab_size,
                                       (PS,)).astype(np.int32),
                   max_new_tokens=4)
    engine.run([req1])
    # simulate a miscounted free: drop 4 pages off the stack top
    engine.cache["free_top"] = engine.cache["free_top"] - 4
    assert int(free_page_count(engine.cache)) == 4
    req2 = Request(prompt=rng.integers(0, cfg.vocab_size,
                                       (4 * PS,)).astype(np.int32),
                   max_new_tokens=PS)              # needs 5 pages
    (out2,), stats = engine.run([req2])
    np.testing.assert_array_equal(out2, _lockstep(model, v, req2))
    assert stats["defrag_runs"] == 1
    assert int(free_page_count(engine.cache)) == 8   # leak collected


def test_defrag_remaps_prefix_cache(rng):
    """defrag while the radix tree holds pages (some pinned by an active
    request): cached pages survive as extra liveness, the tree follows
    the compaction remap, and a post-defrag admission still HITS the
    remapped pages with token-identical output."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    sys_p = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)

    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                               num_pages=20, prefix_cache=True)
    # seed the tree with EXACTLY the 2 system pages (written length 20
    # -> 2 full pages)
    seed = Request(prompt=np.concatenate(
        [sys_p, rng.integers(0, cfg.vocab_size, (1,)).astype(np.int32)]),
        max_new_tokens=4)
    engine.run([seed])
    assert len(engine.prefix) == 2

    # leak 12 pages, then co-admit X (pins the system pages, long decode)
    # and Y (distinct prefix, needs more than the leaked stack holds):
    # eviction finds nothing (tree fully pinned by X) -> defrag recovers
    engine.cache["free_top"] = engine.cache["free_top"] - 12
    req_x = _req(rng, sys_p, 5, 12)
    req_y = Request(prompt=rng.integers(0, cfg.vocab_size,
                                        (21,)).astype(np.int32),
                    max_new_tokens=4)
    outs, stats = engine.run([req_x, req_y])
    np.testing.assert_array_equal(outs[0], _lockstep(model, v, req_x))
    np.testing.assert_array_equal(outs[1], _lockstep(model, v, req_y))
    assert stats["defrag_runs"] >= 1
    assert stats["evicted_pages"] == 0

    # the remapped tree still serves hits, token-identically
    req_z = _req(rng, sys_p, 4, 3)
    (out_z,), s_z = engine.run([req_z])
    np.testing.assert_array_equal(out_z, _lockstep(model, v, req_z))
    assert s_z["prefix_hits"] == 1


def test_llama_paged_and_prefix_cache(rng):
    """generate(paged=True) now covers Llama (GQA + per-slot RoPE
    gather): token-identical to lock-step, with and without the prefix
    cache; sliding-window paged decode raises cleanly."""
    import dataclasses

    from apex_tpu.models.llama import LlamaModel, llama_tiny_config

    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (3, 12)), jnp.int32)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=5))
    out = np.asarray(generate(model, v, prompt, max_new_tokens=5,
                              paged=True, page_size=PS))
    np.testing.assert_array_equal(out, ref)

    # shared-prefix engine workload over the Llama paged path
    sys_p = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)
    reqs = [_req(rng, sys_p, int(t), 4) for t in rng.integers(2, 9, 4)]
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                               prefix_cache=True)
    outs, stats = engine.run(reqs)
    for req, out in zip(reqs, outs):
        np.testing.assert_array_equal(out, _lockstep(model, v, req))
    assert stats["prefix_hits"] >= len(reqs) - 2

    # sliding-window Llama is paged now (ISSUE 9): the band rides the
    # paged kernel; the prefix cache is the one combination refused
    # (dropped-below-window pages can't be shared cache property)
    wmodel = LlamaModel(dataclasses.replace(cfg, sliding_window=PS))
    wout = np.asarray(generate(wmodel, v, prompt, max_new_tokens=3,
                               paged=True, page_size=PS))
    wref = np.asarray(generate(wmodel, v, prompt, max_new_tokens=3))
    np.testing.assert_array_equal(wout, wref)
    with pytest.raises(ValueError):
        PagedDecodeEngine(wmodel, v, num_slots=2, page_size=PS,
                          prefix_cache=True)


def test_engine_counters_reach_metrics_registry(rng):
    """The serving-observability satellite: engine counters land in
    utils.metrics under serving.* names."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               prefix_cache=True)
    metrics.clear()
    try:
        _, stats = engine.run([Request(
            prompt=rng.integers(0, cfg.vocab_size, (10,)).astype(np.int32),
            max_new_tokens=3)])
        for name in ("decode_steps", "admitted", "retired",
                     "slot_occupancy", "prefix_hit_rate",
                     "prefill_tokens_skipped", "evicted_pages"):
            assert metrics.get(f"serving.{name}") == [
                float(stats[name])], name
    finally:
        metrics.clear()


def test_prefix_cache_requires_paged(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError):
        generate(model, v, jnp.zeros((1, 8), jnp.int32), max_new_tokens=2,
                 prefix_cache=True)
