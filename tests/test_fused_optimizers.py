"""Fused optimizer parity tests.

Mirrors tests/L0/run_optimizers/test_fused_optimizer.py (FusedAdam/FusedSGD vs
framework-native reference) and test_lamb.py (FusedLAMB vs an in-test RefLAMB)
from the reference. The pytree has ragged/odd shapes to exercise flat-buffer
padding and per-tensor segmentation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
from apex_tpu.optimizers import fused_adam, fused_lamb, fused_sgd


def make_tree(key, dtype=jnp.float32):
    k = jax.random.split(key, 4)
    return {
        "dense": {"kernel": jax.random.normal(k[0], (37, 129), dtype),
                  "bias": jax.random.normal(k[1], (129,), dtype)},
        "emb": jax.random.normal(k[2], (100, 64), dtype),
        "scale": jax.random.normal(k[3], (7,), dtype),
    }


def tree_close(a, b, rtol=1e-5, atol=1e-5, msg=""):
    fa = jax.tree.leaves(a)
    fb = jax.tree.leaves(b)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x, np.float32), np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol, err_msg=msg)


def test_fused_adam_matches_optax_adamw():
    params = make_tree(jax.random.PRNGKey(0))
    opt = FusedAdam(params, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)

    ref_tx = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    ref_state = ref_tx.init(params)
    ref_params = params

    cur = params
    for i in range(3):
        grads = jax.tree.map(lambda p: jnp.sin(p) * 0.1, cur)
        cur = opt.step(grads)
        ref_grads = jax.tree.map(lambda p: jnp.sin(p) * 0.1, ref_params)
        upd, ref_state = ref_tx.update(ref_grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)
    tree_close(cur, ref_params, rtol=1e-4, atol=1e-5, msg="adam trajectory")


def test_fused_adam_l2_mode():
    """adam_w_mode=False applies wd as L2 into the gradient (reference mode 1)."""
    params = {"w": jnp.ones((8, 8))}
    opt = FusedAdam(params, lr=1e-2, weight_decay=0.1, adam_w_mode=False)
    grads = {"w": jnp.full((8, 8), 0.5)}
    out = opt.step(grads)
    # manual reference
    g = 0.5 + 0.1 * 1.0
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    want = 1.0 - 1e-2 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(np.asarray(out["w"]), np.full((8, 8), want), rtol=1e-5)


def test_fused_adam_bf16_params_fp32_master():
    """bf16 params: master stays fp32, returned params are bf16 (amp-O2 flow)."""
    params = make_tree(jax.random.PRNGKey(1), jnp.bfloat16)
    opt = FusedAdam(params, lr=1e-3)
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)
    out = opt.step(grads)
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(out))
    assert opt.master.dtype == jnp.float32


def test_noop_skips_step():
    """noop=1 (dynamic-loss-scale overflow) leaves params and state unchanged."""
    params = make_tree(jax.random.PRNGKey(2))
    opt = FusedAdam(params, lr=1e-2)
    grads = jax.tree.map(jnp.ones_like, params)
    m0 = opt.master
    out = opt.step(grads, noop=1.0)
    tree_close(out, params, msg="params changed despite noop")
    np.testing.assert_allclose(np.asarray(opt.state["m"]), 0.0)


def ref_lamb_step(params, grads, m, v, step, lr, b1, b2, eps, wd, max_norm):
    """Pure-jnp RefLAMB (mirrors the in-test reference of test_lamb.py)."""
    leaves_g = jax.tree.leaves(grads)
    gnorm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves_g))
    clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0)

    def one(p, g, m, v):
        g = g * clip
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        u = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        pn = jnp.sqrt(jnp.sum(p ** 2))
        un = jnp.sqrt(jnp.sum(u ** 2))
        ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
        return p - lr * ratio * u, m, v

    out = jax.tree.map(one, params, grads, m, v)
    ps = jax.tree.map(lambda t: t[0], out, is_leaf=lambda t: isinstance(t, tuple))
    ms = jax.tree.map(lambda t: t[1], out, is_leaf=lambda t: isinstance(t, tuple))
    vs = jax.tree.map(lambda t: t[2], out, is_leaf=lambda t: isinstance(t, tuple))
    return ps, ms, vs


def test_fused_lamb_matches_ref():
    params = make_tree(jax.random.PRNGKey(3))
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.01
    opt = FusedLAMB(params, lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd,
                    max_grad_norm=1.0)
    ref_p = params
    ref_m = jax.tree.map(jnp.zeros_like, params)
    ref_v = jax.tree.map(jnp.zeros_like, params)
    cur = params
    for i in range(3):
        grads = jax.tree.map(lambda p: jnp.cos(p) * 0.3, cur)
        cur = opt.step(grads)
        ref_g = jax.tree.map(lambda p: jnp.cos(p) * 0.3, ref_p)
        ref_p, ref_m, ref_v = ref_lamb_step(ref_p, ref_g, ref_m, ref_v, i + 1,
                                            lr, b1, b2, eps, wd, 1.0)
    tree_close(cur, ref_p, rtol=2e-4, atol=2e-5, msg="lamb trajectory")


def test_fused_lamb_inf_grads_auto_skip():
    params = make_tree(jax.random.PRNGKey(4))
    opt = FusedLAMB(params, lr=1e-2)
    grads = jax.tree.map(jnp.ones_like, params)
    grads["scale"] = grads["scale"].at[0].set(jnp.inf)
    out = opt.step(grads)
    tree_close(out, params, msg="step applied despite inf grad")


def test_fused_lamb_wd_exclusion():
    """bias/scale excluded from weight decay via path predicate (param-group
    parity)."""
    params = make_tree(jax.random.PRNGKey(5))
    opt = FusedLAMB(params, lr=1e-2, weight_decay=0.5,
                    exclude_from_weight_decay=lambda name: "bias" in name)
    # wd vector: order of tree leaves (dense/bias, dense/kernel, emb, scale)
    wd = np.asarray(opt.wd_per_segment)
    names = ["dense/bias", "dense/kernel", "emb", "scale"]
    want = [0.0, 0.5, 0.5, 0.5]
    np.testing.assert_allclose(wd, want)


def test_fused_adam_wd_exclusion_applies():
    """exclude_from_weight_decay must actually zero decay on excluded tensors
    (per-segment wd path through the adam kernel)."""
    params = {"kernel": jnp.ones((8, 8)), "bias": jnp.ones((8,))}
    opt = FusedAdam(params, lr=0.0, weight_decay=0.5,
                    exclude_from_weight_decay=lambda n: "bias" in n)
    # lr=0 with adamw: p -= lr*(...) = p unchanged regardless — use lr>0 and
    # zero grads so the only update is the decoupled decay term
    opt.defaults["lr"] = 0.1
    grads = jax.tree.map(jnp.zeros_like, params)
    out = opt.step(grads)
    np.testing.assert_allclose(np.asarray(out["bias"]), 1.0)  # excluded: no decay
    np.testing.assert_allclose(np.asarray(out["kernel"]), 1.0 - 0.1 * 0.5)


def test_fused_lamb_ratio_not_applied_to_wd_excluded():
    """use_nvlamb=False (default): decay-excluded tensors get trust ratio 1
    (reference multi_tensor_lamb semantics)."""
    params = {"kernel": jnp.full((8, 8), 2.0), "bias": jnp.full((8,), 2.0)}
    grads = {"kernel": jnp.full((8, 8), 1e-3), "bias": jnp.full((8,), 1e-3)}
    opt = FusedLAMB(params, lr=1e-2, weight_decay=0.5, max_grad_norm=0.0,
                    exclude_from_weight_decay=lambda n: "bias" in n)
    out = opt.step(grads)
    # bias: ratio = 1, u = mhat/(sqrt(vhat)+eps) = 1 elementwise (constant g)
    np.testing.assert_allclose(np.asarray(out["bias"]), 2.0 - 1e-2, rtol=1e-4)
    # kernel: wd>0, ratio = ||p||/||u|| applied
    pn = np.sqrt(64 * 4.0)
    un = np.sqrt(64 * (1.0 + 0.5 * 2.0) ** 2)
    want = 2.0 - 1e-2 * (pn / un) * (1.0 + 0.5 * 2.0)
    np.testing.assert_allclose(np.asarray(out["kernel"]), want, rtol=1e-4)


def test_fused_sgd_first_step_dampening():
    """First momentum step uses the raw gradient (torch/apex first-use rule)."""
    params = {"w": jnp.ones((4, 4))}
    opt = FusedSGD(params, lr=0.1, momentum=0.9, dampening=0.3)
    g = {"w": jnp.full((4, 4), 1.0)}
    out = opt.step(g)
    # step 1: m = g (no dampening), p = 1 - 0.1*1
    np.testing.assert_allclose(np.asarray(out["w"]), 0.9, rtol=1e-6)
    out2 = opt.step(g)
    # step 2: m = 0.9*1 + 0.7*1 = 1.6
    np.testing.assert_allclose(np.asarray(out2["w"]), 0.9 - 0.16, rtol=1e-6)


def test_fused_sgd_matches_optax():
    params = make_tree(jax.random.PRNGKey(6))
    opt = FusedSGD(params, lr=0.1, momentum=0.9, weight_decay=0.01)
    ref_tx = optax.chain(
        optax.add_decayed_weights(0.01),
        optax.sgd(0.1, momentum=0.9),
    )
    ref_state = ref_tx.init(params)
    ref_p = params
    cur = params
    for _ in range(3):
        grads = jax.tree.map(lambda p: jnp.sin(p), cur)
        cur = opt.step(grads)
        rg = jax.tree.map(lambda p: jnp.sin(p), ref_p)
        upd, ref_state = ref_tx.update(rg, ref_state, ref_p)
        ref_p = optax.apply_updates(ref_p, upd)
    tree_close(cur, ref_p, rtol=1e-5, atol=1e-6, msg="sgd trajectory")


def test_fused_novograd_runs_and_descends():
    params = {"w": jnp.ones((16, 130)), "b": jnp.ones((5,))}
    opt = FusedNovoGrad(params, lr=1e-2, betas=(0.95, 0.98))

    def loss(tree):
        return sum(jnp.sum(l ** 2) for l in jax.tree.leaves(tree))

    cur = params
    l0 = float(loss(cur))
    g0 = jax.grad(loss)(cur)
    cur = opt.step(g0)
    # per-tensor v: after step 1 it equals ||g||^2 per tensor (reference
    # init-from-first-grad-norm semantics)
    want_v = [float(jnp.sum(g ** 2)) for g in jax.tree.leaves(g0)]
    np.testing.assert_allclose(np.asarray(opt.state["v_per_tensor"]), want_v, rtol=1e-5)
    for _ in range(4):
        grads = jax.grad(loss)(cur)
        cur = opt.step(grads)
    assert float(loss(cur)) < l0


def test_optax_transforms():
    params = make_tree(jax.random.PRNGKey(7))
    for tx, ref_tx in [
        (fused_adam(1e-2, weight_decay=0.01), optax.adamw(1e-2, weight_decay=0.01)),
        (fused_sgd(0.1), optax.sgd(0.1)),
    ]:
        state = tx.init(params)
        ref_state = ref_tx.init(params)
        p1, p2 = params, params
        for _ in range(2):
            g1 = jax.tree.map(lambda p: jnp.sin(p), p1)
            upd, state = tx.update(g1, state, p1)
            p1 = optax.apply_updates(p1, upd)
            g2 = jax.tree.map(lambda p: jnp.sin(p), p2)
            upd2, ref_state = ref_tx.update(g2, ref_state, p2)
            p2 = optax.apply_updates(p2, upd2)
        tree_close(p1, p2, rtol=1e-4, atol=1e-5)


def test_flat_buffer_roundtrip():
    from apex_tpu.ops import flat_buffer

    tree = make_tree(jax.random.PRNGKey(8), jnp.bfloat16)
    spec = flat_buffer.build_spec(tree)
    flat = flat_buffer.flatten(tree, spec)
    assert flat.shape[1] == flat_buffer.LANE
    back = flat_buffer.unflatten(flat, spec)
    tree_close(back, tree, rtol=1e-2, atol=1e-2)
    seg = spec.segment_rows()
    assert seg.shape == (spec.total_rows,)
    assert seg[0] == 0 and seg[-1] == spec.num_tensors - 1
