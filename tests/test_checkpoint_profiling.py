"""Checkpoint (orbax, mesh-aware) + profiling utilities (SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P


@pytest.mark.slow
def test_save_restore_roundtrip(tmp_path):
    from apex_tpu.utils import restore_checkpoint, save_checkpoint

    state = {"w": jnp.arange(12.0).reshape(3, 4),
             "step": jnp.asarray(7, jnp.int32),
             "nested": {"b": jnp.ones((5,), jnp.bfloat16)}}
    save_checkpoint(str(tmp_path / "ckpt"), state)
    out = restore_checkpoint(str(tmp_path / "ckpt"))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), state, out)


def test_restore_preserves_sharding(mesh8, tmp_path):
    """ZeRO resume: a row-sharded buffer restores row-sharded."""
    from apex_tpu.mesh import DATA_AXIS
    from apex_tpu.utils import restore_checkpoint, save_checkpoint

    sh = NamedSharding(mesh8, P(DATA_AXIS, None))
    buf = jax.device_put(jnp.arange(64.0).reshape(16, 4), sh)
    save_checkpoint(str(tmp_path / "ckpt"), {"master": buf})
    out = restore_checkpoint(str(tmp_path / "ckpt"), like={"master": buf})
    assert out["master"].sharding == sh
    np.testing.assert_array_equal(np.asarray(out["master"]), np.asarray(buf))


def test_bitwise_resume_of_training(tmp_path, rng):
    """save -> restore -> continue == uninterrupted run, bit-identical."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils import restore_checkpoint, save_checkpoint
    from apex_tpu.utils.checkpoint import (load_optimizer_state_dict,
                                           optimizer_state_dict)

    params = {"w": jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)}
    grads = [{"w": jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)}
             for _ in range(6)]

    # uninterrupted
    opt_a = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    p = None
    for g in grads:
        p = opt_a.step(g)
    ref = np.asarray(p["w"])

    # interrupted after 3 steps
    opt_b = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    for g in grads[:3]:
        opt_b.step(g)
    save_checkpoint(str(tmp_path / "ckpt"),
                    optimizer_state_dict(opt_b))

    opt_c = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    load_optimizer_state_dict(opt_c,
                              restore_checkpoint(str(tmp_path / "ckpt")))
    assert int(opt_c.step_count) == 3
    for g in grads[3:]:
        p = opt_c.step(g)
    np.testing.assert_array_equal(np.asarray(p["w"]), ref)


def test_checkpoint_manager_retention(tmp_path):
    from apex_tpu.utils import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
    for step in range(4):
        mgr.save(step, {"x": jnp.asarray(float(step))})
    assert mgr.latest_step() == 3
    out = mgr.restore()
    assert float(out["x"]) == 3.0
    mgr.close()


def test_annotate_and_time_fn():
    from apex_tpu.utils import annotate, time_fn

    @annotate("test_matmul")
    @jax.jit
    def f(x):
        return x @ x

    x = jnp.ones((64, 64))
    dt, out = time_fn(f, x, iters=3, warmup=1)
    assert dt > 0
    np.testing.assert_allclose(np.asarray(out), 64.0 * np.ones((64, 64)))


def test_llama_moe_resume_roundtrip(tmp_path, rng):
    """Round-3 model families resume bit-identically: Llama params +
    FusedAdam state and a GPT-MoE tree (router + stacked experts) both
    roundtrip through orbax."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
    from apex_tpu.models.llama import LlamaModel, llama_tiny_config
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils import restore_checkpoint, save_checkpoint
    from apex_tpu.utils.checkpoint import (load_optimizer_state_dict,
                                           optimizer_state_dict)

    ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)

    lcfg = llama_tiny_config()
    lparams = LlamaModel(lcfg).init(jax.random.PRNGKey(0), ids)["params"]
    opt = FusedAdam(lparams, lr=1e-3)
    lparams = opt.step(jax.tree.map(jnp.ones_like, lparams))

    mcfg = gpt_tiny_config(num_experts=4, moe_layer_freq=2)
    mparams = GPTModel(mcfg).init(jax.random.PRNGKey(1), ids)["params"]

    state = {"llama": lparams, "opt": optimizer_state_dict(opt),
             "moe": mparams}
    save_checkpoint(str(tmp_path / "families"), state)
    out = restore_checkpoint(str(tmp_path / "families"), like=state)

    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(out)):
        assert a.dtype == b.dtype if hasattr(a, "dtype") else True
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    load_optimizer_state_dict(opt, out["opt"])  # restores cleanly
    assert "moe_mlp" in out["moe"]["layer_1"]
