"""Context parallelism composed with tensor parallelism: GPT on a
(data=2, context=2, model=2) mesh — ring attention rotates K/V over
``context`` inside each TP shard while the Megatron collectives run over
``model``. Loss must match the single-device tp=1 model exactly.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

pytestmark = pytest.mark.slow


@pytest.fixture
def cp2_tp2_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(
        2, 1, context_parallel_size_=2)


def test_gpt_cp_tp_loss_matches_single_device(cp2_tp2_mesh, rng):
    from __graft_entry__ import _slice_tp_tree

    tp = 2
    cfg1 = gpt_tiny_config(tensor_parallel_size=1)
    cfg = gpt_tiny_config(tensor_parallel_size=tp, context_parallel=True)
    m1, m2 = GPTModel(cfg1), GPTModel(cfg)

    b, s = 2, 32
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)

    v1 = m1.init(jax.random.PRNGKey(0), ids)["params"]
    ref = float(gpt_loss(m1, {"params": v1}, ids, labels,
                         axis_name="unbound"))

    v2_shape = jax.eval_shape(
        lambda: m2.init(jax.random.PRNGKey(0), ids))["params"]
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[_slice_tp_tree(v1, v2_shape, r, tp) for r in range(tp)])

    seq_sh = P(None, CONTEXT_AXIS)

    @functools.partial(
        jax.shard_map, mesh=cp2_tp2_mesh,
        in_specs=(P(MODEL_AXIS), seq_sh, seq_sh),
        out_specs=P(MODEL_AXIS, CONTEXT_AXIS),
        check_vma=False)
    def cp_tp_loss(vs, ii, ll):
        v = jax.tree.map(lambda t: t[0], vs)
        return gpt_loss(m2, {"params": v}, ii, ll).reshape(1, 1)

    with cp2_tp2_mesh:
        losses = jax.jit(cp_tp_loss)(stacked, ids, labels)
    # every (tp, cp) coordinate agrees with the unsharded model
    np.testing.assert_allclose(np.asarray(losses),
                               np.full((tp, 2), ref), rtol=3e-5, atol=3e-5)
