"""Multi-host (multi-controller) validation over simulated DCN.

SURVEY.md §4: the reference never tests multi-node in CI — "a gap the TPU
rebuild can close cheaply (XLA CPU backend + jax.distributed simulation)".
This spawns TWO processes that each contribute 4 virtual CPU devices to
one 8-device cluster via ``jax.distributed.initialize`` (Gloo over
localhost = the DCN stand-in), builds the apex_tpu parallel_state mesh
with tp=2 so the ``data`` axis spans the process boundary, and runs a
Megatron-TP GPT grad step whose loss/grad pmean crosses hosts. Both
processes must report identical loss and grad norm.
"""

import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def test_two_process_cluster_tp_gpt_step():
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    worker = os.path.join(REPO, "tests", "_multihost_worker.py")
    procs = [subprocess.Popen([sys.executable, worker, str(i), port],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=REPO, env=env)
             for i in range(2)]
    outs = []
    try:
        for i, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                pytest.fail(f"worker {i} timed out (distributed hang?)")
            outs.append(out)
            assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    finally:
        # a failing/timing-out worker must not orphan its Gloo peer
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    results = []
    for i, out in enumerate(outs):
        assert f"PASS mesh pid={i}" in out, out[-2000:]
        assert f"PASS hybrid pid={i}" in out, out[-2000:]
        m = re.search(rf"PASS step pid={i} loss=([\d.eE+-]+) "
                      rf"gnorm=([\d.eE+-]+)", out)
        assert m, out[-2000:]
        results.append((float(m.group(1)), float(m.group(2))))
    # the cross-host pmean must leave both controllers agreeing exactly
    assert results[0] == pytest.approx(results[1], rel=1e-6), results
