"""TP GPT: single-device vs tensor-parallel parity + convergence smoke.

Mirrors the reference's run_gpt_minimal_test.py
(apex/transformer/testing/standalone_gpt.py): the TP model on a mesh must
match the same model with tp=1 given identical weights, and a few training
steps must reduce the loss.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

pytestmark = pytest.mark.slow


@pytest.fixture
def tp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(4)


def _shard_tree(params1, params4, rank):
    """Slice the tp=1 param tree into rank's tp=4 shard, using the tp=4
    shapes as the guide (column vs row vs vocab split inferred by which dim
    shrank). Fused QKV params are sliced per-third: each rank owns ITS
    heads' q, k and v (Megatron layout), not a contiguous row block."""

    def slice_leaf(path, full, shard):
        if full.shape == shard.shape:
            return full
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if "qkv" in name:
            per = shard.shape[0] // 3
            t = full.reshape(3, full.shape[0] // 3, *full.shape[1:])
            return t[:, rank * per:(rank + 1) * per].reshape(shard.shape)
        for ax in range(full.ndim):
            if full.shape[ax] == shard.shape[ax] * 4:
                size = shard.shape[ax]
                idx = [slice(None)] * full.ndim
                idx[ax] = slice(rank * size, (rank + 1) * size)
                return full[tuple(idx)]
        raise AssertionError(f"unsliceable {full.shape} -> {shard.shape}")

    return jax.tree_util.tree_map_with_path(slice_leaf, params1, params4)


def test_tp4_matches_tp1(tp4_mesh, rng):
    cfg1 = gpt_tiny_config(tensor_parallel_size=1)
    cfg4 = gpt_tiny_config(tensor_parallel_size=4)
    ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 16)), jnp.int32)

    m1 = GPTModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), ids)
    loss1 = gpt_loss(m1, v1, ids, labels, axis_name="unbound")

    m4 = GPTModel(cfg4)
    v4_shape = jax.eval_shape(lambda: m4.init(jax.random.PRNGKey(0), ids))
    shards = [
        _shard_tree(v1["params"], v4_shape["params"], r) for r in range(4)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)

    # check_vma=False: interpreted Pallas kernels can't run under the vma
    # checker (kernel-jaxpr constants carry no vma — jax 0.9 limitation);
    # forward numerics are unaffected
    @functools.partial(
        jax.shard_map, mesh=tp4_mesh,
        in_specs=(P(MODEL_AXIS), P(), P()), out_specs=P(MODEL_AXIS),
        check_vma=False)
    def run(vs, ii, ll):
        v = jax.tree.map(lambda t: t[0], vs)
        return gpt_loss(m4, {"params": v}, ii, ll).reshape(1)

    loss4 = run(stacked, ids, labels)
    np.testing.assert_allclose(np.asarray(loss4), float(loss1),
                               rtol=2e-5, atol=2e-5)


def test_tp_gpt_grads_match_tp1(tp4_mesh, rng):
    """Weight grads of the TP model == the correspondingly-sliced grads of
    the dense model (the universal distributed-test pattern)."""
    cfg1 = gpt_tiny_config(tensor_parallel_size=1, num_layers=1)
    cfg4 = gpt_tiny_config(tensor_parallel_size=4, num_layers=1)
    ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 8)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 8)), jnp.int32)

    m1, m4 = GPTModel(cfg1), GPTModel(cfg4)
    v1 = m1.init(jax.random.PRNGKey(0), ids)
    g1 = jax.grad(
        lambda p: gpt_loss(m1, {"params": p}, ids, labels, axis_name="unbound")
    )(v1["params"])

    v4_shape = jax.eval_shape(lambda: m4.init(jax.random.PRNGKey(0), ids))
    shards = [
        _shard_tree(v1["params"], v4_shape["params"], r) for r in range(4)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)
    # params whose shards are full replicas (norms, pos emb, RPL bias) need
    # their grads psum'd across TP ranks — the role of the reference's
    # grad all-reduce over shared params (check_vma=False does not insert it)
    replicated = jax.tree.map(lambda f, s: f.shape == s.shape,
                              v1["params"], v4_shape["params"])

    @functools.partial(
        jax.shard_map, mesh=tp4_mesh,
        in_specs=(P(MODEL_AXIS), P(), P()), out_specs=P(MODEL_AXIS),
        check_vma=False)
    def run(vs, ii, ll):
        v = jax.tree.map(lambda t: t[0], vs)
        g = jax.grad(lambda p: gpt_loss(m4, {"params": p}, ii, ll))(v)
        return jax.tree.map(lambda t: t[None], g)

    g4 = run(stacked, ids, labels)
    g1_shards = [_shard_tree(g1, v4_shape["params"], r) for r in range(4)]
    g1_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *g1_shards)

    def check(g_tp, g_ref, rep):
        g_tp, g_ref = np.asarray(g_tp), np.asarray(g_ref)
        if rep:
            # replicated params: the copy-region backward all-reduce makes
            # every rank's grad COMPLETE and identical (Megatron semantics —
            # no extra shared-param all-reduce needed within the TP group)
            for r in range(4):
                np.testing.assert_allclose(g_tp[r], g_ref[0],
                                           rtol=5e-3, atol=1e-4)
        else:
            np.testing.assert_allclose(g_tp, g_ref, rtol=5e-3, atol=1e-4)

    jax.tree.map(check, g4, g1_stacked, replicated)


def test_gpt_train_smoke(rng):
    from apex_tpu.optimizers import FusedAdam

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), ids)
    params = v["params"]
    opt = FusedAdam(params, lr=1e-3)
    step = jax.jit(jax.value_and_grad(
        lambda p: gpt_loss(model, {"params": p}, ids, labels,
                           axis_name="unbound")))
    losses = []
    for _ in range(8):
        loss, g = step(params)
        params = opt.step(g)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_remat_same_loss_and_grads(rng):
    """cfg.remat recomputes blocks in backward: loss AND grads must be
    bit-compatible with the non-remat model (same params, same tree)."""
    import dataclasses

    cfg = gpt_tiny_config()
    cfg_r = dataclasses.replace(cfg, remat=True)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    m, mr = GPTModel(cfg), GPTModel(cfg_r)
    v = m.init(jax.random.PRNGKey(0), ids)
    # identical param tree (remat must not rewrap/rename)
    vr = mr.init(jax.random.PRNGKey(0), ids)
    assert jax.tree_util.tree_structure(v) == jax.tree_util.tree_structure(vr)

    l, g = jax.value_and_grad(
        lambda p: gpt_loss(m, {"params": p}, ids, labels))(v["params"])
    lr_, gr_ = jax.value_and_grad(
        lambda p: gpt_loss(mr, {"params": p}, ids, labels))(v["params"])
    np.testing.assert_allclose(float(l), float(lr_), rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr_)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_remat_with_moe_keeps_aux(rng):
    """remat + MoE: the sown aux must survive the lifted checkpoint (a
    zeroed aux would silently disable load balancing)."""
    import dataclasses

    cfg = gpt_tiny_config(num_experts=4, moe_capacity_factor=3.0,
                          moe_aux_loss_coeff=0.0)
    cfg1 = dataclasses.replace(cfg, moe_aux_loss_coeff=1.0, remat=True)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    m0 = GPTModel(dataclasses.replace(cfg, remat=True))
    m1 = GPTModel(cfg1)
    v = m0.init(jax.random.PRNGKey(0), ids)
    l0 = float(gpt_loss(m0, v, ids, labels))
    l1 = float(gpt_loss(m1, v, ids, labels))
    assert l1 > l0 + 0.5  # balance loss >= 1 at any routing
    # the aux must be counted EXACTLY once under remat: equal to the
    # non-remat MoE model's loss (a doubled sow would inflate it)
    l1_plain = float(gpt_loss(GPTModel(dataclasses.replace(
        cfg1, remat=False)), v, ids, labels))
    np.testing.assert_allclose(l1, l1_plain, rtol=1e-6, atol=1e-6)
