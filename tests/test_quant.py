"""int8 W8A8 serving: quant ops, post-training conversion, model fidelity.

Beyond reference (apex has no quantization story). Contract: the int8 MXU
dot with per-channel weight scales + dynamic per-token activation scales
(ops/quant.py) approximates the fp matmul to quantization error; a
converted model's logits stay faithful (cosine) and the decode paths run
unchanged on the quantized tree; TP=2 quantized tracks TP=1 quantized to
cosine > 0.999 (row-parallel shards requantize per rank, so their scales
differ from the whole-row ones by design — see docs/quantization.md).
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.models.llama import LlamaModel, llama_tiny_config
from apex_tpu.models.quantize import quantize_model_params
from apex_tpu.ops.quant import int8_matmul, quantize_weight


def test_quantize_weight_roundtrip_error_bound(rng):
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    q, s = quantize_weight(w)
    assert q.dtype == jnp.int8 and s.shape == (64,)
    deq = q.astype(jnp.float32) * s[:, None]
    # symmetric rounding: per-element error <= half a step of its channel
    err = np.abs(np.asarray(w - deq))
    assert (err <= np.asarray(s)[:, None] * 0.5 + 1e-7).all()


def test_int8_matmul_approximates_fp(rng):
    x = jnp.asarray(rng.standard_normal((4, 10, 48)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    q, s = quantize_weight(w)
    y = np.asarray(int8_matmul(x, q, s))
    ref = np.asarray(x @ w.T)
    # ~1% relative error vs the fp result at 127 levels on both operands
    rel = np.linalg.norm(y - ref) / np.linalg.norm(ref)
    assert rel < 0.02, rel
    # exact when both operands already sit on their int8 grids
    xg = jnp.round(x * 127 / jnp.max(jnp.abs(x), axis=-1, keepdims=True))
    xg = xg * jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127
    deq = q.astype(jnp.float32) * s[:, None]
    np.testing.assert_allclose(np.asarray(int8_matmul(xg, q, s)),
                               np.asarray(xg @ deq.T), rtol=1e-4, atol=1e-4)


def _cosine(a, b, axis=-1):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    num = (a * b).sum(axis)
    return num / (np.linalg.norm(a, axis=axis) * np.linalg.norm(b, axis=axis))


@pytest.mark.parametrize("family", ["gpt", "llama"])
@pytest.mark.slow
def test_quantized_model_logits_faithful(rng, family):
    """Post-training int8 conversion: per-position logits cosine > 0.99
    vs the fp model, and generate() runs on the quantized tree."""
    if family == "gpt":
        cfg = gpt_tiny_config()
        model, qmodel = GPTModel(cfg), GPTModel(
            dataclasses.replace(cfg, quantize_int8=True))
    else:
        cfg = llama_tiny_config(sliding_window=6)
        model, qmodel = LlamaModel(cfg), LlamaModel(
            dataclasses.replace(cfg, quantize_int8=True))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    qparams = quantize_model_params(qmodel, v, ids)
    assert qparams["layer_0"]["qkv" if family == "gpt" else "q_proj"][
        "weight"].dtype == jnp.int8

    fp = np.asarray(model.apply(v, ids), np.float32)
    qt = np.asarray(qmodel.apply({"params": qparams}, ids), np.float32)
    cos = _cosine(fp, qt)
    assert cos.min() > 0.99, cos.min()

    out = np.asarray(generate(qmodel, {"params": qparams}, ids[:, :4],
                              max_new_tokens=5))
    assert out.shape == (2, 9)


def test_quantized_training_path_raises():
    from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

    with pytest.raises(ValueError):
        ColumnParallelLinear(8, 8, quantize=True, world_size=1,
                             gradient_accumulation_fusion=True).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8)))


@pytest.mark.slow
def test_quantized_tp2_matches_tp1(rng):
    """Per-shard quantization is deterministic, so sliced-then-applied
    int8 shards reproduce the tp=1 quantized logits (allclose)."""
    from apex_tpu.transformer import parallel_state
    from tests.test_llama_model import _shard_tree

    tp = 2
    mesh = parallel_state.initialize_model_parallel(tp)
    cfg1 = llama_tiny_config()
    q1 = LlamaModel(dataclasses.replace(cfg1, quantize_int8=True))
    qt = LlamaModel(dataclasses.replace(
        cfg1, quantize_int8=True, tensor_parallel_size=tp))
    ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 8)), jnp.int32)

    m1 = LlamaModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), ids)
    qp1 = quantize_model_params(q1, v1, ids)
    ref = np.asarray(q1.apply({"params": qp1}, ids), np.float32)

    # slice the tp=1 QUANTIZED tree per rank: column shards carry their
    # scale slices; ROW shards must requantize per-shard (their scale is
    # over the full input dim) -> instead quantize per-rank from the fp
    # shards so scales match what a per-rank conversion would produce
    mt = LlamaModel(dataclasses.replace(cfg1, tensor_parallel_size=tp))
    vt_shape = jax.eval_shape(lambda: mt.init(jax.random.PRNGKey(0), ids))
    qt_shape = jax.eval_shape(lambda: qt.init(jax.random.PRNGKey(0), ids))
    from apex_tpu.models.quantize import quantize_params_like

    shards = []
    for r in range(tp):
        fp_shard = _shard_tree(v1["params"], vt_shape["params"], r, tp)
        shards.append(quantize_params_like(qt_shape["params"], fp_shard))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(MODEL_AXIS), P()), out_specs=P(MODEL_AXIS),
        check_vma=False)
    def run(vs, ii):
        v = jax.tree.map(lambda t: t[0], vs)
        from apex_tpu.transformer.tensor_parallel.mappings import (
            gather_from_tensor_model_parallel_region as gather)

        return gather(qt.apply({"params": v}, ii), MODEL_AXIS)[None]

    with mesh:
        out = np.asarray(jax.jit(run)(stacked, ids))[0]
    # row-parallel per-shard scales differ from the tp=1 whole-row scales,
    # so exact equality only holds for column layers; assert faithfulness
    cos = _cosine(ref, out.astype(np.float32))
    assert cos.min() > 0.999, cos.min()


def test_quantize_moe_combination_raises(rng):
    cfg = gpt_tiny_config(num_experts=2, quantize_int8=True)
    model = GPTModel(cfg)
    ids = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(NotImplementedError):
        model.init(jax.random.PRNGKey(0), ids)


@pytest.mark.slow
@pytest.mark.parametrize("ff,tie", [("relu", True), ("gated-gelu", False)])
def test_quantized_t5_logits_faithful(rng, ff, tie):
    """The encoder-decoder family under int8 — BOTH FFN variants and
    both head conventions: teacher-forced logits cosine > 0.99 vs fp,
    and t5_generate runs on the quantized tree."""
    from apex_tpu.models.t5 import T5Model, t5_generate, t5_tiny_config

    cfg = t5_tiny_config(ff_act=ff, tie_word_embeddings=tie)
    model = T5Model(cfg)
    qmodel = T5Model(dataclasses.replace(cfg, quantize_int8=True))
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)
    qparams = quantize_model_params(qmodel, v, enc_ids, dec_ids)
    assert qparams["enc_0"]["self_attn"]["qkv"]["weight"].dtype == jnp.int8

    fp = np.asarray(model.apply(v, enc_ids, dec_ids), np.float32)
    qt = np.asarray(qmodel.apply({"params": qparams}, enc_ids, dec_ids),
                    np.float32)
    cos = _cosine(fp, qt)
    assert cos.min() > 0.99, cos.min()

    out = np.asarray(t5_generate(qmodel, {"params": qparams}, enc_ids,
                                 max_new_tokens=5))
    assert out.shape == (2, 5)


def test_assert_quantized_loaded_guards_placeholders(rng):
    """ADVICE r4: a quantize_int8 model init()s to all-zero int8 weights;
    the guard must reject that tree, accept the converted one, and reject
    a tree with no int8 leaves at all."""
    from apex_tpu.models.quantize import assert_quantized_loaded

    cfg = dataclasses.replace(gpt_tiny_config(), quantize_int8=True)
    qmodel = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), jnp.int32)
    placeholder = qmodel.init(jax.random.PRNGKey(0), ids)["params"]
    with pytest.raises(ValueError, match="all zeros"):
        assert_quantized_loaded(placeholder)

    fp_model = GPTModel(gpt_tiny_config())
    v = fp_model.init(jax.random.PRNGKey(0), ids)
    qparams = quantize_model_params(qmodel, v, ids)
    assert_quantized_loaded(qparams)  # must not raise

    with pytest.raises(ValueError, match="no int8"):
        assert_quantized_loaded(v["params"])
