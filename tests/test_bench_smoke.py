"""bench.py smoke: the measurement path must shard over every local device.

VERDICT r2 weakness #4: throughput divided by n_chips while the step ran on
one device. This runs bench.py as a subprocess on an 8-virtual-CPU-device
platform with the tiny BERT config and asserts the emitted JSON proves the
batch was split 8 ways (n_data_shards == n_chips == 8) with a nonzero
throughput — i.e. per-chip numbers come from a genuinely sharded step.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_sharded_over_8_cpu_devices():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8").strip(),
        "APEX_TPU_BENCH_PLATFORM": "cpu",
        "APEX_TPU_BENCH_CONFIG": "tiny",
        "APEX_TPU_BENCH_BATCH": "2",      # per chip -> global batch 16
        "APEX_TPU_BENCH_SEQ": "64",
        "APEX_TPU_BENCH_STEPS": "2",
        "APEX_TPU_BENCH_RETRIES": "1",
        "APEX_TPU_BENCH_COMPILE_RETRIES": "1",
        "APEX_TPU_BENCH_INIT_TIMEOUT": "120",
    })
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    line = r.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert "error" not in rec, f"bench failed: {rec}\nstderr: {r.stderr[-2000:]}"
    assert rec["n_chips"] == 8
    assert rec["n_data_shards"] == 8, (
        "batch not sharded over the device mesh — per-chip throughput would "
        f"be fictional: {rec}")
    assert rec["value"] > 0


def test_decode_bench_smoke_emits_json():
    """tpu_decode_bench.py in smoke mode prints one parseable JSON record
    with a nonzero steady-state decode throughput."""
    env = dict(os.environ)
    env["APEX_TPU_DECODE_SMOKE"] = "1"
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tpu_decode_bench.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "gpt2_decode_tokens_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["unit"] == "tokens/s/chip"
    # speedup may round toward 0 under extreme CPU scheduler noise —
    # assert presence/sanity, not a ratio
    assert rec["int8_tokens_per_sec"] > 0 and rec["int8_speedup"] >= 0
