"""bench.py smoke: the measurement path must shard over every local device.

VERDICT r2 weakness #4: throughput divided by n_chips while the step ran on
one device. This runs bench.py as a subprocess on an 8-virtual-CPU-device
platform with the tiny BERT config and asserts the emitted JSON proves the
batch was split 8 ways (n_data_shards == n_chips == 8) with a nonzero
throughput — i.e. per-chip numbers come from a genuinely sharded step.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_sharded_over_8_cpu_devices():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8").strip(),
        "APEX_TPU_BENCH_PLATFORM": "cpu",
        "APEX_TPU_BENCH_CONFIG": "tiny",
        "APEX_TPU_BENCH_BATCH": "2",      # per chip -> global batch 16
        "APEX_TPU_BENCH_SEQ": "64",
        "APEX_TPU_BENCH_STEPS": "2",
        "APEX_TPU_BENCH_RETRIES": "1",
        "APEX_TPU_BENCH_COMPILE_RETRIES": "1",
        "APEX_TPU_BENCH_INIT_TIMEOUT": "120",
    })
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    line = r.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert "error" not in rec, f"bench failed: {rec}\nstderr: {r.stderr[-2000:]}"
    assert rec["n_chips"] == 8
    assert rec["n_data_shards"] == 8, (
        "batch not sharded over the device mesh — per-chip throughput would "
        f"be fictional: {rec}")
    assert rec["value"] > 0


def test_decode_bench_smoke_emits_json(tmp_path):
    """tpu_decode_bench.py in smoke mode prints its parseable JSON
    records (lock-step, paged, int8-kv paged, w8 weight-streaming,
    tp=2, prefix-cached, host-tier churn,
    async frontend, speculative, chunked-prefill TTFT A/B), the paged
    record carries the TTFT/decode-step percentile fields (ISSUE 4), the
    frontend record carries the open-loop TTFT/TPOT/deadline-miss fields
    with preemptions > 0 under the adversarial burst (ISSUE 6), and the
    metrics snapshot artifact lands where APEX_TPU_METRICS_OUT points."""
    env = dict(os.environ)
    env["APEX_TPU_DECODE_SMOKE"] = "1"
    # the tp=2 section needs >= 2 devices; don't rely on conftest's
    # env mutation having taken the XLA_FLAGS fallback path
    if "host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
    snap_path = tmp_path / "metrics_snapshot.json"
    env["APEX_TPU_METRICS_OUT"] = str(snap_path)
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tpu_decode_bench.py")],
                       capture_output=True, text=True, timeout=600, env=env,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    recs = {}
    for line in r.stdout.splitlines():
        if line.startswith("{"):
            rec = json.loads(line)
            recs[rec["metric"]] = rec

    rec = recs["gpt2_decode_tokens_per_sec_per_chip"]
    assert rec["value"] > 0
    assert rec["unit"] == "tokens/s/chip"
    # speedup may round toward 0 under extreme CPU scheduler noise —
    # assert presence/sanity, not a ratio
    assert rec["int8_tokens_per_sec"] > 0 and rec["int8_speedup"] >= 0

    paged = recs["gpt2_paged_decode_tokens_per_sec_per_chip"]
    assert paged["gpt2_paged_decode_ttft_ms_p50"] > 0
    assert (paged["gpt2_paged_decode_ttft_ms_p95"]
            >= paged["gpt2_paged_decode_ttft_ms_p50"])
    assert paged["decode_step_ms_p50"] > 0
    assert paged["decode_step_ms_p95"] >= paged["decode_step_ms_p50"]
    assert paged["queue_wait_ms_p50"] >= 0
    assert paged["tpot_ms_p50"] > 0

    # the quantized KV-page engine's record (ISSUE 14, docs/serving.md
    # "Quantized KV pages"): throughput parses, the slot-capacity
    # telemetry carries the >= 1.9x fixed-budget win, and — asserted
    # inside the bench itself — every request's shape and first token
    # match the fp paged engine (full token parity is tolerance-pinned
    # in tests/test_quantized_kv.py, not an exact-identity bench gate)
    q8 = recs["gpt2_int8kv_paged_decode_tokens_per_sec_per_chip"]
    assert q8["value"] > 0
    assert q8["unit"] == "tokens/s/chip"
    assert q8["kv_dtype"] == "int8"
    assert q8["generated_tokens"] == paged["generated_tokens"]
    assert q8["page_bytes_int8"] < q8["page_bytes_fp"]
    assert q8["int8_slot_capacity"] >= 1.9 * q8["fp_slot_capacity"]
    assert q8["slot_capacity_ratio"] >= 1.9
    assert q8["gpt2_int8kv_paged_decode_ttft_ms_p50"] > 0
    assert (q8["gpt2_int8kv_paged_decode_ttft_ms_p95"]
            >= q8["gpt2_int8kv_paged_decode_ttft_ms_p50"])
    assert q8["tpot_ms_p50"] > 0

    # the quantized WEIGHT-streaming record (ISSUE 16, docs/serving.md
    # "Quantized weight streaming"): throughput parses, the weight-tree
    # byte telemetry shows the quantized tree genuinely below the fp
    # tree, and — asserted inside the bench itself — every request's
    # shape and first token match the fp paged engine (fixed-seed pin;
    # tolerance parity lives in tests/test_quantized_weights.py)
    w8 = recs["gpt2_w8_paged_decode_tokens_per_sec_per_chip"]
    assert w8["value"] > 0
    assert w8["unit"] == "tokens/s/chip"
    assert w8["weight_dtype"] == "int8"
    assert w8["generated_tokens"] > 0
    assert w8["w8_weight_bytes"] < w8["fp_weight_bytes"]
    assert 0.0 < w8["weight_bytes_ratio_vs_fp"] < 1.0
    assert w8["gpt2_w8_paged_decode_ttft_ms_p50"] > 0
    assert (w8["gpt2_w8_paged_decode_ttft_ms_p95"]
            >= w8["gpt2_w8_paged_decode_ttft_ms_p50"])
    assert w8["tpot_ms_p50"] > 0

    # the tensor-parallel paged engine's record (ISSUE 10,
    # docs/tp_serving.md): the tp=2 run must have actually happened
    # (conftest forces 8 virtual CPU devices into this subprocess's
    # env), carry the per-chip headline + TTFT/TPOT percentiles, and —
    # asserted inside the bench itself — be greedy token-identical to
    # the single-chip paged engine on the same workload
    tp = recs["gpt2_tp2_paged_decode_tokens_per_sec_per_chip"]
    assert "skipped" not in tp, tp
    assert tp["value"] > 0
    assert tp["tp_world"] == 2
    assert tp["gpt2_tp2_paged_decode_ttft_ms_p50"] > 0
    assert (tp["gpt2_tp2_paged_decode_ttft_ms_p95"]
            >= tp["gpt2_tp2_paged_decode_ttft_ms_p50"])
    assert tp["gpt2_tp2_paged_decode_tpot_ms_p50"] > 0
    assert tp["aggregate_tokens_per_sec"] >= tp["value"]

    pc = recs["gpt2_prefix_cached_decode_tokens_per_sec_per_chip"]
    assert pc["ttft_ms_p50"] > 0 and pc["decode_step_ms_p50"] > 0

    # the tiered KV pool's record (ISSUE 17, docs/serving.md "Tiered KV
    # pool"): the churn workload at a thrash-sized pool actually
    # demoted AND promoted, the promote-hit rate parses, and — asserted
    # inside the bench itself — the tier-on run is token-identical to
    # the tier-off engine with strictly more prefix hits
    ht = recs["gpt2_host_tier_decode_tokens_per_sec_per_chip"]
    assert ht["value"] > 0
    assert ht["unit"] == "tokens/s/chip"
    assert ht["host_tier_enabled"] is True
    assert ht["host_tier_budget_bytes"] > 0
    assert ht["host_tier_demotes"] > 0
    assert ht["host_tier_promotes"] > 0
    assert 0.0 < ht["host_tier_promote_hit_rate"] <= 1.0
    assert ht["evicted_pages"] > 0            # the pool really thrashed
    assert ht["prefill_tokens_skipped"] > 0

    # the async front-end's open-loop record (docs/frontend.md): TTFT /
    # TPOT percentiles + deadline accounting parse, and the adversarial
    # burst (slots pinned low-priority, high-priority arrival) actually
    # exercised the preempt/spill/resume path
    fe = recs["gpt2_frontend_decode_tokens_per_sec_per_chip"]
    assert fe["value"] > 0
    assert fe["gpt2_frontend_ttft_ms_p50"] > 0
    assert (fe["gpt2_frontend_ttft_ms_p95"]
            >= fe["gpt2_frontend_ttft_ms_p50"])
    assert fe["gpt2_frontend_tpot_ms_p50"] > 0
    assert (fe["gpt2_frontend_tpot_ms_p95"]
            >= fe["gpt2_frontend_tpot_ms_p50"])
    assert 0.0 <= fe["gpt2_frontend_deadline_miss_rate"] <= 1.0
    assert (fe["gpt2_frontend_deadline_misses"]
            <= fe["deadlined_requests"])
    assert fe["preemptions"] > 0
    assert fe["resumes"] > 0
    assert fe["peak_queue_depth"] >= 1
    assert fe["prefill_tokens_skipped"] > 0   # resume = a cache hit
    # pump pipeline attribution + recompile window (ISSUE 8): the
    # acceptance fields, present and sane
    assert fe["pump.bubble_ms"] >= 0.0
    assert fe["pump.dispatch_ready_ms_p50"] > 0
    assert fe["pump.host_work_ms_p50"] >= 0
    assert fe["jit.compiles"] >= 0
    assert fe["jit.trace_cache_misses"] >= 0
    assert fe["tpot_slo_misses"] >= 0 and 0.0 <= fe["slo_burn"] <= 1.0

    # the in-engine speculative record (ISSUE 13, docs/serving.md):
    # throughput parses, the self-draft run actually ran speculative
    # rounds, and acceptance telemetry exceeds 1 token per round —
    # token identity against the plain paged engine is asserted inside
    # the bench itself
    sp = recs["gpt2_spec_decode_tokens_per_sec_per_chip"]
    assert sp["value"] > 0
    assert sp["unit"] == "tokens/s/chip"
    assert sp["draft_len"] >= 1 and sp["self_draft"] is True
    assert sp["spec_rounds"] >= 1
    assert sp["spec_tokens"] >= sp["spec_rounds"]
    assert sp["mean_acceptance_len"] > 1.0
    assert sp["mean_acceptance_len"] <= sp["draft_len"] + 1
    assert sp["generated_tokens"] > 0

    # the chunked-prefill TTFT A/B (ISSUE 13, docs/frontend.md): both
    # variants' percentile fields parse, the chunk path engaged on the
    # long prompt (many chunks per chunked admission), and the bench
    # itself asserted token identity between the two runs — the p95
    # reduction is an on-chip number, not a CPU-smoke assert
    cp = recs["gpt2_frontend_chunked_ttft_ms_p95"]
    assert cp["value"] == cp["gpt2_frontend_chunked_ttft_ms_p95"]
    assert cp["gpt2_frontend_chunked_ttft_ms_p50"] > 0
    assert (cp["gpt2_frontend_chunked_ttft_ms_p95"]
            >= cp["gpt2_frontend_chunked_ttft_ms_p50"])
    assert cp["gpt2_frontend_monolithic_ttft_ms_p50"] > 0
    assert (cp["gpt2_frontend_monolithic_ttft_ms_p95"]
            >= cp["gpt2_frontend_monolithic_ttft_ms_p50"])
    assert cp["prefill_chunk"] == cp["page_size"]
    assert cp["chunked_prefills"] >= 1
    assert cp["prefill_chunks"] > cp["chunked_prefills"]

    # the run_tpu_round.sh metrics artifact: a strict-JSON registry
    # snapshot holding the serving histograms
    with open(snap_path) as f:
        snap = json.load(f)
    hist_names = {h["name"] for h in snap["histograms"]}
    assert {"serving.ttft_ms", "serving.decode_step_ms",
            "serving.queue_wait_ms"} <= hist_names
    assert snap["source"] == "tpu_decode_bench"
