"""Full model-parallel composition: PP x TP x CP in one jitted step.

GPT decoder stage-partitioned over ``stage``, Megatron TP over ``model``
inside each stage, and the sequence sharded over ``context`` with ring
attention — all three model-parallel axes of the mesh active in a single
shard_map program (data=1 on the 8-device CPU mesh). Loss must match the
single-device tp=1 unpipelined model.

Schedule note: ring attention emits ppermute (a global collective), so the
dispatcher's _stage_issues_ppermute detection must route this model to the
uniform autodiff schedule — the explicit 1F1B's dead-slot branches would
deadlock the permute rendezvous (this test exercises that routing).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS, STAGE_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

pytestmark = pytest.mark.slow


@pytest.fixture
def pp2_tp2_cp2_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(
        2, 2, context_parallel_size_=2)


def test_gpt_pp_tp_cp_one_step(pp2_tp2_cp2_mesh, rng):
    from __graft_entry__ import _slice_tp_tree

    from apex_tpu.models.gpt_pipeline import (
        make_gpt_pipeline_fns, split_gpt_params_for_pipeline)
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    mesh = pp2_tp2_cp2_mesh
    tp = pp = 2
    n_layers = 2 * pp
    cfg1 = gpt_tiny_config(tensor_parallel_size=1, num_layers=n_layers)
    cfg = gpt_tiny_config(tensor_parallel_size=tp, num_layers=n_layers,
                          context_parallel=True)

    m, b, s = 4, 2, 32
    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)),
                         jnp.int32)

    # reference: unsharded tp=1 model, mean loss over microbatches
    m1 = GPTModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), mbs[0])["params"]
    ref = float(jax.vmap(
        lambda ii, ll: gpt_loss(m1, {"params": v1}, ii, ll,
                                axis_name="unbound"))(mbs, labels).mean())

    v_tp_shape = jax.eval_shape(
        lambda: GPTModel(cfg).init(jax.random.PRNGKey(0), mbs[0]))["params"]
    per_rank = []
    for r in range(tp):
        tp_tree = _slice_tp_tree(v1, v_tp_shape, r, tp)
        per_rank.append(split_gpt_params_for_pipeline(tp_tree, pp, n_layers))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *per_rank)
    stacked = {"blocks": jax.tree.map(lambda t: t[:, :, 0], stacked["blocks"]),
               "shared": stacked["shared"]}

    first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg)

    seq_sh = P(None, None, CONTEXT_AXIS)   # [M, B, S] sharded on S

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS, MODEL_AXIS), seq_sh, seq_sh),
        out_specs=(P(), P(STAGE_AXIS, MODEL_AXIS)),
        check_vma=False)
    def step(p_stacked, mb, lb):
        local = jax.tree.map(lambda t: t[0, 0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, local, mb, loss_aux=lb,
                              first_fn=first_fn, loss_with_params=True)
        return loss, jax.tree.map(lambda t: t[None, None], grads)

    with mesh:
        loss, grads = jax.jit(step)(stacked, mbs, labels)
    jax.block_until_ready(grads)

    np.testing.assert_allclose(float(loss), ref, rtol=3e-5, atol=3e-5)
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))
