"""Scaled-mask-softmax kernels + FusedScaleMaskSoftmax dispatch vs unfused.

Mirrors tests/L0/run_transformer/test_fused_softmax.py (fused kernels vs the
torch fallback path on the same inputs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.scaled_softmax import (
    MASK_FILL,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax


def _ref_masked(x, mask, scale):
    s = jnp.where(mask, MASK_FILL, x.astype(jnp.float32) * scale)
    return jax.nn.softmax(s, -1).astype(x.dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("scale", [1.0, 0.5])
def test_scaled_masked_softmax(rng, dtype, scale):
    b, h, sq, sk = 2, 3, 40, 100
    x = jnp.asarray(rng.standard_normal((b, h, sq, sk)), dtype)
    mask = jnp.asarray(rng.random((b, 1, sq, sk)) < 0.3)
    y = scaled_masked_softmax(x, mask, scale)
    ref = _ref_masked(x, mask, scale)
    tol = 1e-6 if dtype == jnp.float32 else 1e-2
    np.testing.assert_allclose(y.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=tol)


def test_scaled_masked_softmax_grad(rng):
    b, h, sq, sk = 1, 2, 24, 72
    x = jnp.asarray(rng.standard_normal((b, h, sq, sk)), jnp.float32)
    mask = jnp.asarray(rng.random((b, 1, sq, sk)) < 0.3)
    g = jax.grad(lambda x: (scaled_masked_softmax(x, mask, 0.7) ** 2).sum())(x)
    gr = jax.grad(lambda x: (_ref_masked(x, mask, 0.7) ** 2).sum())(x)
    np.testing.assert_allclose(g, gr, atol=1e-6)


def test_upper_triang(rng):
    ab, s = 6, 33
    x = jnp.asarray(rng.standard_normal((ab, s, s)), jnp.float32)
    y = scaled_upper_triang_masked_softmax(x, 2.0)
    tri = jnp.tril(jnp.ones((s, s), bool))
    ref = jax.nn.softmax(jnp.where(tri, x * 2.0, MASK_FILL), -1)
    np.testing.assert_allclose(y, ref, atol=1e-6)
    g = jax.grad(lambda x: (scaled_upper_triang_masked_softmax(x, 2.0) ** 3).sum())(x)
    gr = jax.grad(lambda x: (jax.nn.softmax(
        jnp.where(tri, x * 2.0, MASK_FILL), -1) ** 3).sum())(x)
    np.testing.assert_allclose(g, gr, atol=1e-6)


def test_no_mask(rng):
    x = jnp.asarray(rng.standard_normal((2, 2, 16, 130)), jnp.float32)
    np.testing.assert_allclose(scaled_softmax(x, 1.3),
                               jax.nn.softmax(x * 1.3, -1), atol=1e-6)


class TestFusedScaleMaskSoftmax:
    def test_padding_mask_dispatch(self, rng):
        m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding,
                                  scale=0.5)
        x = jnp.asarray(rng.standard_normal((2, 2, 16, 48)), jnp.float32)
        mask = jnp.asarray(rng.random((2, 1, 16, 48)) < 0.2)
        fused = m(x, mask)
        unfused = m.forward_torch_softmax(x, mask)
        np.testing.assert_allclose(fused, unfused, atol=1e-6)

    def test_causal_dispatch(self, rng):
        m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)
        x = jnp.asarray(rng.standard_normal((2, 2, 24, 24)), jnp.float32)
        fused = m(x)
        unfused = m.forward_torch_softmax(x, None)
        np.testing.assert_allclose(fused, unfused, atol=1e-6)

    def test_reference_assertions(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(scale=2.0, softmax_in_fp32=False)
