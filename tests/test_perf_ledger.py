"""Perf ledger + regression gate (apex_tpu/obs/ledger.py).

Unit tier (synthetic metrics, no tracing): append/load round trips,
seeding from the driver's BENCH wrapper artifacts, and the check
semantics — deterministic ``cost.*`` metrics gate EXACTLY, wall-time
metrics gate direction-aware inside a band, informational counters never
gate. Acceptance tier: the committed ``PERF_LEDGER.jsonl`` has the
seeded history plus a HEAD entry, and ``--check`` against HEAD's
freshly computed cost report exits 0 (a perturbed ledger exits 1) —
run as a subprocess exactly like the ``run_tpu_round.sh`` gate.
"""

import json
import os
import subprocess
import sys

import pytest

from apex_tpu.obs import ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(metrics, kind="cost", tag="t0"):
    return {"schema": 1, "kind": kind, "tag": tag, "git_rev": "abc",
            "time_unix": 0.0, "metrics": metrics}


# --------------------------------------------------------------------------
# storage
# --------------------------------------------------------------------------

def test_append_and_load_roundtrip(tmp_path):
    path = tmp_path / "ledger.jsonl"
    e1 = ledger.append_entry(path, kind="cost", tag="r01",
                             metrics={"cost.x": 1.0}, root=REPO)
    e2 = ledger.append_entry(path, kind="bench", tag="r02",
                             metrics={"tok_per_sec": 10.0}, root=REPO,
                             meta={"note": "n"})
    entries = ledger.load(path)
    assert [e["tag"] for e in entries] == ["r01", "r02"]
    assert entries[0]["metrics"] == {"cost.x": 1.0}
    assert entries[1]["meta"] == {"note": "n"}
    assert e1["git_rev"] and e2["git_rev"]


def test_load_rejects_corrupt_lines(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text(json.dumps(_entry({"a": 1.0})) + "\nnot json\n")
    with pytest.raises(ValueError, match="corrupt"):
        ledger.load(path)
    path.write_text(json.dumps({"no": "metrics"}) + "\n")
    with pytest.raises(ValueError, match="without metrics"):
        ledger.load(path)


def test_bench_metrics_from_wrapper_and_jsonl(tmp_path):
    # the driver's BENCH_r0N.json wrapper shape
    wrapper = tmp_path / "BENCH_r03.json"
    wrapper.write_text(json.dumps({
        "n": 3, "rc": 0, "tail": "...",
        "parsed": {"metric": "bert_tokens_per_sec", "value": 123.4,
                   "error": "tunnel down"}}))
    m, meta = ledger.bench_metrics_from_file(wrapper)
    assert m == {"bert_tokens_per_sec": 123.4}
    assert meta["errors"] == ["tunnel down"]
    # the DECODE_*.json JSONL-of-records shape
    decode = tmp_path / "DECODE_r06.json"
    decode.write_text(
        json.dumps({"metric": "gpt2_decode_tokens_per_sec_per_chip",
                    "value": 50.0, "step_ms": 2.5}) + "\n"
        + json.dumps({"metric": "gpt2_frontend_decode_tokens_per_sec"
                              "_per_chip",
                      "value": 40.0, "pump.bubble_ms": 0.8,
                      "jit.compiles": 3}) + "\n")
    m2, _ = ledger.bench_metrics_from_file(decode)
    assert m2["gpt2_decode_tokens_per_sec_per_chip"] == 50.0
    assert m2["step_ms"] == 2.5
    assert m2["pump.bubble_ms"] == 0.8 and m2["jit.compiles"] == 3.0


def test_seed_history_from_banked_artifacts(tmp_path):
    root = tmp_path
    for n, parsed in ((1, None),
                      (2, {"metric": "m", "value": 0.0, "error": "down"}),
                      (3, {"metric": "m", "value": 7.0})):
        (root / f"BENCH_r0{n}.json").write_text(json.dumps(
            {"n": n, "rc": 1 if parsed is None else 0, "parsed": parsed}))
    path = root / "L.jsonl"
    seeded = ledger._seed_history(root, path)
    entries = ledger.load(path)
    assert seeded == 2                 # the parse-less round is skipped
    assert [e["tag"] for e in entries] == ["r02", "r03"]
    assert entries[1]["metrics"]["m"] == 7.0
    assert all(e["kind"] == "seed" for e in entries)
    # idempotent: a re-run appends nothing (no duplicate trajectory)
    assert ledger._seed_history(root, path) == 0
    assert len(ledger.load(path)) == 2


# --------------------------------------------------------------------------
# check semantics
# --------------------------------------------------------------------------

def test_check_exact_on_cost_metrics():
    entries = [_entry({"cost.total_flops": 100.0})]
    assert ledger.check({"cost.total_flops": 100.0}, entries) == []
    regs = ledger.check({"cost.total_flops": 100.1}, entries)
    assert len(regs) == 1 and regs[0].kind == "exact-drift"
    # drift DOWN trips too: any change must be appended, i.e. reviewed
    assert ledger.check({"cost.total_flops": 99.9}, entries)


def test_check_band_is_direction_aware():
    entries = [_entry({"decode_tokens_per_sec": 100.0,
                       "ttft_ms_p95": 50.0}, kind="bench")]
    # throughput: 25% drop fails, 15% drop passes, any rise passes
    assert ledger.check({"decode_tokens_per_sec": 75.0}, entries)
    assert not ledger.check({"decode_tokens_per_sec": 85.0}, entries)
    assert not ledger.check({"decode_tokens_per_sec": 300.0}, entries)
    # latency: 25% rise fails, 25% fall passes
    assert ledger.check({"ttft_ms_p95": 62.6}, entries)
    assert not ledger.check({"ttft_ms_p95": 37.5}, entries)
    # tightened band flips the verdict
    assert ledger.check({"decode_tokens_per_sec": 85.0}, entries,
                        band_pct=5.0)


def test_check_rates_gate_on_absolute_tolerance():
    """[0,1] ratios with small integer denominators (a ~8-deadline
    scenario quantizes miss_rate in 0.125 steps) use an absolute band —
    one noise-flipped request must not fail the round."""
    entries = [_entry({"scenario.x.deadline_miss_rate": 0.125,
                       "prefix_hit_rate": 0.9}, kind="bench")]
    # one extra miss (+0.125, a 100% relative jump) stays inside the
    # absolute tolerance; a wholesale collapse (+0.5) gates
    assert not ledger.check({"scenario.x.deadline_miss_rate": 0.25},
                            entries)
    assert ledger.check({"scenario.x.deadline_miss_rate": 0.625},
                        entries)
    # hit_rate is higher-better: small dips pass, a collapse gates
    assert not ledger.check({"prefix_hit_rate": 0.8}, entries)
    assert ledger.check({"prefix_hit_rate": 0.5}, entries)
    # a 0.0 miss-rate baseline is a healthy PERFECT score, not a
    # dead-round seed — a collapse from it must still gate (the
    # zero-baseline skip applies only to the relative-band metrics)
    entries0 = [_entry({"scenario.x.deadline_miss_rate": 0.0},
                       kind="bench")]
    assert ledger.check({"scenario.x.deadline_miss_rate": 1.0}, entries0)
    assert not ledger.check({"scenario.x.deadline_miss_rate": 0.125},
                            entries0)


def test_check_skips_informational_and_unmatched():
    entries = [_entry({"decode_steps": 40.0, "old_metric_ms": 1.0})]
    # unknown-direction counters and metrics missing on one side don't gate
    assert ledger.check({"decode_steps": 400.0,
                         "brand_new_metric_ms": 9.0}, entries) == []
    # a zero baseline (the failed-round seeds) never gates
    entries = [_entry({"tok_per_sec": 0.0}, kind="seed")]
    assert ledger.check({"tok_per_sec": 0.0}, entries) == []


def test_check_uses_most_recent_value_per_metric():
    entries = [_entry({"cost.a": 1.0}, tag="old"),
               _entry({"cost.a": 2.0}, tag="new")]
    assert ledger.check({"cost.a": 2.0}, entries) == []
    regs = ledger.check({"cost.a": 1.0}, entries)
    assert regs and "new" in regs[0].baseline_tag
    # a bench metric keeps gating even after many cost-only rounds
    # appended on top (the dead-tunnel cadence) — baselines are
    # per-metric most-recent, not a fixed entry window
    entries = [_entry({"ttft_ms_p95": 50.0}, kind="bench", tag="bench")]
    entries += [_entry({"cost.a": 1.0}, tag=f"r{i}") for i in range(10)]
    regs = ledger.check({"ttft_ms_p95": 100.0, "cost.a": 1.0}, entries)
    assert [r.metric for r in regs] == ["ttft_ms_p95"]


# --------------------------------------------------------------------------
# CLI + acceptance (subprocess, like the run_tpu_round.sh gate)
# --------------------------------------------------------------------------

def _run_ledger(*args, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "apex_tpu.obs.ledger", *args],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)


def test_committed_ledger_has_history_and_head_entry():
    """Acceptance: PERF_LEDGER.jsonl exists with >= 2 entries — the
    seeded (empty-trajectory) history plus HEAD's cost entry."""
    entries = ledger.load(os.path.join(REPO, ledger.LEDGER_NAME))
    assert len(entries) >= 2
    kinds = {e["kind"] for e in entries}
    assert "seed" in kinds and "cost" in kinds
    head = [e for e in entries if e["kind"] == "cost"][-1]
    assert any(k.startswith("cost.case.") for k in head["metrics"])
    assert "cost.decode.weight_fraction" in head["metrics"]


def test_cli_check_exit_codes_synthetic(tmp_path, capsys):
    """The gate's 0/1/2 contract without tracing: main() against a
    synthetic costs report + ledger (fast tier-1 twin of the
    subprocess acceptance test below)."""
    costs_json = tmp_path / "c.json"
    costs_json.write_text(json.dumps({
        "schema": 1, "totals": {"flops": 10, "hbm_bytes": 20,
                                "predicted_ms": 0.5},
        "by_domain": {}, "cases": [], "decode_split": None,
        "errors": []}))
    path = tmp_path / "L.jsonl"
    args = ["--root", REPO, "--ledger", str(path),
            "--costs", str(costs_json)]
    assert ledger.main(["--check", *args]) == 2       # missing ledger
    assert ledger.main(["--append", "--tag", "t1", *args]) == 0
    assert ledger.main(["--check", *args]) == 0       # clean re-run
    # seeded regression: perturb the entry, check must exit 1
    doc = json.loads(path.read_text())
    doc["metrics"]["cost.total_flops"] = 11.0
    path.write_text(json.dumps(doc) + "\n")
    assert ledger.main(["--check", *args]) == 1
    out = capsys.readouterr().out
    assert "cost.total_flops" in out and "--append" in out


@pytest.mark.slow
def test_check_clean_at_head_and_perturbed_trips(tmp_path):
    """Acceptance: a clean --check at HEAD exits 0; a seeded regression
    (perturbed last entry) exits nonzero. Runs the real CLI so the
    gate's environment is exactly what run_tpu_round.sh executes.

    If this fails after an intentional kernel/model change, the cost
    metrics moved: run  python -m apex_tpu.obs.ledger --append --tag
    <tag>  and commit the updated PERF_LEDGER.jsonl (the perf delta
    then shows up as a reviewable line in the PR)."""
    costs_json = tmp_path / "costs.json"
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.obs.costs", "--json",
         str(costs_json)],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]

    r = _run_ledger("--check", "--costs", str(costs_json))
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]

    # perturb the newest cost entry -> exact-drift -> exit 1
    src = os.path.join(REPO, ledger.LEDGER_NAME)
    lines = open(src).read().splitlines()
    perturbed = tmp_path / "perturbed.jsonl"
    doc = json.loads(lines[-1])
    doc["metrics"]["cost.total_flops"] += 1.0
    perturbed.write_text("\n".join(lines[:-1]
                                   + [json.dumps(doc)]) + "\n")
    r = _run_ledger("--check", "--costs", str(costs_json),
                    "--ledger", str(perturbed))
    assert r.returncode == 1
    assert "cost.total_flops" in r.stdout

    # a missing ledger is a hard error — the trajectory must not
    # silently go empty again
    r = _run_ledger("--check", "--costs", str(costs_json),
                    "--ledger", str(tmp_path / "absent.jsonl"))
    assert r.returncode == 2
