"""In-engine speculative decode + chunked prefill (ISSUE 13).

Spec tier: the engine's per-slot speculative rounds (``draft_len``
proposals drafted through a second paged pool, verified in ONE
``s = draft_len + 1`` paged target step) must be greedy token-identical
to BOTH lock-step ``speculative_generate`` and the non-speculative
engine — under full acceptance (self-draft), mixed accept/reject
(unrelated random draft), a smaller-architecture draft, EOS landing
inside a draft block, and preemption/resume mid-stream. Chunked tier:
admission through fixed ``prefill_chunk``-token paged pieces must be
token-identical to monolithic admission, scheduling-invariant across
``sync_every``, and compose with the prefix cache (cached head pages +
chunked tail). All tiny models run in f32, where the s>1 and s=1
forwards agree exactly (the repo's chunked-verify exactness contract)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import speculative_generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import PagedDecodeEngine, Request, free_page_count
from apex_tpu.serving.frontend import ServingFrontend
from apex_tpu.serving.kv_pool import num_pages_of
from apex_tpu.serving.policy import PriorityDeadlinePolicy


@pytest.fixture(scope="module")
def setup():
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    probe = jnp.zeros((1, 8), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), probe)
    # an UNRELATED draft (same dims, different weights): low acceptance,
    # every round exercises the reject/rollback path
    draft = GPTModel(cfg)
    dv = draft.init(jax.random.PRNGKey(99), probe)
    # a smaller-architecture draft: the draft pool's head/width dims
    # differ from the target pool's
    scfg = dataclasses.replace(cfg, hidden_size=32, num_heads=2,
                               num_layers=1)
    small = GPTModel(scfg)
    sv = small.init(jax.random.PRNGKey(5), probe)
    return cfg, model, v, draft, dv, small, sv


def _reqs(rng, sizes=((5, 6), (19, 6), (29, 6))):
    return [Request(prompt=rng.integers(0, 128, s).astype(np.int32),
                    max_new_tokens=m) for s, m in sizes]


def _run(model, v, reqs, **kw):
    eng = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                            sync_every=2, **kw)
    return eng.run(reqs)


@pytest.fixture(scope="module")
def baseline(setup):
    """One non-speculative monolithic reference run, shared by the spec
    and chunked identity tests (every engine instance compiles its own
    programs, so the shared baseline saves a full compile per test).
    The workload mixes short prompts (monolithic fallback under
    chunking) with multi-page ones."""
    _, model, v, *_ = setup
    reqs = _reqs(np.random.default_rng(7))
    outs, _ = PagedDecodeEngine(model, v, num_slots=2,
                                page_size=8).run(reqs)
    return reqs, outs


def test_spec_engine_identical_full_acceptance(setup, baseline):
    """The correctness contract: the self-draft spec engine (every
    proposal accepted) emits the target's greedy stream
    request-for-request, across a ``sync_every`` change vs the
    reference; telemetry shows multi-token rounds. (Rejecting drafts —
    mixed acceptance, smaller architecture — ride the slow tier to
    respect the tier-1 wall budget.)"""
    _, model, v, *_ = setup
    reqs, base = baseline
    full, s_full = _run(model, v, reqs, draft_model=model,
                        draft_variables=v, draft_len=3)
    for a, b in zip(base, full):
        np.testing.assert_array_equal(a, b)
    # self-draft accepts every proposal except budget-clipped final
    # rounds
    assert s_full["mean_acceptance_len"] > 2.0
    assert s_full["spec_rounds"] < s_full["spec_tokens"]


@pytest.mark.slow
def test_spec_engine_identical_mixed_acceptance(setup, baseline):
    """An UNRELATED random draft (most proposals rejected) still emits
    the target's greedy stream — the reject/rollback path is
    token-exact — and the acceptance telemetry stays near the
    one-token-per-round floor."""
    _, model, v, draft, dv, *_ = setup
    reqs, base = baseline
    mixed, s_mixed = _run(model, v, reqs, draft_model=draft,
                          draft_variables=dv, draft_len=3)
    for a, c in zip(base, mixed):
        np.testing.assert_array_equal(a, c)
    # a random draft on a random target accepts ~none: every round
    # still banks the verify step's own token (the floor is 1.0)
    assert 1.0 <= s_mixed["mean_acceptance_len"] < 2.0


@pytest.mark.slow
def test_spec_engine_matches_lockstep_speculative_generate(setup, rng):
    """Same-length prompts run through lock-step
    ``speculative_generate`` (min-over-batch acceptance) and the engine
    (per-slot acceptance): both are exactly target-greedy, so the token
    streams agree even though the round boundaries differ."""
    cfg, model, v, draft, dv, _, _ = setup
    prompts = rng.integers(0, cfg.vocab_size, (3, 9)).astype(np.int32)
    ref = np.asarray(speculative_generate(
        model, v, draft, dv, jnp.asarray(prompts), max_new_tokens=10,
        k=3))[:, 9:]
    outs, _ = _run(model, v,
                   [Request(prompt=p, max_new_tokens=10) for p in prompts],
                   draft_model=draft, draft_variables=dv, draft_len=2)
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(ref[i], np.asarray(out))


@pytest.mark.slow
def test_spec_eos_inside_draft_block(setup, rng):
    """EOS predicted mid-block: emission stops AT the EOS (never past
    it), matching the non-speculative engine's stream exactly."""
    cfg, model, v, _, _, _, _ = setup
    prompt = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    base, _ = _run(model, v, [Request(prompt=prompt, max_new_tokens=12)])
    # pick the 4th greedy token as EOS — it lands inside the first
    # self-draft block of 4 (k = draft_len + 1)
    eos = int(base[0][3])
    r = [Request(prompt=prompt, max_new_tokens=12)]
    o_spec, _ = _run(model, v, r, eos_token_id=eos, draft_model=model,
                     draft_variables=v, draft_len=3)
    o_base, _ = _run(model, v, r, eos_token_id=eos)
    np.testing.assert_array_equal(o_spec[0], o_base[0])
    assert int(o_spec[0][-1]) == eos and len(o_spec[0]) == 4


@pytest.mark.slow
def test_spec_preemption_resumes_token_identical(setup, rng):
    """A speculative slot preempted mid-stream (both pools released,
    discard-and-recompute resume — the spec engine refuses the prefix
    cache) must still emit the uninterrupted greedy stream, and the
    pool must drain clean."""
    cfg, model, v, _, _, small, sv = setup
    lo = Request(prompt=rng.integers(0, 128, 9).astype(np.int32),
                 max_new_tokens=16, priority=0)
    hi = Request(prompt=rng.integers(0, 128, 4).astype(np.int32),
                 max_new_tokens=6, priority=5)
    base, _ = PagedDecodeEngine(model, v, num_slots=1,
                                page_size=8).run([lo, hi])
    eng = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                            draft_model=small, draft_variables=sv,
                            draft_len=2)
    fe = ServingFrontend(
        eng, policy=PriorityDeadlinePolicy(preempt_on_priority=True))
    h_lo = fe.submit(lo, request_id=0)
    fe.pump()
    fe.pump()                      # lo is mid-draft when hi arrives
    h_hi = fe.submit(hi, request_id=1)
    fe.drain()
    stats = fe.stats()
    assert stats["preemptions"] >= 1 and stats["resumes"] >= 1
    np.testing.assert_array_equal(np.asarray(h_lo.result(timeout=0)),
                                  base[0])
    np.testing.assert_array_equal(np.asarray(h_hi.result(timeout=0)),
                                  base[1])
    # both pools fully drained (the zero-leak contract covers the twin)
    assert int(free_page_count(eng.cache)) == num_pages_of(eng.cache) - 1
    assert int(free_page_count(eng.draft_cache)) == \
        num_pages_of(eng.draft_cache) - 1


def test_spec_engine_refuses_invalid_modes(setup):
    cfg, model, v, draft, dv, _, _ = setup
    mk = lambda **kw: PagedDecodeEngine(model, v, num_slots=1,
                                        page_size=8, **kw)
    with pytest.raises(ValueError, match="draft_model"):
        mk(draft_len=2)
    with pytest.raises(ValueError, match="greedy-only"):
        mk(draft_model=draft, draft_variables=dv, draft_len=2,
           temperature=0.5, rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="prefix_cache"):
        mk(draft_model=draft, draft_variables=dv, draft_len=2,
           prefix_cache=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        mk(draft_model=draft, draft_variables=dv, draft_len=2,
           prefill_chunk=8)
    with pytest.raises(ValueError, match="query-block limit"):
        mk(draft_model=draft, draft_variables=dv, draft_len=8)
    with pytest.raises(ValueError, match="1..page_size"):
        mk(prefill_chunk=9)


def test_windowed_models_refuse_spec_and_chunked(setup):
    """Sliding-window models either get the s>1 band (the kernel has
    it) or the ENGINE modes refuse by name — never a silent wrong-mask
    path through the frontend."""
    from apex_tpu.models.llama import LlamaModel, llama_tiny_config
    _, _, _, _, _, small, sv = setup
    wcfg = dataclasses.replace(llama_tiny_config(), sliding_window=6)
    wm = LlamaModel(wcfg)
    wv = wm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="sliding-window"):
        PagedDecodeEngine(wm, wv, num_slots=1, page_size=8,
                          draft_model=small, draft_variables=sv,
                          draft_len=2)
    with pytest.raises(ValueError, match="sliding-window"):
        PagedDecodeEngine(wm, wv, num_slots=1, page_size=8,
                          prefill_chunk=8)


def test_spec_validate_request_draft_overshoot(setup, rng):
    """The draft block's position/page overshoot bound is enforced at
    submit time for BOTH configs."""
    cfg, model, v, _, _, small, sv = setup
    eng = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                            draft_model=small, draft_variables=sv,
                            draft_len=2)
    prompt = rng.integers(0, 128, 8).astype(np.int32)
    # fits without the draft block, overflows with it
    over = cfg.max_position_embeddings - prompt.shape[0] - 1
    with pytest.raises(ValueError, match="draft block"):
        eng._validate_request(Request(prompt=prompt, max_new_tokens=over))


def test_chunked_prefill_identical_and_sync_invariant(setup, baseline):
    """Chunked admission is token-identical to monolithic admission for
    every request — with the two engines at different ``sync_every``
    settings, so the same A/B pins scheduling invariance — and short
    prompts fall back to the monolithic path inside the same engine."""
    _, model, v, *_ = setup
    reqs, base = baseline
    # the monolithic reference runs at sync_every=1, the chunked engine
    # at sync_every=3 — one A/B covers both the admission mode and the
    # chunk cadence (the slow-tier composition test runs chunking at
    # sync_every=1 again)
    eng = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                            sync_every=3, prefill_chunk=8)
    outs, stats = eng.run(reqs)
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(a, b)
    # the 5-token prompt rode the monolithic path; the rest chunked
    assert stats["chunked_prefills"] == 2
    assert stats["prefill_chunks"] > stats["chunked_prefills"]
    assert int(free_page_count(eng.cache)) == \
        num_pages_of(eng.cache) - 1


@pytest.mark.slow
def test_chunked_prefill_composes_with_prefix_cache(setup, rng):
    """A prefix-cache hit admits the cached head as shared pages and
    chunks only the uncached tail — token-identical to the cache-off
    monolithic engine."""
    cfg, model, v, _, _, _, _ = setup
    shared = rng.integers(0, 128, 24).astype(np.int32)
    reqs = [Request(prompt=np.concatenate(
        [shared, rng.integers(0, 128, 13).astype(np.int32)]),
        max_new_tokens=6) for _ in range(4)]
    base, _ = PagedDecodeEngine(model, v, num_slots=2,
                                page_size=8).run(reqs)
    eng = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                            prefix_cache=True, prefill_chunk=8)
    outs, stats = eng.run(reqs)
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(a, b)
    assert stats["prefix_hits"] >= 1
    assert stats["chunked_prefills"] >= 1
    assert stats["prefill_tokens_skipped"] > 0


def test_chunked_prefill_cancel_mid_prefill_frees_pages(setup, rng):
    """Cancellation between chunks aborts the prefill cleanly: the
    handle finishes empty and every page returns to the stack."""
    cfg, model, v, _, _, _, _ = setup
    req = Request(prompt=rng.integers(0, 128, 61).astype(np.int32),
                  max_new_tokens=6)
    eng = PagedDecodeEngine(model, v, num_slots=1, page_size=8,
                            prefill_chunk=8)
    fe = ServingFrontend(eng)
    handle = fe.submit(req, request_id=0)
    fe.pump()
    fe.pump()                        # a few chunks in, far from done
    handle.cancel()
    fe.drain()
    assert len(handle.result(timeout=0)) == 0
    assert int(free_page_count(eng.cache)) == num_pages_of(eng.cache) - 1
