"""T5 encoder-decoder family: relative-bias buckets, training, cached
decode parity, generation, TP parity.

No reference analog (apex ships no models); this family exercises the
encoder-decoder surface — non-causal flash attention, cross-attention
through separate kv operands, the kernel's additive-bias slot carrying the
bucketed relative bias, and encoder-KV caching at decode time.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.t5 import (T5Model, relative_position_bucket, t5_generate,
                                t5_loss, t5_tiny_config)

TOL = dict(rtol=5e-5, atol=5e-5)


def _np_bucket(rel, bidirectional, num_buckets, max_distance):
    """Independent numpy reimplementation of the mesh-tf/HF formula."""
    import math

    ret = 0
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret += (rel > 0).astype(np.int32) * num_buckets
        n = np.abs(rel)
    else:
        n = np.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val = max_exact + (np.log(np.maximum(n, 1) / max_exact)
                       / math.log(max_distance / max_exact)
                       * (num_buckets - max_exact)).astype(np.int32)
    val = np.minimum(val, num_buckets - 1)
    return ret + np.where(is_small, n, val)


@pytest.mark.parametrize("bidir", [True, False])
def test_relative_position_bucket_matches_reference(bidir):
    rel = np.arange(-200, 201, dtype=np.int32)
    got = np.asarray(relative_position_bucket(
        jnp.asarray(rel), bidirectional=bidir, num_buckets=32,
        max_distance=128))
    want = _np_bucket(rel, bidir, 32, 128)
    np.testing.assert_array_equal(got, want)
    assert got.min() >= 0 and got.max() < 32


@pytest.mark.slow
def test_t5_trains(rng):
    """Teacher-forced loss decreases over a few adam steps (both FFN
    variants' params exist and get gradients)."""
    import optax

    cfg = t5_tiny_config(ff_act="gated-gelu")
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 10)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 8)), jnp.int32)
    labels = jnp.roll(dec_ids, -1, axis=1)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)

    opt = optax.adam(1e-2)
    state = opt.init(v["params"])

    @jax.jit
    def step(p, s):
        def loss_fn(pp):
            return t5_loss(model, {"params": pp}, enc_ids, dec_ids, labels,
                           axis_name="unbound")
        loss, g = jax.value_and_grad(loss_fn)(p)
        up, s = opt.update(g, s)
        return jax.tree.map(lambda a, b: a + b, p, up), s, loss

    p = v["params"]
    losses = []
    for _ in range(8):
        p, state, loss = step(p, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_t5_cached_decode_matches_teacher_forced(rng):
    """Incremental decode (self-attn KV cache + cross-KV computed once)
    reproduces the teacher-forced decoder logits position by position."""
    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 7)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)

    full = np.asarray(model.apply(v, enc_ids, dec_ids), np.float32)

    from apex_tpu.models.generation import init_cache, seal_cache

    enc = model.apply(v, enc_ids, method=T5Model.encode)
    cache = init_cache(cfg, 2, 7)
    logits, cache = model.apply(v, dec_ids[:, :3], enc, cache,
                                method=T5Model.decode)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :3], **TOL)
    cache = seal_cache(cache)  # exercise the traced-length path too
    for p in range(3, 7):
        step, cache = model.apply(v, dec_ids[:, p:p + 1], enc, cache,
                                  method=T5Model.decode)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)


@pytest.mark.slow
def test_t5_generate_greedy_matches_teacher_forced(rng):
    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, enc_ids[:, :2])

    out = np.asarray(t5_generate(model, v, enc_ids, max_new_tokens=7))
    assert out.shape == (2, 7)

    # teacher-forced loop at ONE fixed shape: the decoder is causal, so
    # trailing padding can't influence position t-1 — one jitted apply
    # reused 7 times instead of 7 growing-length compiles (r5 rebalance)
    apply = jax.jit(lambda d: model.apply(v, enc_ids, d))
    dec = np.full((2, 8), cfg.decoder_start_token_id, np.int32)
    for t in range(1, 8):
        logits = np.asarray(apply(jnp.asarray(dec)), np.float32)
        dec[:, t] = logits[:, t - 1].argmax(-1).astype(np.int32)
    np.testing.assert_array_equal(out, dec[:, 1:])


@pytest.mark.slow
def test_t5_cross_kv_projected_once(rng):
    """After the first decode step the encoder K/V live in the cache:
    zeroing ``enc`` must not change later step logits (the projected-once
    contract — a recompute-from-enc bug would alter them)."""
    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 3)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)

    from apex_tpu.models.generation import init_cache

    enc = model.apply(v, enc_ids, method=T5Model.encode)
    cache = init_cache(cfg, 2, 4)
    _, cache = model.apply(v, dec_ids[:, :1], enc, cache,
                           method=T5Model.decode)
    assert "ck" in cache["layers"][0]
    step_real, _ = model.apply(v, dec_ids[:, 1:2], enc, cache,
                               method=T5Model.decode)
    step_zero, _ = model.apply(v, dec_ids[:, 1:2], jnp.zeros_like(enc),
                               cache, method=T5Model.decode)
    np.testing.assert_array_equal(np.asarray(step_real),
                                  np.asarray(step_zero))


@pytest.mark.slow
def test_t5_decode_bounds_raise_at_trace_time(rng):
    """A statically out-of-range decoder chunk raises instead of letting
    dynamic_update_slice clamp and corrupt the cache tail."""
    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 5)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 6)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)

    from apex_tpu.models.generation import init_cache

    enc = model.apply(v, enc_ids, method=T5Model.encode)
    cache = init_cache(cfg, 1, 4)  # buffer smaller than the chunk
    with pytest.raises(ValueError):
        model.apply(v, dec_ids, enc, cache, method=T5Model.decode)


@pytest.mark.slow
def test_t5_v11_untied_head_cached_decode(rng):
    """v1.1 shape: gated-gelu FFN + untied lm_head, no d_model^-0.5
    rescale; cached decode must still match teacher forcing."""
    cfg = t5_tiny_config(ff_act="gated-gelu", tie_word_embeddings=False)
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, dec_ids)
    assert "lm_head" in v["params"]

    full = np.asarray(model.apply(v, enc_ids, dec_ids), np.float32)

    from apex_tpu.models.generation import init_cache, seal_cache

    enc = model.apply(v, enc_ids, method=T5Model.encode)
    cache = init_cache(cfg, 2, 5)
    logits, cache = model.apply(v, dec_ids[:, :2], enc, cache,
                                method=T5Model.decode)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :2], **TOL)
    cache = seal_cache(cache)
    for p in range(2, 5):
        step, cache = model.apply(v, dec_ids[:, p:p + 1], enc, cache,
                                  method=T5Model.decode)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)


@pytest.mark.slow
def test_t5_generate_sampling_and_eos(rng):
    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, enc_ids[:, :2])

    kw = dict(max_new_tokens=6, temperature=0.9, top_k=8,
              rng=jax.random.PRNGKey(3))
    s1 = np.asarray(t5_generate(model, v, enc_ids, **kw))
    s2 = np.asarray(t5_generate(model, v, enc_ids, **kw))
    np.testing.assert_array_equal(s1, s2)

    free = np.asarray(t5_generate(model, v, enc_ids, max_new_tokens=6))
    eos = int(free[0, 0])
    out = np.asarray(t5_generate(model, v, enc_ids, max_new_tokens=6,
                                 eos_token_id=eos))
    assert (out[0] == eos).all()


def _t5_shard_tree(params1, params_tp_shape, rank, tp):
    """tp=1 tree -> rank's shard. T5's FUSED column projections need
    per-part slicing (local layout is [A_r | B_r | ...], not a contiguous
    chunk of the fused dim): self-attn ``qkv`` is 3-part, cross-attn
    ``kv`` and gated-gelu ``wi`` are 2-part. Everything else infers the
    split dim from which one shrank (as tests/test_llama_model.py)."""

    def fused_parts(name):
        if "qkv" in name:
            return 3
        if "cross_attn/kv" in name or "/wi/" in name:
            return 2
        return 1

    def slice_leaf(path, full, shard):
        if full.shape == shard.shape:
            return full
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        parts = fused_parts(name)
        # fused projections split the OUTPUT dim; find it via shrinkage
        for ax in range(full.ndim):
            if full.shape[ax] == shard.shape[ax] * tp:
                if parts > 1:
                    per = shard.shape[ax] // parts
                    t = jnp.moveaxis(full, ax, 0)
                    t = t.reshape(parts, t.shape[0] // parts, *t.shape[1:])
                    t = t[:, rank * per:(rank + 1) * per]
                    t = t.reshape(parts * per, *t.shape[2:])
                    return jnp.moveaxis(t, 0, ax)
                size = shard.shape[ax]
                idx = [slice(None)] * full.ndim
                idx[ax] = slice(rank * size, (rank + 1) * size)
                return full[tuple(idx)]
        raise AssertionError(f"unsliceable {name}: {full.shape} -> "
                             f"{shard.shape}")

    return jax.tree_util.tree_map_with_path(slice_leaf, params1,
                                            params_tp_shape)


@pytest.mark.slow
@pytest.mark.parametrize("ff_act", ["relu", "gated-gelu"])
def test_t5_tp2_matches_tp1(rng, ff_act):
    from apex_tpu.transformer import parallel_state

    tp = 2
    mesh = parallel_state.initialize_model_parallel(tp)
    cfg1 = t5_tiny_config(tensor_parallel_size=1, ff_act=ff_act)
    cfgt = t5_tiny_config(tensor_parallel_size=tp, ff_act=ff_act)
    enc_ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 8)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 6)), jnp.int32)
    labels = jnp.roll(dec_ids, -1, axis=1)

    m1 = T5Model(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), enc_ids, dec_ids)
    loss1 = float(t5_loss(m1, v1, enc_ids, dec_ids, labels,
                          axis_name="unbound"))

    mt = T5Model(cfgt)
    vt_shape = jax.eval_shape(
        lambda: mt.init(jax.random.PRNGKey(0), enc_ids, dec_ids))
    shards = [_t5_shard_tree(v1["params"], vt_shape["params"], r, tp)
              for r in range(tp)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(MODEL_AXIS), P(), P(), P()), out_specs=P(MODEL_AXIS),
        check_vma=False)
    def run(vs, ei, di, ll):
        v = jax.tree.map(lambda t: t[0], vs)
        return t5_loss(mt, {"params": v}, ei, di, ll).reshape(1)

    losst = run(stacked, enc_ids, dec_ids, labels)
    np.testing.assert_allclose(np.asarray(losst), loss1, rtol=2e-5, atol=2e-5)
