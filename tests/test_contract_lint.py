"""tpu-lint contract tier (apex_tpu.analysis.contract) coverage.

Mirrors the PR 7 load-bearing pattern for the fifth tier, per ISSUE 20:

1. per-rule fixture pairs — a bad surface (python + text files) that
   triggers EXACTLY its rule (and passes with the rule deselected), and
   a good twin that is clean;
2. machinery — rename pairing, raw-stamp detection, inline suppression
   in BOTH pragma dialects (tokenize for ``.py``, line-regex for the
   markdown/prom surface), the tier-partitioned baseline, CLI usage
   errors, ``--diff`` coverage, the golden regeneration helper;
3. seeded mutations against the LIVE repo: renaming one ``fleet.*``
   gauge, dropping one SSE frame kind from the client parsers, and
   stripping a schema pin each light exactly one rule;
4. end-to-end — ``--contract`` over the repo itself exits 0 at HEAD:
   the tier-1 twin of the ``run_tpu_round.sh`` contract gate.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.analysis import cli                              # noqa: E402
from apex_tpu.analysis.contract import (CONTRACT_RULES,        # noqa: E402
                                        analyze_contract_sources,
                                        build_contract_index,
                                        read_text_surface)
from apex_tpu.analysis.tiers import tier_of, tier_of_key       # noqa: E402

# --------------------------------------------------------------------------
# per-rule fixture pairs: {rule: (bad surface, good surface)} where a
# surface is a {rel path: content} map mixing python and text files
# --------------------------------------------------------------------------

_CATALOG_ONE = """\
## Instrument catalog

| family | meaning |
| --- | --- |
| `serving.base` | documented |
"""

_CATALOG_BOTH = _CATALOG_ONE + "| `serving.fresh` | documented too |\n"

_CATALOG_STALE = _CATALOG_ONE + "| `serving.gone_stat` | retired |\n"

_TWO_FAMILIES = """\
def observe(metrics):
    metrics.counter("serving.base").inc()
    metrics.counter("serving.fresh").inc()
"""

_ENDPOINTS_ONE = """\
## Endpoints

| route | notes |
| --- | --- |
| `GET /ok` | fine |
"""

_ENDPOINTS_BOTH = _ENDPOINTS_ONE + "| `GET /zap` | also served |\n"

_DISPATCH = """\
def dispatch(path):
    if path == "/ok":
        return 1
    if path == "/zap":
        return 2
    return 0
"""

_GOLDEN_OK = """\
# HELP serving_ok requests admitted
# TYPE serving_ok counter
serving_ok 3
"""

_GOLDEN_STALE = _GOLDEN_OK + """\
# TYPE serving_gone counter
serving_gone 1
"""

FIXTURES = {
    "contract-undocumented-metric": (
        {"apex_tpu/mod.py": _TWO_FAMILIES,
         "docs/observability.md": _CATALOG_ONE},
        {"apex_tpu/mod.py": _TWO_FAMILIES,
         "docs/observability.md": _CATALOG_BOTH},
    ),
    "contract-stale-doc-metric": (
        {"apex_tpu/mod.py": _TWO_FAMILIES,
         "docs/observability.md": _CATALOG_STALE.replace(
             "| `serving.base` | documented |\n",
             "| `serving.base` | documented |\n"
             "| `serving.fresh` | documented too |\n")},
        {"apex_tpu/mod.py": _TWO_FAMILIES,
         "docs/observability.md": _CATALOG_BOTH},
    ),
    "contract-label-drift": (
        {"apex_tpu/mod.py": """\
def one(metrics, shard):
    metrics.counter("pool.allocs", labels={"shard": shard}).inc()

def two(metrics, tier):
    metrics.counter("pool.allocs", labels={"tier": tier}).inc()
"""},
        {"apex_tpu/mod.py": """\
def one(metrics, shard):
    metrics.counter("pool.allocs", labels={"shard": shard}).inc()

def two(metrics, shard):
    metrics.counter("pool.allocs", labels={"shard": shard}).inc()
"""},
    ),
    "contract-orphan-event": (
        {"apex_tpu/mod.py": """\
def run(events):
    events.emit("zap", {"n": 1})
"""},
        {"apex_tpu/mod.py": """\
def run(events):
    events.emit("zap", {"n": 1})

def react(e):
    if e["kind"] == "zap":
        return 1
    return 0
"""},
    ),
    "contract-dead-event-consumer": (
        {"apex_tpu/mod.py": """\
def react(e):
    if e["kind"] == "ghost":
        return 1
    return 0
"""},
        {"apex_tpu/mod.py": """\
def run(events):
    events.emit("ghost", {"n": 1})

def react(e):
    if e["kind"] == "ghost":
        return 1
    return 0
"""},
    ),
    "contract-schema-unpinned": (
        {"apex_tpu/mod.py": """\
DOC_SCHEMA = "apex-tpu/thing/v1"
"""},
        {"apex_tpu/mod.py": """\
DOC_SCHEMA = "apex-tpu/thing/v1"

def write(payload):
    return {"schema": DOC_SCHEMA, "payload": payload}

def validate(doc):
    if doc.get("schema") != DOC_SCHEMA:
        raise ValueError("bad schema")
    return doc
"""},
    ),
    "contract-endpoint-undocumented": (
        {"apex_tpu/mod.py": _DISPATCH,
         "docs/http.md": _ENDPOINTS_ONE},
        {"apex_tpu/mod.py": _DISPATCH,
         "docs/http.md": _ENDPOINTS_BOTH},
    ),
    "contract-ledger-class-drift": (
        {"apex_tpu/mod.py": """\
_HIGHER_BETTER = ("tokens_per_sec", "hit_rate")
_LOWER_BETTER = ("_ms", "misses")
_RATE_SUFFIXES = ("hit_rate",)

_BENCH_FIELDS = (
    "decode_ttft_ms",
    "prefix_hit_rate",
    "mystery_knob",
)
"""},
        {"apex_tpu/mod.py": """\
_HIGHER_BETTER = ("tokens_per_sec", "hit_rate")
_LOWER_BETTER = ("_ms", "misses")
_RATE_SUFFIXES = ("hit_rate",)

_BENCH_FIELDS = (
    "decode_ttft_ms",
    "prefix_hit_rate",
)
"""},
    ),
    "contract-golden-stale": (
        {"apex_tpu/mod.py": """\
def observe(metrics):
    metrics.counter("serving.ok").inc()
""",
         "tests/golden/observability.prom": _GOLDEN_STALE},
        {"apex_tpu/mod.py": """\
def observe(metrics):
    metrics.counter("serving.ok").inc()
""",
         "tests/golden/observability.prom": _GOLDEN_OK},
    ),
}


def _run(sources, select=None):
    return analyze_contract_sources(dict(sources), select=select)


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_bad_surface_triggers_exactly_its_rule(rule):
    findings, _ = _run(FIXTURES[rule][0])
    fired = [f.rule for f in findings]
    assert fired, f"bad surface for {rule} produced no findings"
    assert set(fired) == {rule}, fired


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_good_surface_is_clean(rule):
    findings, _ = _run(FIXTURES[rule][1])
    assert not findings, [(f.rule, f.message) for f in findings]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_contract_rules_individually_load_bearing(rule):
    """With the rule deselected (≈ deleted), its bad surface passes: no
    other contract rule shadows it."""
    others = [r for r in CONTRACT_RULES if r != rule]
    findings, _ = _run(FIXTURES[rule][0], select=others)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_every_contract_rule_has_a_fixture():
    assert set(CONTRACT_RULES) == set(FIXTURES)


# --------------------------------------------------------------------------
# machinery: rename pairing, raw stamps, suppression, tiers, CLI
# --------------------------------------------------------------------------

def test_rename_reported_once_naming_both_sides():
    """A produced family missing from the docs paired with a
    near-identical doc-only family is ONE undocumented-metric finding
    describing the rename, not an undocumented + stale double hit."""
    sources = {
        "apex_tpu/mod.py": """\
def observe(metrics):
    metrics.counter("serving.retired_total").inc()
""",
        "docs/observability.md": """\
## Instrument catalog

| family | meaning |
| --- | --- |
| `serving.retire_total` | old name |
""",
    }
    findings, _ = _run(sources)
    assert [f.rule for f in findings] == ["contract-undocumented-metric"]
    msg = findings[0].message
    assert "renamed" in msg
    assert "serving.retired_total" in msg
    assert "serving.retire_total" in msg


def test_unresolvable_metric_name_is_reported():
    findings, _ = _run({"apex_tpu/mod.py": """\
def observe(metrics, name):
    metrics.counter(name).inc()
"""})
    assert [f.rule for f in findings] == ["contract-undocumented-metric"]
    assert "not statically resolvable" in findings[0].message


def test_raw_schema_stamp_is_reported():
    findings, _ = _run({"apex_tpu/mod.py": """\
def write(payload):
    return {"schema": "apex-tpu/raw/v1", "payload": payload}
"""})
    assert [f.rule for f in findings] == ["contract-schema-unpinned"]
    assert "raw schema literal" in findings[0].message


def test_client_path_must_be_served():
    """The client side of the route contract: a request path no server
    dispatch serves fires even when the docs table is absent."""
    findings, _ = _run({"apex_tpu/mod.py": _DISPATCH + """\

def probe(client):
    return client._get_json("/nope")
"""})
    assert [f.rule for f in findings] == \
        ["contract-endpoint-undocumented"]
    assert "/nope" in findings[0].message


def test_sse_contract_both_directions():
    src = """\
class Srv:
    async def _sse(self, writer, kind, payload):
        return kind

    async def serve(self, writer):
        await self._sse(writer, "token", {})
        await self._sse(writer, "done", {})

def parse(event):
    if event == "token":
        return 1
    if event == "ghost":
        return 2
    return 0
"""
    findings, _ = _run({"apex_tpu/mod.py": src})
    msgs = {f.message for f in findings}
    assert {f.rule for f in findings} == \
        {"contract-endpoint-undocumented"}
    assert any("`done`" in m for m in msgs)      # emitted, never parsed
    assert any("`ghost`" in m for m in msgs)     # parsed, never emitted


def test_contract_finding_is_inline_suppressible_in_python():
    bad = FIXTURES["contract-schema-unpinned"][0]["apex_tpu/mod.py"]
    src = bad.replace(
        'DOC_SCHEMA = "apex-tpu/thing/v1"',
        'DOC_SCHEMA = "apex-tpu/thing/v1"  '
        "# tpu-lint: disable=contract-schema-unpinned -- test")
    findings, suppressed = _run({"apex_tpu/mod.py": src})
    assert not findings
    assert suppressed == 2           # unstamped + unvalidated, one site


def test_contract_finding_is_inline_suppressible_in_markdown():
    """The text-surface pragma dialect: an HTML comment on the line
    above a table row suppresses findings anchored to that row."""
    bad = dict(FIXTURES["contract-stale-doc-metric"][0])
    bad["docs/observability.md"] = bad["docs/observability.md"].replace(
        "| `serving.gone_stat` | retired |",
        "<!-- tpu-lint: disable=contract-stale-doc-metric -- kept -->\n"
        "| `serving.gone_stat` | retired |")
    findings, suppressed = _run(bad)
    assert not findings, [(f.rule, f.message) for f in findings]
    assert suppressed == 1


def test_tier_registry_covers_contract():
    assert tier_of("contract-golden-stale") == "contract"
    assert tier_of("conc-lock-order-cycle") == "conc"
    assert tier_of_key("a.py::contract-orphan-event::fn") == "contract"
    assert tier_of_key("a.py::host-sync-in-jit::fn") == "ast"


def test_contract_write_baseline_keeps_other_tiers(tmp_path, monkeypatch):
    """--contract --write-baseline replaces only contract-* entries;
    AST, IR and conc debt survives."""
    from apex_tpu.analysis.walker import Finding

    baseline = tmp_path / "tpu_lint_baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": {
        "x.py::contract-orphan-event::old": 1,
        "y.py::ir-dead-output::case_b": 2,
        "z.py::conc-resource-leak::fn": 3,
    }}))
    fresh = Finding(rule="contract-golden-stale", severity="error",
                    path="g.prom", line=1, col=1, message="m",
                    scope="<module>")
    import apex_tpu.analysis.contract as contract_pkg
    monkeypatch.setattr(contract_pkg, "analyze_contract",
                        lambda root, select=None: ([fresh], 0))
    assert cli.main(["--root", str(tmp_path), "--contract",
                     "--write-baseline"]) == 0
    counts = json.loads(baseline.read_text())["findings"]
    assert counts == {
        "g.prom::contract-golden-stale::<module>": 1,  # tier replaced
        "y.py::ir-dead-output::case_b": 2,             # IR kept
        "z.py::conc-resource-leak::fn": 3,             # conc kept
    }


def test_contract_cli_usage_errors(capsys):
    assert cli.main(["--root", REPO, "--contract",
                     "--select", "no-such-contract-rule"]) == 2
    # conc rule names are not valid in contract mode
    assert cli.main(["--root", REPO, "--contract",
                     "--select", "conc-lock-order-cycle"]) == 2
    assert cli.main(["apex_tpu", "--root", REPO, "--contract"]) == 2
    assert cli.main(["--root", REPO, "--contract", "--mem"]) == 2
    assert cli.main(["--root", REPO, "--contract",
                     "--diff", "HEAD"]) == 2


def test_list_rules_shows_contract_tier(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "contract:wire" in out
    assert "contract-ledger-class-drift" in out
    assert "mem:budget" in out


# --------------------------------------------------------------------------
# the golden regeneration helper and its contract-tier check
# --------------------------------------------------------------------------

def test_golden_regeneration_matches_checked_in_file(tmp_path):
    """``python -m apex_tpu.obs.export --golden`` reproduces the
    checked-in golden byte-for-byte — the seed registry in export.py is
    the single source both the test and the regeneration share."""
    from apex_tpu.obs import export
    from apex_tpu.utils import metrics

    # seed_golden_registry() writes the process-wide registry; clear on
    # both sides so the golden families (different histogram params)
    # never collide with later tests' production registrations
    metrics.clear()
    try:
        out = tmp_path / "observability.prom"
        assert export.main(["--golden", "--out", str(out)]) == 0
        checked_in = Path(REPO, "tests", "golden",
                          "observability.prom").read_text()
        assert out.read_text() == checked_in
    finally:
        metrics.clear()


def test_golden_families_are_produced_at_head():
    """Every ``# TYPE`` family the golden pins maps back (dots to
    underscores, raw-series suffixes stripped) to a family some live
    registration site produces — what contract-golden-stale proves."""
    index, parse_findings = build_contract_index(_contract_sources())
    assert not parse_findings
    assert index.golden_families, "golden exposition lost its TYPE lines"
    produced = {f.replace(".", "_") for f in index.produced_families()}
    for fam in index.golden_families:
        candidates = {fam}
        for suf in ("_count", "_mean", "_last"):
            if fam.endswith(suf):
                candidates.add(fam[: -len(suf)])
        assert candidates & produced, fam


# --------------------------------------------------------------------------
# --diff covers the contract tier
# --------------------------------------------------------------------------

_DIFF_PY = """\
def observe(metrics):
    metrics.counter("scratch.ok").inc()
"""

_DIFF_DOC = """\
## Instrument catalog

| family | meaning |
| --- | --- |
| `scratch.ok` | fine |
"""


def _git(repo, *args):
    subprocess.run(["git", "-C", str(repo), *args], check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_diff_covers_contract_tier(tmp_path, capsys):
    """A metric family registered since the base rev without a catalog
    entry fails the diff gate; the committed state is diff-clean."""
    _git(tmp_path, "init", "-q")
    mod = tmp_path / "tpu_scratch.py"
    mod.write_text(_DIFF_PY)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(_DIFF_DOC)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "base")
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD"]) == 0
    capsys.readouterr()
    mod.write_text(_DIFF_PY + """\

def observe_more(metrics):
    metrics.counter("scratch.fresh").inc()
""")
    rc = cli.main(["--root", str(tmp_path), "--diff", "HEAD"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "contract-undocumented-metric" in out
    assert "scratch.fresh" in out


# --------------------------------------------------------------------------
# seeded mutations against the live repo surface
# --------------------------------------------------------------------------

def _surface_sources():
    root = Path(REPO)
    return {cli._rel(root, p): p.read_text()
            for p in cli.discover(root, ())}


def _contract_sources():
    sources = _surface_sources()
    sources.update(read_text_surface(REPO))
    return sources


_FLEET = "apex_tpu/obs/fleet.py"
_FLEET_GAUGE = 'metrics.gauge("fleet.scrape_age_s"'


def test_mutation_renamed_gauge_is_caught():
    """ISSUE 20 acceptance: renaming one ``fleet.*`` gauge at its live
    registration site fires exactly contract-undocumented-metric, as a
    rename pairing naming both the new and the cataloged name."""
    sources = _contract_sources()
    src = sources[_FLEET]
    assert src.count(_FLEET_GAUGE) == 1, "fleet gauge anchor moved"
    sources[_FLEET] = src.replace(
        _FLEET_GAUGE, 'metrics.gauge("fleet.scrape_age_z"')
    findings, _ = analyze_contract_sources(sources)
    assert {f.rule for f in findings} == \
        {"contract-undocumented-metric"}, \
        [(f.rule, f.message) for f in findings]
    msg = findings[0].message
    assert "fleet.scrape_age_z" in msg
    assert "fleet.scrape_age_s" in msg


_SSE_DONE = 'elif event == "done":'
_SSE_CONSUMERS = ("apex_tpu/serving/http.py",
                  "apex_tpu/serving/scenarios/http_driver.py")


def test_mutation_dropped_sse_parse_arm_is_caught():
    """ISSUE 20 acceptance: dropping the ``done`` parse arm from EVERY
    live SSE client (parse facts union across files) fires exactly
    contract-endpoint-undocumented on the emit site."""
    sources = _contract_sources()
    for rel in _SSE_CONSUMERS:
        assert sources[rel].count(_SSE_DONE) == 1, \
            f"SSE done-arm anchor moved in {rel}"
        sources[rel] = sources[rel].replace(
            _SSE_DONE, 'elif event == "token":')
    findings, _ = analyze_contract_sources(sources)
    assert {f.rule for f in findings} == \
        {"contract-endpoint-undocumented"}, \
        [(f.rule, f.message) for f in findings]
    assert any("`done`" in f.message for f in findings)


_REPORT = "apex_tpu/serving/scenarios/report.py"
_SCHEMA_STAMP = '        "schema": REPORT_SCHEMA,\n'


def test_mutation_stripped_schema_pin_is_caught():
    """ISSUE 20 acceptance: removing the report writer's schema stamp
    fires exactly contract-schema-unpinned on the constant."""
    sources = _contract_sources()
    src = sources[_REPORT]
    assert src.count(_SCHEMA_STAMP) == 1, "report schema stamp moved"
    sources[_REPORT] = src.replace(_SCHEMA_STAMP, "")
    findings, _ = analyze_contract_sources(sources)
    assert {f.rule for f in findings} == {"contract-schema-unpinned"}, \
        [(f.rule, f.message) for f in findings]
    assert "REPORT_SCHEMA" in findings[0].message
    assert "never stamped" in findings[0].message


def test_unmutated_surface_is_clean():
    """The live surface carries no contract findings beyond the
    inline-suppressed intentional gaps."""
    findings, suppressed = analyze_contract_sources(_contract_sources())
    assert not findings, [(f.rule, f.path, f.line) for f in findings]
    assert suppressed >= 1           # the documented intentional gaps


# --------------------------------------------------------------------------
# end-to-end: the repo is contract-clean at HEAD (tier-1 gate twin)
# --------------------------------------------------------------------------

def test_repo_contract_is_clean_at_head(capsys):
    rc = cli.main(["--root", REPO, "--contract"])
    out = capsys.readouterr().out
    assert rc == 0, \
        f"tpu-lint --contract found new issues in the repo:\n{out}"
