"""Real-model pipeline: GPT stage-partitioned over ``stage`` composed with
TP over ``model``, vs the single-device model (VERDICT round-1 item 4).

Also covers the interleaved (VPP) schedule vs a sequential reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS, STAGE_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config
from apex_tpu.models.gpt_pipeline import (
    make_gpt_pipeline_fns,
    merge_pipeline_grads_to_gpt,
    split_gpt_params_for_pipeline,
)

pytestmark = pytest.mark.slow


def _shard_tree(params1, params_tp_shape, rank, tp):
    """Slice a tp=1 GPT param tree into rank's tp shard (see
    tests/test_gpt_model.py; generalized over tp)."""

    def slice_leaf(path, full, shard):
        if full.shape == shard.shape:
            return full
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if "qkv" in name:
            per = shard.shape[0] // 3
            t = full.reshape(3, full.shape[0] // 3, *full.shape[1:])
            return t[:, rank * per:(rank + 1) * per].reshape(shard.shape)
        for ax in range(full.ndim):
            if full.shape[ax] == shard.shape[ax] * tp:
                size = shard.shape[ax]
                idx = [slice(None)] * full.ndim
                idx[ax] = slice(rank * size, (rank + 1) * size)
                return full[tuple(idx)]
        raise AssertionError(f"unsliceable {full.shape} -> {shard.shape}")

    return jax.tree_util.tree_map_with_path(slice_leaf, params1,
                                            params_tp_shape)


@pytest.mark.parametrize("schedule", ["1f1b", "interleaved"])
def test_gpt_pp2_tp2_matches_single_device(mesh_tp2_pp2_dp2, rng, schedule):
    mesh = mesh_tp2_pp2_dp2
    pp, tp = 2, 2
    vpp = 2 if schedule == "interleaved" else 1
    n_layers = 4
    m, b, s = 4, 2, 8

    cfg1 = gpt_tiny_config(tensor_parallel_size=1, num_layers=n_layers)
    cfg2 = gpt_tiny_config(tensor_parallel_size=tp, num_layers=n_layers)

    mbs = jnp.asarray(rng.integers(0, cfg1.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg1.vocab_size, (m, b, s)),
                         jnp.int32)

    # reference: single-device GPT, mean loss over microbatches
    m1 = GPTModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), mbs[0])["params"]

    def ref_loss(p):
        per = jax.vmap(lambda ii, ll: gpt_loss(
            m1, {"params": p}, ii, ll, axis_name="unbound"))(mbs, labels)
        return per.mean()

    ref_l, ref_g = jax.value_and_grad(ref_loss)(v1)

    # tp-slice the full tree per rank, then stage-partition each
    m2 = GPTModel(cfg2)
    v2_shape = jax.eval_shape(
        lambda: m2.init(jax.random.PRNGKey(0), mbs[0]))["params"]
    per_rank = []
    for r in range(tp):
        tp_tree = _shard_tree(v1, v2_shape, r, tp)
        per_rank.append(split_gpt_params_for_pipeline(
            tp_tree, pp, n_layers, virtual_chunks=vpp))
    # stack [S, T, ...]: stage leading (P(STAGE_AXIS, MODEL_AXIS))
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs, axis=1), *per_rank)

    first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg2)
    from tests.conftest import make_sched_adapters
    fwd_bwd, to_sched_tree, from_sched_tree = make_sched_adapters(
        schedule, vpp)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS, MODEL_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS, MODEL_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        local = jax.tree.map(lambda t: t[0, 0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, to_sched_tree(local), mb,
                              loss_aux=lb, first_fn=first_fn,
                              loss_with_params=True)
        grads = from_sched_tree(grads)
        return loss.reshape(1), jax.tree.map(lambda t: t[None, None], grads)

    losses, grads = jax.jit(run)(stacked, mbs, labels)
    np.testing.assert_allclose(np.asarray(losses), float(ref_l),
                               rtol=2e-5, atol=2e-5)

    # reassemble per-TP-rank GPT grad trees; shared grads psum over stages
    for r in range(tp):
        g_rank = jax.tree.map(lambda t, r=r: t[:, r], grads)
        gpt_grads = merge_pipeline_grads_to_gpt(g_rank, pp, n_layers,
                                                virtual_chunks=vpp)
        ref_rank = _shard_tree(ref_g, v2_shape, r, tp)
        replicated = jax.tree.map(lambda f, s: f.shape == s.shape,
                                  ref_g, v2_shape)

        def check(g_pp, g_ref, rep):
            np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref),
                                       rtol=5e-3, atol=1e-4)

        jax.tree.map(check, gpt_grads, ref_rank, replicated)


def test_interleaved_toy_matches_sequential(rng):
    """VPP with V=2 chunks on pp=4: 8 virtual stages vs an 8-layer chain."""
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving as fwd_bwd)

    mesh = parallel_state.initialize_model_parallel(1, 4)
    S, V, D, m = 4, 2, 8, 6
    # virtual stage v*S + s lives at [s, v] in the stacked layout
    w_virt = rng.standard_normal((V * S, D, D)).astype(np.float32) / np.sqrt(D)
    b_virt = (rng.standard_normal((V * S, D)) * 0.1).astype(np.float32)
    w = np.zeros((S, V, D, D), np.float32)
    bb = np.zeros((S, V, D), np.float32)
    for v in range(V):
        for s in range(S):
            w[s, v] = w_virt[v * S + s]
            bb[s, v] = b_virt[v * S + s]
    params = {"w": jnp.asarray(w), "b": jnp.asarray(bb)}
    mbs = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    labels = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(y, lb):
        return jnp.mean((y - lb) ** 2)

    def ref(pw, pb):
        def per_mb(mb, lb):
            x = mb
            for i in range(V * S):
                x = jnp.tanh(x @ pw[i] + pb[i])
            return jnp.mean((x - lb) ** 2)

        return jax.vmap(per_mb)(mbs, labels).mean()

    ref_l, (ref_gw, ref_gb) = jax.value_and_grad(ref, argnums=(0, 1))(
        jnp.asarray(w_virt), jnp.asarray(b_virt))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        local = jax.tree.map(lambda t: t[0], p_stacked)  # [V, ...] chunks
        loss, grads = fwd_bwd(stage_fn, loss_fn, local, mb, loss_aux=lb)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)

    losses, grads = jax.jit(run)(params, mbs, labels)
    np.testing.assert_allclose(np.asarray(losses), float(ref_l),
                               rtol=1e-5, atol=1e-6)
    gw, gb = np.asarray(grads["w"]), np.asarray(grads["b"])
    for v in range(V):
        for s in range(S):
            np.testing.assert_allclose(gw[s, v], np.asarray(ref_gw)[v * S + s],
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gb[s, v], np.asarray(ref_gb)[v * S + s],
                                       rtol=1e-4, atol=1e-5)


def test_get_forward_backward_func_interleaved_dispatch():
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving,
        get_forward_backward_func)

    assert (get_forward_backward_func(2, 4)
            is forward_backward_pipelining_with_interleaving)


def test_pipeline_remat_matches_no_remat(mesh_tp2_pp2_dp2, rng):
    """cfg.remat inside stage_fn (jax.checkpoint on the scanned block
    apply): identical loss + grads to the non-remat pipeline."""
    import dataclasses

    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    mesh = mesh_tp2_pp2_dp2
    pp, n_layers, m, b, s = 2, 4, 4, 2, 8
    cfg = gpt_tiny_config(num_layers=n_layers)
    cfg_r = dataclasses.replace(cfg, remat=True)
    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.roll(mbs, -1, axis=-1)
    v = GPTModel(cfg).init(jax.random.PRNGKey(0), mbs[0])["params"]
    stacked = split_gpt_params_for_pipeline(v, pp, n_layers)

    def run_with(cfg_x):
        first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg_x)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(P(STAGE_AXIS), P(), P()),
            out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)), check_vma=False)
        def run(p, mb, lb):
            local = jax.tree.map(lambda t: t[0], p)
            sched = {"blocks": jax.tree.map(lambda t: t[0],
                                            local["blocks"]),
                     "shared": local["shared"]}
            loss, g = fwd_bwd(stage_fn, loss_fn, sched, mb, loss_aux=lb,
                              first_fn=first_fn, loss_with_params=True)
            g = {"blocks": jax.tree.map(lambda t: t[None], g["blocks"]),
                 "shared": g["shared"]}
            return loss.reshape(1), jax.tree.map(lambda t: t[None], g)

        return jax.jit(run)(stacked, mbs, labels)

    l0, g0 = run_with(cfg)
    l1, g1 = run_with(cfg_r)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                               rtol=1e-6, atol=1e-6)
    for a, c in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-5, atol=1e-6)
