"""MLP and FusedDense modules vs composed reference ops.

Mirrors tests/L0/run_mlp/test_mlp.py (MLP vs nn.Sequential) and
tests/L0/run_fused_dense/.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import DenseNoBias, FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP


@pytest.mark.parametrize("activation", ["none", "relu", "sigmoid"])
@pytest.mark.parametrize("use_bias", [True, False])
def test_mlp_matches_sequential(rng, activation, use_bias):
    sizes = (480, 1024, 1024, 512)
    x = jnp.asarray(rng.standard_normal((16, sizes[0])), jnp.float32)
    m = MLP(mlp_sizes=sizes, bias=use_bias, activation=activation)
    variables = m.init(jax.random.PRNGKey(0), x)

    def ref(x):
        h = x
        for i in range(len(sizes) - 1):
            h = h @ variables["params"][f"weight_{i}"].T
            if use_bias:
                h = h + variables["params"][f"bias_{i}"]
            if activation == "relu":
                h = jax.nn.relu(h)
            elif activation == "sigmoid":
                h = jax.nn.sigmoid(h)
        return h

    np.testing.assert_allclose(m.apply(variables, x), ref(x), atol=1e-5,
                               rtol=1e-5)
    g = jax.grad(lambda x: (m.apply(variables, x) ** 2).sum())(x)
    gr = jax.grad(lambda x: (ref(x) ** 2).sum())(x)
    np.testing.assert_allclose(g, gr, atol=1e-4, rtol=1e-4)


def test_mlp_input_width_checked(rng):
    m = MLP(mlp_sizes=(8, 4))
    with pytest.raises(AssertionError):
        m.init(jax.random.PRNGKey(0), jnp.zeros((2, 9)))


def test_fused_dense(rng):
    x = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
    m = FusedDense(32, 16)
    variables = m.init(jax.random.PRNGKey(0), x)
    ref = x @ variables["params"]["weight"].T + variables["params"]["bias"]
    np.testing.assert_allclose(m.apply(variables, x), ref, atol=1e-6)

    m2 = DenseNoBias(32, 16)
    v2 = m2.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(m2.apply(v2, x),
                               x @ v2["params"]["weight"].T, atol=1e-6)


def test_fused_dense_gelu_dense(rng):
    x = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
    m = FusedDenseGeluDense(32, 64, 32)
    variables = m.init(jax.random.PRNGKey(0), x)
    p = variables["params"]
    h = x @ p["weight1"].T + p["bias1"]
    h = jax.nn.gelu(h, approximate=True)
    ref = h @ p["weight2"].T + p["bias2"]
    np.testing.assert_allclose(m.apply(variables, x), ref, atol=1e-6)
