"""Observability layer coverage (docs/observability.md).

Five tiers, matching ISSUE 4's acceptance criteria:

1. instrument math — Counter/Gauge semantics, log-bucket placement (le
   boundaries), quantile interpolation + single-value exactness, labels.
2. the jit-safe channel — recording order inside ``jit`` + ``lax.scan``
   (read after ``jax.effects_barrier()``), the hoisted per-name callback
   (no fresh closure per call), thread-safe delivery.
3. spans — lifecycle assembly from a fake clock, and the engine
   integration: a mixed-length serving run reconstructs queue-wait /
   TTFT / TPOT for EVERY request, with run stats derived from the
   instrument registry.
4. export — Prometheus text exposition pinned by a golden file, a
   parse check of a real serving run's exposition, the JSON snapshot,
   and the stdlib HTTP endpoint.
5. event log — ring-buffer wraparound + the JSONL postmortem dump.
"""

import json
import math
import os
import re
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.obs import (EventLog, SpanTracer, json_snapshot,
                          prometheus_text, serve, write_snapshot)
from apex_tpu.obs import export
from apex_tpu.serving import PagedDecodeEngine, Request, kv_pool
from apex_tpu.utils import metrics

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "observability.prom")


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.clear()
    yield
    metrics.clear()


# --------------------------------------------------------------------------
# 1. instrument math
# --------------------------------------------------------------------------

def test_counter_monotonic():
    c = metrics.counter("c")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert metrics.counter("c") is c          # interned by (name, labels)


def test_gauge_set_inc_dec():
    g = metrics.gauge("g")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == 4.0


def test_labels_make_distinct_instruments():
    a = metrics.counter("req", labels={"route": "a"})
    b = metrics.counter("req", labels={"route": "b"})
    assert a is not b
    a.inc(3)
    b.inc(1)
    assert (a.value, b.value) == (3.0, 1.0)
    assert metrics.counter("req", labels={"route": "a"}) is a


def test_kind_conflict_raises():
    metrics.counter("kind_clash")
    with pytest.raises(TypeError):
        metrics.gauge("kind_clash")


def test_kind_conflict_across_label_sets_raises():
    """Kind is a property of the NAME: a Counter under one label set and
    a Gauge under another would be one Prometheus family with
    conflicting TYPE metadata."""
    metrics.counter("xlabel_clash", labels={"engine": "0"})
    with pytest.raises(TypeError):
        metrics.gauge("xlabel_clash", labels={"engine": "1"})


def test_exposition_one_type_line_per_family():
    """Multiple label sets of one name are samples of ONE family — a
    second '# TYPE' line is invalid exposition (two engine-labeled
    counters is exactly the serving scenario)."""
    metrics.counter("fam.total", labels={"engine": "0"}).inc(1)
    metrics.counter("fam.total", labels={"engine": "1"}).inc(2)
    text = prometheus_text()
    assert text.count("# TYPE fam_total counter") == 1
    assert 'fam_total{engine="0"} 1' in text
    assert 'fam_total{engine="1"} 2' in text


def test_histogram_config_conflict_raises():
    """Re-registering a histogram with different buckets must fail loudly
    — silently returning the old layout would mis-bucket everything."""
    h = metrics.histogram("cfg_clash", base=1.0, growth=2.0)
    with pytest.raises(ValueError, match="different config"):
        metrics.histogram("cfg_clash", base=1e-6, n_buckets=64)
    assert metrics.histogram("cfg_clash", base=1.0, growth=2.0) is h
    assert metrics.histogram("cfg_clash") is h   # no kwargs: no check


def test_histogram_config_consistent_across_label_sets():
    """Bucket layout is a property of the FAMILY: a sibling label set
    with different buckets would make cross-label aggregation
    (histogram_quantile over engines) silently wrong."""
    metrics.histogram("fam_cfg", labels={"engine": "0"}, base=1.0)
    with pytest.raises(ValueError, match="registered with"):
        metrics.histogram("fam_cfg", labels={"engine": "1"}, base=1e-3)
    metrics.histogram("fam_cfg", labels={"engine": "1"}, base=1.0)


def test_histogram_bucket_boundaries_le():
    """Bucket i covers (base*g**(i-1), base*g**i] — a value exactly on a
    boundary lands in the LOWER bucket (le semantics)."""
    h = metrics.histogram("h_le", base=1.0, growth=2.0, n_buckets=6)
    for v in (0.5, 1.0, 2.0, 2.0001, 4.0, 1000.0):
        h.observe(v)
    les = [le for le, _ in h.buckets()]
    assert les == [1.0, 2.0, 4.0, 8.0, 16.0, math.inf]
    cums = [c for _, c in h.buckets()]
    # 0.5,1.0 -> le=1; 2.0 -> le=2; 2.0001,4.0 -> le=4; 1000 -> +Inf
    assert cums == [2, 3, 5, 5, 5, 6]
    assert h.count == 6 and h.sum == pytest.approx(1009.5001)


def test_histogram_quantiles_interpolate():
    h = metrics.histogram("h_q", base=1.0, growth=2.0)
    for v in (1.0, 2.0, 4.0, 8.0):           # one count per bucket 0..3
        h.observe(v)
    # target rank 2 falls at the end of bucket 1 -> its upper bound
    assert h.quantile(0.5) == pytest.approx(2.0)
    # p100 == max; clamping keeps every quantile inside [min, max]
    assert h.quantile(1.0) == pytest.approx(8.0)
    assert h.quantile(0.0) >= 1.0
    p = h.percentiles()
    assert set(p) == {"p50", "p90", "p99"} and p["p50"] <= p["p99"]


def test_histogram_single_value_exact_everywhere():
    h = metrics.histogram("h_one")
    h.observe(7.31)
    for q in (0.0, 0.5, 0.9, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(7.31)


def test_histogram_empty_and_bad_quantile():
    h = metrics.histogram("h_empty")
    assert h.quantile(0.5) == 0.0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_clear_name_drops_series_and_instruments():
    metrics.counter("doomed").inc()
    metrics.record("doomed", 1.0)
    metrics.counter("kept").inc()
    metrics.clear("doomed")
    assert metrics.get("doomed") == []
    assert metrics.counter("doomed").value == 0.0   # fresh registration
    assert metrics.counter("kept").value == 1.0


# --------------------------------------------------------------------------
# 2. the jit-safe channel
# --------------------------------------------------------------------------

def test_record_inside_jit_scan_ordered():
    """Values recorded by a scan body arrive in execution order once
    ``jax.effects_barrier()`` drains the callbacks."""

    @jax.jit
    def run(x):
        def body(c, t):
            metrics.record("obs.scan", c)
            return c + t, c
        c, _ = lax.scan(body, x, jnp.arange(4.0))
        return c

    run(jnp.float32(0.0)).block_until_ready()
    jax.effects_barrier()
    assert metrics.get("obs.scan") == [0.0, 0.0, 1.0, 3.0]


def test_record_callback_is_hoisted_per_name():
    """The jit path must bake ONE module-level callable per metric name
    into every trace — not a fresh lambda per record() call (the
    satellite fix: per-call closures defeat jaxpr caching)."""
    cb = metrics._callback_for("obs.hoist")
    assert metrics._callback_for("obs.hoist") is cb

    @jax.jit
    def step(x):
        metrics.record("obs.hoist", x.sum())
        return x * 2

    step(jnp.ones((4,))).block_until_ready()
    step(jnp.ones((8,))).block_until_ready()     # second trace, same cb
    jax.effects_barrier()
    assert metrics._callback_for("obs.hoist") is cb
    assert metrics.get("obs.hoist") == [4.0, 8.0]


def test_registry_is_thread_safe():
    """Callbacks can arrive on runtime threads; concurrent appends and
    instrument updates must not lose writes."""
    n_threads, n_each = 8, 500
    h = metrics.histogram("obs.mt_ms")

    def work():
        for i in range(n_each):
            metrics.record("obs.mt", float(i))
            metrics.counter("obs.mt_count").inc()
            h.observe(float(i % 17) + 0.1)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(metrics.get("obs.mt")) == n_threads * n_each
    assert metrics.counter("obs.mt_count").value == n_threads * n_each
    assert h.count == n_threads * n_each


def test_step_timer_feeds_histogram_once():
    """The satellite de-dup: one observe() = exactly one raw-series entry
    + one histogram observation (the old AverageMeter double write is
    gone)."""
    t = metrics.StepTimer("obs.t_ms")
    t.start()
    out = jax.jit(lambda x: x * 2)(jnp.ones((16,)))
    dt = t.observe(out)
    assert dt > 0
    assert metrics.get("obs.t_ms") == [dt]
    assert t.hist.count == 1
    assert t.hist.quantile(0.5) == pytest.approx(dt)
    with pytest.raises(RuntimeError):
        t.observe()


# --------------------------------------------------------------------------
# 3. spans
# --------------------------------------------------------------------------

def _fake_clock():
    t = [0.0]

    def clock():
        return t[0]

    clock.advance = lambda dt: t.__setitem__(0, t[0] + dt)
    return clock


def test_span_lifecycle_assembly():
    clk = _fake_clock()
    tr = SpanTracer(clock=clk)
    tr.event(17, "enqueue", prompt_tokens=48)
    clk.advance(0.010)                               # 10 ms queued
    tr.event(17, "admit", slot=1)
    with tr.span(17, "prefill", cached_tokens=32, computed_tokens=16):
        clk.advance(0.020)                           # 20 ms prefill
    tr.event(17, "first_token")
    tr.begin(17, "decode")
    clk.advance(0.100)                               # 100 ms decoding
    tr.end(17, "decode", new_tokens=11)
    tr.event(17, "retire")

    life = tr.lifecycle(17)
    assert life["queue_wait_ms"] == pytest.approx(10.0)
    assert life["ttft_ms"] == pytest.approx(30.0)
    assert life["prefill_ms"] == pytest.approx(20.0)
    assert life["cached_tokens"] == 32 and life["computed_tokens"] == 16
    assert life["decode_ms"] == pytest.approx(100.0)
    assert life["tpot_ms"] == pytest.approx(10.0)    # 100 ms / (11 - 1)
    assert life["total_ms"] == pytest.approx(130.0)
    assert [s.name for s in tr.spans(17)] == [
        "enqueue", "admit", "prefill", "first_token", "decode", "retire"]
    assert tr.lifecycles().keys() == {17}


def test_span_misuse_raises():
    tr = SpanTracer(clock=_fake_clock())
    with pytest.raises(RuntimeError):
        tr.end(0, "never_opened")
    tr.begin(0, "twice")
    with pytest.raises(RuntimeError):
        tr.begin(0, "twice")
    # a double-begin with annotation must raise BEFORE entering the
    # TraceMe (no leaked annotation); later nested spans still work
    with pytest.raises(RuntimeError):
        tr.begin(0, "twice", annotate=True)
    with tr.span(0, "after"):
        pass
    assert tr.spans(0)[-1].duration_ms is not None


def _tiny_engine(**kw):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, PagedDecodeEngine(model, v, num_slots=2, page_size=8, **kw)


def test_engine_spans_reconstruct_every_request():
    """Acceptance: a mixed-length workload's span trace yields queue-wait
    + TTFT + TPOT for every request, and run() stats come from the
    instrument registry (second run's deltas are clean)."""
    rng = np.random.default_rng(0)
    cfg, engine = _tiny_engine()
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (int(s),)
                                        ).astype(np.int32),
                    max_new_tokens=int(m))
            for s, m in zip([10, 20, 7, 33, 12], [5, 3, 8, 4, 1])]
    outs, stats = engine.run(reqs)

    for i, out in enumerate(outs):
        life = engine.tracer.lifecycle(i)
        assert life["queue_wait_ms"] >= 0.0, life
        assert life["ttft_ms"] >= life["queue_wait_ms"], life
        assert life["tpot_ms"] >= 0.0, life
        assert life["new_tokens"] == len(out), life
        assert life["computed_tokens"] >= 1
    for key in ("ttft_ms_p50", "ttft_ms_p95", "tpot_ms_p50",
                "queue_wait_ms_p50", "decode_step_ms_p50",
                "decode_step_ms_p95"):
        assert stats[key] >= 0.0, key
    assert stats["ttft_ms_p95"] >= stats["ttft_ms_p50"]

    # stats are registry deltas over the engine's OWN labeled counters:
    # they carry across runs, stats don't — and another engine's traffic
    # cannot leak into them
    retired = metrics.counter("serving.retired", labels=engine.obs_labels)
    assert retired.value == len(reqs)
    _, stats2 = engine.run(reqs)
    assert stats2["admitted"] == len(reqs)
    assert stats2["retired"] == len(reqs)
    assert retired.value == 2 * len(reqs)
    assert metrics.histogram("serving.ttft_ms",
                             labels=engine.obs_labels).count == 2 * len(reqs)
    other = metrics.counter("serving.retired", labels={"engine": "ghost"})
    other.inc(100)                         # concurrent-engine traffic
    _, stats3 = engine.run(reqs)
    assert stats3["retired"] == len(reqs)  # isolation: 100 not counted

    # the engine's event ring saw every admission and retirement
    kinds = [e["kind"] for e in engine.events.tail()]
    assert kinds.count("admit") == 3 * len(reqs)
    assert kinds.count("retire") == 3 * len(reqs)


def test_engine_pool_and_prefix_gauges():
    """kv_pool/prefix_cache publish residency gauges during a cached
    serving run."""
    rng = np.random.default_rng(1)
    cfg, engine = _tiny_engine(prefix_cache=True)
    head = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    reqs = [Request(prompt=np.concatenate(
                [head, rng.integers(0, cfg.vocab_size, (int(s),)
                                    ).astype(np.int32)]),
                    max_new_tokens=3) for s in (4, 6, 5)]
    _, stats = engine.run(reqs)
    lbl = engine.obs_labels        # pool/prefix gauges are per-engine
    assert metrics.gauge("prefix_cache.pages",
                         labels=lbl).value == len(engine.prefix)
    assert metrics.gauge("kv_pool.free_pages", labels=lbl).value >= 0
    assert metrics.gauge("kv_pool.pages_total", labels=lbl).value == \
        kv_pool.num_pages_of(engine.cache) - 1
    assert metrics.counter("prefix_cache.inserted_pages",
                           labels=lbl).value == len(engine.prefix)
    _, stats2 = engine.run(reqs)
    assert stats2["prefix_hits"] > 0      # warm cache: the head is shared


def test_observe_pool_direct():
    vals = kv_pool.observe_pool({
        "layers": [{"k_pages": jnp.zeros((5, 1, 8, 4)),
                    "v_pages": jnp.zeros((5, 1, 8, 4))}],
        "page_ref": jnp.asarray([0, 2, 1, 0, 0], jnp.int32),
        "free_top": jnp.asarray(2, jnp.int32),
    })
    assert vals == {"kv_pool.free_pages": 2, "kv_pool.pages_total": 4,
                    "kv_pool.shared_pages_active": 2,
                    "kv_pool.page_refs_total": 3}
    assert metrics.gauge("kv_pool.page_refs_total").value == 3


# --------------------------------------------------------------------------
# 4. export
# --------------------------------------------------------------------------

def _seed_golden_registry():
    # the canonical seeded state lives in export.py so the golden can
    # be regenerated (`python -m apex_tpu.obs.export --golden`) instead
    # of hand-edited — the test and the regenerator CANNOT drift
    export.seed_golden_registry()


def test_prometheus_exposition_golden_file():
    _seed_golden_registry()
    with open(GOLDEN) as f:
        assert prometheus_text() == f.read()


_PROM_LINE = re.compile(
    r"^(?:# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (?:counter|gauge|histogram)"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? -?(?:[0-9.e+-]+|\+Inf))$")


def test_serving_run_exposition_parses():
    """Acceptance: the Prometheus text exposition of a real serving run
    parses line by line, every family's # HELP line immediately
    precedes its # TYPE line, and histogram buckets are cumulative."""
    rng = np.random.default_rng(2)
    cfg, engine = _tiny_engine()
    engine.run([Request(prompt=rng.integers(0, cfg.vocab_size, (9,)
                                            ).astype(np.int32),
                        max_new_tokens=4)])
    text = prometheus_text()
    assert "serving_ttft_ms_bucket" in text
    assert "serving_slots_in_use" in text
    cums = []
    lines = text.rstrip("\n").split("\n")
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
        if line.startswith("serving_ttft_ms_bucket"):
            cums.append(float(line.rsplit(" ", 1)[1]))
    assert cums == sorted(cums) and cums[-1] == 1.0
    # HELP/TYPE pairing: exactly one HELP per family, named the same as
    # — and directly above — its TYPE line (the exposition contract
    # registered HELP text rides on; docs/observability.md)
    helps = [i for i, ln in enumerate(lines) if ln.startswith("# HELP")]
    types = [i for i, ln in enumerate(lines) if ln.startswith("# TYPE")]
    assert helps and len(helps) == len(types)
    for i in helps:
        assert lines[i + 1].startswith("# TYPE")
        assert lines[i].split(" ")[2] == lines[i + 1].split(" ")[2]
    # a registered description is used verbatim; the fallback is generic
    assert "# HELP serving_ttft_ms Time to first token per request" \
           in text


def test_exposition_survives_nan_and_inf():
    """A diverging loss (NaN) is exactly when metrics matter — the
    exporter must emit the valid literals, not crash the scrape."""
    metrics.record("train.loss", float("nan"))
    metrics.gauge("weird").set(float("inf"))
    text = prometheus_text()
    assert "train_loss_last NaN" in text
    assert "weird +Inf" in text


def test_step_timer_survives_registry_clear():
    """clear() between observations must not orphan the timer's
    histogram — observations after the clear land in the re-interned
    instrument that snapshots actually see."""
    t = metrics.StepTimer("obs.clear_ms")
    t.start()
    t.observe()
    metrics.clear()
    t.start()
    t.observe()
    assert t.hist.count == 1
    assert metrics.histogram("obs.clear_ms") is t.hist


def test_exposition_no_duplicate_family_for_step_timer():
    """A name that is both a Histogram and a raw record() series (what
    every StepTimer produces) must export ONE metric family — a second
    `x_count` with conflicting TYPE metadata makes the scrape invalid."""
    t = metrics.StepTimer("obs.step_ms")
    t.start()
    t.observe()
    text = prometheus_text()
    assert text.count("obs_step_ms_count") == 1
    assert "# TYPE obs_step_ms histogram" in text
    assert "# TYPE obs_step_ms_count gauge" not in text


def test_json_snapshot_and_write(tmp_path):
    _seed_golden_registry()
    doc = json_snapshot(extra={"tag": "t"})
    assert doc["tag"] == "t"
    hists = {h["name"]: h for h in doc["histograms"]}
    assert hists["serving.ttft_ms"]["count"] == 4
    assert hists["serving.ttft_ms"]["buckets"][-1] == [None, 4]

    path = write_snapshot(str(tmp_path / "snap.json"))
    with open(path) as f:
        parsed = json.load(f)          # strict JSON round trip
    assert parsed["counters"]
    prom = write_snapshot(str(tmp_path / "snap.prom"))
    with open(prom) as f:
        assert "# TYPE serving_admitted counter" in f.read()


def test_http_endpoint():
    _seed_golden_registry()
    server = serve(port=0)
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            assert b"serving_admitted 3" in r.read()
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics.json") as r:
            doc = json.loads(r.read())
            assert doc["gauges"][0]["name"] == "kv_pool.free_pages"
    finally:
        server.shutdown()
        server.server_close()


#: the /healthz payload shape (ISSUE 8 satellite) — golden-pinned key
#: set so operators' probes can rely on it
_HEALTHZ_KEYS = {"ok", "time_unix", "frontend", "pump_alive",
                 "queue_depth", "active_slots", "failure"}


def test_healthz_endpoint_without_frontend():
    from apex_tpu.obs import export

    server = serve(port=0)
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz") as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read())
        assert set(doc) == _HEALTHZ_KEYS
        assert doc["ok"] is True and doc["frontend"] is False
        assert doc["pump_alive"] is False
        assert doc["queue_depth"] is None and doc["failure"] is None
    finally:
        server.shutdown()
        server.server_close()
    # the doc builder is directly usable too (no server needed)
    assert set(export.health_doc()) == _HEALTHZ_KEYS


def test_healthz_endpoint_with_live_frontend():
    from apex_tpu.serving.frontend import ServingFrontend

    rng = np.random.default_rng(5)
    cfg, engine = _tiny_engine()
    fe = ServingFrontend(engine)
    fe.start()
    server = serve(port=0, frontend=fe)
    try:
        host, port = server.server_address[:2]
        h = fe.submit(Request(
            prompt=rng.integers(0, cfg.vocab_size, (9,)
                                ).astype(np.int32), max_new_tokens=4))
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz") as r:
            doc = json.loads(r.read())
        assert doc["ok"] is True and doc["frontend"] is True
        assert doc["pump_alive"] is True
        assert doc["queue_depth"] >= 0 and doc["active_slots"] >= 0
        h.result(timeout=60.0)
    finally:
        fe.stop()
        server.shutdown()
        server.server_close()


class _StubReplica:
    """Just enough of router.py's replica record for health_doc."""

    def __init__(self, index, alive, queue_depth, dead_reason=None):
        self.index = index
        self.alive = alive
        self.draining = False
        self.dead_reason = dead_reason

        class _FE:
            pump_alive = alive

        _FE.queue_depth = queue_depth
        self.frontend = _FE()


def test_healthz_router_block(tmp_path):
    """ISSUE 15 satellite: ``serve(router=)`` / ``health_doc(router=)``
    add per-replica liveness + queue depth, and overall ``ok`` goes
    false only when NO replica is alive."""
    from apex_tpu.obs import export

    class _StubRouter:
        replicas = [_StubReplica(0, True, 3),
                    _StubReplica(1, False, 0,
                                 dead_reason=RuntimeError("killed"))]

    doc = export.health_doc(router=_StubRouter())
    r = doc["router"]
    assert (r["replicas"], r["alive"], r["queue_depth"]) == (2, 1, 3)
    assert doc["ok"] is True             # one survivor keeps us healthy
    rows = {row["replica"]: row for row in r["per_replica"]}
    assert rows[0]["alive"] and rows[0]["pump_alive"]
    assert rows[0]["queue_depth"] == 3 and rows[0]["failure"] is None
    assert not rows[1]["alive"] and rows[1]["queue_depth"] is None
    assert "killed" in rows[1]["failure"]
    # fleet-plane fields (ISSUE 19 satellite): every row carries the
    # supervision-tick age, its failover count, and its federation
    # scrape staleness — None/0 on a router without the fleet plane
    for row in rows.values():
        assert set(row) >= {"last_tick_age_s", "failovers",
                            "scrape_age_s"}
        assert row["last_tick_age_s"] is None    # stub has no tick
        assert row["failovers"] == 0
        assert row["scrape_age_s"] is None       # stub has no collector

    class _DeadRouter:
        replicas = [_StubReplica(0, False, 0,
                                 dead_reason=RuntimeError("gone"))]

    assert export.health_doc(router=_DeadRouter())["ok"] is False

    server = serve(port=0, router=_StubRouter())
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz") as resp:
            served = json.loads(resp.read())
        assert served["router"]["alive"] == 1
        assert len(served["router"]["per_replica"]) == 2
    finally:
        server.shutdown()
        server.server_close()


def test_costs_endpoint_payload_shape():
    """/costs 404s until a snapshot is published, then serves the
    report with the pinned top-level shape."""
    from apex_tpu.obs import export

    server = serve(port=0)
    try:
        host, port = server.server_address[:2]
        url = f"http://{host}:{port}/costs"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url)
        assert err.value.code == 404
        export.publish_costs({
            "schema": 1, "profile": {"name": "v5e"},
            "totals": {"flops": 1, "hbm_bytes": 2, "predicted_ms": 0.1},
            "cases": [], "by_domain": {}, "decode_split": None,
            "errors": []})
        with urllib.request.urlopen(url) as r:
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read())
        assert set(doc) == {"schema", "profile", "totals", "cases",
                            "by_domain", "decode_split", "errors"}
        assert export.latest_costs()["schema"] == 1
    finally:
        export.publish_costs(None)     # leave no cross-test snapshot
        server.shutdown()
        server.server_close()


# --------------------------------------------------------------------------
# 5. event log
# --------------------------------------------------------------------------

def test_event_ring_wraparound(tmp_path):
    clock = iter(range(100)).__next__
    log = EventLog(capacity=4, clock=lambda: float(clock()))
    for i in range(10):
        log.emit("tick", i=i)
    assert len(log) == 4
    assert log.total == 10 and log.dropped == 6
    assert [e["seq"] for e in log.tail()] == [6, 7, 8, 9]
    assert [e["i"] for e in log.tail(2)] == [8, 9]

    path = tmp_path / "events.jsonl"
    text = log.dump(str(path))
    assert path.read_text() == text
    lines = [json.loads(line) for line in text.splitlines()]
    assert lines[0] == {"kind": "event_log_header", "capacity": 4,
                        "total": 10, "dropped": 6}
    assert [r["seq"] for r in lines[1:]] == [6, 7, 8, 9]
    assert all(r["kind"] == "tick" for r in lines[1:])

    # emit returns a copy: mutating it must not corrupt the ring
    rec = log.emit("tick", i=99)
    rec["i"] = "mutated"
    assert log.tail(1)[0]["i"] == 99


def test_event_log_validates_capacity():
    with pytest.raises(ValueError):
        EventLog(capacity=0)
