"""Tiered KV page pool (apex_tpu/serving/host_tier.py + the kv_pool
gather/promote ops + the frontend demote/promote wiring).

Invariant tier (no model): HostPageTier budget-LRU semantics (insert /
run_length / pop / oldest-first eviction over a byte budget) and the
``gather_pages`` -> host -> ``promote_pages`` roundtrip restoring page
bytes (and quantized scales) EXACTLY.

Engine tier (tiny GPT): the acceptance bars — a thrashing pool that
previously re-prefilled on every churned hit now PROMOTES (strictly more
prefix hits tier-on than tier-off, token-identical outputs vs tier-off
and vs the all-HBM pool), preemption spill -> demote -> promote-resume
identity, defrag composing with resident tier entries (keys are token
paths, nothing to remap), an int8 pool demoting losslessly, and TP=2
token identity with the tier on — plus the zero-leak bar: after the
churn every non-cached page is back on the free stack and no refcount
survives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import (HostPageTier, PagedDecodeEngine,
                              PriorityDeadlinePolicy, Request,
                              free_page_count, init_paged_cache)
from apex_tpu.serving import kv_pool
from apex_tpu.serving.frontend import ServingFrontend

PS = 8


def _model():
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, v


def _lockstep(model, v, req):
    return np.asarray(generate(model, v, np.asarray(req.prompt)[None],
                               max_new_tokens=req.max_new_tokens)
                      )[0, np.asarray(req.prompt).shape[0]:]


def _churn_reqs(rng, cfg, *, tenants=3, header_pages=3, n=9):
    """Round-robin over ``tenants`` shared headers, each ``header_pages``
    pages long: at ``num_pages=8`` (7 usable) the headers cannot all stay
    device-resident, so every revisit is a churned hit — the workload the
    tier exists for."""
    headers = [rng.integers(0, cfg.vocab_size,
                            (header_pages * PS,)).astype(np.int32)
               for _ in range(tenants)]
    reqs = []
    for i in range(n):
        tail = rng.integers(0, 100, (2 + i % 4,)).astype(np.int32)
        reqs.append(Request(
            prompt=np.concatenate([headers[i % tenants], tail]),
            max_new_tokens=3))
    return reqs


def _run_seq(engine, reqs):
    """One request at a time (keeps the churn order deterministic);
    returns (outputs, per-run stats summed across the sequence)."""
    outs, total = [], {}
    for r in reqs:
        (o,), stats = engine.run([r])
        outs.append(np.asarray(o))
        for k, val in stats.items():
            if isinstance(val, (int, float)):
                total[k] = total.get(k, 0) + val
    return outs, total


def _assert_no_leak(engine):
    """Free-stack + refcount hygiene: every page is either on the free
    stack or named by the radix tree, and no slot refcount survives."""
    usable = engine.cache["free_stack"].shape[0] - 1
    assert int(free_page_count(engine.cache)) + len(engine.prefix) == usable
    assert int(np.asarray(engine.cache["page_ref"]).sum()) == 0


# --- invariant tier ----------------------------------------------------------


def test_tier_budget_lru_and_run_length():
    """Budget-LRU semantics without a model: oldest entries evict when
    the byte budget overflows, run_length bumps recency (a re-hit page
    survives an eviction that takes a colder one), and pop removes."""
    page = {"k_pages": np.zeros((4, 1, PS, 4), np.float32),
            "v_pages": np.zeros((4, 1, PS, 4), np.float32)}
    per_page = 2 * 1 * PS * 4 * 4
    tier = HostPageTier(3 * per_page, page_size=PS)

    keys = [((i,) * PS,) for i in range(4)]
    tier.put_pending(keys[:3], [page], 3)
    tier.drain()
    assert len(tier) == 3 and tier.resident_bytes == 3 * per_page

    # recency: touch key 0 so the NEXT eviction takes key 1, not 0
    assert tier.run_length((), [keys[0][0]]) == 1
    tier.put_pending(keys[3:], [{k: a[:1] for k, a in page.items()}], 1)
    tier.drain()
    st = tier.stats()
    assert st["host_tier_evicted_pages"] == 1
    assert tier.run_length((), [keys[1][0]]) == 0      # evicted (coldest)
    assert tier.run_length((), [keys[0][0]]) == 1      # survived

    # run_length walks CONSECUTIVE residency from the base path
    assert tier.run_length((), [keys[1][0], keys[2][0]]) == 0
    payload = tier.pop(keys[2])
    assert payload is not None and tier.pop(keys[2]) is None
    st = tier.stats()
    assert st["host_tier_promotes"] == 1
    assert 0.0 < st["host_tier_promote_hit_rate"] < 1.0

    # an entry bigger than the whole budget is dropped, not inserted
    tiny = HostPageTier(per_page - 1, page_size=PS)
    tiny.put_pending(keys[:1], [{k: a[:1] for k, a in page.items()}], 1)
    tiny.drain()
    assert len(tiny) == 0

    with pytest.raises(ValueError):
        HostPageTier(0, page_size=PS)


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_gather_promote_roundtrip_bitexact(rng, kv_dtype):
    """demote -> host -> promote restores the page bytes (and, quantized,
    the per-(page, kv_head) scales) EXACTLY — promote never requantizes,
    so the PR 14 full-page bit-stability invariant survives the tier."""
    cfg = gpt_tiny_config()
    cache = init_paged_cache(cfg, num_slots=1, num_pages=8, page_size=PS,
                             kv_dtype=kv_dtype)
    layers = []
    for lc in cache["layers"]:
        lc = dict(lc)
        for name, arr in lc.items():
            vals = rng.integers(-100, 100, arr.shape)
            lc[name] = jnp.asarray(vals, arr.dtype)
        layers.append(lc)
    cache = dict(cache, layers=layers)

    pages = jnp.asarray([3, 5, 2, 0], jnp.int32)      # row is null-padded
    tiles = kv_pool.gather_pages(cache, pages)
    host = [{k: np.asarray(a) for k, a in lc.items()} for lc in tiles]

    # scribble over the source pages, then promote the host copy back
    # into the SAME physical ids (popped off a stack arranged to yield
    # them) — every byte must round-trip
    wiped = [{k: a.at[pages[:3]].set(jnp.zeros_like(a[pages[:3]]))
              for k, a in lc.items()} for lc in cache["layers"]]
    stack = np.asarray(cache["free_stack"]).copy()
    stack[5:8] = [2, 5, 3]                # alloc pops stack[top-1] first
    cache2 = dict(cache, layers=wiped,
                  free_stack=jnp.asarray(stack),
                  free_top=jnp.asarray(8, jnp.int32))
    cache2 = kv_pool.promote_pages(
        cache2, pages, jnp.asarray(3, jnp.int32),
        [{k: jnp.asarray(a) for k, a in lc.items()} for lc in host])
    assert int(cache2["free_top"]) == 5
    for lc0, lc2 in zip(cache["layers"], cache2["layers"]):
        for name in lc0:
            np.testing.assert_array_equal(
                np.asarray(lc0[name][pages[:3]]),
                np.asarray(lc2[name][pages[:3]]), err_msg=name)


# --- engine tier -------------------------------------------------------------


def test_churned_hits_promote_not_reprefill(rng):
    """THE acceptance bar: at a pool size where round-robin tenants thrash
    the radix cache, the tier turns every churned re-prefill into a
    promote — strictly more prefix hits than tier-off, matching the
    all-HBM pool's hit count, token-identical outputs across all three,
    and zero device pages leaked after the churn."""
    cfg, model, v = _model()
    reqs = _churn_reqs(rng, cfg)
    kw = dict(num_slots=1, page_size=PS, prefix_cache=True)

    e_tier = PagedDecodeEngine(model, v, num_pages=8,
                               host_tier_bytes=1 << 24, **kw)
    e_off = PagedDecodeEngine(model, v, num_pages=8, **kw)
    e_big = PagedDecodeEngine(model, v, num_pages=64, **kw)
    (o_t, st), (o_o, so), (o_b, sb) = (_run_seq(e, reqs)
                                       for e in (e_tier, e_off, e_big))

    for a, b, c in zip(o_t, o_o, o_b):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    assert st["prefix_hits"] > so["prefix_hits"]
    assert st["prefix_hits"] == sb["prefix_hits"]
    assert st["prefill_tokens_skipped"] > so["prefill_tokens_skipped"]

    ht = e_tier.host_tier.stats()
    assert ht["host_tier_demotes"] > 0 and ht["host_tier_promotes"] > 0
    assert ht["host_tier_promote_hit_rate"] > 0
    assert e_off.host_tier is None
    _assert_no_leak(e_tier)
    _assert_no_leak(e_off)


def test_preempt_spill_demotes_then_resume_promotes(rng):
    """Preemption under POOL pressure: the high-priority admission evicts
    the victim's freshly spilled refcount-0 pages, which now DEMOTE; the
    resume finds them host-resident and promotes instead of re-prefilling
    — and every request stays token-identical to its lock-step run."""
    cfg, model, v = _model()
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                       ).astype(np.int32),
                   max_new_tokens=12, priority=0) for _ in range(2)]
    hi = Request(prompt=rng.integers(0, cfg.vocab_size, (24,)
                                     ).astype(np.int32),
                 max_new_tokens=8, priority=5)
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                               num_pages=12, prefix_cache=True,
                               host_tier_bytes=1 << 24)
    fe = ServingFrontend(
        engine, policy=PriorityDeadlinePolicy(preempt_on_priority=True))
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(low)]
    while fe.queue_depth:
        fe.pump()
    for _ in range(3):
        fe.pump()
    handles.append(fe.submit(hi, request_id=len(low)))
    fe.drain()

    stats = fe.stats()
    assert stats["preemptions"] >= 1 and stats["resumes"] >= 1
    assert stats["host_tier_demotes"] > 0
    assert stats["host_tier_promotes"] > 0
    for h, req in zip(handles, low + [hi]):
        np.testing.assert_array_equal(h.result(), _lockstep(model, v, req))
    _assert_no_leak(engine)


def test_defrag_composes_with_resident_tier(rng):
    """The tier keys pages by TOKEN PATHS, so a defrag between demote and
    promote has nothing to remap: demote a header, leak the free stack so
    the next admission must defrag, and the follow-up hit still promotes
    into (compaction-renamed) fresh pages token-identically."""
    cfg, model, v = _model()
    sys_p = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               num_pages=10, prefix_cache=True,
                               host_tier_bytes=1 << 24)

    def _hdr_req(tail_len, max_new):
        tail = rng.integers(0, 100, (tail_len,)).astype(np.int32)
        return Request(prompt=np.concatenate([sys_p, tail]),
                       max_new_tokens=max_new)

    def _fat_req():
        return Request(prompt=rng.integers(0, cfg.vocab_size,
                                           (8 * PS,)).astype(np.int32),
                       max_new_tokens=4)

    engine.run([_hdr_req(4, 4)])          # seed: 2 header pages cached
    engine.run([_fat_req()])              # 9 pages: evicts+demotes header
    assert engine.host_tier.stats()["host_tier_demotes"] >= 2

    # leak a free page, then another fat admission: eviction demotes the
    # previous fat's cached pages but stays one short -> defrag recovers
    # the leaked page at the sync boundary, tier entries untouched
    engine.cache["free_top"] = engine.cache["free_top"] - 1
    (out_y,), stats = engine.run([(req_y := _fat_req())])
    np.testing.assert_array_equal(out_y, _lockstep(model, v, req_y))
    assert stats["defrag_runs"] >= 1

    # the post-defrag hit still promotes the header, token-identically —
    # the tier keys by tokens, so compaction renamed nothing it holds
    req = _hdr_req(5, 4)
    (out,), _ = engine.run([req])
    np.testing.assert_array_equal(out, _lockstep(model, v, req))
    assert engine.host_tier.stats()["host_tier_promotes"] >= 2
    _assert_no_leak(engine)


def test_quantized_pool_demote_is_lossless(rng):
    """int8 pool: pages demote as raw int8 bytes + their f32 scales and
    promote without requantizing, so the tiered engine is token-identical
    to the all-HBM int8 engine (same match depths, same stored bytes —
    the structural identity a lossy demote could not give)."""
    cfg, model, v = _model()
    reqs = _churn_reqs(rng, cfg)
    kw = dict(num_slots=1, page_size=PS, prefix_cache=True,
              kv_dtype="int8")
    e_tier = PagedDecodeEngine(model, v, num_pages=8,
                               host_tier_bytes=1 << 24, **kw)
    e_big = PagedDecodeEngine(model, v, num_pages=64, **kw)
    for a, b in zip(_run_seq(e_tier, reqs)[0], _run_seq(e_big, reqs)[0]):
        np.testing.assert_array_equal(a, b)
    ht = e_tier.host_tier.stats()
    assert ht["host_tier_promotes"] > 0
    # the resident payloads really are quantized: int8 page bytes + f32
    # scales, not dequantized fp copies
    payload = next(iter(e_tier.host_tier._entries.values()))[0]
    assert payload[0]["k_pages"].dtype == np.int8
    assert payload[0]["k_scales"].dtype == np.float32
    _assert_no_leak(e_tier)


def test_tp2_tier_token_identity(rng):
    """TP=2 with the tier on: each chip demotes its kv-head shard through
    the same shard_map'd gather, and outputs stay token-identical to the
    single-chip tiered engine (which is itself churn-verified above)."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                     shard_model_variables, tp_mesh)
    cfg, model, v = _model()
    cfg2 = gpt_tiny_config(tensor_parallel_size=2)
    m2 = GPTModel(cfg2)
    mesh = tp_mesh(2)
    v2, _ = shard_model_variables(m2, v, mesh)
    reqs = _churn_reqs(rng, cfg, n=6)
    kw = dict(num_slots=1, page_size=PS, num_pages=8, prefix_cache=True,
              host_tier_bytes=1 << 24)
    e_tp = TensorParallelPagedEngine(m2, v2, mesh=mesh, **kw)
    e_1 = PagedDecodeEngine(model, v, **kw)
    for a, b in zip(_run_seq(e_tp, reqs)[0], _run_seq(e_1, reqs)[0]):
        np.testing.assert_array_equal(a, b)
    assert e_tp.host_tier.stats()["host_tier_promotes"] > 0
    _assert_no_leak(e_tp)
