"""Contrib long tail vs pure-jnp/torch-style references (VERDICT round-1
item 10): GroupNorm NHWC, transducer loss, FastLayerNorm shim, focal loss,
index_mul_2d, halo exchange, groupbn, conv_bias_relu, fmha varlen shim.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS


# ---------------------------------------------------------------- group_norm
@pytest.mark.parametrize("act", [None, "silu"])
@pytest.mark.parametrize("shape,groups", [
    ((2, 4, 4, 256), 2),     # kernel path (cg=128)
    ((2, 3, 5, 24), 4),      # fallback path (cg=6)
])
def test_group_norm_matches_reference(rng, act, shape, groups):
    from apex_tpu.ops.group_norm import group_norm_nhwc, group_norm_reference

    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    c = shape[-1]
    w = jnp.asarray(rng.standard_normal((c,)) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.standard_normal((c,)) * 0.1, jnp.float32)

    y = group_norm_nhwc(x, w, b, groups, 1e-5, act)
    y_ref = group_norm_reference(x, w, b, groups, 1e-5, act)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)

    # grads vs autodiff of the reference formulation
    def loss_k(x, w, b):
        return jnp.sum(group_norm_nhwc(x, w, b, groups, 1e-5, act) ** 2)

    def loss_r(x, w, b):
        return jnp.sum(group_norm_reference(x, w, b, groups, 1e-5, act) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, b)
    for a_, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_group_norm_module(rng):
    from apex_tpu.contrib.group_norm import GroupNorm

    x = jnp.asarray(rng.standard_normal((2, 4, 4, 32)), jnp.float32)
    gn = GroupNorm(num_groups=4, num_channels=32, act="silu")
    p = gn.init(jax.random.PRNGKey(0), x)
    y = gn.apply(p, x)
    assert y.shape == x.shape


# ---------------------------------------------------------------- transducer
def _transducer_loss_ref(log_probs, labels, T, U, blank=0):
    """O(T*U) literal DP in numpy (the textbook RNN-T forward recursion)."""
    lp = np.asarray(log_probs, np.float64)
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]])
            if cands:
                alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_transducer_loss_matches_dp(rng):
    from apex_tpu.contrib.transducer import transducer_loss

    b, t, u, v = 3, 6, 4, 8
    logits = rng.standard_normal((b, t, u + 1, v)).astype(np.float32)
    log_probs = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    labels = rng.integers(1, v, (b, u)).astype(np.int32)
    f_len = np.array([6, 5, 4], np.int32)
    y_len = np.array([4, 3, 2], np.int32)

    out = transducer_loss(log_probs, jnp.asarray(labels),
                          jnp.asarray(f_len), jnp.asarray(y_len))
    for i in range(b):
        ref = _transducer_loss_ref(np.asarray(log_probs)[i], labels[i],
                                   int(f_len[i]), int(y_len[i]))
        np.testing.assert_allclose(float(out[i]), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_transducer_loss_differentiable(rng):
    from apex_tpu.contrib.transducer import TransducerLoss

    b, t, u, v = 2, 5, 3, 6
    x = jnp.asarray(rng.standard_normal((b, t, u + 1, v)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, v, (b, u)), jnp.int32)
    f_len = jnp.asarray([t, t - 1], jnp.int32)
    y_len = jnp.asarray([u, u - 1], jnp.int32)
    crit = TransducerLoss()

    g = jax.grad(lambda x: crit(x, labels, f_len, y_len).sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    # grad wrt a position beyond every valid (t,u) diagonal must be zero
    assert float(jnp.abs(g[1, t - 1, u, :]).sum()) == 0.0


def test_transducer_joint(rng):
    from apex_tpu.contrib.transducer import TransducerJoint

    f = jnp.asarray(rng.standard_normal((2, 5, 8)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((2, 3, 8)), jnp.float32)
    out = TransducerJoint(relu=True)(f, g)
    assert out.shape == (2, 5, 3, 8)
    ref = jax.nn.relu(f[:, :, None, :] + g[:, None, :, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------- layer_norm
def test_fast_layer_norm_shim(rng):
    from apex_tpu.contrib.layer_norm import FastLayerNorm
    from apex_tpu.normalization import FusedLayerNorm

    x = jnp.asarray(rng.standard_normal((4, 768)), jnp.float32)
    fast = FastLayerNorm(768)
    fused = FusedLayerNorm(768)
    p = fast.init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(np.asarray(fast.apply(p, x)),
                                  np.asarray(fused.apply(p, x)))


# ---------------------------------------------------------------- focal loss
def test_focal_loss_matches_reference(rng):
    from apex_tpu.contrib.focal_loss import focal_loss

    n, c = 64, 8
    logits = jnp.asarray(rng.standard_normal((n, c)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, c + 1, (n,)), jnp.int32)

    out = focal_loss(logits, targets, c, alpha=0.25, gamma=2.0)

    # literal numpy reference
    x = np.asarray(logits, np.float64)
    t = np.zeros((n, c))
    for i, ti in enumerate(np.asarray(targets)):
        if ti > 0:
            t[i, ti - 1] = 1.0
    p = 1 / (1 + np.exp(-x))
    bce = np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))
    pt = p * t + (1 - p) * (1 - t)
    at = 0.25 * t + 0.75 * (1 - t)
    ref = (at * (1 - pt) ** 2.0 * bce).sum()
    np.testing.assert_allclose(float(out), ref, rtol=1e-5)
    # differentiable
    g = jax.grad(lambda l: focal_loss(l, targets, c))(logits)
    assert np.isfinite(np.asarray(g)).all()


# -------------------------------------------------------------- index_mul_2d
def test_index_mul_2d(rng):
    from apex_tpu.contrib.index_mul_2d import index_mul_2d

    in1 = jnp.asarray(rng.standard_normal((10, 7)), jnp.float32)
    in2 = jnp.asarray(rng.standard_normal((5, 7)), jnp.float32)
    idx = jnp.asarray(rng.integers(0, 5, (10,)), jnp.int32)
    out = index_mul_2d(in1, in2, idx)
    ref = np.asarray(in1) * np.asarray(in2)[np.asarray(idx)]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)
    # backward: scatter-add into in2
    g2 = jax.grad(lambda a: index_mul_2d(in1, a, idx).sum())(in2)
    ref_g2 = np.zeros((5, 7), np.float32)
    np.add.at(ref_g2, np.asarray(idx), np.asarray(in1))
    np.testing.assert_allclose(np.asarray(g2), ref_g2, rtol=1e-5)


# ------------------------------------------------------------- halo exchange
def test_halo_exchange_1d(rng):
    from apex_tpu.contrib.peer_memory import halo_exchange_1d
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=8)
    # global image [1, 32, 4, 2] split along H over 8 ranks -> slabs of 4
    full = jnp.asarray(rng.standard_normal((1, 32, 4, 2)), jnp.float32)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=P(None, CONTEXT_AXIS), out_specs=P(None, CONTEXT_AXIS))
    def run(x):
        return halo_exchange_1d(x, 1, CONTEXT_AXIS, spatial_dim=1)

    out = run(full)  # [1, 8*(4+2), 4, 2]
    out = np.asarray(out).reshape(1, 8, 6, 4, 2)
    fullv = np.asarray(full).reshape(1, 8, 4, 4, 2)
    for r in range(8):
        np.testing.assert_array_equal(out[:, r, 1:5], fullv[:, r])
        if r > 0:
            np.testing.assert_array_equal(out[:, r, 0], fullv[:, r - 1, -1])
        else:
            np.testing.assert_array_equal(out[:, r, 0], 0 * out[:, r, 0])
        if r < 7:
            np.testing.assert_array_equal(out[:, r, 5], fullv[:, r + 1, 0])
        else:
            np.testing.assert_array_equal(out[:, r, 5], 0 * out[:, r, 5])


# ------------------------------------------------------------------- groupbn
def test_groupbn_nhwc_add_relu(rng):
    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

    x = jnp.asarray(rng.standard_normal((4, 4, 4, 16)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((4, 4, 4, 16)), jnp.float32)
    bn = BatchNorm2d_NHWC(16, fuse_relu=True)
    variables = bn.init(jax.random.PRNGKey(0), x)
    y, _ = bn.apply(variables, x, z=z, mutable=["batch_stats"])
    assert (np.asarray(y) >= 0).all()
    # matches manual BN + add + relu
    xm = np.asarray(x, np.float64)
    mean = xm.mean(axis=(0, 1, 2))
    var = xm.var(axis=(0, 1, 2))
    ref = (xm - mean) / np.sqrt(var + 1e-5) + np.asarray(z)
    np.testing.assert_allclose(np.asarray(y), np.maximum(ref, 0),
                               rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------ conv_bias_relu
def test_conv_bias_relu_family(rng):
    from apex_tpu.contrib.conv_bias_relu import (ConvBias, ConvBiasMaskReLU,
                                                 ConvBiasReLU)

    x = jnp.asarray(rng.standard_normal((2, 8, 8, 3)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 4)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((4,)) * 0.1, jnp.float32)

    y = ConvBias(x, w, b, padding=1)
    assert y.shape == (2, 8, 8, 4)
    yr = ConvBiasReLU(x, w, b, padding=1)
    np.testing.assert_allclose(np.asarray(yr),
                               np.maximum(np.asarray(y), 0), rtol=1e-6)
    mask = jnp.asarray(rng.integers(0, 2, (2, 8, 8, 4)), jnp.float32)
    ym = ConvBiasMaskReLU(x, w, b, mask, padding=1)
    np.testing.assert_allclose(np.asarray(ym),
                               np.maximum(np.asarray(y) * np.asarray(mask), 0),
                               rtol=1e-6)
    g = jax.grad(lambda w: ConvBiasReLU(x, w, b, padding=1).sum())(w)
    assert np.isfinite(np.asarray(g)).all()


# ---------------------------------------------------------------- fmha shim
@pytest.mark.slow
def test_fmha_varlen_matches_dense(rng):
    from apex_tpu.contrib.fmha import fmha
    from apex_tpu.ops import flash_attention

    h, d = 2, 32
    lens = [5, 9, 3]
    total = sum(lens)
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    qkv = jnp.asarray(rng.standard_normal((total, 3, h, d)), jnp.float32)

    out = fmha(qkv, cu, max_s=16, is_training=False)
    assert out.shape == (total, h, d)

    # per-sequence dense attention reference
    off = 0
    for L in lens:
        seq = qkv[off:off + L]
        q, k, v = (seq[:, i].transpose(1, 0, 2)[None] for i in range(3))
        ref = flash_attention(q, k, v)[0].transpose(1, 0, 2)
        np.testing.assert_allclose(np.asarray(out[off:off + L]),
                                   np.asarray(ref), rtol=2e-3, atol=2e-3)
        off += L


# ---------------------------------------------------------------- openfold
def test_openfold_entry_points(rng):
    """Reference: apex/contrib/openfold_triton — LN + attention core mapped
    onto the library kernels (VERDICT r2 missing #4)."""
    from apex_tpu.contrib.openfold import attention_core, layer_norm
    from apex_tpu.ops.flash_attention import mha_reference

    # LN over an OpenFold-ish pair activation [B, N, N, c_z]
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64,)) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.standard_normal((64,)) * 0.1, jnp.float32)
    y = layer_norm(x, w, b)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(np.asarray(var) + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # attention core with the two additive biases (mask + pair)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 4, 16, 32)), jnp.float32)
               for _ in range(3))
    mask_bias = jnp.where(
        jnp.asarray(rng.random((2, 1, 1, 16)) < 0.2), -1e9, 0.0
    ).astype(jnp.float32)
    pair_bias = jnp.asarray(rng.standard_normal((1, 4, 16, 16)), jnp.float32)
    out = attention_core(q, k, v, mask_bias, pair_bias)
    ref = mha_reference(q, k, v, bias=mask_bias + pair_bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
