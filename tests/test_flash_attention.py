"""Flash attention kernel vs unfused reference.

Mirrors the reference test strategy (SURVEY.md §4): fused kernel vs pure
framework implementation over dtype/shape/flag grids
(apex/contrib/test/multihead_attn/, apex/contrib/test/fmha/test_fmha.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import (
    flash_attention,
    mha_reference,
)

TOLS = {jnp.float32: 2e-5, jnp.bfloat16: 2e-2}


def _qkv(rng, b, h, sq, sk, d, dtype):
    q = jnp.asarray(rng.standard_normal((b, h, sq, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 64, 64, 32), (2, 2, 100, 100, 64),
                                   (1, 1, 72, 136, 40)])
def test_forward_matches_reference(rng, dtype, causal, shape):
    b, h, sq, sk, d = shape
    q, k, v = _qkv(rng, b, h, sq, sk, d, dtype)
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32),
        atol=TOLS[dtype], rtol=TOLS[dtype])


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(rng, causal):
    q, k, v = _qkv(rng, 2, 2, 72, 72, 32, jnp.float32)

    g = jax.grad(lambda *a: (flash_attention(*a, causal=causal) ** 2).sum(),
                 argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(a, b_, atol=5e-5, rtol=5e-4)


@pytest.mark.slow
def test_bias_and_cross_attention(rng):
    b, h, sq, sk, d = 2, 2, 40, 88, 32
    q, k, v = _qkv(rng, b, h, sq, sk, d, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((1, h, sq, sk)), jnp.float32)
    out = flash_attention(q, k, v, bias=bias)
    ref = mha_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    g = jax.grad(lambda q: (flash_attention(q, k, v, bias=bias) ** 2).sum())(q)
    gr = jax.grad(lambda q: (mha_reference(q, k, v, bias=bias) ** 2).sum())(q)
    np.testing.assert_allclose(g, gr, atol=5e-5, rtol=5e-4)


def test_segment_ids_varlen(rng):
    """Packed-sequence masking (reference fmha cu_seqlens equivalent)."""
    b, h, s, d = 2, 2, 96, 32
    q, k, v = _qkv(rng, b, h, s, s, d, jnp.float32)
    seg = jnp.asarray(rng.integers(0, 3, (b, s)), jnp.int32)
    seg = jnp.sort(seg, axis=1)  # packed layout: contiguous segments
    out = flash_attention(q, k, v, segment_ids=seg)
    ref = mha_reference(q, k, v, segment_ids=seg)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_block_size_invariance(rng):
    q, k, v = _qkv(rng, 1, 2, 256, 256, 32, jnp.float32)
    a = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    b_ = flash_attention(q, k, v, causal=True, block_q=64, block_k=256)
    np.testing.assert_allclose(a, b_, atol=1e-5, rtol=1e-5)


def _np_keep(bh, s1, s2, rate, seed):
    """Reimplementation of the kernel's counter-based dropout hash."""
    rows = np.arange(s1, dtype=np.uint32)[:, None] * np.uint32(0x9E3779B1)
    cols = np.arange(s2, dtype=np.uint32)[None, :] * np.uint32(0x85EBCA77)
    with np.errstate(over="ignore"):
        x = rows + cols + np.uint32(bh) * np.uint32(0xC2B2AE3D) + np.uint32(seed)
        x ^= x >> np.uint32(16)
        x *= np.uint32(0x85EBCA6B)
        x ^= x >> np.uint32(13)
        x *= np.uint32(0xC2B2AE35)
        x ^= x >> np.uint32(16)
    thr = np.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
    return (x >= thr).astype(np.float32) / (1.0 - rate)


@pytest.mark.slow
def test_dropout_exact_vs_explicit_mask(rng):
    """Fwd AND bwd must equal an explicitly-masked softmax with the same
    keep mask (reference: fused softmax-dropout in fast_multihead_attn)."""
    b, h, s, d = 1, 2, 64, 32
    rate, seed = 0.3, 7
    q, k, v = _qkv(rng, b, h, s, s, d, jnp.float32)
    keep = jnp.stack([
        jnp.stack([jnp.asarray(_np_keep(bi * h + hi, s, s, rate, seed))
                   for hi in range(h)]) for bi in range(b)])

    def ref_drop(q, k, v):
        p = jax.nn.softmax(
            jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5), -1) * keep
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    fused = lambda q, k, v: flash_attention(
        q, k, v, dropout_rate=rate, dropout_seed=seed)
    np.testing.assert_allclose(fused(q, k, v), ref_drop(q, k, v),
                               atol=2e-5, rtol=2e-5)
    g = jax.grad(lambda *a: (fused(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (ref_drop(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(a, b_, atol=5e-5, rtol=5e-4)


def test_dropout_traced_seed_jit(rng):
    """Seed is a traced scalar: varying it must not recompile or freeze."""
    q, k, v = _qkv(rng, 1, 1, 32, 32, 16, jnp.float32)

    @jax.jit
    def run(seed):
        return flash_attention(q, k, v, dropout_rate=0.5, dropout_seed=seed)

    a = run(jnp.int32(1))
    b_ = run(jnp.int32(1))
    c = run(jnp.int32(2))
    assert jnp.array_equal(a, b_)
    assert not jnp.array_equal(a, c)


def test_fully_masked_rows_output_zero(rng):
    """Rows with no live keys must output exactly 0 (and zero grads), not a
    uniform average over padded keys — regression for the finite-fill
    degenerate case."""
    # causal cross-attention with q_len > kv_len: first rows see no keys
    q, k, v = _qkv(rng, 1, 1, 64, 32, 16, jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    assert bool(jnp.all(out[:, :, :31] == 0.0))  # offset = kv-q = -32

    # segment id present in q but absent in kv
    sq = jnp.zeros((1, 64), jnp.int32).at[:, -8:].set(9)
    sk_ids = jnp.zeros((1, 32), jnp.int32)
    out = flash_attention(q, k, v, segment_ids=sq, kv_segment_ids=sk_ids)
    ref = mha_reference(q, k, v, segment_ids=sq, kv_segment_ids=sk_ids)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    assert bool(jnp.all(out[:, :, -8:] == 0.0))
    g = jax.grad(lambda v: (flash_attention(
        q, k, v, segment_ids=sq, kv_segment_ids=sk_ids)[:, :, -8:] ** 2).sum())(v)
    assert bool(jnp.all(g == 0.0))


def test_long_sequence_no_cap(rng):
    """The reference fmha caps seqlen at 512; this kernel must not."""
    q, k, v = _qkv(rng, 1, 1, 2048, 2048, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("kvh,causal", [(1, False), (2, True)])
def test_gqa_native_kv_heads(rng, kvh, causal):
    """GQA/MQA: kv_heads < heads handled by kernel index maps (no repeated
    K/V in HBM). Forward vs the repeat-based reference; grads vs the
    jnp.repeat formulation (whose VJP is the same per-group sum)."""
    b, h, s, d = 2, 4, 64, 32
    rep = h // kvh
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)

    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_native(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_repeat(q, k, v):
        return jnp.sum(flash_attention(q, jnp.repeat(k, rep, axis=1),
                                       jnp.repeat(v, rep, axis=1),
                                       causal=causal) ** 2)

    gn = jax.grad(loss_native, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_repeat, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gn, gr):
        assert a.shape == r.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)


def test_gqa_rejects_non_divisible(rng):
    q = jnp.zeros((1, 6, 16, 32), jnp.float32)
    k = jnp.zeros((1, 4, 16, 32), jnp.float32)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, k)


@pytest.mark.slow
@pytest.mark.parametrize("window,s", [(16, 128), (64, 200), (1, 64)])
def test_sliding_window_matches_reference(rng, window, s):
    """Mistral-style causal sliding window: parity vs the masked dense
    reference in fwd AND grads (the block-skip must not drop live tiles)."""
    b, h, d = 1, 2, 32
    q, k, v = _qkv(rng, b, h, s, s, d, jnp.float32)

    out = flash_attention(q, k, v, causal=True, window=window)
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_k(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       window=window) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True,
                                     window=window) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)


def test_sliding_window_with_gqa(rng):
    """window composes with GQA kv-head indexing."""
    b, h, kvh, s, d = 1, 4, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=True, window=32)
    ref = mha_reference(q, k, v, causal=True, window=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sliding_window_requires_causal(rng):
    q, k, v = _qkv(rng, 1, 1, 16, 16, 32, jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=8)


@pytest.mark.slow
@pytest.mark.parametrize("window", [8, 24, 56, 200])
def test_sliding_window_banded_grid_small_blocks(rng, window):
    """Small blocks force multi-block bands with edge clamping: the
    band-restricted grid (dead blocks don't exist, saving DMA too) must
    match the dense reference in fwd and all grads."""
    b, h, s, d = 1, 2, 256, 32
    q, k, v = _qkv(rng, b, h, s, s, d, jnp.float32)
    kw = dict(causal=True, window=window, block_q=32, block_k=32)

    out = flash_attention(q, k, v, **kw)
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    gk = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, **kw) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        mha_reference(q, k, v, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_flash_property_fuzz_vs_reference(rng):
    """Property fuzz (hypothesis): random (shape, causal, window, kv_heads,
    block sizes) must match the dense reference in forward. Catches band /
    GQA / padding edge interactions no enumerated grid covers."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=25, deadline=None, derandomize=True)
    @given(
        b=st.integers(1, 2),
        h_pow=st.integers(0, 2),          # heads in {1, 2, 4}
        kv_div=st.integers(0, 2),         # kv_heads = heads / 2**kv_div
        sq=st.integers(9, 150),
        d=st.sampled_from([8, 32, 40]),
        causal=st.booleans(),
        window=st.one_of(st.none(), st.integers(1, 200)),
        bq=st.sampled_from([None, 16, 32]),
    )
    def check(b, h_pow, kv_div, sq, d, causal, window, bq):
        h = 2 ** h_pow
        kvh = max(1, h >> kv_div)   # power-of-two divisor of h by construction
        if window is not None and not causal:
            causal = True
        local = np.random.default_rng(b * 1000 + sq)
        q = jnp.asarray(local.standard_normal((b, h, sq, d)), jnp.float32)
        k = jnp.asarray(local.standard_normal((b, kvh, sq, d)), jnp.float32)
        v = jnp.asarray(local.standard_normal((b, kvh, sq, d)), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, window=window,
                              block_q=bq, block_k=bq)
        ref = mha_reference(q, k, v, causal=causal, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=3e-5, atol=3e-5)

    check()
