"""Ring attention (context parallelism) vs single-device flash attention.

VERDICT r2 missing #2: the promised ops/ring_attention.py. Parity contract:
sharding the sequence over the ``context`` axis and rotating K/V around the
ring must reproduce the single-device flash_attention result (and grads) up
to accumulation-order tolerance, at cp=2 and cp=4, causal and not.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops import flash_attention, flash_attention_with_lse, ring_attention
from apex_tpu.ops.flash_attention import mha_reference


def cp_mesh(cp):
    devs = np.asarray(jax.devices()[:cp])
    return Mesh(devs, ("context",))


def ring_sharded(q, k, v, cp, causal):
    mesh = cp_mesh(cp)
    spec = P(None, None, "context", None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="context", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)


@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_matches_single_device(rng, cp, causal):
    b, h, s, d = 2, 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=causal)
    out = ring_sharded(q, k, v, cp, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_grads_match_single_device(rng, causal):
    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    dout = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_sharded(q, k, v, 4, causal) * dout)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) * dout)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=3e-4, rtol=3e-4,
                                   err_msg=f"d{name} mismatch")


def test_with_lse_matches_reference_softmax(rng):
    """The (o, lse) building block: lse must equal logsumexp of the scaled
    scores, and o must match flash_attention (scale default path included —
    r2 shipped this with an unimported np.sqrt NameError)."""
    b, h, s, d = 1, 2, 64, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    o, lse = flash_attention_with_lse(q, k, v)  # default scale: the r2 bug
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(flash_attention(q, k, v)),
                               atol=1e-6, rtol=1e-6)
    s_mat = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
    ref_lse = jax.scipy.special.logsumexp(s_mat, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=1e-5, rtol=1e-5)


def test_with_lse_grad_includes_lse_cotangent(rng):
    """d/dq of a function of lse alone must match the jnp reference — this
    exercises the delta_adjust path in the flash backward."""
    b, h, s, d = 1, 1, 32, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def f_kernel(q):
        o, lse = flash_attention_with_lse(q, k, v)
        return jnp.sum(lse) + jnp.sum(o)

    def f_ref(q):
        s_mat = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        p = jax.nn.softmax(s_mat, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(jax.scipy.special.logsumexp(s_mat, axis=-1)) + jnp.sum(o)

    np.testing.assert_allclose(np.asarray(jax.grad(f_kernel)(q)),
                               np.asarray(jax.grad(f_ref)(q)),
                               atol=2e-4, rtol=2e-4)


def zigzag_sharded(q, k, v, cp, **kw):
    from apex_tpu.ops import ring_attention_zigzag

    mesh = cp_mesh(cp)
    spec = P(None, None, "context", None)
    fn = shard_map(
        functools.partial(ring_attention_zigzag, axis_name="context", **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)


def test_zigzag_permutation_roundtrip(rng):
    from apex_tpu.ops import from_zigzag, to_zigzag

    x = jnp.asarray(rng.standard_normal((1, 2, 32, 4)), jnp.float32)
    for cp in (2, 4):
        z = to_zigzag(x, cp)
        np.testing.assert_array_equal(np.asarray(from_zigzag(z, cp)),
                                      np.asarray(x))


@pytest.mark.slow
@pytest.mark.parametrize("cp", [2, 4])
def test_zigzag_ring_matches_single_device_causal(rng, cp):
    from apex_tpu.ops import from_zigzag, to_zigzag

    b, h, s, d = 1, 2, 256, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True)
    out_z = zigzag_sharded(to_zigzag(q, cp), to_zigzag(k, cp),
                           to_zigzag(v, cp), cp)
    out = from_zigzag(out_z, cp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


@pytest.mark.slow
def test_zigzag_ring_grads_match_single_device(rng):
    from apex_tpu.ops import from_zigzag, to_zigzag

    cp = 2
    b, h, s, d = 1, 1, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    dout = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def loss_z(q, k, v):
        o = from_zigzag(zigzag_sharded(to_zigzag(q, cp), to_zigzag(k, cp),
                                       to_zigzag(v, cp), cp), cp)
        return jnp.sum(o * dout)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) * dout)

    g_z = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gz, gr, name in zip(g_z, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(gz), np.asarray(gr),
                                   atol=3e-4, rtol=3e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_ring_bf16_matches_single_device(rng):
    """bf16 inputs: the ring's f32 lse-merge must keep parity with the
    single-device bf16 flash kernel at bf16-level tolerance."""
    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)

    ref = flash_attention(q, k, v, causal=True)
    out = ring_sharded(q, k, v, 4, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gqa_kv_heads(rng, causal):
    """GQA ring: unexpanded kv heads rotate around the ring; result matches
    the single-device GQA flash attention."""
    b, h, kvh, s, d = 1, 4, 2, 128, 32
    cp = 2
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=causal)
    out = ring_sharded(q, k, v, cp, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_gqa_grads_match_single_device(rng):
    """GQA K/V gradients through the ring (rep-sum composing with the
    ppermute transpose) == single-device GQA flash grads."""
    b, h, kvh, s, d = 1, 4, 2, 128, 32
    cp = 2
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_sharded(q, k, v, cp, True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gg, gr):
        assert a.shape == r.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_zigzag_gqa_matches_single_device(rng):
    """Zigzag causal ring with unexpanded GQA K/V (half-chunk lax.cond
    branches + merges) == single-device GQA flash."""
    from apex_tpu.ops import from_zigzag, to_zigzag

    b, h, kvh, s, d = 1, 4, 2, 128, 32
    cp = 2
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True)
    qz, kz, vz = (to_zigzag(t, cp) for t in (q, k, v))
    out = from_zigzag(zigzag_sharded(qz, kz, vz, cp), cp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
# (4, 96) was dropped in the r5 tier rebalance: same "window spans chunks"
# regime as (4, 48) with no new hop-liveness pattern, at ~72 s of
# compile-bound test time on the 1-core box
@pytest.mark.parametrize("cp,window", [(2, 24), (4, 48), (4, 300), (2, 1),
                                       (4, 16)])
def test_zigzag_sliding_window_matches_single_device(rng, cp, window):
    # (4, 16): hop 2 is wholly out-of-band (d_max=1) while hop 3 is live
    # via the LL wrap — the ONLY case exercising the composed delta=2
    # rotation (skipped hops folding into one multi-step ppermute)
    """VERDICT r3 weak #5: the load-balanced zigzag layout composes with
    sliding windows — static-offset EE/LL bands, a dynamic-offset
    late-vs-early block, and hop skipping with composed rotations — and
    must match single-device windowed flash across window < half-chunk,
    window spanning chunks, window > sequence, and window=1."""
    from apex_tpu.ops import from_zigzag, to_zigzag

    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True, window=window)
    qz, kz, vz = (to_zigzag(t, cp) for t in (q, k, v))
    out = from_zigzag(zigzag_sharded(qz, kz, vz, cp, window=window), cp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_zigzag_sliding_window_grads_match(rng):
    """Grads through the windowed zigzag (dynamic-offset kernel backward +
    composed-rotation ppermute transposes) == single-device windowed
    flash."""
    from apex_tpu.ops import from_zigzag, to_zigzag

    b, h, s, d, cp, window = 1, 2, 128, 32, 4, 48
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def loss_z(q, k, v):
        o = from_zigzag(zigzag_sharded(to_zigzag(q, cp), to_zigzag(k, cp),
                                       to_zigzag(v, cp), cp, window=window),
                        cp)
        return jnp.sum(o ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       window=window) ** 2)

    gz = jax.grad(loss_z, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, r, name in zip(gz, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_ring_dropout_matches_single_device(rng):
    """VERDICT r3 missing #5: attention dropout under CP. The ring seeds
    the counter-based kernel PRNG at GLOBAL coordinates, so with the same
    seed it draws the IDENTICAL keep mask as one unsharded call — exact
    parity, not just statistics."""
    b, h, s, d, cp = 1, 2, 128, 32, 2
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                          dropout_seed=11)
    mesh = cp_mesh(cp)
    spec = P(None, None, "context", None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="context", causal=True,
                          dropout_rate=0.3, dropout_seed=11),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    # and the backward: same masks regenerate in the ring's dq/dk/dv
    gr = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, dropout_rate=0.3, dropout_seed=11) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, r, name in zip(gg, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=3e-3, atol=3e-3,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_zigzag_dropout_matches_single_device(rng):
    """Zigzag CP dropout: global-coordinate PRNG bases follow the zigzag
    chunk ids, so the permuted layout still reproduces the single-device
    mask exactly."""
    from apex_tpu.ops import from_zigzag, to_zigzag

    b, h, s, d, cp = 1, 2, 128, 32, 2
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True, dropout_rate=0.25,
                          dropout_seed=5)
    qz, kz, vz = (to_zigzag(t, cp) for t in (q, k, v))
    out = from_zigzag(zigzag_sharded(qz, kz, vz, cp, dropout_rate=0.25,
                                     dropout_seed=5), cp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("cp,window", [(2, 24), (4, 48), (4, 300), (2, 1)])
def test_ring_sliding_window_matches_single_device(rng, cp, window):
    """Window-aware ring: parity vs single-device windowed flash across
    window < chunk, window spanning chunks, window > sequence, window=1."""
    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    ref = flash_attention(q, k, v, causal=True, window=window)

    mesh = cp_mesh(cp)
    spec = P(None, None, "context", None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="context", causal=True,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_sliding_window_grads_match(rng):
    """Grads through the statically-shortened windowed ring (unrolled
    rotation + ppermute transpose) == single-device windowed flash."""
    b, h, s, d, cp, window = 1, 2, 128, 32, 4, 48
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    mesh = cp_mesh(cp)
    spec = P(None, None, "context", None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="context", causal=True,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)

    gr = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True, window=window) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gg, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-3)
