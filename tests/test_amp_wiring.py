"""amp policy wired into modules + model-parallel found-inf agreement.

VERDICT round-1 weakness #5: ``amp.initialize(opt_level="O1")`` must actually
flip module compute dtypes (the reference's O1 monkey-patching), and an inf
on one TP rank must skip the optimizer step on ALL ranks (reference:
apex/transformer/amp/grad_scaler.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.mesh import MODEL_AXIS


@pytest.mark.slow
def test_o1_flips_bert_activation_dtype():
    """O1 initialize changes activation dtypes with NO config change."""
    from apex_tpu.models import BertForPreTraining, bert_tiny_config

    cfg = bert_tiny_config()           # cfg.dtype is float32
    model = BertForPreTraining(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    mlm, _ = model.apply({"params": params}, ids)
    assert mlm.dtype == jnp.float32    # no policy -> config dtype

    amp.initialize(params, opt_level="O1")
    mlm, _ = model.apply({"params": params}, ids)
    assert mlm.dtype == jnp.bfloat16   # policy flipped compute dtype
    # params untouched under O1 (patch-the-ops, not the weights)
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(params))


@pytest.mark.slow
def test_o1_flips_mlp_and_fused_dense_dtype():
    from apex_tpu.fused_dense import FusedDenseGeluDense
    from apex_tpu.mlp import MLP

    x = jnp.ones((4, 16), jnp.float32)
    mlp = MLP([16, 8])
    p1 = mlp.init(jax.random.PRNGKey(0), x)
    fd = FusedDenseGeluDense(16, 32, 8)
    p2 = fd.init(jax.random.PRNGKey(0), x)

    assert mlp.apply(p1, x).dtype == jnp.float32
    assert fd.apply(p2, x).dtype == jnp.float32
    amp.initialize({}, opt_level="O1")
    assert mlp.apply(p1, x).dtype == jnp.bfloat16
    assert fd.apply(p2, x).dtype == jnp.bfloat16


def test_o0_keeps_fp32():
    from apex_tpu.mlp import MLP

    x = jnp.ones((4, 16), jnp.float32)
    mlp = MLP([16, 8])
    p = mlp.init(jax.random.PRNGKey(0), x)
    amp.initialize({}, opt_level="O0")
    assert mlp.apply(p, x).dtype == jnp.float32


def test_multihead_attn_consults_policy():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    x = jnp.ones((8, 2, 32), jnp.float32)
    mha = SelfMultiheadAttn(32, 4, impl="default")
    p = mha.init(jax.random.PRNGKey(0), x, is_training=False)
    out, _ = mha.apply(p, x, is_training=False)
    assert out.dtype == jnp.float32
    amp.initialize({}, opt_level="O1")
    out, _ = mha.apply(p, x, is_training=False)
    assert out.dtype == jnp.bfloat16


def test_found_inf_agreed_across_tp_ranks(mesh_tp2_pp2_dp2):
    """Inf in the grads seen under a bound model axis must skip the step for
    every rank — master params stay identical and unchanged."""
    from apex_tpu.optimizers import FusedAdam

    mesh = mesh_tp2_pp2_dp2
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    opt = FusedAdam(params, lr=0.1)
    _, opt = amp.initialize(params, opt, half_dtype=jnp.float16,
                            opt_level="O2", loss_scale="dynamic")

    # rank-dependent grads: only model-rank 0 sees an inf
    def step_with_rank_local_inf(master, state, scount, sstate):
        def body(master, state, scount, sstate):
            r = jax.lax.axis_index(MODEL_AXIS)
            g = {"w": jnp.where(r == 0, jnp.inf, 1.0)
                 * jnp.ones((8, 8), jnp.float32)}
            # call the optimizer's pure step path manually (facade .step jits
            # without the axis bound; here we exercise the shard_map path)
            from apex_tpu.ops import flat_buffer, optim_kernels
            from apex_tpu.optimizers.common import (
                _agree_found_inf_across_model_parallel)

            g_flat = flat_buffer.flatten(g, opt.spec)
            _, finite, _ = optim_kernels.global_grad_norm_and_finite(
                g_flat, opt.seg_rows, opt.spec.num_tensors)
            found_inf = 1.0 - finite.astype(jnp.float32)
            found_inf = _agree_found_inf_across_model_parallel(found_inf)
            return found_inf[None]

        return jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P(), P(), P()),
            out_specs=P(MODEL_AXIS), check_vma=False,
        )(master, state, scount, sstate)

    found = step_with_rank_local_inf(opt.master, opt.state, opt.step_count,
                                     opt._amp_scaler.state)
    # every model rank must report found_inf = 1 (agreement), even though
    # only rank 0 actually saw the inf
    np.testing.assert_array_equal(np.asarray(found), np.ones(2, np.float32))


def test_grad_scaler_api(mesh_tp2_pp2_dp2):
    from apex_tpu.transformer.amp import GradScaler

    gs = GradScaler(init_scale=2.0 ** 8)
    st = gs.state
    st2 = gs.update(st, jnp.float32(1.0))   # overflow halves
    assert float(st2.scale) == 2.0 ** 7
    st3 = gs.update(st2, jnp.float32(0.0))
    assert float(st3.scale) == 2.0 ** 7
