"""tpu-lint IR tier (apex_tpu.analysis.ir) coverage.

Mirrors the PR 3 load-bearing pattern one layer down, per ISSUE 5:

1. per-rule fixture pairs — a bad PROGRAM whose jaxpr triggers EXACTLY
   its rule (and passes with the rule deselected), and a good twin
   that is clean;
2. machinery — source-info anchoring, inline suppression of IR
   findings, the trace-error path, the case registry's domain span;
3. interprocedural AST-tier fixtures that need a cross-module package
   (host-sync through an imported helper, imported donated wrappers,
   the host-boundary pragma);
4. end-to-end — ``--ir`` over the repo itself exits 0 at HEAD: the
   tier-1 twin of the ``run_tpu_round.sh`` IR gate.
"""

import os
import sys
import textwrap
from pathlib import Path

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402
import numpy as np                                             # noqa: E402
from jax import lax                                            # noqa: E402
from jax.experimental import pallas as pl                      # noqa: E402

from apex_tpu.analysis import cli                              # noqa: E402
from apex_tpu.analysis.ir import IR_RULES, analyze_ir          # noqa: E402
from apex_tpu.analysis.ir.harness import (AnalysisCase,        # noqa: E402
                                          CaseProgram,
                                          analysis_cases,
                                          build_case_ir)
from apex_tpu.analysis.ir.ir_report import findings_for_case   # noqa: E402

f32, bf16, i32 = jnp.float32, jnp.bfloat16, jnp.int32


def _sds(shape, dtype=f32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _trace_case(name, fn, args, **kw):
    return build_case_ir(AnalysisCase(
        name, "test", lambda: CaseProgram(fn=fn, args=tuple(args), **kw)))


def _fired(ir, select=None):
    return [f.rule for f in findings_for_case(ir, Path(REPO),
                                              select=select)]


# --------------------------------------------------------------------------
# per-rule program fixture pairs
# --------------------------------------------------------------------------
# Each entry: rule -> (bad CaseProgram builder, good CaseProgram builder).
# Builders are lazy so a broken fixture fails its own test, not import.

def _promotion_bad():
    def f(x):
        y = x.astype(f32) * 2.0            # 16 MiB fp32 round trip
        return y.astype(bf16)
    return CaseProgram(fn=f, args=(_sds((2048, 2048), bf16),))


def _promotion_good():
    def f(x):
        return x * 2
    return CaseProgram(fn=f, args=(_sds((2048, 2048), bf16),))


def _x64_bad():
    def f(x):
        return x.astype(jnp.float64).sum()
    return CaseProgram(fn=f, args=(_sds((64, 64), f32),), x64=True)


def _x64_good():
    def f(x):
        return x.sum()
    return CaseProgram(fn=f, args=(_sds((64, 64), f32),))


def _dead_output_bad():
    def f(a, b):
        _unused = a @ b                    # dead dot_general
        return a + b
    return CaseProgram(fn=f, args=(_sds((256, 256)), _sds((256, 256))))


def _dead_output_good():
    def f(a, b):
        return a @ b
    return CaseProgram(fn=f, args=(_sds((256, 256)), _sds((256, 256))))


def _dead_carry_bad():
    def f(x, vestigial):
        def body(carry, _):
            a, d = carry
            return (a + 1.0, d), a.sum()
        (_, _), ys = lax.scan(body, (x, vestigial), None, length=3)
        return ys
    return CaseProgram(fn=f, args=(_sds((8, 128)), _sds((4,))))


def _dead_carry_good():
    def f(x, offset):
        def body(carry, _):
            a, d = carry
            return (a + d.sum(), d), a.sum()    # read-only state: fine
        (_, _), ys = lax.scan(body, (x, offset), None, length=3)
        return ys
    return CaseProgram(fn=f, args=(_sds((8, 128)), _sds((4,))))


def _donation_bad():
    def f(x):
        return x.astype(bf16)              # no f32 output to alias
    return CaseProgram(fn=f, args=(_sds((1024, 1024)),), donate=(0,))


def _donation_good():
    def f(x):
        return x + 1.0
    return CaseProgram(fn=f, args=(_sds((1024, 1024)),), donate=(0,))


_BIG_CONST = np.ones((512, 512), np.float32)       # 1 MiB
_SMALL_CONST = np.ones((16, 16), np.float32)


def _const_bad():
    def f(x):
        return x + jnp.asarray(_BIG_CONST)
    return CaseProgram(fn=f, args=(_sds((512, 512)),))


def _const_good():
    def f(x):
        return x[:16, :16] + jnp.asarray(_SMALL_CONST)
    return CaseProgram(fn=f, args=(_sds((512, 512)),))


def _blowup_bad():
    def f(x):
        return jnp.broadcast_to(x[None, :], (4096, 1024)) + 0.5
    return CaseProgram(fn=f, args=(_sds((1024,)),))


def _blowup_good():
    def f(x):
        return jnp.broadcast_to(x[None, :], (4, 1024)) + 0.5
    return CaseProgram(fn=f, args=(_sds((1024,)),))


def _effectful_bad():
    def f(x):
        def body(c, _):
            jax.debug.print("step {c}", c=c.sum())
            return c + 1.0, c.sum()
        c, ys = lax.scan(body, x, None, length=2)
        return c, ys
    return CaseProgram(fn=f, args=(_sds((8,)),))


def _effectful_good():
    def f(x):
        def body(c, _):
            return c + 1.0, c.sum()
        c, ys = lax.scan(body, x, None, length=2)
        jax.debug.print("done {c}", c=c.sum())   # chunk boundary: fine
        return c, ys
    return CaseProgram(fn=f, args=(_sds((8,)),))


def _cardinality_bad():
    # the "bucketing" fails to collapse: each raw length is its own trace
    def f(x):
        return x * 2.0
    return CaseProgram(fn=f, args=(_sds((90,)),),
                       variants=[(_sds((93,)),)], max_traces=1)


def _cardinality_good():
    def f(x):
        return x * 2.0
    bucket = (_sds((96,)),)                   # both lengths pad to 96
    return CaseProgram(fn=f, args=bucket, variants=[bucket],
                       max_traces=1)


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _transpose_bad():
    def f(x):
        y = jnp.swapaxes(x, -1, -2)           # 4 MiB minor-dim relayout
        return pl.pallas_call(
            _copy_kernel,
            out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
            interpret=True)(y)
    return CaseProgram(fn=f, args=(_sds((8, 512, 256)),))


def _transpose_good():
    def f(x):
        return pl.pallas_call(
            _copy_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)
    return CaseProgram(fn=f, args=(_sds((8, 512, 256)),))


IR_FIXTURES = {
    "ir-dtype-promotion-drift": (_promotion_bad, _promotion_good),
    "ir-x64-leak": (_x64_bad, _x64_good),
    "ir-dead-output": (_dead_output_bad, _dead_output_good),
    "ir-dead-scan-carry": (_dead_carry_bad, _dead_carry_good),
    "ir-donation-ineffective": (_donation_bad, _donation_good),
    "ir-large-const-capture": (_const_bad, _const_good),
    "ir-broadcast-blowup": (_blowup_bad, _blowup_good),
    "ir-effectful-in-scan": (_effectful_bad, _effectful_good),
    "ir-compile-key-cardinality": (_cardinality_bad, _cardinality_good),
    "ir-transpose-heavy-layout": (_transpose_bad, _transpose_good),
}


def _ir_for(builder, name):
    return build_case_ir(AnalysisCase(name, "test", builder))


@pytest.mark.parametrize("rule", sorted(IR_FIXTURES))
def test_bad_program_triggers_exactly_its_rule(rule):
    ir = _ir_for(IR_FIXTURES[rule][0], f"bad_{rule}")
    fired = _fired(ir)
    assert fired, f"bad program for {rule} produced no findings"
    assert set(fired) == {rule}, fired


@pytest.mark.parametrize("rule", sorted(IR_FIXTURES))
def test_good_program_is_clean(rule):
    ir = _ir_for(IR_FIXTURES[rule][1], f"good_{rule}")
    assert not _fired(ir)


@pytest.mark.parametrize("rule", sorted(IR_FIXTURES))
def test_ir_rules_individually_load_bearing(rule):
    """With the rule deselected (≈ deleted), its bad program passes: no
    other IR rule shadows it."""
    ir = _ir_for(IR_FIXTURES[rule][0], f"bad_{rule}")
    others = [r for r in IR_RULES if r != rule]
    assert not _fired(ir, select=others)


def test_every_ir_rule_has_a_fixture():
    assert set(IR_RULES) == set(IR_FIXTURES)


# --------------------------------------------------------------------------
# machinery: anchoring, suppression, trace errors, registry
# --------------------------------------------------------------------------

def test_findings_anchor_to_this_file():
    """eqn.source_info maps the dead dot_general back to the fixture's
    own line in this test file."""
    ir = _ir_for(_dead_output_bad, "anchor_case")
    (finding,) = findings_for_case(ir, Path(REPO))
    assert finding.path == "tests/test_ir_lint.py"
    assert finding.scope == "anchor_case"
    src = Path(REPO, finding.path).read_text().splitlines()
    assert "a @ b" in src[finding.line - 1]


def test_ir_finding_is_inline_suppressible(tmp_path):
    """The ordinary disable pragma, placed at the ANCHORED source line,
    silences an IR finding — proven through analyze_ir's suppression
    path by anchoring a finding into a scratch root."""
    mod = tmp_path / "prog.py"
    mod.write_text(textwrap.dedent("""\
        import jax.numpy as jnp

        def wasteful(a, b):
            _unused = a @ b  # tpu-lint: disable=ir-dead-output -- test
            return a + b
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        import prog

        def build():
            return CaseProgram(fn=prog.wasteful,
                               args=(_sds((256, 256)), _sds((256, 256))))
        from apex_tpu.analysis.ir import ir_report
        case = AnalysisCase("supp_case", "test", build)
        ir = build_case_ir(case)
        findings = findings_for_case(ir, tmp_path)
        assert [f.rule for f in findings] == ["ir-dead-output"]
        supp = ir_report._SuppressionCache(tmp_path)
        assert supp.get(findings[0].path).covers(findings[0])
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("prog", None)


def test_trace_error_is_a_finding_not_a_crash(monkeypatch):
    import apex_tpu.analysis.ir.ir_report as ir_report

    def boom():
        raise RuntimeError("fixture exploded")

    monkeypatch.setattr(
        ir_report, "analysis_cases",
        lambda root: [AnalysisCase("boom_case", "test", boom)])
    findings, suppressed, n = analyze_ir(REPO)
    assert n == 1
    assert [f.rule for f in findings] == ["ir-trace-error"]
    assert "boom_case" in findings[0].message
    assert "fixture exploded" in findings[0].message


def test_registry_spans_the_stack():
    """ISSUE 5 acceptance: >= 6 registered cases spanning serving,
    models, ops and optimizers."""
    cases = analysis_cases(REPO)
    assert len(cases) >= 6
    domains = {c.domain for c in cases}
    assert {"serving", "models", "ops", "optimizers"} <= domains
    names = [c.name for c in cases]
    assert len(names) == len(set(names)), "duplicate case names"
    for expected in ("gpt2s_engine_decode_chunk",
                     "gpt2s_engine_admit_bucketed",
                     "gpt2s_prefix_cached_admit",
                     "paged_attention_gpt2s_decode"):
        assert expected in names


def test_unknown_ir_case_and_rule_are_usage_errors(capsys):
    assert cli.main(["--root", REPO, "--ir-case", "no-such-case"]) == 2
    assert cli.main(["--root", REPO, "--ir",
                     "--select", "no-such-ir-rule"]) == 2
    # AST rule names are not valid in IR mode (and vice versa)
    assert cli.main(["--root", REPO, "--ir",
                     "--select", "host-sync-in-jit"]) == 2


def test_ir_rejects_paths(capsys):
    assert cli.main(["apex_tpu", "--root", REPO, "--ir"]) == 2


def test_diff_refuses_ir(capsys):
    assert cli.main(["--root", REPO, "--ir", "--diff", "HEAD"]) == 2


# --------------------------------------------------------------------------
# cardinality contract of the real admission case
# --------------------------------------------------------------------------

def test_admit_bucketing_case_collapses_variants():
    """The registered serving admission case traces its two same-bucket
    prompt lengths to ONE program (the engine's compile-key contract)."""
    (case,) = [c for c in analysis_cases(REPO)
               if c.name == "gpt2s_engine_admit_bucketed"]
    ir = build_case_ir(case)
    assert ir.variant_closed, "case lost its cardinality variants"
    assert not [r for r in _fired(ir)
                if r == "ir-compile-key-cardinality"]


# --------------------------------------------------------------------------
# end-to-end: the repo's staged programs are clean (tier-1 IR gate twin)
# --------------------------------------------------------------------------

def test_repo_ir_is_clean_at_head(capsys):
    rc = cli.main(["--root", REPO, "--ir"])
    out = capsys.readouterr().out
    assert rc == 0, f"tpu-lint --ir found new issues in the repo:\n{out}"


def test_ir_case_scoped_write_baseline_keeps_other_cases(tmp_path,
                                                         monkeypatch):
    """--ir-case A --write-baseline replaces only case A's entries;
    other cases' (and the AST tier's) baselined debt survives."""
    import json

    from apex_tpu.analysis.walker import Finding

    baseline = tmp_path / "tpu_lint_baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": {
        "x.py::ir-dead-output::case_a": 1,
        "y.py::ir-dead-output::case_b": 2,
        "z.py::host-sync-in-jit::fn": 3,
    }}))
    fresh_a = Finding(rule="ir-x64-leak", severity="error", path="x.py",
                      line=1, col=1, message="m", scope="case_a")
    import apex_tpu.analysis.ir as ir_pkg
    monkeypatch.setattr(ir_pkg, "analyze_ir",
                        lambda root, select=None, case=None:
                        ([fresh_a], 0, 1))
    assert cli.main(["--root", str(tmp_path), "--ir-case", "case_a",
                     "--write-baseline"]) == 0
    counts = json.loads(baseline.read_text())["findings"]
    assert counts == {
        "x.py::ir-x64-leak::case_a": 1,       # case A replaced
        "y.py::ir-dead-output::case_b": 2,    # other case kept
        "z.py::host-sync-in-jit::fn": 3,      # AST tier kept
    }


def test_registry_build_failure_is_a_finding(monkeypatch):
    """An import-time error in tpu_aot.py keeps the findings-not-crashes
    contract instead of dumping a traceback with a misleading exit 1."""
    import apex_tpu.analysis.ir.ir_report as ir_report

    def boom_registry(root):
        raise RuntimeError("tpu_aot import exploded")

    monkeypatch.setattr(ir_report, "analysis_cases", boom_registry)
    findings, suppressed, n = analyze_ir(REPO)
    assert n == 0 and suppressed == 0
    assert [f.rule for f in findings] == ["ir-trace-error"]
    assert "registry" in findings[0].message
    assert "tpu_aot import exploded" in findings[0].message
