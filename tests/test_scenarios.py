"""Scenario engine (apex_tpu/serving/scenarios, docs/scenarios.md).

Trace tier (no model forward): seeded arrival/length samplers, JSONL
round-trip, byte-identical materialization per seed, the catalog's
spec/JSON round-trip.

Replay tier (tiny models): the ISSUE 9 acceptance bars — same seed ⇒
identical trace sha AND identical greedy tokens across two full replays;
``check=`` token-identity + scheduling-invariance amplifiers pass; the
pinned report schema with per-tenant SLO splits; multi-tenant isolation
(a flood tenant cannot starve a higher-priority tenant's deadline under
``PriorityDeadlinePolicy``); eviction-churn lights the
``prefix_cache.churn`` / ``evicted_reinserted`` instruments; and
windowed-Llama runs PAGED — token-identical to the rolling-cache
lock-step at window < prompt length, with dead pages dropped and the
pool fully recovered."""

import dataclasses
import json

import numpy as np
import pytest

from apex_tpu.serving.scenarios import (AGGREGATE_FIELDS, SCENARIOS,
                                        TENANT_FIELDS, Arrival,
                                        EngineSpec, Lengths, ScenarioSpec,
                                        Tenant, Trace, materialize,
                                        replay, run_scenario,
                                        scenario_names, scenario_spec,
                                        validate_report)
from apex_tpu.serving.scenarios.traces import TraceEvent
from apex_tpu.utils import metrics

# a deliberately small spec for the replay-tier tests: one engine
# compile footprint, a few seconds on CPU
_SMALL = ScenarioSpec(
    name="small", seed=7, n_requests=6,
    arrival=Arrival(kind="poisson", rate_rps=500.0),
    prompt_lens=Lengths(kind="uniform", lo=4, hi=20),
    output_lens=Lengths(kind="uniform", lo=3, hi=7),
    tenants=(Tenant("default"),),
    engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                      prefix_cache=False))


# --- trace tier --------------------------------------------------------------


def test_arrival_kinds_sorted_and_seeded():
    rng = np.random.default_rng(3)
    for kind in ("poisson", "bursty", "closed"):
        arr = Arrival(kind=kind)
        t = arr.sample_ms(32, np.random.default_rng(3))
        assert t.shape == (32,) and (np.diff(t) >= 0).all()
        assert (t >= 0).all()
        t2 = arr.sample_ms(32, np.random.default_rng(3))
        np.testing.assert_array_equal(t, t2)       # seeded
    with pytest.raises(ValueError):
        Arrival(kind="warp").sample_ms(4, rng)
    # degenerate parameters fail loudly, not with ZeroDivisionError
    for bad in (Arrival(kind="closed", users=0),
                Arrival(kind="closed", think_ms=0.0),
                Arrival(kind="poisson", rate_rps=0.0),
                Arrival(kind="bursty", idle_rate_rps=-1.0)):
        with pytest.raises(ValueError):
            bad.sample_ms(4, rng)


def test_length_kinds_bounded():
    rng = np.random.default_rng(5)
    for kind in ("lognormal", "zipf", "uniform", "fixed"):
        v = Lengths(kind=kind, lo=3, hi=17).sample(200, rng)
        assert v.dtype == np.int32
        assert v.min() >= 3 and v.max() <= 17
    # the long tail actually reaches past the body
    z = Lengths(kind="zipf", zipf_a=1.3, lo=3, hi=64).sample(
        500, np.random.default_rng(1))
    assert z.max() > 32 and np.median(z) < 10
    with pytest.raises(ValueError):
        Lengths(kind="normal").sample(4, rng)
    with pytest.raises(ValueError):
        Lengths(lo=5, hi=4).sample(4, rng)


def test_trace_determinism_and_jsonl_roundtrip(tmp_path):
    """Same seed ⇒ byte-identical materialized trace; different seed
    differs; save/load round-trips exactly."""
    a = materialize(_SMALL)
    b = materialize(_SMALL)
    assert a.to_jsonl() == b.to_jsonl()
    assert a.sha256() == b.sha256()
    c = materialize(dataclasses.replace(_SMALL, seed=8))
    assert c.sha256() != a.sha256()

    path = tmp_path / "t.jsonl"
    a.save(path)
    loaded = Trace.load(path)
    assert loaded.to_jsonl() == a.to_jsonl()
    # corruption fails loudly
    path.write_text(a.to_jsonl().rsplit("\n", 2)[0] + "\n")
    with pytest.raises(ValueError):
        Trace.load(path)


def test_catalog_specs_build_and_roundtrip():
    """Every registered scenario builds, names itself consistently, and
    survives the JSON spec round-trip; the ISSUE 9 six-plus are all
    present."""
    required = {"steady-poisson", "burst-storm", "long-tail-lengths",
                "multi-tenant-shared-prefix", "eviction-churn",
                "priority-flood", "windowed-llama"}
    assert required <= set(scenario_names())
    for name in scenario_names():
        spec = scenario_spec(name, seed=11)
        assert spec.name == name and spec.seed == 11
        back = ScenarioSpec.from_json(spec.to_json())
        assert back == spec
        trace = materialize(spec)          # bounds-clipped, materializes
        assert len(trace.events) == spec.n_requests
    with pytest.raises(KeyError):
        scenario_spec("no-such-scenario")
    # overrides apply at the top level
    assert scenario_spec("steady-poisson", n_requests=3).n_requests == 3


def test_materialize_rejects_oversized_system_prompt():
    """A tenant header too long for the model's position table raises a
    ValueError naming the tenant, not an opaque numpy error."""
    spec = ScenarioSpec(
        name="big-header",
        tenants=(Tenant("big", system_prompt_tokens=4096),))
    with pytest.raises(ValueError, match="'big'"):
        materialize(spec)


def test_tenant_prompts_deterministic_and_weighted():
    from apex_tpu.serving.scenarios.tenants import (assign_tenants,
                                                    system_prompt)

    t = Tenant("acme", system_prompt_tokens=16)
    p1 = system_prompt(t, 128, seed=5)
    p2 = system_prompt(t, 128, seed=5)
    np.testing.assert_array_equal(p1, p2)
    assert p1.shape == (16,)
    assert not np.array_equal(p1, system_prompt(t, 128, seed=6))
    other = Tenant("other", system_prompt_tokens=16)
    assert not np.array_equal(p1, system_prompt(other, 128, seed=5))
    idx = assign_tenants([Tenant("a", weight=9.0),
                          Tenant("b", weight=1.0)], 200,
                         np.random.default_rng(0))
    assert (idx == 0).sum() > (idx == 1).sum()


# --- replay tier -------------------------------------------------------------


def test_run_determinism_and_report_schema():
    """ISSUE 9 acceptance: re-running with the same seed reproduces an
    identical trace AND identical greedy tokens; the report carries the
    pinned schema."""
    r1 = run_scenario(_SMALL)
    r2 = run_scenario(_SMALL)
    assert r1.trace.sha256() == r2.trace.sha256()
    assert r1.report["trace_sha256"] == r1.trace.sha256()
    for a, b in zip(r1.outputs, r2.outputs):
        np.testing.assert_array_equal(a, b)
    validate_report(r1.report)
    assert set(AGGREGATE_FIELDS) <= set(r1.report["aggregate"])
    for block in r1.report["per_tenant"].values():
        assert set(TENANT_FIELDS) <= set(block)
    assert r1.report["aggregate"]["generated_tokens"] > 0
    assert r1.report["aggregate"]["tpot_ms_p95"] > 0


@pytest.mark.slow
def test_check_mode_amplifiers_pass():
    """check= re-derives every output via lock-step generate and re-runs
    the trace at a different sync_every — both must agree. (Slow tier
    since ISSUE 15 to hold the 870 s verify wall: tier-1 keeps a full
    check=True path in test_chaos_slow_reader_scenario_spills_over_the_wire
    — both amplifiers, over the wire — and CI's scenario/chaos/HTTP
    smokes run --check on five catalog entries every round.)"""
    r = run_scenario(_SMALL, check=True)
    assert r.report["checks"]["greedy_identity_requests"] == 6
    assert r.report["checks"]["scheduling_invariance"] is True


@pytest.mark.slow
def test_saved_trace_replays_identically(tmp_path):
    """A trace saved to JSONL and replayed (the --trace path) yields the
    same tokens as the materialized original. (Slow tier since ISSUE 15
    to hold the 870 s verify wall: the CLI --trace round-trip — save,
    wrong-scenario refusal, seed provenance, sha pin — stays tier-1 in
    test_cli_json_document_and_ledger_extraction.)"""
    r1 = run_scenario(_SMALL)
    path = tmp_path / "small.trace.jsonl"
    r1.trace.save(path)
    r2 = run_scenario(_SMALL, trace=Trace.load(path))
    for a, b in zip(r1.outputs, r2.outputs):
        np.testing.assert_array_equal(a, b)


def test_multi_tenant_isolation_under_priority_policy():
    """ISSUE 9 isolation pin: tenant A's burst cannot starve tenant B's
    higher-priority deadline — B's requests preempt into service and
    miss no (generous) deadline while A floods every slot."""
    events = []
    # six flood requests land first and pin both slots with long decodes
    for i in range(6):
        events.append(TraceEvent(
            request_id=i, arrival_ms=float(i), tenant="flood",
            prompt=list(range(4, 20)), max_new_tokens=24))
    # two vip requests arrive mid-flood with a deadline the policy must
    # protect by preempting flood work
    for j in range(2):
        events.append(TraceEvent(
            request_id=6 + j, arrival_ms=40.0 + j, tenant="vip",
            prompt=list(range(8 + j, 20 + j)), max_new_tokens=4,
            priority=5, deadline_ms=8000.0))
    spec = ScenarioSpec(
        name="isolation", seed=0, n_requests=len(events),
        tenants=(Tenant("flood"),
                 Tenant("vip", priority=5, deadline_ms=8000.0)),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                          prefix_cache=True, preempt_on_priority=True))
    trace = Trace(scenario="isolation", seed=0, events=events)
    outputs, stats, tracer, wall = replay(spec, trace)
    assert stats["preemptions"] >= 1          # vip displaced flood work
    assert stats["deadline_misses"] == 0
    vip = [tracer.lifecycle(6 + j) for j in range(2)]
    flood = [tracer.lifecycle(i) for i in range(6)]
    # vip TTFT beats the flood's tail: the burst did not starve it
    assert (max(lf["ttft_ms"] for lf in vip)
            < max(lf["ttft_ms"] for lf in flood))


def test_eviction_churn_scenario_lights_the_churn_instruments():
    """The adversarial tenant set actually thrashes the radix tree, and
    the PR's churn observability (evicted_reinserted counter + churn
    gauge) reports it."""
    metrics.clear()
    try:
        r = run_scenario(scenario_spec("eviction-churn", seed=0))
        assert r.report["aggregate"]["evicted_pages"] > 0
        assert r.report["aggregate"]["prefix_hit_rate"] > 0
        reinserted = churn = 0.0
        for inst in metrics.instruments():
            if inst.name == "prefix_cache.evicted_reinserted":
                reinserted = max(reinserted, inst.value)
            if inst.name == "prefix_cache.churn":
                churn = max(churn, inst.value)
        assert reinserted > 0, "no evicted path was ever re-inserted"
        assert churn > 0, "churn gauge never left zero"
    finally:
        metrics.clear()


def test_windowed_llama_paged_identity_and_page_drops():
    """ISSUE 9 acceptance: windowed-Llama generate(paged=True) is
    token-identical to the ROLLING-cache lock-step at window < prompt
    length, while the engine drops dead pages (O(window) live pages) and
    returns every page to the pool."""
    import jax.numpy as jnp

    from apex_tpu.models.generation import generate
    from apex_tpu.models.llama import LlamaModel
    from apex_tpu.serving.scenarios.runner import build_model

    cfg, model, v = build_model("llama-tiny-windowed")
    W = cfg.sliding_window
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, W + 9)),
                         jnp.int32)                 # window < prompt
    rmodel = LlamaModel(dataclasses.replace(cfg, rolling_cache=True))
    from apex_tpu.serving import generate_paged

    ref = np.asarray(generate(rmodel, v, prompt, max_new_tokens=30))
    out, stats = generate_paged(model, v, prompt, max_new_tokens=30,
                                page_size=8, sync_every=2,
                                return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert stats["window_dropped_pages"] > 0


def test_windowed_scenario_runs_and_recovers_the_pool():
    r = run_scenario(scenario_spec("windowed-llama", seed=1,
                                   n_requests=4))
    assert r.report["aggregate"]["window_dropped_pages"] > 0
    assert r.report["model"] == "llama-tiny-windowed"


# --- ISSUE 11: chaos / router scenarios + the preemption-storm adversary -----


@pytest.mark.slow
def test_chaos_replica_kill_scenario_recovers_token_exact():
    """ISSUE 11 acceptance: the catalogued mid-decode replica kill
    completes every request — the greedy-identity amplifier proves the
    failover corrupted nothing — with the failure facts in the pinned
    router block and both rates banked for the ledger. (Slow tier since
    ISSUE 15 to hold the 870 s verify wall: the kill bar stays tier-1
    twice over — tests/test_router.py::
    test_replica_kill_mid_decode_recovers_token_identical in-process and
    tests/test_http.py::
    test_router_over_http_replicas_kill_recovers_token_identical over
    the wire — and CI's chaos smoke replays this full entry with
    --check per round.)"""
    from apex_tpu.serving.scenarios.runner import _check_greedy_identity

    spec = scenario_spec("chaos-replica-kill", seed=0, n_requests=8)
    r = run_scenario(spec)
    rb = r.report["router"]
    assert rb["replicas"] == 2 and rb["replicas_alive"] == 1
    assert rb["replica_deaths"] == 1
    assert rb["failover_requests"] >= 1
    assert rb["failover_recovered_rate"] == 1.0
    # the greedy-identity amplifier, directly: every replayed output
    # (failed-over ones included) must equal lock-step generate. (The
    # scheduling-invariance half of --check runs in CI's chaos smoke
    # and the slow-tier A/B test — it re-replays the whole trace on a
    # fresh engine, which tier-1's budget doesn't need twice.)
    assert _check_greedy_identity(spec, r.trace, r.outputs) == 8
    validate_report(r.report)


@pytest.mark.slow
def test_chaos_pump_stall_scenario_is_latency_only():
    """(slow tier: the latency-not-death contract is already pinned in
    tier-1 by tests/test_router.py::test_pump_stall_is_latency_not_death;
    this adds the catalogued-scenario + amplifier form.)"""
    r = run_scenario(scenario_spec("chaos-pump-stall", seed=0),
                     check=True)
    rb = r.report["router"]
    assert rb["replica_deaths"] == 0 and rb["failovers"] == 0
    assert rb["replicas_alive"] == 2
    assert r.report["checks"]["greedy_identity_requests"] == 10


@pytest.mark.slow
def test_router_affinity_ab_beats_round_robin():
    """ISSUE 11 acceptance: the multi-tenant workload's aggregate
    prefix hit-rate under affinity routing strictly beats round-robin
    on the same trace (both numbers + the delta land in the report for
    the ledger to bank). (Slow tier: the deterministic tier-1 twin is
    tests/test_router.py::
    test_affinity_hit_rate_beats_round_robin_deterministic; CI's chaos
    smoke replays this full entry per round and the ledger gates it.)"""
    r = run_scenario(scenario_spec("router-affinity-ab", seed=0))
    rb = r.report["router"]
    assert rb["routing"] == "affinity"
    assert rb["affinity_hit_rate"] > rb["round_robin_hit_rate"]
    assert rb["affinity_delta_hit_rate"] == pytest.approx(
        rb["affinity_hit_rate"] - rb["round_robin_hit_rate"], abs=1e-3)


def test_tenant_output_tokens_override():
    """A tenant with a pinned output budget overrides the sampled
    output length (the preemption-storm's urgent-vs-bulk shape)."""
    spec = ScenarioSpec(
        name="pin", seed=0, n_requests=12,
        output_lens=Lengths(kind="uniform", lo=20, hi=30),
        tenants=(Tenant("short", output_tokens=2),))
    trace = materialize(spec)
    assert all(e.max_new_tokens == 2 for e in trace.events)


@pytest.mark.slow
def test_preemption_storm_scenario_no_compile_storm():
    """The catalogued storm replays clean: whatever preempt/resume
    cycles the pacing produced, the resume compile-key set stayed
    bounded — no compile_storm event, a bounded jit.compiles delta
    (the deterministic cycle-count pin is the frontend-driven test
    below)."""
    r = run_scenario(scenario_spec("preemption-storm", seed=0))
    eng = r.report["engine"]
    assert eng["compile_storms"] == 0
    assert eng["jit.compiles"] <= 24
    assert eng["deadline_misses"] == 0
    validate_report(r.report)


def test_preemption_storm_deterministic_cycles_bounded_compiles(rng):
    """ISSUE 11 satellite (ROADMAP 5's named gap), deterministically: a
    bulk long-runner on ONE slot is preempted by six consecutive urgent
    arrivals — six full preempt/spill/resume cycles — and the recompile
    watcher pins the resume compile-key set: zero compile_storm events
    and a bounded jit.compiles delta (page-quantized resume t_starts
    reuse their shared-admit programs instead of growing one compile
    per cycle), with the bulk output still token-identical to an
    undisturbed run."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.generation import generate
    from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
    from apex_tpu.serving import (PagedDecodeEngine,
                                  PriorityDeadlinePolicy, Request)
    from apex_tpu.serving.frontend import ServingFrontend

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=16,
                               prefix_cache=True)
    fe = ServingFrontend(engine, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    bulk_prompt = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
    h_bulk = fe.submit(Request(prompt=bulk_prompt, max_new_tokens=36),
                       request_id=0)
    while fe.queue_depth:
        fe.pump()
    n_cycles = 6
    for k in range(n_cycles):
        fe.pump()                        # let the victim make progress
        h = fe.submit(Request(
            prompt=rng.integers(0, cfg.vocab_size, (10,)
                                ).astype(np.int32),
            max_new_tokens=2, priority=5), request_id=1 + k)
        while not h.done:                # urgent runs to completion
            fe.pump()
    fe.drain()
    stats = fe.stats()
    assert stats["preemptions"] >= n_cycles - 1
    assert stats["resumes"] >= n_cycles - 1
    # the recompile-watcher pin: no program recompiled storm-many
    # times, and the whole storm cost a bounded number of compiles
    assert stats["compile_storms"] == 0
    ring = engine.events.tail()
    assert not any(e["kind"] == "compile_storm" for e in ring)
    assert stats["jit.compiles"] <= 20, stats["jit.compiles"]
    ref = np.asarray(generate(model, v, bulk_prompt[None],
                              max_new_tokens=36))[0, 12:]
    np.testing.assert_array_equal(h_bulk.result(timeout=0), ref)


def test_chaos_specs_roundtrip_with_faults():
    """A chaos spec's fault plan survives the JSON round-trip (the
    replayability contract: same spec file, same kills)."""
    spec = scenario_spec("chaos-replica-kill", seed=3)
    back = ScenarioSpec.from_json(spec.to_json())
    assert back == spec
    assert back.faults[0].kind == "kill_replica"
    assert back.engine.replicas == 2
    # the HTTP tier's knobs round-trip too (and stay JSON-back-compat:
    # specs that predate them load with the defaults)
    spec = scenario_spec("chaos-slow-reader", seed=3)
    back = ScenarioSpec.from_json(spec.to_json())
    assert back == spec
    assert back.engine.http and back.engine.backpressure_window == 6
    assert back.engine.sse_pad_bytes == 2048
    assert back.engine.sndbuf == 4096
    assert back.faults[0].kind == "slow_reader"
    doc = json.loads(_SMALL.to_json())
    assert "http" not in json.dumps(doc) or not doc["engine"]["http"]
    assert ScenarioSpec.from_json(_SMALL.to_json()).engine.http is False


# --- ISSUE 15: the over-the-wire (HTTP/SSE) chaos tier -----------------------


def test_chaos_slow_reader_scenario_spills_over_the_wire():
    """ISSUE 15 acceptance: the catalogued slow-reader chaos replays
    over a REAL localhost socket — stalled readers cross the
    backpressure window, slots spill (never pinning pages for a
    socket), and every stream still completes token-identically on
    resume; the facts land in the report's pinned ``http`` block. (The
    tier-1 single-request twin of the spill mechanics is
    tests/test_http.py::test_backpressure_spill_resume_token_identical;
    CI's HTTP smoke replays this entry per round and banks it.)"""
    r = run_scenario(scenario_spec("chaos-slow-reader", seed=0),
                     check=True)
    hb = r.report["http"]
    assert hb["streams"] == 4 and hb["errors"] == 0
    assert hb["slow_reader_stalls"] == 2
    assert hb["backpressure_spills"] >= 1        # the no-pin proof
    assert hb["disconnects"] == 0
    assert hb["free_pages_recovered"] > 0        # pool settled clean
    assert r.report["checks"]["greedy_identity_requests"] == 4
    assert r.report["checks"]["scheduling_invariance"] is True
    validate_report(r.report)


@pytest.mark.slow
def test_chaos_disconnect_storm_prefixes_and_no_leak():
    """ISSUE 15 acceptance: mid-stream socket drops + torn submits —
    the server cancels and frees every page (the driver's in-band leak
    check), survivors complete token-identically, and each dropped
    stream's banked output is the exact prefix it read (the
    prefix-tolerant identity amplifier). (Slow tier: the tier-1
    disconnect-frees-pages twin is tests/test_http.py::
    test_disconnect_cancels_and_frees_pages; CI's HTTP smoke replays
    this full entry per round.)"""
    r = run_scenario(scenario_spec("chaos-disconnect-storm", seed=0),
                     check=True)
    hb = r.report["http"]
    assert hb["streams"] == 10 and hb["errors"] == 0
    assert hb["disconnects"] == 4
    assert hb["conn_reset_retries"] == 2
    # 4 dropped streams read exactly at=3 tokens; 6 survivors run their
    # pinned 24 out
    assert sorted(len(np.asarray(o)) for o in r.outputs) \
        == [3] * 4 + [24] * 6
    assert r.report["checks"]["greedy_identity_requests"] == 10
    validate_report(r.report)


def test_ledger_extracts_router_fields(tmp_path):
    """CHAOS_<tag>.json (a scenarios/v1 document of router scenarios)
    yields the band-gated scenario.<name>.failover_recovered_rate and
    hit-rate A/B metrics."""
    import json as json_mod

    from apex_tpu.obs.ledger import bench_metrics_from_file

    doc = {"schema": "apex-tpu/scenarios/v1", "seed": 0,
           "scenarios": {"chaos-replica-kill": {
               "aggregate": {"ttft_ms_p95": 12.5, "tpot_ms_p95": 3.0,
                             "deadline_miss_rate": 0.0},
               "router": {"failover_recovered_rate": 1.0,
                          "affinity_hit_rate": 0.6,
                          "round_robin_hit_rate": 0.45,
                          "affinity_delta_hit_rate": 0.15},
               "http": {"backpressure_spills": 2, "disconnects": 4,
                        "conn_reset_retries": 2,
                        "slow_reader_stalls": 2, "errors": 0}}}}
    path = tmp_path / "CHAOS_test.json"
    path.write_text(json_mod.dumps(doc))
    m, meta = bench_metrics_from_file(path)
    assert m["scenario.chaos-replica-kill.failover_recovered_rate"] \
        == 1.0
    assert m["scenario.chaos-replica-kill.affinity_hit_rate"] == 0.6
    assert m["scenario.chaos-replica-kill.affinity_delta_hit_rate"] \
        == pytest.approx(0.15)
    # the HTTP chaos block lands as informational (never band-gated)
    # counters — the banked spill/disconnect proof per round
    assert m["scenario.chaos-replica-kill.http_backpressure_spills"] \
        == 2.0
    assert m["scenario.chaos-replica-kill.http_disconnects"] == 4.0
    # direction classes: recovered/hit rates gate on the absolute rate
    # band as higher-better
    from apex_tpu.obs.ledger import check as ledger_check
    entries = [{"metrics": m, "tag": "base", "git_rev": "x"}]
    worse = dict(m)
    worse["scenario.chaos-replica-kill.failover_recovered_rate"] = 0.5
    regs = ledger_check(worse, entries)
    assert any("failover_recovered_rate" in r.metric for r in regs)
    assert not ledger_check(dict(m), entries)


def test_host_tier_churn_scenario_beats_tier_off():
    """ISSUE 17 acceptance: at the eviction-churn pool size the host
    spill tier turns churned re-prefills into promotes — the report's
    host_tier block banks a STRICTLY positive tier-on-vs-off hit-rate
    delta on the same trace, with the identity amplifiers green (the
    tier changed nothing about WHAT was generated, only how its K/V
    came back)."""
    r = run_scenario(scenario_spec("host-tier-churn", seed=0),
                     check=True)
    ht = r.report["host_tier"]
    assert ht["demotes"] > 0 and ht["promotes"] > 0
    assert ht["tier_on_hit_rate"] > ht["tier_off_hit_rate"]
    assert ht["tier_delta_hit_rate"] == pytest.approx(
        ht["tier_on_hit_rate"] - ht["tier_off_hit_rate"], abs=1e-3)
    assert ht["promote_hit_rate"] > 0
    assert r.report["checks"]["scheduling_invariance"] is True


def test_ledger_extracts_host_tier_fields(tmp_path):
    """A scenarios/v1 document with a host_tier block yields the
    band-gated scenario.<name>.tier_*_hit_rate / promote_hit_rate
    metrics (all end in hit_rate: absolute rate band, higher-better)."""
    import json as json_mod

    from apex_tpu.obs.ledger import bench_metrics_from_file

    doc = {"schema": "apex-tpu/scenarios/v1", "seed": 0,
           "scenarios": {"host-tier-churn": {
               "aggregate": {"ttft_ms_p95": 9.0},
               "host_tier": {"tier_on_hit_rate": 0.75,
                             "tier_off_hit_rate": 0.625,
                             "tier_delta_hit_rate": 0.125,
                             "promote_hit_rate": 0.33,
                             "demotes": 42, "promotes": 16}}}}
    path = tmp_path / "SCENARIOS_test.json"
    path.write_text(json_mod.dumps(doc))
    m, _ = bench_metrics_from_file(path)
    assert m["scenario.host-tier-churn.tier_on_hit_rate"] == 0.75
    assert m["scenario.host-tier-churn.tier_off_hit_rate"] == 0.625
    assert m["scenario.host-tier-churn.tier_delta_hit_rate"] \
        == pytest.approx(0.125)
    assert m["scenario.host-tier-churn.promote_hit_rate"] \
        == pytest.approx(0.33)

    # a tier-delta collapse gates as a regression (higher-better rate)
    from apex_tpu.obs.ledger import check as ledger_check
    entries = [{"metrics": m, "tag": "base", "git_rev": "x"}]
    worse = dict(m)
    worse["scenario.host-tier-churn.tier_on_hit_rate"] = 0.3
    regs = ledger_check(worse, entries)
    assert any("tier_on_hit_rate" in r.metric for r in regs)
    assert not ledger_check(dict(m), entries)


# --- CLI + ledger integration ------------------------------------------------


def test_cli_json_document_and_ledger_extraction(tmp_path):
    """python -m apex_tpu.serving.scenarios writes the scenarios/v1
    document whose per-scenario SLO fields the perf ledger extracts as
    scenario.<name>.* (the band-gated wall-time metrics)."""
    from apex_tpu.obs.ledger import bench_metrics_from_file
    from apex_tpu.serving.scenarios.__main__ import main

    out = tmp_path / "scen.json"
    rc = main(["--scenario", "bench-mixed-length", "--seed", "4",
               "--json", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["schema"] == "apex-tpu/scenarios/v1"
    rep = doc["scenarios"]["bench-mixed-length"]
    validate_report(rep)
    m, meta = bench_metrics_from_file(out)
    assert meta["schema"] == "apex-tpu/scenarios/v1"
    assert m["scenario.bench-mixed-length.ttft_ms_p95"] > 0
    assert m["scenario.bench-mixed-length.tpot_ms_p95"] > 0
    assert "scenario.bench-mixed-length.deadline_miss_rate" in m
    # unknown scenario is a usage error caught BEFORE any replay runs
    # (a typo in the last --scenario must not cost the first ones'
    # replay time), --list succeeds
    assert main(["--scenario", "nope"]) == 2
    assert main(["--scenario", "bench-mixed-length",
                 "--scenario", "nope"]) == 2
    assert main(["--list"]) == 0
    # --trace refuses a trace materialized for a DIFFERENT scenario
    # (its events carry the other spec's model bounds, and its report
    # would bank under the wrong ledger baselines)
    tr = tmp_path / "mixed.trace.jsonl"
    materialize(scenario_spec("bench-mixed-length", seed=4)).save(tr)
    assert main(["--scenario", "steady-poisson",
                 "--trace", str(tr)]) == 2
    # a --trace replay records the TRACE's seed (the one that
    # regenerates its sha), not the CLI --seed default
    out2 = tmp_path / "replayed.json"
    assert main(["--scenario", "bench-mixed-length",
                 "--trace", str(tr), "--json", str(out2)]) == 0
    doc2 = json.loads(out2.read_text())
    assert doc2["seed"] == 4
    assert (doc2["scenarios"]["bench-mixed-length"]["trace_sha256"]
            == doc["scenarios"]["bench-mixed-length"]["trace_sha256"])


@pytest.mark.slow
def test_cli_http_flag_drives_the_wire(tmp_path):
    """--http forces EngineSpec(http=True) on any catalog entry: the
    replay goes over real localhost SSE and the banked document grows
    the pinned http block — the flag CI's HTTP smoke
    (run_tpu_round.sh, HTTP_<tag>.json) is built on."""
    from apex_tpu.serving.scenarios.__main__ import main

    out = tmp_path / "http.json"
    rc = main(["--scenario", "bench-shared-prefix", "--http", "--check",
               "--seed", "0", "--json", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    rep = doc["scenarios"]["bench-shared-prefix"]
    validate_report(rep)
    hb = rep["http"]
    assert hb["streams"] == 8 and hb["errors"] == 0
    assert hb["free_pages_recovered"] > 0
    assert rep["checks"]["greedy_identity_requests"] == 8
