"""Replica router + fault injection (apex_tpu/serving/router.py,
faults.py) — ISSUE 11.

Fault tier (no model): FaultSpec validation, FaultPlan JSON round-trip
and seeded determinism, RouterPolicy validation, backoff schedule.

Router tier (tiny GPT): the acceptance bars — a replica killed
mid-decode loses no request (survivor outputs greedy token-identical to
an unfailed run, streamed tokens never re-delivered, survivor pool
page-clean); a request with no survivor (or retries exhausted) raises a
terminal ServingError instead of hanging; affinity routing keys
same-tenant traffic to one replica; overload sheds with retry-after;
admission rejects retry elsewhere; graceful drain migrates actives with
tokens preserved; and the whole stack runs threaded (background pumps +
supervisor) through a kill."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving import (FaultPlan, FaultSpec, OverloadError,
                              PagedDecodeEngine, ReplicaRouter, Request,
                              RouterPolicy, ServingError, ServingFrontend,
                              free_page_count)
from apex_tpu.serving.faults import FaultInjector, InjectedFault


@pytest.fixture(scope="module")
def tiny():
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, v


def _refs(model, v, reqs):
    return [np.asarray(generate(model, v, np.asarray(r.prompt)[None],
                                max_new_tokens=r.max_new_tokens)
                       )[0, np.asarray(r.prompt).shape[0]:]
            for r in reqs]


def _router(tiny, n_replicas, *, plan=None, policy=None, num_slots=2,
            prefix_cache=True, **engine_kw):
    cfg, model, v = tiny
    plan = plan if plan is not None else FaultPlan()
    fes = []
    for i in range(n_replicas):
        engine = PagedDecodeEngine(model, v, num_slots=num_slots,
                                   page_size=8,
                                   prefix_cache=prefix_cache,
                                   **engine_kw)
        fes.append(ServingFrontend(engine,
                                   fault_hook=plan.injector(i)))
    return ReplicaRouter(fes, policy=policy if policy is not None
                         else RouterPolicy(backoff_base_ms=1.0))


def _reqs(cfg, rng, n, s0=12, max_new=8):
    return [Request(prompt=rng.integers(0, cfg.vocab_size, (s0,)
                                        ).astype(np.int32),
                    max_new_tokens=max_new) for _ in range(n)]


def _assert_pool_clean(engine):
    usable = engine.cache["free_stack"].shape[0] - 1
    cached = len(engine.prefix) if engine.prefix is not None else 0
    assert int(free_page_count(engine.cache)) == usable - cached
    # cached pages are resident but refcount-0 (no dangling readers)
    assert int(np.asarray(engine.cache["page_ref"]).sum()) == 0


# --------------------------------------------------------------------------
# faults (no model)
# --------------------------------------------------------------------------

def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor")
    with pytest.raises(ValueError, match="count"):
        FaultSpec(kind="kill_replica", count=0)
    with pytest.raises(ValueError, match="delay_ms"):
        FaultSpec(kind="pump_stall", delay_ms=0.0)
    with pytest.raises(ValueError):
        FaultSpec(kind="kill_replica", replica=-1)


def test_fault_plan_roundtrip_and_seeded():
    plan = FaultPlan(specs=(
        FaultSpec(kind="kill_replica", replica=1, at=3),
        FaultSpec(kind="pump_stall", replica=0, at=2, count=2,
                  delay_ms=5.0)))
    back = FaultPlan.from_json(plan.to_json())
    assert back == plan
    assert plan.for_replica(1) == (plan.specs[0],)
    assert plan.injector(2) is None      # nothing planned for replica 2
    # seeded sampling is deterministic
    a = FaultPlan.random(7, 3, n_faults=2,
                         kinds=("kill_replica", "pump_stall"))
    b = FaultPlan.random(7, 3, n_faults=2,
                         kinds=("kill_replica", "pump_stall"))
    assert a == b and a.to_json() == b.to_json()
    assert FaultPlan.random(8, 3, n_faults=2,
                            kinds=("kill_replica", "pump_stall")) != a


def test_injector_kill_and_reject_counters():
    inj = FaultInjector([FaultSpec(kind="kill_replica", at=2)])
    inj.on_pump(None)
    inj.on_pump(None)
    with pytest.raises(InjectedFault):
        inj.on_pump(None)
    inj2 = FaultInjector([FaultSpec(kind="admission_reject", at=1,
                                    count=2)])
    inj2.on_submit(None, None)           # submission 0 passes
    with pytest.raises(ServingError):
        inj2.on_submit(None, None)       # 1 rejected
    with pytest.raises(ServingError):
        inj2.on_submit(None, None)       # 2 rejected
    inj2.on_submit(None, None)           # count exhausted
    assert inj2.fired == ["admission_reject", "admission_reject"]


class _StubEngine:
    eos_token_id = None

    @staticmethod
    def _validate_request(r):
        return None


class _StubFrontend:
    """Just enough frontend surface to construct a router without
    compiling an engine (policy-tier tests)."""

    engine = _StubEngine()
    fault_hook = None
    failure = None
    queue_depth = 0

    def submit(self, request, *, request_id=None):
        raise ServingError("stub refuses everything")


def test_router_policy_validation_and_backoff():
    with pytest.raises(ValueError, match="routing"):
        RouterPolicy(routing="random")
    with pytest.raises(ValueError):
        RouterPolicy(affinity_tokens=0)
    with pytest.raises(ValueError):
        ReplicaRouter([])
    router = ReplicaRouter([_StubFrontend()], policy=RouterPolicy(
        backoff_base_ms=10.0, backoff_cap_ms=35.0))
    assert router._backoff_s(1) == pytest.approx(0.010)
    assert router._backoff_s(2) == pytest.approx(0.020)
    assert router._backoff_s(3) == pytest.approx(0.035)   # capped
    assert router._backoff_s(9) == pytest.approx(0.035)


def test_supervision_crash_fails_handles_not_hangs(monkeypatch):
    """A bug escaping the supervision tick is TERMINAL, not a silent
    supervisor death: every outstanding handle fails with ServingError
    (the no-hung-handles guarantee survives bugs in the tick itself)."""
    router = ReplicaRouter([_StubFrontend()], policy=RouterPolicy(
        retry_limit=5, backoff_base_ms=1000.0))
    h = router.submit(Request(prompt=np.zeros((4,), np.int32),
                              max_new_tokens=2), request_id=0)
    assert not h.done                    # queued behind the backoff
    monkeypatch.setattr(router, "_tick_impl",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("tick bug")))
    with pytest.raises(RuntimeError, match="tick bug"):
        router._tick()
    assert h.done
    with pytest.raises(ServingError, match="supervision failed"):
        h.result(timeout=0)
    assert any(e["kind"] == "supervisor_failed"
               for e in router.events.tail())


# --------------------------------------------------------------------------
# routing
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_round_robin_spreads_and_completes(tiny, rng):
    """(token identity through the router is pinned by the kill test's
    lock-step refs; this pins the spread + hygiene cheaply)"""
    cfg, model, v = tiny
    router = _router(tiny, 2, policy=RouterPolicy(routing="round_robin"))
    reqs = _reqs(cfg, rng, 6, max_new=4)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h in handles:
        assert h.result(timeout=0).shape[0] == 4
    stats = router.stats()
    routed = [p["routed"] for p in stats["per_replica"]]
    assert routed == [3, 3]              # strict alternation
    assert stats["completed"] == 6 and stats["failed"] == 0
    assert stats["failovers"] == 0
    for rep in router.replicas:
        _assert_pool_clean(rep.frontend.engine)


@pytest.mark.slow
def test_affinity_keys_stick_and_rebalance_minimally(tiny, rng):
    """Same affinity key -> same replica; distinct keys spread; and the
    placement is a pure function of (key, live set) — the rendezvous
    property failure rebalancing relies on."""
    cfg, model, v = tiny
    router = _router(tiny, 3)
    reqs = _reqs(cfg, rng, 9, max_new=3)
    # keys chosen to rendezvous onto distinct replicas of 3 (alpha->1,
    # beta->0, gamma->2 — deterministic, hashlib not hash())
    keys = ["alpha", "beta", "gamma"] * 3
    handles = [router.submit(r, request_id=i, affinity_key=keys[i])
               for i, r in enumerate(reqs)]
    router.drain()
    for h in handles:
        assert h.result(timeout=0).shape[0] == 3
    routes = {e["request"]: e["replica"]
              for e in router.events.tail() if e["kind"] == "route"}
    by_key = {}
    for i, key in enumerate(keys):
        by_key.setdefault(key, set()).add(routes[i])
    for key, replicas in by_key.items():
        assert len(replicas) == 1, (key, replicas)   # sticky
    assert len({next(iter(s)) for s in by_key.values()}) >= 2  # spread


def test_affinity_hit_rate_beats_round_robin_deterministic(tiny, rng):
    """ISSUE 11 acceptance (tier-1 form): two tenants with shared
    2-page headers over 2 replicas, requests submitted-and-drained
    sequentially so the admission order is exact. Affinity keeps each
    tenant on one replica (one cold miss per tenant → 6/8 hits);
    round-robin smears both headers over both caches (one cold miss
    per tenant PER replica → 4/8). Strictly better, deterministically.
    The full-size trace-driven A/B (`router-affinity-ab`) runs in the
    slow tier and in the CI chaos smoke, which banks both rates."""
    from apex_tpu.serving.router import _rendezvous

    cfg, model, v = tiny
    names = ["alpha", "beta", "gamma", "delta"]
    # two keys that rendezvous onto DIFFERENT replicas of 2
    first = names[0]
    second = next(k for k in names[1:]
                  if (_rendezvous(k, 0) > _rendezvous(k, 1))
                  != (_rendezvous(first, 0) > _rendezvous(first, 1)))
    headers = {first: rng.integers(0, cfg.vocab_size, (16,)
                                   ).astype(np.int32),
               second: rng.integers(0, cfg.vocab_size, (16,)
                                    ).astype(np.int32)}

    def run(routing):
        router = _router(tiny, 2,
                         policy=RouterPolicy(routing=routing))
        for i in range(8):
            # AABB pattern: a strictly alternating order would ALIGN
            # round-robin's replica cycle with the tenant cycle and
            # hand it affinity's hit rate by accident
            tenant = (first, second)[(i // 2) % 2]
            tail = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
            prompt = np.concatenate([headers[tenant], tail])
            router.submit(Request(prompt=prompt, max_new_tokens=2),
                          request_id=i, affinity_key=tenant)
            router.drain()               # sequential: order is exact
        return router.stats()["prefix_hit_rate"]

    affinity, rr = run("affinity"), run("round_robin")
    assert affinity == pytest.approx(6 / 8)
    assert rr == pytest.approx(4 / 8)
    assert affinity > rr                 # strictly better


def test_overload_sheds_with_retry_after(tiny, rng):
    cfg, model, v = tiny
    router = _router(tiny, 2, policy=RouterPolicy(
        routing="round_robin", shed_queue_depth=1))
    reqs = _reqs(cfg, rng, 8, max_new=4)
    handles, shed = [], 0
    for i, r in enumerate(reqs):
        try:
            handles.append(router.submit(r, request_id=i))
        except OverloadError as e:
            shed += 1
            assert e.retry_after_s > 0
    assert shed >= 1                     # the flood hit the bound
    router.drain()
    for h in handles:                    # accepted work still completes
        assert h.result(timeout=0).shape[0] == 4
    stats = router.stats()
    assert stats["shed_requests"] == shed
    ring = router.events.tail()
    assert any(e["kind"] == "shed" for e in ring)


@pytest.mark.slow
def test_admission_reject_fault_retries_elsewhere(tiny, rng):
    """A replica refusing submissions is routed around — every request
    still completes, and the rejections are counted."""
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="admission_reject", replica=0, at=0, count=3),))
    router = _router(tiny, 2, plan=plan)
    reqs = _reqs(cfg, rng, 4, max_new=4)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h, ref in zip(handles, _refs(model, v, reqs)):
        np.testing.assert_array_equal(h.result(timeout=0), ref)
    stats = router.stats()
    assert stats["rejected_submits"] >= 1
    assert stats["completed"] == 4 and stats["failed"] == 0


@pytest.mark.slow
def test_duplicate_request_id_rejected(tiny, rng):
    cfg, model, v = tiny
    router = _router(tiny, 1)
    r = _reqs(cfg, rng, 1, max_new=2)[0]
    router.submit(r, request_id="dup")
    with pytest.raises(ValueError, match="duplicate"):
        router.submit(r, request_id="dup")
    router.drain()


# --------------------------------------------------------------------------
# failure recovery — THE acceptance bar
# --------------------------------------------------------------------------

def test_replica_kill_mid_decode_recovers_token_identical(tiny, rng):
    """ISSUE 11 acceptance: a replica killed mid-decode completes every
    request — migrated requests greedy token-identical to an unfailed
    run, streamed tokens delivered exactly once in order, zero hung
    handles, zero leaked pages on the survivor."""
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="kill_replica", replica=0, at=4),))
    router = _router(tiny, 2, plan=plan)
    reqs = _reqs(cfg, rng, 8, s0=16, max_new=10)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    streamed = {i: [] for i in range(len(reqs))}
    # interleave streaming consumption with the pump (mid-stream reads
    # must survive the failover without duplication or loss)
    while router.pump():
        for i, h in enumerate(handles):
            streamed[i].extend(h.tokens_so_far()[len(streamed[i]):])
    stats = router.stats()
    assert stats["replica_deaths"] == 1
    assert stats["failover_requests"] >= 1
    assert stats["failover_recovered_rate"] == 1.0
    assert stats["failed"] == 0 and stats["completed"] == len(reqs)
    for i, (h, ref) in enumerate(zip(handles,
                                     _refs(model, v, reqs))):
        out = h.result(timeout=0)
        np.testing.assert_array_equal(out, ref)
        streamed[i].extend(h.tokens_so_far()[len(streamed[i]):])
        assert streamed[i] == list(out)  # once, in order, nothing lost
    assert any(h.failovers >= 1 for h in handles)
    ring = router.events.tail()
    assert any(e["kind"] == "replica_dead" for e in ring)
    assert any(e["kind"] == "failover" for e in ring)
    # the survivor's pool is clean after the drain
    survivor = next(rep for rep in router.replicas if rep.alive)
    _assert_pool_clean(survivor.frontend.engine)
    # cross-replica lifecycle/stats adapters (the report surface)
    life = router.lifecycle(0)
    assert life["ttft_ms"] >= 0.0 and life["new_tokens"] == 10
    assert router.lifecycle("nope") == {"request_id": "nope"}
    assert isinstance(router.spans(0), list)
    assert len(stats["per_replica"]) == 2
    with pytest.raises(ValueError, match="duplicate"):
        router.submit(reqs[0], request_id=0)
    # the router keeps serving on the survivor
    late = _reqs(cfg, rng, 1, max_new=3)[0]
    h = router.submit(late, request_id=99)
    router.drain()
    np.testing.assert_array_equal(h.result(timeout=0),
                                  _refs(model, v, [late])[0])


def test_no_survivor_fails_terminally_never_hangs(tiny, rng):
    """Killing the ONLY replica turns every in-flight request into a
    terminal ServingError within a bounded drain — no handle hangs, the
    drain loop terminates."""
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="kill_replica", replica=0, at=2),))
    router = _router(tiny, 1, plan=plan)
    reqs = _reqs(cfg, rng, 3, max_new=12)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()                       # must terminate
    for h in handles:
        assert h.done
        with pytest.raises(ServingError):
            h.result(timeout=0)
    stats = router.stats()
    assert stats["failed"] == 3
    assert stats["replicas_alive"] == 0
    with pytest.raises(ServingError, match="no live replicas"):
        router.submit(_reqs(cfg, rng, 1)[0], request_id=50)


@pytest.mark.slow
def test_both_replicas_killed_retries_bounded(tiny, rng):
    """With every replica killed the retry loop is BOUNDED: handles
    fail after at most retry_limit failovers instead of spinning."""
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="kill_replica", replica=0, at=2),
        FaultSpec(kind="kill_replica", replica=1, at=3)))
    router = _router(tiny, 2, plan=plan,
                     policy=RouterPolicy(retry_limit=2,
                                         backoff_base_ms=1.0))
    reqs = _reqs(cfg, rng, 4, max_new=12)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h in handles:
        assert h.done
        with pytest.raises(ServingError):
            h.result(timeout=0)
    assert router.stats()["failover_recovered_rate"] == 0.0


@pytest.mark.slow
def test_pump_stall_is_latency_not_death(tiny, rng):
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="pump_stall", replica=0, at=1, count=3,
                  delay_ms=10.0),))
    router = _router(tiny, 2, plan=plan)
    reqs = _reqs(cfg, rng, 6, max_new=4)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h, ref in zip(handles, _refs(model, v, reqs)):
        np.testing.assert_array_equal(h.result(timeout=0), ref)
    stats = router.stats()
    assert stats["replica_deaths"] == 0 and stats["failovers"] == 0


@pytest.mark.slow
def test_slow_consumer_fault_stays_ordered(tiny, rng):
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="slow_consumer", replica=0, delay_ms=2.0),))
    router = _router(tiny, 2, plan=plan)
    reqs = _reqs(cfg, rng, 4, max_new=4)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h, ref in zip(handles, _refs(model, v, reqs)):
        np.testing.assert_array_equal(h.result(timeout=0), ref)


@pytest.mark.slow
def test_router_handle_cancel_truncates(tiny, rng):
    cfg, model, v = tiny
    router = _router(tiny, 1)
    req = _reqs(cfg, rng, 1, max_new=20)[0]
    h = router.submit(req, request_id=0)
    for _ in range(4):
        router.pump()
    h.cancel()
    router.drain()
    out = h.result(timeout=0)
    assert 0 <= out.shape[0] < 20
    ref = _refs(model, v, [req])[0]
    np.testing.assert_array_equal(out, ref[:out.shape[0]])


# --------------------------------------------------------------------------
# graceful drain
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_drain_replica_migrates_actives(tiny, rng):
    """drain_replica(migrate=True) takes the replica out of rotation and
    MIGRATES its actives: cancel-at-boundary, resume on a survivor,
    outputs token-identical, pools clean on both sides."""
    cfg, model, v = tiny
    router = _router(tiny, 2)
    reqs = _reqs(cfg, rng, 3, s0=12, max_new=10)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    for _ in range(4):                   # give everything some progress
        router.pump()
    victim = next(e["replica"] for e in router.events.tail()
                  if e["kind"] == "route")
    router.drain_replica(victim, migrate=True)
    router.drain()
    for h, ref in zip(handles, _refs(model, v, reqs)):
        np.testing.assert_array_equal(h.result(timeout=0), ref)
    stats = router.stats()
    assert stats["migrations"] >= 1
    drained = router.replicas[victim]
    assert not drained.alive
    _assert_pool_clean(drained.frontend.engine)
    ring = router.events.tail()
    assert any(e["kind"] == "replica_drained" for e in ring)


@pytest.mark.slow
def test_router_shutdown_resolves_everything(tiny, rng):
    """(slow tier: the frontend-level shutdown contract — the satellite
    — is pinned in tier-1 by tests/test_frontend.py; this covers the
    router-wide composition.)"""
    cfg, model, v = tiny
    router = _router(tiny, 2)
    reqs = _reqs(cfg, rng, 4, max_new=6)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.shutdown(deadline_s=120.0, mode="drain")
    for h, ref in zip(handles, _refs(model, v, reqs)):
        np.testing.assert_array_equal(h.result(timeout=0), ref)
    with pytest.raises(ServingError, match="draining"):
        router.submit(reqs[0], request_id=77)
    for rep in router.replicas:
        assert not rep.frontend.pump_alive
        _assert_pool_clean(rep.frontend.engine)
    with pytest.raises(ValueError):
        router.shutdown(mode="explode")


# --------------------------------------------------------------------------
# threaded mode: background pumps + supervisor through a kill
# --------------------------------------------------------------------------

def test_threaded_supervisor_recovers_from_kill(tiny, rng):
    cfg, model, v = tiny
    plan = FaultPlan(specs=(
        FaultSpec(kind="kill_replica", replica=0, at=3),))
    router = _router(tiny, 2, plan=plan)
    router.start()
    try:
        reqs = _reqs(cfg, rng, 3, max_new=6)
        handles = [router.submit(r, request_id=i)
                   for i, r in enumerate(reqs)]
        for h, ref in zip(handles, _refs(model, v, reqs)):
            np.testing.assert_array_equal(h.result(timeout=300.0), ref)
    finally:
        router.stop()
    stats = router.stats()
    assert stats["replica_deaths"] == 1
    assert stats["failover_recovered_rate"] == 1.0
    assert stats["completed"] == 3
    # supervisor + pump threads all joined
    names = {t.name for t in threading.enumerate()}
    assert "serving-router-supervisor" not in names
    assert "serving-frontend-pump" not in names
    with pytest.raises(RuntimeError, match="supervisor"):
        router.start() or router.pump()  # pump refused while started
    router.stop()


# --------------------------------------------------------------------------
# lifecycle / stats adapters
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_lifecycle_and_stats_surface(tiny, rng):
    cfg, model, v = tiny
    router = _router(tiny, 2)
    reqs = _reqs(cfg, rng, 4, max_new=6)
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h in handles:
        h.result(timeout=0)
    life = router.lifecycle(0)
    assert life["ttft_ms"] >= 0.0
    assert life["new_tokens"] == 6
    assert life["tpot_ms"] >= 0.0
    assert "queue_wait_ms" in life       # from the serving replica
    assert router.lifecycle("nope") == {"request_id": "nope"}
    assert isinstance(router.spans(0), list)
    stats = router.stats()
    assert stats["requests"] == 4 and stats["routed"] >= 4
    assert stats["admitted"] >= 4 and stats["retired"] >= 4
    assert 0.0 <= stats["prefix_hit_rate"] <= 1.0
    assert len(stats["per_replica"]) == 2
    assert time.time() > 0               # keep the import honest


def test_burn_rate_alert_fires_on_violation_silent_on_steady(tiny, rng):
    """ISSUE 19 acceptance: a fleet whose every request misses an
    impossible TTFT deadline drives the federated ``slo_burn`` to 1 and
    the router's burn-rate alerter FIRES (``fleet.alert`` in the router
    ring, ``alerts_fired`` in the pinned fleet block); the same fleet
    under deadline-free traffic stays silent. The hysteresis band
    itself is pinned with injected clocks in tests/test_fleet.py —
    this is the real-serving twin."""
    cfg, model, v = tiny
    router = _router(tiny, 2)
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (12,)
                                        ).astype(np.int32),
                    max_new_tokens=4, deadline_ms=0.001)
            for _ in range(6)]
    handles = [router.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    router.drain()
    for h in handles:
        h.result(timeout=0)              # misses never drop requests
    router.fleet.tick(force=True)        # sample the final burn
    fleet = router.stats()["fleet"]
    assert fleet["slo_burn"] == pytest.approx(1.0)
    assert fleet["alerts_fired"] >= 1 and fleet["alert_firing"]
    fired = [e for e in router.events.tail()
             if e["kind"] == "fleet.alert"]
    assert fired and fired[0]["state"] == "firing"
    assert fired[0]["threshold"] == router.alerter.threshold

    # deadline-free traffic on a FRESH fleet: burn stays 0, no alert
    steady = _router(tiny, 2)
    reqs = _reqs(cfg, rng, 6, max_new=4)
    handles = [steady.submit(r, request_id=i)
               for i, r in enumerate(reqs)]
    steady.drain()
    for h in handles:
        h.result(timeout=0)
    steady.fleet.tick(force=True)
    fleet = steady.stats()["fleet"]
    assert fleet["slo_burn"] == 0.0
    assert fleet["alerts_fired"] == 0 and not fleet["alert_firing"]
    assert not any(e["kind"] == "fleet.alert"
                   for e in steady.events.tail())
