"""Hybrid ICI-inner/DCN-outer mesh construction (VERDICT r4 missing #4).

SURVEY §2.4 closing: the comm-backend equivalence requires "ICI for
intra-slice and DCN for multi-slice axes". ``build_mesh(...,
dcn_data_parallel_size=N)`` is the ``mesh_utils.create_hybrid_device_mesh``
analog: devices grouped by slice, ``data`` ordered slice-outer, and the
model/stage/context axes never crossing a slice boundary.

On the 8-virtual-CPU-device test platform every device reports
process_index 0, so these tests stub the slice id from ``device.id`` —
the real grouping attribute path (``slice_index``/``process_index``) is
exercised end-to-end by tests/test_multihost.py's two-process cluster.
"""

import jax
import numpy as np
import pytest

from apex_tpu import mesh as mesh_lib


@pytest.fixture
def four_per_slice(monkeypatch):
    """Pretend devices 0-3 are slice 0 and devices 4-7 are slice 1."""
    monkeypatch.setattr(mesh_lib, "_slice_key", lambda d: d.id // 4)


def _slice_of(dev):
    return dev.id // 4


def test_data_axis_is_slice_outer(four_per_slice):
    m = mesh_lib.build_mesh(tensor_model_parallel_size=2,
                            dcn_data_parallel_size=2)
    assert m.devices.shape == (4, 1, 1, 2)
    # model pairs never cross the slice boundary
    for d in range(4):
        pair = m.devices[d, 0, 0, :]
        assert _slice_of(pair[0]) == _slice_of(pair[1])
    # data ranks 0-1 live in slice 0, ranks 2-3 in slice 1: consecutive
    # data ranks stay on ICI; only the outer stride crosses DCN
    slices_by_dp = [_slice_of(m.devices[d, 0, 0, 0]) for d in range(4)]
    assert slices_by_dp == [0, 0, 1, 1]


def test_stage_axis_stays_intra_slice(four_per_slice):
    m = mesh_lib.build_mesh(pipeline_model_parallel_size=2,
                            context_parallel_size=2,
                            dcn_data_parallel_size=2)
    assert m.devices.shape == (2, 2, 2, 1)
    for d in range(2):
        block = m.devices[d].ravel()
        assert len({_slice_of(x) for x in block}) == 1, (
            "a stage/context block crossed the slice boundary")


def test_interleaved_device_list_regrouped(four_per_slice):
    # a shuffled device list must still come out slice-grouped
    devs = jax.devices()
    shuffled = [devs[i] for i in (3, 4, 0, 7, 1, 6, 2, 5)]
    m = mesh_lib.build_mesh(tensor_model_parallel_size=2,
                            devices=shuffled, dcn_data_parallel_size=2)
    slices_by_dp = [_slice_of(m.devices[d, 0, 0, 0]) for d in range(4)]
    assert slices_by_dp == [0, 0, 1, 1]


def test_model_axis_may_not_cross_slice(four_per_slice):
    # tp=8 needs all 8 devices in one block but each slice has only 4
    with pytest.raises(RuntimeError, match="slice"):
        mesh_lib.build_mesh(tensor_model_parallel_size=8,
                            dcn_data_parallel_size=2)


def test_wrong_slice_count_raises(four_per_slice):
    with pytest.raises(RuntimeError, match="spans"):
        mesh_lib.build_mesh(dcn_data_parallel_size=4)


def test_uneven_slices_raise(monkeypatch):
    monkeypatch.setattr(mesh_lib, "_slice_key",
                        lambda d: 0 if d.id < 3 else 1)
    with pytest.raises(RuntimeError, match="uneven"):
        mesh_lib.build_mesh(dcn_data_parallel_size=2)


def test_default_path_unchanged():
    m = mesh_lib.build_mesh(tensor_model_parallel_size=2)
    flat = [d.id for d in m.devices.ravel()]
    assert flat == list(range(8))


def test_parallel_state_plumbs_dcn(four_per_slice):
    from apex_tpu.transformer import parallel_state

    m = parallel_state.initialize_model_parallel(
        2, 1, dcn_data_parallel_size_=2)
    slices_by_dp = [_slice_of(m.devices[d, 0, 0, 0]) for d in range(4)]
    assert slices_by_dp == [0, 0, 1, 1]


def test_hybrid_mesh_gradient_step_runs(four_per_slice):
    """A dp x model hybrid mesh must actually run a sharded psum step."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh_lib.build_mesh(tensor_model_parallel_size=2,
                            dcn_data_parallel_size=2)
    x = jnp.arange(32, dtype=jnp.float32).reshape(4, 8)
    xs = jax.device_put(x, NamedSharding(m, P("data", "model")))
    y = jax.jit(lambda a: a.sum())(xs)
    np.testing.assert_allclose(float(y), float(x.sum()))
