"""BERT pretraining model: forward shapes, loss finiteness, DP/TP step.

Mirrors the role of the reference's run_bert_minimal_test.py
(apex/transformer/testing/standalone_bert.py driver): build the model, run
fwd+bwd+optimizer on a toy config, assert loss decreases; plus mesh-sharded
step on the 8-device CPU mesh (strictly beyond the reference's GPU-only CI).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import (
    BertForPreTraining,
    bert_pretrain_loss,
    bert_tiny_config,
    make_pretrain_step,
    param_partition_specs,
    synthetic_batch,
)
from apex_tpu.optimizers import FusedLAMB


@pytest.fixture
def tiny_setup(rng):
    cfg = bert_tiny_config()
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, batch_size=4, seq_len=32)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"], batch["attention_mask"])["params"]
    return cfg, model, params, batch


@pytest.mark.slow
def test_forward_shapes(tiny_setup):
    cfg, model, params, batch = tiny_setup
    mlm, nsp = model.apply({"params": params}, batch["input_ids"],
                           batch["token_type_ids"], batch["attention_mask"])
    assert mlm.shape == (4, 32, cfg.vocab_size)
    assert nsp.shape == (4, 2)
    loss = bert_pretrain_loss(mlm, nsp, batch["mlm_labels"], batch["nsp_labels"])
    assert jnp.isfinite(loss)


@pytest.mark.slow
def test_gathered_mlm_head_matches_dense(tiny_setup):
    """The max_predictions_per_seq head (masked_positions) must produce
    exactly the dense head's logits at the selected positions, and the same
    loss on the same batch — pins the take_along_axis gather the benchmark
    path trains through."""
    cfg, model, params, batch = tiny_setup
    dense_mlm, nsp = model.apply({"params": params}, batch["input_ids"],
                                 batch["token_type_ids"],
                                 batch["attention_mask"])
    gathered_mlm, nsp_g = model.apply(
        {"params": params}, batch["input_ids"], batch["token_type_ids"],
        batch["attention_mask"], masked_positions=batch["mlm_positions"])
    k = batch["mlm_positions"].shape[1]
    assert gathered_mlm.shape == (4, k, cfg.vocab_size)
    expect = jnp.take_along_axis(
        dense_mlm, batch["mlm_positions"][..., None], axis=1)
    np.testing.assert_allclose(np.asarray(gathered_mlm, np.float32),
                               np.asarray(expect, np.float32),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(nsp_g), np.asarray(nsp),
                               rtol=1e-6, atol=1e-6)
    loss_dense = bert_pretrain_loss(dense_mlm, nsp, batch["mlm_labels"],
                                    batch["nsp_labels"])
    loss_gathered = bert_pretrain_loss(gathered_mlm, nsp_g,
                                       batch["mlm_gathered_labels"],
                                       batch["nsp_labels"])
    np.testing.assert_allclose(float(loss_gathered), float(loss_dense),
                               rtol=1e-5)


@pytest.mark.slow
def test_train_loss_decreases(tiny_setup):
    cfg, model, params, batch = tiny_setup
    step = make_pretrain_step(model)
    opt = FusedLAMB(params, lr=1e-3)
    losses = []
    for i in range(8):
        loss, grads = step(params, batch, i)
        params = opt.step(grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_mesh_dp_tp_step_matches_single_device(tiny_setup):
    """TP x DP sharded grad step == replicated grad step (the reference's
    universal distributed-test pattern, SURVEY.md §4)."""
    from apex_tpu.transformer import parallel_state

    cfg, model, params, batch = tiny_setup
    loss0, grads0 = make_pretrain_step(model)(params, batch, 0)

    mesh = parallel_state.initialize_model_parallel(2)
    step, place_params, batch_sh = make_pretrain_step(
        model, mesh=mesh, partition_params=True)
    sh_params = place_params(params)
    sh_batch = jax.tree.map(jax.device_put, batch, batch_sh)
    loss1, grads1 = step(sh_params, sh_batch, 0)

    np.testing.assert_allclose(float(loss0), float(loss1), rtol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5),
        grads0, grads1)


def test_partition_specs_cover_attention_and_mlp(tiny_setup):
    _, _, params, _ = tiny_setup
    specs = param_partition_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    from apex_tpu.optimizers.common import path_name

    by_name = {path_name(p): s for p, s in flat}
    sharded = [n for n, s in by_name.items() if s != jax.sharding.PartitionSpec()]
    assert any("qkv_weight" in n for n in sharded)
    assert any("mlp_weight1" in n for n in sharded)
    assert any("out_weight" in n for n in sharded)
    assert any("word_embeddings" in n for n in sharded)
