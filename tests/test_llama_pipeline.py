"""Llama pipeline: PP x TP composition vs the single-device Llama model.

Mirrors tests/test_gpt_pipeline.py for the second model family — loss AND
reassembled grads must match the dense model, with the shared
embed/norm/head grads summed over stages.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS, STAGE_AXIS
from apex_tpu.models.llama import LlamaModel, llama_loss, llama_tiny_config
from apex_tpu.models.llama_pipeline import (
    make_llama_pipeline_fns,
    merge_pipeline_grads_to_llama,
    split_llama_params_for_pipeline,
)
from tests.test_llama_model import _shard_tree

pytestmark = pytest.mark.slow


@pytest.mark.parametrize("schedule", ["1f1b", "interleaved"])
def test_llama_pp2_tp2_matches_single_device(mesh_tp2_pp2_dp2, rng,
                                             schedule):
    mesh = mesh_tp2_pp2_dp2
    pp, tp = 2, 2
    vpp = 2 if schedule == "interleaved" else 1
    n_layers = 4
    m, b, s = 4, 2, 16

    cfg1 = llama_tiny_config(tensor_parallel_size=1, num_layers=n_layers)
    cfg2 = llama_tiny_config(tensor_parallel_size=tp, num_layers=n_layers)

    mbs = jnp.asarray(rng.integers(0, cfg1.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg1.vocab_size, (m, b, s)),
                         jnp.int32)

    m1 = LlamaModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), mbs[0])["params"]

    def ref_loss(p):
        per = jax.vmap(lambda ii, ll: llama_loss(
            m1, {"params": p}, ii, ll, axis_name="unbound"))(mbs, labels)
        return per.mean()

    ref_l, ref_g = jax.value_and_grad(ref_loss)(v1)

    m2 = LlamaModel(cfg2)
    v2_shape = jax.eval_shape(
        lambda: m2.init(jax.random.PRNGKey(0), mbs[0]))["params"]
    per_rank = []
    for r in range(tp):
        tp_tree = _shard_tree(v1, v2_shape, r, tp)
        per_rank.append(split_llama_params_for_pipeline(
            cfg2, tp_tree, pp, virtual_chunks=vpp))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *per_rank)

    first_fn, stage_fn, loss_fn = make_llama_pipeline_fns(cfg2)
    from tests.conftest import make_sched_adapters
    fwd_bwd, to_sched_tree, from_sched_tree = make_sched_adapters(
        schedule, vpp)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS, MODEL_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS, MODEL_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        local = jax.tree.map(lambda t: t[0, 0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, to_sched_tree(local), mb,
                              loss_aux=lb, first_fn=first_fn,
                              loss_with_params=True)
        grads = from_sched_tree(grads)
        return loss.reshape(1), jax.tree.map(lambda t: t[None, None], grads)

    losses, grads = jax.jit(run)(stacked, mbs, labels)
    np.testing.assert_allclose(np.asarray(losses), float(ref_l),
                               rtol=2e-5, atol=2e-5)

    for r in range(tp):
        g_rank = jax.tree.map(lambda t, r=r: t[:, r], grads)
        back = merge_pipeline_grads_to_llama(cfg2, g_rank, pp,
                                             virtual_chunks=vpp)
        ref_rank = _shard_tree(ref_g, v2_shape, r, tp)

        def check(g_pp, g_ref):
            np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref),
                                       rtol=5e-3, atol=1e-4)

        jax.tree.map(check, back, ref_rank)
