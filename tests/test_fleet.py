"""Fleet observability plane (apex_tpu/obs/fleet.py) — ISSUE 19.

The acceptance bars, unit-tier (the over-the-wire integration bars —
stitching across a real HTTP replica kill, remote scrape fidelity —
live in tests/test_http.py):

- trace ids are process-independent, traceparent round-trips, and
  malformed headers degrade to None (a fresh mint), never an error;
- ``stitch_traces`` merges per-replica span dumps into ONE lifecycle
  per request across a failover: TTFT anchored at the FIRST replica's
  first token, the failover gap counted into ``preempted_ms``, the
  synthesized failover segment naming both replicas, zero orphans for
  fully-bound dumps;
- the burn-rate alerter is multi-window with pinned hysteresis under
  injected clocks: a fast spike alone never fires, sustained burn
  fires exactly once, and it resolves only below
  ``threshold * hysteresis``;
- federated rows reproduce replica-local registry values exactly
  (``row_from_snapshot`` / ``_merged_quantile`` vs
  ``Histogram.quantile``);
- the flight bundle is schema-pinned: ``validate_flight`` accepts what
  ``build_flight`` produces and names every missing key otherwise;
- ``EventLog.since`` is an incremental cursor with gap detection (the
  federation scrape's second endpoint).
"""

import numpy as np
import pytest

from apex_tpu.obs.events import EventLog
from apex_tpu.obs.fleet import (BurnRateAlerter, FLIGHT_SCHEMA,
                                FleetCollector, _merged_quantile,
                                build_flight, mint_trace_id,
                                parse_traceparent, row_from_snapshot,
                                stitch_traces, traceparent,
                                validate_flight)
from apex_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.clear()
    yield
    metrics.clear()


# --------------------------------------------------------------------------
# trace ids
# --------------------------------------------------------------------------

def test_mint_trace_id_format_and_uniqueness():
    ids = {mint_trace_id() for _ in range(256)}
    assert len(ids) == 256
    for tid in ids:
        assert len(tid) == 32
        assert all(c in "0123456789abcdef" for c in tid)


def test_traceparent_round_trip():
    tid = mint_trace_id()
    header = traceparent(tid)
    assert header == f"00-{tid}-{'0' * 16}-01"
    assert parse_traceparent(header) == tid
    # a bare 32-hex id is accepted too (the JSON-body carrier)
    assert parse_traceparent(tid) == tid


def test_parse_traceparent_malformed_degrades_to_none():
    # malformed headers must degrade to a fresh mint (None), never 400
    for bad in (None, "", "nonsense", "00-zz-00-01", "00-abc-def-01",
                "00-" + "g" * 32 + "-0000000000000000-01", 123,
                "0" * 31, "0" * 33):
        assert parse_traceparent(bad) is None


# --------------------------------------------------------------------------
# stitching
# --------------------------------------------------------------------------

def _span(request_id, name, t0, t1, **attrs):
    return {"request_id": request_id, "name": name, "t_start": t0,
            "t_end": t1, "duration_ms": None if t1 is None
            else (t1 - t0) * 1e3, "attrs": attrs}


def test_stitch_traces_single_lifecycle_across_failover():
    tid = mint_trace_id()
    # replica0 serves enqueue..first_token then dies at t=3.0; replica1
    # resumes at t=5.0 (the 2 s gap is the failover) and retires at 7.0
    dumps = {
        "replica0": [
            _span(7, "enqueue", 1.0, 1.0, trace_id=tid),
            _span(7, "admit", 1.5, 1.5),
            _span(7, "prefill", 1.5, 2.0, computed_tokens=8,
                  cached_tokens=0),
            _span(7, "first_token", 2.0, 2.0),
            _span(7, "decode", 2.0, 3.0, new_tokens=4),
        ],
        "replica1": [
            _span(7, "enqueue", 5.0, 5.0, trace_id=tid),
            _span(7, "admit", 5.0, 5.0),
            _span(7, "prefill", 5.0, 5.5, computed_tokens=12,
                  cached_tokens=8),
            _span(7, "first_token", 5.5, 5.5),
            _span(7, "decode", 5.5, 6.5, new_tokens=4),
            _span(7, "retire", 7.0, 7.0),
        ],
    }
    st = stitch_traces(dumps)
    assert st["orphans"] == []
    assert list(st["traces"]) == [tid]
    tr = st["traces"][tid]
    assert tr["trace_id"] == tid
    assert tr["replicas"] == ["replica0", "replica1"]
    assert tr["request_ids"] == [7]
    # TTFT anchors at the FIRST replica's first token, not the resume
    assert tr["ttft_ms"] == pytest.approx((2.0 - 1.0) * 1e3)
    assert tr["total_ms"] == pytest.approx((7.0 - 1.0) * 1e3)
    # the failover gap (replica0's last span end -> replica1's first
    # span start) is preemption time from the caller's point of view
    assert len(tr["failovers"]) == 1
    fo = tr["failovers"][0]
    assert fo["from_replica"] == "replica0"
    assert fo["to_replica"] == "replica1"
    assert fo["gap_ms"] == pytest.approx((5.0 - 3.0) * 1e3)
    assert tr["preempted_ms"] == pytest.approx(fo["gap_ms"])
    assert tr["preemptions"] == 1
    # per-replica segments cover both sides, in failover order
    assert [s["replica"] for s in tr["segments"]] == ["replica0",
                                                      "replica1"]
    assert tr["cached_tokens"] == 8      # the survivor's prefix hit


def test_stitch_traces_unbound_spans_are_orphans():
    tid = mint_trace_id()
    dumps = {
        "replica0": [_span(1, "enqueue", 0.0, 0.0, trace_id=tid),
                     _span(1, "retire", 1.0, 1.0),
                     _span(2, "admit", 0.5, 0.5)],   # no trace_id bound
    }
    st = stitch_traces(dumps)
    assert list(st["traces"]) == [tid]
    assert len(st["orphans"]) == 1
    assert st["orphans"][0]["request_id"] == 2
    assert st["orphans"][0]["replica"] == "replica0"


# --------------------------------------------------------------------------
# burn-rate alerting (injected clocks — the hysteresis pin)
# --------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_burn_alert_fast_spike_alone_does_not_fire():
    clk = _Clock()
    a = BurnRateAlerter(threshold=0.1, fast_window_s=60.0,
                        slow_window_s=600.0, clock=clk)
    # one hot sample inside the fast window, long cold history behind
    # it: the fast mean crosses, the slow mean does not -> no alert
    for _ in range(60):
        a.observe(0.0)
        clk.t += 10.0
    fired = a.observe(0.9)
    assert fired is False and a.fired == 0 and not a.firing


def test_burn_alert_fires_once_and_resolves_with_hysteresis():
    clk = _Clock()
    events = EventLog(capacity=64)
    a = BurnRateAlerter(threshold=0.1, fast_window_s=60.0,
                        slow_window_s=600.0, hysteresis=0.5,
                        events=events, clock=clk)
    # sustained burn fills BOTH windows -> fires exactly once
    for _ in range(80):
        a.observe(0.5)
        clk.t += 10.0
    assert a.firing and a.fired == 1
    # above threshold*hysteresis (0.05): still firing (the hysteresis
    # band suppresses flapping)
    for _ in range(10):
        a.observe(0.07)
        clk.t += 10.0
    assert a.firing and a.fired == 1
    # below the resolve bound -> resolves
    for _ in range(10):
        a.observe(0.01)
        clk.t += 10.0
    assert not a.firing and a.fired == 1
    kinds = [(e["kind"], e["state"]) for e in events.tail()
             if e["kind"] == "fleet.alert"]
    assert kinds == [("fleet.alert", "firing"),
                     ("fleet.alert", "resolved")]


def test_burn_alert_silent_on_steady_zero():
    clk = _Clock()
    a = BurnRateAlerter(threshold=0.1, clock=clk)
    for _ in range(200):
        a.observe(0.0)
        clk.t += 5.0
    assert a.fired == 0 and not a.firing


def test_burn_alerter_validates_params():
    for kw in ({"threshold": 0.0}, {"hysteresis": 1.5},
               {"fast_window_s": 60.0, "slow_window_s": 30.0}):
        with pytest.raises(ValueError):
            BurnRateAlerter(**kw)


# --------------------------------------------------------------------------
# federation fidelity: merged quantiles == Histogram.quantile
# --------------------------------------------------------------------------

def test_row_from_snapshot_matches_local_registry():
    labels = {"engine": "0"}
    h = metrics.histogram("serving.ttft_ms", labels=labels)
    rng = np.random.default_rng(0)
    for v in rng.lognormal(3.0, 1.0, 500):
        h.observe(float(v))
    t = metrics.histogram("serving.tpot_ms", labels=labels)
    for v in rng.lognormal(1.0, 0.5, 300):
        t.observe(float(v))
    metrics.gauge("serving.queue_depth", labels=labels).set(7)
    metrics.gauge("serving.slo_burn", labels=labels).set(0.25)

    row = row_from_snapshot(metrics.snapshot(), labels=labels)
    # the federated row must reproduce the replica-local instruments
    # EXACTLY (same bucket interpolation — the scrape fidelity bar)
    assert row["ttft_ms_p95"] == pytest.approx(h.quantile(0.95))
    assert row["tpot_ms_p95"] == pytest.approx(t.quantile(0.95))
    assert row["queue_depth"] == 7
    assert row["slo_burn"] == 0.25


def test_merged_quantile_sums_replica_buckets():
    h0 = metrics.histogram("serving.ttft_ms", labels={"engine": "0"})
    h1 = metrics.histogram("serving.ttft_ms", labels={"engine": "1"})
    both = metrics.histogram("merged.ttft_ms")
    rng = np.random.default_rng(1)
    for i, v in enumerate(rng.lognormal(3.0, 1.0, 400)):
        (h0 if i % 2 else h1).observe(float(v))
        both.observe(float(v))
    snap = metrics.snapshot()
    entries = [e for e in snap["histograms"]
               if e["name"] == "serving.ttft_ms"]
    assert len(entries) == 2
    # fleet-level p95 over BOTH replicas == one histogram fed everything
    # (identical bucket layout, so the merge is exact up to min/max
    # clamping — use interior quantiles)
    for q in (0.5, 0.9, 0.95):
        assert _merged_quantile(entries, q) == pytest.approx(
            both.quantile(q), rel=1e-9)


# --------------------------------------------------------------------------
# the collector over stub replicas (injected clock)
# --------------------------------------------------------------------------

class _StubFrontend:
    def __init__(self, name, depth):
        class _Eng:
            obs_labels = {"engine": name}
            events = EventLog(capacity=32)
        self.engine = _Eng()
        self.queue_depth = depth


class _StubRouter:
    def __init__(self, targets):
        self._targets = targets

    def fleet_targets(self):
        return list(self._targets)


def test_collector_federates_local_rows_and_staleness():
    fe0, fe1 = _StubFrontend("0", 3), _StubFrontend("1", 5)
    metrics.gauge("serving.slo_burn", labels={"engine": "0"}).set(0.4)
    metrics.gauge("serving.slo_burn", labels={"engine": "1"}).set(0.1)
    fe0.engine.events.emit("compile_storm", fn="decode")
    fe1.engine.events.emit("admit", request=1)

    clk = _Clock()
    router = _StubRouter([("replica0", True, fe0),
                          ("replica1", False, fe1)])
    alerter = BurnRateAlerter(threshold=0.1, fast_window_s=60.0,
                              slow_window_s=60.0, clock=clk)
    col = FleetCollector(router, interval_s=0.05, alerter=alerter,
                         clock=clk)
    assert col.tick(force=True) is True
    # throttle: a second tick inside interval_s is a no-op
    assert col.tick() is False

    block = col.block()
    rows = {r["replica"]: r for r in block["per_replica"]}
    assert rows["replica0"]["queue_depth"] == 3
    assert rows["replica0"]["slo_burn"] == 0.4
    assert rows["replica0"]["compile_storms"] == 1
    # the dead replica is never scraped: zeros + alive=False
    assert rows["replica1"]["alive"] is False
    assert rows["replica1"]["slo_burn"] == 0.0
    assert block["queue_depth"] == 3           # sum over scraped rows
    assert block["slo_burn"] == 0.4            # max over live rows
    assert block["replicas"] == 2
    # fleet.* gauges carry replica= labels
    g = metrics.gauge("fleet.slo_burn", labels={"replica": "replica0"})
    assert g.value == 0.4
    # staleness: the scrape age grows with the injected clock
    clk.t += 2.0
    assert col.scrape_ages()["replica0"] == pytest.approx(2.0)
    assert col.scrape_ages()["replica1"] is None
    # the burn fed the alerter (max over live rows)
    assert alerter.windows()[0] == pytest.approx(0.4)


# --------------------------------------------------------------------------
# flight bundle schema
# --------------------------------------------------------------------------

def _flight_fixture():
    tid = mint_trace_id()
    dumps = {"replica0": [_span(1, "enqueue", 0.0, 0.0, trace_id=tid),
                          _span(1, "retire", 1.0, 1.0)],
             "replica1": []}
    routing = [{"replica": "replica0", "alive": True, "draining": False,
                "routed": 4, "dead_reason": None, "queue_depth": 2},
               {"replica": "replica1", "alive": False,
                "draining": False, "routed": 1,
                "dead_reason": "InjectedFault('kill')",
                "queue_depth": 0}]
    return build_flight(
        reason="replica_dead:1", routing=routing,
        counters={"routed": 5, "failovers": 1},
        router_events=[{"kind": "replica_dead", "seq": 0}],
        dumps=dumps,
        replica_events={"replica0": [{"kind": "admit", "seq": 0}],
                        "replica1": [{"kind": "step", "seq": 3}]},
        tag="t1")


def test_build_flight_is_schema_valid_and_names_every_replica():
    doc = _flight_fixture()
    assert validate_flight(doc) is doc
    assert doc["schema"] == FLIGHT_SCHEMA
    assert set(doc["replicas"]) == {"replica0", "replica1"}
    assert doc["replicas"]["replica1"]["events"] == [{"kind": "step",
                                                      "seq": 3}]
    assert doc["router"]["counters"]["failovers"] == 1
    assert len(doc["traces"]) == 1 and doc["orphan_spans"] == []


def test_validate_flight_names_every_problem():
    doc = _flight_fixture()
    doc.pop("traces")
    doc["schema"] = "wrong/schema"
    doc["replicas"]["replica0"].pop("queue_depth")
    with pytest.raises(ValueError) as err:
        validate_flight(doc)
    msg = str(err.value)
    assert "traces" in msg and "schema" in msg and "queue_depth" in msg
    with pytest.raises(ValueError):
        validate_flight({"schema": FLIGHT_SCHEMA})
    with pytest.raises(ValueError):
        validate_flight([])


# --------------------------------------------------------------------------
# the event cursor (the federation scrape's gap detector)
# --------------------------------------------------------------------------

def test_event_log_since_cursor_and_gap_detection():
    log = EventLog(capacity=4)
    for i in range(3):
        log.emit("tick", i=i)
    events, dropped = log.since(-1)
    assert [e["seq"] for e in events] == [0, 1, 2] and dropped == 0
    cursor = events[-1]["seq"]
    events, dropped = log.since(cursor)
    assert events == [] and dropped == 0
    # the ring laps the cursor: 6 more events into capacity 4 — two of
    # the post-cursor events are gone, and the scraper must learn it
    for i in range(3, 9):
        log.emit("tick", i=i)
    events, dropped = log.since(cursor)
    assert [e["seq"] for e in events] == [5, 6, 7, 8]
    assert dropped == 2                  # seqs 3 and 4 lapped away


def test_event_log_dump_with_cursor(tmp_path):
    log = EventLog(capacity=8)
    for i in range(5):
        log.emit("tick", i=i)
    import json
    text = log.dump(str(tmp_path / "e.jsonl"), since_seq=1)
    lines = [json.loads(ln) for ln in text.splitlines()]
    assert lines[0]["since_seq"] == 1 and lines[0]["dropped"] == 0
    assert [r["seq"] for r in lines[1:]] == [2, 3, 4]
