"""Fused softmax-xentropy vs (log_softmax + nll) reference.

Mirrors apex/contrib/test/xentropy/test_label_smoothing.py: fused loss vs the
composed-ops reference across smoothing x dtype grids, fwd and bwd.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
from apex_tpu.ops import softmax_cross_entropy


def _ref_loss(logits, labels, smoothing=0.0, padding_idx=None):
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, -1)
    rows = jnp.arange(logits.shape[0])
    loss = lse - (1 - smoothing) * logits[rows, labels]
    if smoothing > 0:
        loss = loss - smoothing * logits.mean(-1)
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("vocab", [1000, 777, 32000])
def test_forward(rng, smoothing, vocab):
    n = 40
    logits = jnp.asarray(rng.standard_normal((n, vocab)) * 3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, n), jnp.int32)
    loss = softmax_cross_entropy(logits, labels, smoothing)
    np.testing.assert_allclose(loss, _ref_loss(logits, labels, smoothing),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_backward(rng, smoothing):
    n, vocab = 24, 501
    logits = jnp.asarray(rng.standard_normal((n, vocab)) * 2, jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, n), jnp.int32)
    w = jnp.asarray(rng.standard_normal(n), jnp.float32)
    g = jax.grad(lambda l: (softmax_cross_entropy(l, labels, smoothing) * w).sum())(logits)
    gr = jax.grad(lambda l: (_ref_loss(l, labels, smoothing) * w).sum())(logits)
    np.testing.assert_allclose(g, gr, atol=2e-6, rtol=2e-5)


def test_padding_idx(rng):
    n, vocab = 16, 100
    logits = jnp.asarray(rng.standard_normal((n, vocab)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, vocab, n), jnp.int32).at[::4].set(0)
    loss = softmax_cross_entropy(logits, labels, 0.1, padding_idx=0)
    assert bool(jnp.all(loss[::4] == 0.0))
    g = jax.grad(lambda l: softmax_cross_entropy(l, labels, 0.1, 0).sum())(logits)
    assert bool(jnp.all(g[::4] == 0.0))
    assert bool(jnp.any(g[1::4] != 0.0))


def test_bf16_and_batch_shape(rng):
    b, s, vocab = 2, 10, 333
    logits = jnp.asarray(rng.standard_normal((b, s, vocab)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, vocab, (b, s)), jnp.int32)
    loss = softmax_cross_entropy(logits, labels, 0.1)
    assert loss.shape == (b, s)
    ref = _ref_loss(logits.reshape(-1, vocab), labels.reshape(-1), 0.1)
    np.testing.assert_allclose(loss.reshape(-1), ref, atol=3e-2, rtol=3e-2)


def test_module_facade(rng):
    n, vocab = 8, 50
    logits = jnp.asarray(rng.standard_normal((n, vocab)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, vocab, n), jnp.int32)
    a = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.0, 0, True)
    b = softmax_cross_entropy(logits, labels)
    np.testing.assert_allclose(a, b)
