"""Incremental decoding (KV cache) + generate loop.

Beyond reference (apex ships no inference path). Parity contract: the
cached path (models/generation.py — flash-kernel prefill + masked
dot-product decode over the static buffer) must reproduce the training
forward position by position — prefill in one chunk, chunked continuation
(static offset), then single-token steps, on GPT and on Llama with GQA +
sliding window; the generate loop's greedy output must match a
teacher-forced full-forward argmax loop; TP=2 decode must match
single-device decode.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import (generate, init_cache,
                                        speculative_generate)
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.models.llama import LlamaModel, llama_tiny_config

TOL = dict(rtol=5e-5, atol=5e-5)


def _full_logits(model, v, ids):
    return np.asarray(model.apply(v, ids), np.float32)


def test_gpt_prefill_matches_full_forward(rng):
    # deliberately NOT slow: the smoke tier keeps one real decode-parity
    # check (this is the cheapest — one forward + one cached prefill)
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)

    cache = init_cache(cfg, 2, 16)
    logits, cache = model.apply(v, ids, cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               _full_logits(model, v, ids), **TOL)
    assert int(cache["len"]) == 12


@pytest.mark.slow
def test_gpt_incremental_steps_match_full_forward(rng):
    """Prefill 6 tokens then 6 single-token steps: step logits equal the
    full forward's logits at the same absolute position."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    full = _full_logits(model, v, ids)

    cache = init_cache(cfg, 2, 12)
    logits, cache = model.apply(v, ids[:, :6], cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :6], **TOL)
    for p in range(6, 12):
        step, cache = model.apply(v, ids[:, p:p + 1], cache=cache)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)
    assert int(cache["len"]) == 12


@pytest.mark.slow
def test_llama_gqa_window_incremental_matches_full_forward(rng):
    """GQA (kv=2 < h=4) + sliding window: the cache holds UNEXPANDED kv
    heads and the absolute-position band mask reproduces the banded flash
    kernel."""
    cfg = llama_tiny_config(sliding_window=5)
    model = LlamaModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    full = _full_logits(model, v, ids)

    cache = init_cache(cfg, 2, 16)
    logits, cache = model.apply(v, ids[:, :8], cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :8], **TOL)
    for p in range(8, 16):
        step, cache = model.apply(v, ids[:, p:p + 1], cache=cache)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)


@pytest.mark.slow
def test_generate_greedy_matches_teacher_forced(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    out = np.asarray(generate(model, v, prompt, max_new_tokens=8))
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(out[:, :5], np.asarray(prompt))

    # teacher-forced loop at ONE fixed shape: GPT is causal, so trailing
    # padding can't influence position t-1 — one jitted apply reused 8
    # times instead of 8 growing-length compiles (r5 rebalance)
    apply = jax.jit(lambda ids: model.apply(v, ids))
    seq = np.zeros((2, 13), np.int32)
    seq[:, :5] = np.asarray(prompt)
    for t in range(5, 13):
        logits = np.asarray(apply(jnp.asarray(seq)), np.float32)
        seq[:, t] = logits[:, t - 1].argmax(-1).astype(np.int32)
    np.testing.assert_array_equal(out, seq)


@pytest.mark.slow
def test_generate_is_jittable_end_to_end(rng):
    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    fn = jax.jit(functools.partial(generate, model, max_new_tokens=6))
    out_jit = np.asarray(fn(v, prompt))
    out = np.asarray(generate(model, v, prompt, max_new_tokens=6))
    np.testing.assert_array_equal(out_jit, out)


@pytest.mark.slow
def test_generate_eos_padding(rng):
    """Once a row emits EOS every later position is EOS."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    free = np.asarray(generate(model, v, prompt, max_new_tokens=6))
    eos = int(free[0, 4])  # the first greedy token of row 0 -> instant EOS
    out = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                              eos_token_id=eos))
    assert (out[0, 4:] == eos).all()


@pytest.mark.slow
def test_generate_sampling_topk_support_and_reproducibility(rng):
    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    key = jax.random.PRNGKey(7)

    kw = dict(max_new_tokens=6, temperature=1.0, top_k=4, rng=key)
    out1 = np.asarray(generate(model, v, prompt, **kw))
    out2 = np.asarray(generate(model, v, prompt, **kw))
    np.testing.assert_array_equal(out1, out2)  # same key -> same draw

    # every sampled token lies in the teacher-forced top-k support
    seq = np.asarray(prompt)
    for p in range(6):
        logits = _full_logits(model, v, jnp.asarray(out1[:, :4 + p]))[:, -1]
        topk = np.argsort(-logits, axis=-1)[:, :4]
        for row in range(2):
            assert out1[row, 4 + p] in topk[row]

    with pytest.raises(ValueError):
        generate(model, v, prompt, max_new_tokens=2, temperature=1.0)
    with pytest.raises(ValueError):  # sampling knobs under greedy decode
        generate(model, v, prompt, max_new_tokens=2, top_k=4)


@pytest.mark.slow
def test_generate_top_p_nucleus(rng):
    """top_p -> 0 degenerates to greedy (only the modal token survives);
    moderate top_p draws stay inside the teacher-forced nucleus set."""
    cfg = llama_tiny_config()
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    greedy = np.asarray(generate(model, v, prompt, max_new_tokens=5))
    tiny_p = np.asarray(generate(model, v, prompt, max_new_tokens=5,
                                 temperature=1.0, top_p=1e-9,
                                 rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(tiny_p, greedy)

    with pytest.raises(ValueError):  # top_p=0 would sample the full dist
        generate(model, v, prompt, max_new_tokens=2, temperature=1.0,
                 top_p=0.0, rng=jax.random.PRNGKey(1))

    out = np.asarray(generate(model, v, prompt, max_new_tokens=5,
                              temperature=1.0, top_p=0.7,
                              rng=jax.random.PRNGKey(2)))
    for p in range(5):
        logits = _full_logits(model, v, jnp.asarray(out[:, :4 + p]))[:, -1]
        for row in range(2):
            probs = np.exp(logits[row] - logits[row].max())
            probs /= probs.sum()
            order = np.argsort(-probs)
            mass_before = np.cumsum(probs[order]) - probs[order]
            nucleus = set(order[mass_before < 0.7].tolist())
            assert int(out[row, 4 + p]) in nucleus


@pytest.mark.slow
def test_chunked_continuation_matches_full_forward(rng):
    """Static-offset multi-token chunks (speculative-decoding shape):
    prefill 4, then a 4-token chunk through the dense cached path."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    full = _full_logits(model, v, ids)

    cache = init_cache(cfg, 2, 12)
    logits, cache = model.apply(v, ids[:, :4], cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :4], **TOL)
    logits, cache = model.apply(v, ids[:, 4:8], cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, 4:8], **TOL)
    assert cache["len"] == 8  # plain-int arithmetic keeps the offset static


def test_direct_apply_bounds_raise_at_trace_time(rng):
    """check_chunk_bounds: a statically out-of-range chunk raises instead
    of letting dynamic_slice clamp and silently reuse positions."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.zeros((1, 8), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    cache = init_cache(cfg, 1, 4)  # buffer smaller than the chunk
    with pytest.raises(ValueError):
        model.apply(v, prompt, cache=cache)
    cache = init_cache(cfg, 1, cfg.max_position_embeddings + 8)
    _, cache = model.apply(v, prompt, cache=cache)
    cache["len"] = cfg.max_position_embeddings - 4  # static offset
    with pytest.raises(ValueError):
        model.apply(v, prompt, cache=cache)  # would pass the RoPE range


def test_generate_validates_lengths(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    with pytest.raises(ValueError):
        generate(model, v, prompt,
                 max_new_tokens=cfg.max_position_embeddings)
    with pytest.raises(ValueError):
        generate(model, v, prompt, max_new_tokens=4, max_len=6)


@pytest.mark.slow
def test_moe_decode_matches_full_forward(rng):
    """MoE routing is per-token, so with undropped capacity the cached path
    reproduces the full forward."""
    cfg = gpt_tiny_config(num_experts=2, moe_layer_freq=1,
                          moe_capacity_factor=8.0)
    model = GPTModel(cfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    full = _full_logits(model, v, ids)

    cache = init_cache(cfg, 2, 8)
    logits, cache = model.apply(v, ids[:, :4], cache=cache)
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :4], **TOL)
    for p in range(4, 8):
        step, cache = model.apply(v, ids[:, p:p + 1], cache=cache)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)


@pytest.mark.slow
def test_generate_tp2_matches_tp1(rng):
    """Head-/vocab-sharded decode inside shard_map: same tokens as the
    single-device generate (the gather + replicated argmax make every rank
    agree)."""
    from apex_tpu.transformer import parallel_state
    from tests.test_llama_model import _shard_tree

    tp = 2
    mesh = parallel_state.initialize_model_parallel(tp)
    cfg1 = llama_tiny_config(tensor_parallel_size=1)
    cfgt = llama_tiny_config(tensor_parallel_size=tp)
    prompt = jnp.asarray(rng.integers(0, cfg1.vocab_size, (2, 5)), jnp.int32)

    m1 = LlamaModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), prompt)
    out1 = np.asarray(generate(m1, v1, prompt, max_new_tokens=6,
                               axis_name="unbound"))

    mt = LlamaModel(cfgt)
    vt_shape = jax.eval_shape(lambda: mt.init(jax.random.PRNGKey(0), prompt))
    shards = [_shard_tree(v1["params"], vt_shape["params"], r, tp)
              for r in range(tp)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(MODEL_AXIS), P()), out_specs=P(),
        check_vma=False)
    def run(vs, ii):
        v = jax.tree.map(lambda t: t[0], vs)
        return generate(mt, {"params": v}, ii, max_new_tokens=6)

    with mesh:
        outt = np.asarray(jax.jit(run)(stacked, prompt))
    np.testing.assert_array_equal(outt, out1)


@pytest.mark.slow
def test_speculative_equals_greedy_self_draft(rng):
    """Draft == target: every proposal accepted, output == plain greedy."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=10))
    out = np.asarray(speculative_generate(model, v, model, v, prompt,
                                          max_new_tokens=10, k=4))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_speculative_equals_greedy_random_draft(rng):
    """An unrelated random draft (low acceptance): rejections roll the
    caches back and the output is STILL exactly the target's greedy
    decode — the correctness contract of speculative decoding."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    draft = GPTModel(cfg)
    dv = draft.init(jax.random.PRNGKey(99), prompt)   # different weights

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=9))
    out = np.asarray(speculative_generate(model, v, draft, dv, prompt,
                                          max_new_tokens=9, k=3))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_speculative_llama_gqa_window_draft(rng):
    """Target Llama (GQA + sliding window) with a differently-seeded
    draft; exactness must hold through the windowed decode path."""
    cfg = llama_tiny_config(sliding_window=6)
    model = LlamaModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    draft = LlamaModel(cfg)
    dv = draft.init(jax.random.PRNGKey(7), prompt)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=8))
    out = np.asarray(speculative_generate(model, v, draft, dv, prompt,
                                          max_new_tokens=8, k=4))
    np.testing.assert_array_equal(out, ref)


def test_speculative_validates_position_slack(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    with pytest.raises(ValueError):  # total + k must fit the position table
        speculative_generate(model, v, model, v, prompt,
                             max_new_tokens=cfg.max_position_embeddings - 4,
                             k=4)
    with pytest.raises(ValueError):
        speculative_generate(model, v, model, v, prompt, max_new_tokens=4,
                             k=1)


@pytest.mark.slow
def test_beam1_equals_greedy(rng):
    from apex_tpu.models.generation import generate_beam

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=7))
    seqs, scores = generate_beam(model, v, prompt, max_new_tokens=7,
                                 num_beams=1)
    assert seqs.shape == (2, 1, 12)
    np.testing.assert_array_equal(np.asarray(seqs)[:, 0], ref)
    assert np.isfinite(np.asarray(scores)).all()


@pytest.mark.slow
def test_beam_exhaustive_width_finds_global_optimum(rng):
    """vocab=4, T=3, num_beams=16 = V^(T-1): the beam pool provably holds
    every live prefix at every depth, so the returned best must equal the
    brute-force argmax over all 64 sequences' teacher-forced log-prob."""
    from apex_tpu.models.generation import generate_beam

    cfg = gpt_tiny_config(vocab_size=4)
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, 4, (2, 3)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    seqs, scores = generate_beam(model, v, prompt, max_new_tokens=3,
                                 num_beams=16, length_penalty=0.0)
    seqs, scores = np.asarray(seqs), np.asarray(scores)

    import itertools

    # brute force, BATCHED: all 64 continuations of one row score in a
    # single jitted forward (was 128 un-jitted applies = 400+ s of test
    # time for identical oracle strength)
    conts = np.asarray(list(itertools.product(range(4), repeat=3)),
                       np.int32)                              # (64, 3)

    @jax.jit
    def all_scores(full_ids):                                 # (64, 6)
        logits = model.apply(v, full_ids)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        pos = jnp.arange(3) + 2
        tok = full_ids[:, 3:]
        return jnp.take_along_axis(
            logp[:, pos, :], tok[..., None], axis=-1)[..., 0].sum(-1)

    for row in range(2):
        full = np.concatenate(
            [np.broadcast_to(np.asarray(prompt[row]), (64, 3)), conts], 1)
        s = np.asarray(all_scores(jnp.asarray(full)))
        best = int(np.argmax(s))
        np.testing.assert_array_equal(seqs[row, 0, 3:], conts[best])
        np.testing.assert_allclose(scores[row, 0], s[best], rtol=2e-4,
                                   atol=2e-4)


@pytest.mark.slow
def test_beam_scores_match_teacher_forced(rng):
    """Every returned beam's score equals its sequence's recomputed
    teacher-forced log-prob (penalty 0)."""
    from apex_tpu.models.generation import generate_beam

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    seqs, scores = generate_beam(model, v, prompt, max_new_tokens=4,
                                 num_beams=3, length_penalty=0.0)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    assert (np.diff(scores[0]) <= 1e-6).all()    # sorted best-first
    for j in range(3):
        ids = seqs[0, j]
        logits = np.asarray(model.apply(v, jnp.asarray(ids[None])),
                            np.float32)[0]
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        want = sum(logp[3 + t, ids[4 + t]] for t in range(4))
        np.testing.assert_allclose(scores[0, j], want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_beam_eos_freezes_and_ranks(rng):
    """A beam that emits EOS keeps emitting it at zero added cost, and the
    returned sequences pad with EOS after the first one."""
    from apex_tpu.models.generation import generate_beam

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 4)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    free, _ = generate_beam(model, v, prompt, max_new_tokens=5, num_beams=2)
    eos = int(np.asarray(free)[0, 0, 4])     # best beam's first token
    seqs, _ = generate_beam(model, v, prompt, max_new_tokens=5, num_beams=2,
                            eos_token_id=eos)
    seqs = np.asarray(seqs)
    for j in range(2):
        row = seqs[0, j, 4:]
        hits = np.where(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all()


@pytest.mark.slow
def test_t5_beam1_equals_greedy(rng):
    from apex_tpu.models.t5 import (T5Model, t5_beam_search, t5_generate,
                                    t5_tiny_config)

    cfg = t5_tiny_config()
    model = T5Model(cfg)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), enc_ids, enc_ids[:, :2])

    ref = np.asarray(t5_generate(model, v, enc_ids, max_new_tokens=5))
    seqs, _ = t5_beam_search(model, v, enc_ids, max_new_tokens=5,
                             num_beams=1)
    np.testing.assert_array_equal(np.asarray(seqs)[:, 0], ref)


@pytest.mark.slow
def test_rolling_cache_matches_full_cache(rng):
    """O(window) ring buffer: stepwise decode logits equal BOTH the
    full-length-cache decode and the training forward, past the point
    where the ring has wrapped several times."""
    import dataclasses

    cfg = llama_tiny_config(sliding_window=5)
    rcfg = dataclasses.replace(cfg, rolling_cache=True)
    model, rmodel = LlamaModel(cfg), LlamaModel(rcfg)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    full = _full_logits(model, v, ids)

    cache = init_cache(rcfg, 2, 16)
    assert cache["layers"][0]["k"].shape[2] == 5  # ring = window slots
    logits, cache = rmodel.apply(v, ids[:, :8], cache=cache)  # prefill > R
    np.testing.assert_allclose(np.asarray(logits, np.float32),
                               full[:, :8], **TOL)
    for p in range(8, 16):
        step, cache = rmodel.apply(v, ids[:, p:p + 1], cache=cache)
        np.testing.assert_allclose(np.asarray(step[:, 0], np.float32),
                                   full[:, p], **TOL)


@pytest.mark.slow
def test_rolling_cache_generate_and_beam_parity(rng):
    import dataclasses

    from apex_tpu.models.generation import generate_beam

    cfg = llama_tiny_config(sliding_window=4)
    rcfg = dataclasses.replace(cfg, rolling_cache=True)
    model, rmodel = LlamaModel(cfg), LlamaModel(rcfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    ref = np.asarray(generate(model, v, prompt, max_new_tokens=9))
    out = np.asarray(generate(rmodel, v, prompt, max_new_tokens=9))
    np.testing.assert_array_equal(out, ref)

    bref, _ = generate_beam(model, v, prompt, max_new_tokens=6, num_beams=3,
                            length_penalty=0.0)
    brol, _ = generate_beam(rmodel, v, prompt, max_new_tokens=6, num_beams=3,
                            length_penalty=0.0)
    np.testing.assert_array_equal(np.asarray(brol), np.asarray(bref))


@pytest.mark.slow
def test_rolling_cache_rejects_chunked_continuation(rng):
    """Multi-token chunks past prefill would overwrite slots earlier
    in-chunk queries need — the ring path raises instead."""
    import dataclasses

    rcfg = llama_tiny_config(sliding_window=4, rolling_cache=True)
    model = LlamaModel(rcfg)
    ids = jnp.asarray(rng.integers(0, rcfg.vocab_size, (1, 8)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), ids)
    cache = init_cache(rcfg, 1, 12)
    _, cache = model.apply(v, ids[:, :4], cache=cache)
    with pytest.raises(NotImplementedError):
        model.apply(v, ids[:, 4:8], cache=cache)  # s=4 continuation
    with pytest.raises(ValueError):  # rolling without a window
        init_cache(dataclasses.replace(rcfg, sliding_window=None), 1, 8)


@pytest.mark.slow
def test_generate_gspmd_dp_sharded_batch(rng):
    """The OTHER distribution path (no shard_map): jit + NamedSharding
    params/batch — generate partitions under GSPMD and matches the
    unsharded output."""
    from jax.sharding import NamedSharding

    from apex_tpu.mesh import build_mesh

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 5)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)
    ref = np.asarray(generate(model, v, prompt, max_new_tokens=6))

    mesh = build_mesh()  # all 8 virtual devices on the data axis
    with mesh:
        vs = jax.device_put(v, NamedSharding(mesh, P()))
        ps = jax.device_put(prompt, NamedSharding(mesh, P("data")))
        fn = jax.jit(functools.partial(generate, model, max_new_tokens=6,
                                       axis_name="unbound"))
        out = np.asarray(fn(vs, ps))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_beam_length_penalty_normalizes_generated_length(rng):
    """ADVICE r5 (reverting the r4 change): with length_penalty=1 and no
    EOS the returned score must be sum-logprob / gen_len — transformers
    >= 4.36 normalizes by GENERATED length only (BeamSearchScorer divides
    by cur_len + 1 - decoder_prompt_len; prompt excluded)."""
    from apex_tpu.models.generation import generate_beam

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    s0, t = 4, 3
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, s0)), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), prompt)

    seqs, scores = generate_beam(model, v, prompt, max_new_tokens=t,
                                 num_beams=2, length_penalty=1.0)
    seqs, scores = np.asarray(seqs), np.asarray(scores)
    for j in range(2):
        ids = seqs[0, j]
        logits = np.asarray(model.apply(v, jnp.asarray(ids[None])),
                            np.float32)[0]
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        raw = sum(logp[s0 - 1 + k, ids[s0 + k]] for k in range(t))
        np.testing.assert_allclose(scores[0, j], raw / t,
                                   rtol=2e-4, atol=2e-4)
