"""gradient_accumulation_fusion: fp32 wgrad GEMM + persistent fp32
main-grad buffer (VERDICT round-1 item 8; reference:
csrc/megatron/fused_weight_gradient_dense.cpp)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS


def test_fp32_wgrad_matmul_matches_and_accumulates_fp32(rng):
    from apex_tpu.transformer.tensor_parallel.layers import fp32_wgrad_matmul

    x = jnp.asarray(rng.standard_normal((4, 8, 16)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)

    y = fp32_wgrad_matmul(x, w)
    y_ref = x @ w.astype(jnp.bfloat16).T
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(y_ref, np.float32))

    def loss(w):
        return jnp.sum(fp32_wgrad_matmul(x, w).astype(jnp.float32) ** 2)

    dw = jax.grad(loss)(w)
    assert dw.dtype == jnp.float32
    # reference value: fp32 computation throughout
    xf = np.asarray(x, np.float32).reshape(-1, 16)
    g = 2.0 * (xf @ np.asarray(w).T.astype(np.float32))
    # forward ran in bf16, so g from bf16 y; recompute with bf16 fwd
    yf = np.asarray(x @ w.astype(jnp.bfloat16).T, np.float32).reshape(-1, 32)
    dw_ref = (2.0 * yf).T @ xf
    np.testing.assert_allclose(np.asarray(dw), dw_ref, rtol=2e-2, atol=1e-2)


def test_tp_linear_flag_no_longer_ignored(rng):
    """With the flag on, grads must match the unfused path (numerics) while
    the wgrad is computed by the fp32 custom vjp."""
    from apex_tpu.transformer.tensor_parallel import ColumnParallelLinear

    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    lin_off = ColumnParallelLinear(16, 32, world_size=1,
                                   gradient_accumulation_fusion=False)
    lin_on = ColumnParallelLinear(16, 32, world_size=1,
                                  gradient_accumulation_fusion=True)
    p = lin_off.init(jax.random.PRNGKey(0), x)

    g_off = jax.grad(lambda v: jnp.sum(lin_off.apply(v, x) ** 2))(p)
    g_on = jax.grad(lambda v: jnp.sum(lin_on.apply(v, x) ** 2))(p)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5), g_off, g_on)


def test_main_grad_buffer_fp32_accumulation(rng):
    """Sum of bf16 microbatch grads accumulated in fp32 == fp32 sum (and
    != the bf16 running sum when magnitudes differ)."""
    from apex_tpu.optimizers.grad_accum import MainGradBuffer

    params = {"w": jnp.zeros((64, 64), jnp.float32),
              "b": jnp.zeros((100,), jnp.float32)}
    buf = MainGradBuffer(params)
    micro = []
    for i in range(8):
        g = {"w": jnp.asarray(rng.standard_normal((64, 64)) * 10.0 ** (-i),
                              jnp.bfloat16),
             "b": jnp.asarray(rng.standard_normal((100,)), jnp.bfloat16)}
        micro.append(g)
        buf.accumulate(g)

    total = buf.grads(mean=False)
    ref = {k: np.sum([np.asarray(g[k], np.float32) for g in micro], axis=0)
           for k in params}
    for k in params:
        np.testing.assert_allclose(np.asarray(total[k]), ref[k],
                                   rtol=1e-6, atol=1e-6)
    mean = buf.grads(mean=True)
    np.testing.assert_allclose(np.asarray(mean["w"]), ref["w"] / 8,
                               rtol=1e-6, atol=1e-7)
    buf.zero()
    assert buf.num_accumulated == 0
    assert float(jnp.abs(buf.buf).sum()) == 0.0


def test_grad_accum_feeds_fused_optimizer(rng):
    """End-to-end: accumulate microbatch grads, step FusedAdam on the mean —
    matches stepping on the directly-computed mean grad."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.optimizers.grad_accum import MainGradBuffer

    params = {"w": jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)}
    micro = [{"w": jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)}
             for _ in range(4)]
    mean_g = {"w": jnp.stack([g["w"] for g in micro]).mean(0)}

    opt_a = FusedAdam(params, lr=1e-2)
    p_ref = opt_a.step(mean_g)

    opt_b = FusedAdam(params, lr=1e-2)
    buf = MainGradBuffer(params)
    for g in micro:
        buf.accumulate(g)
    p_acc = opt_b.step(buf.grads())
    np.testing.assert_allclose(np.asarray(p_acc["w"]), np.asarray(p_ref["w"]),
                               rtol=1e-6, atol=1e-7)
