"""Recompile watcher (apex_tpu/obs/compile_watch.py).

The load-bearing scenario is the seeded recompile storm: a jitted
function called at shape-varying arguments must show up in the watcher's
per-name compile counts, trip ``storms()``, and — through the serving
frontend — land a ``compile_storm`` warning event in the engine's
postmortem ring. Install/uninstall must leave jax's internals exactly as
found.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.obs import compile_watch
from apex_tpu.utils import metrics


@pytest.fixture
def fresh_watcher():
    """An isolated watcher: the process-wide one (installed by any
    earlier test that built a ServingFrontend) is parked for the
    duration so its listener cannot double-count these tests' events."""
    proc = compile_watch._PROCESS_WATCHER
    if proc is not None:
        proc.uninstall()
    w = compile_watch.CompileWatcher().install()
    yield w
    w.uninstall()
    if proc is not None:
        proc.install()


def _storm(n=4, name="storm_fn"):
    def storm_fn(x):
        return x * 2 + 1
    storm_fn.__name__ = name
    jf = jax.jit(storm_fn)
    for i in range(1, n + 1):
        jf(jnp.zeros((i,)))            # every shape = retrace + compile


def test_seeded_recompile_storm_counted_and_detected(fresh_watcher):
    w = fresh_watcher
    base = w.counts()
    _storm(4, "storm_a")
    counts = w.counts()
    key = "jit(storm_a)"
    assert counts.get(key, 0) - base.get(key, 0) == 4
    assert w.trace_misses().get("storm_a", 0) >= 4
    storms = w.storms(base, threshold=3)
    assert key in storms and storms[key] == 4
    # below threshold: quiet
    assert key not in w.storms(w.counts(), threshold=1)


def test_instruments_keyed_by_function_name(fresh_watcher):
    _storm(2, "storm_b")
    snap = metrics.snapshot()
    compiles = {tuple(sorted(c["labels"].items())): c["value"]
                for c in snap["counters"] if c["name"] == "jit.compiles"}
    assert compiles[(("fn", "jit(storm_b)"),)] == 2.0
    hists = {tuple(sorted(h["labels"].items())): h
             for h in snap["histograms"]
             if h["name"] == "jit.compile_ms"}
    h = hists[(("fn", "jit(storm_b)"),)]
    assert h["count"] == 2 and h["sum"] > 0
    traces = {tuple(sorted(c["labels"].items())): c["value"]
              for c in snap["counters"]
              if c["name"] == "jit.trace_cache_misses"}
    assert traces[(("fn", "storm_b"),)] == 2.0


def test_totals_and_repeat_calls_do_not_recount(fresh_watcher):
    w = fresh_watcher
    c0, t0 = w.totals()

    def once(x):
        return x + 1

    jf = jax.jit(once)
    for _ in range(5):
        jf(jnp.ones((3,)))             # one compile, four cache hits
    c1, t1 = w.totals()
    assert c1 - c0 >= 1
    counts = w.counts()
    assert counts.get("jit(once)", 0) == 1


def test_fallback_mode_without_monitoring(monkeypatch, fresh_watcher):
    """With the jax.monitoring listener unavailable, the wrapped
    lowering timer alone must keep the counters fed (degraded
    durations, same instruments)."""
    fresh_watcher.uninstall()
    w = compile_watch.CompileWatcher()
    # simulate a jax without monitoring: the register call raises
    monkeypatch.setattr(
        "jax.monitoring.register_event_duration_secs_listener",
        lambda cb: (_ for _ in ()).throw(RuntimeError("no monitoring")))
    w.install()
    try:
        assert not w._listener_active
        _storm(3, "storm_c")
        assert w.counts().get("jit(storm_c)", 0) == 3
        assert metrics.counter(
            "jit.compiles", labels={"fn": "jit(storm_c)"}).value == 3
    finally:
        w.uninstall()


def test_install_uninstall_restore_jax_hooks():
    from jax._src import dispatch, monitoring

    orig = dispatch.log_elapsed_time
    n_listeners = len(monitoring.get_event_duration_listeners())
    w = compile_watch.CompileWatcher().install()
    assert dispatch.log_elapsed_time is not orig
    assert len(monitoring.get_event_duration_listeners()) \
        == n_listeners + 1
    w.install()                        # idempotent
    assert len(monitoring.get_event_duration_listeners()) \
        == n_listeners + 1
    w.uninstall()
    assert dispatch.log_elapsed_time is orig
    assert len(monitoring.get_event_duration_listeners()) == n_listeners
    w.uninstall()                      # idempotent


def test_process_watcher_is_shared():
    assert compile_watch.watcher() is compile_watch.watcher()


def test_frontend_emits_compile_storm_event(monkeypatch, rng):
    """A storm during a frontend's lifetime lands a compile_storm
    warning in the engine's event ring, once per function name."""
    from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
    from apex_tpu.serving import PagedDecodeEngine, Request
    from apex_tpu.serving.frontend import ServingFrontend

    monkeypatch.setattr(compile_watch, "DEFAULT_STORM_THRESHOLD", 3)
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8)
    fe = ServingFrontend(engine)
    _storm(4, "storm_d")               # the "recompiling op" stand-in
    h = fe.submit(Request(
        prompt=rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32),
        max_new_tokens=3))
    fe.drain()
    h.result(timeout=0)
    storms = [e for e in engine.events.tail()
              if e["kind"] == "compile_storm"]
    assert any(e["fn"] == "jit(storm_d)" for e in storms)
    # once per name, not once per pump iteration
    assert len([e for e in storms if e["fn"] == "jit(storm_d)"]) == 1
