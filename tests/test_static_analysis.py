"""tpu-lint (apex_tpu.analysis) coverage.

Three layers, matching ISSUE 3's acceptance criteria:

1. fixture pairs — per rule, a bad snippet that triggers EXACTLY that
   rule and a good twin that is clean. Running the bad fixture with the
   rule deselected must also be clean, so every rule is individually
   load-bearing (deleting one makes precisely its fixture pass).
2. machinery — inline suppressions, the baseline workflow, the JSON
   format, exit codes, the AOT case-drift project rule.
3. end-to-end — the repo itself is clean at the current baseline: the
   tier-1 twin of the ``run_tpu_round.sh`` fail-fast gate.
"""

import json
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.analysis import cli                              # noqa: E402
from apex_tpu.analysis.rules import RULES, module_rules        # noqa: E402

# --------------------------------------------------------------------------
# per-rule fixture pairs
# --------------------------------------------------------------------------

_PALLAS_HEADER = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
"""


def _pallas(body):
    return _PALLAS_HEADER + textwrap.dedent(body)

FIXTURES = {
    "host-sync-in-jit": (
        """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return float(x) + np.asarray(x).sum()
        """,
        """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.asarray(x).sum() + x
        """,
    ),
    "pallas-index-map-arity": (
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4, 2),
                in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
    ),
    "pallas-block-tiling": (
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((7, 100), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((16, 256), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 1), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
    ),
    "pallas-dtype-drift": (
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, jnp.bfloat16),
            )(x)
        """),
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
    ),
    "pallas-traced-branch": (
        _pallas("""
        def kernel(x_ref, o_ref):
            if x_ref[0, 0] > 0:
                o_ref[...] = x_ref[...]

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
        _pallas("""
        def kernel(x_ref, o_ref):
            o_ref[...] = jnp.where(x_ref[...] > 0, x_ref[...], 0.0)

        def call(x):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
        """),
    ),
    "jit-unhashable-static": (
        """\
        import jax

        def f(cfg, x):
            return x

        g = jax.jit(f, static_argnums=(0,))

        def run(x):
            return g({"mode": "fast"}, x)
        """,
        """\
        import jax

        def f(cfg, x):
            return x

        g = jax.jit(f, static_argnums=(0,))
        CFG = ("mode", "fast")

        def run(x):
            return g(CFG, x)
        """,
    ),
    "compile-key-unbounded": (
        """\
        import jax

        _step_jit = {}

        def get_step(fn, seq_len):
            if f"s{seq_len}" not in _step_jit:
                _step_jit[f"s{seq_len}"] = jax.jit(fn)
            return _step_jit[f"s{seq_len}"]
        """,
        """\
        import jax

        _step_jit = {}

        def get_step(fn, seq_len):
            bucket = 1 << (seq_len - 1).bit_length()
            if bucket not in _step_jit:
                _step_jit[bucket] = jax.jit(fn)
            return _step_jit[bucket]
        """,
    ),
    "jit-donated-reuse": (
        """\
        import jax

        def f(buf):
            return buf + 1

        g = jax.jit(f, donate_argnums=(0,))

        def run(buf):
            out = g(buf)
            return out + buf.sum()
        """,
        """\
        import jax

        def f(buf):
            return buf + 1

        g = jax.jit(f, donate_argnums=(0,))

        def run(buf):
            buf = g(buf)
            return buf + buf.sum()
        """,
    ),
}


def _run_on(tmp_path, source, select=None):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(source))
    findings, suppressed = cli.analyze_paths(
        [str(f)], root=tmp_path, select=select, with_project_rules=False)
    return findings, suppressed


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_bad_fixture_triggers_exactly_its_rule(rule, tmp_path):
    bad, _ = FIXTURES[rule]
    findings, _ = _run_on(tmp_path, bad)
    assert findings, f"bad fixture for {rule} produced no findings"
    assert {f.rule for f in findings} == {rule}, [
        (f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_good_fixture_is_clean(rule, tmp_path):
    _, good = FIXTURES[rule]
    findings, _ = _run_on(tmp_path, good)
    assert not findings, [(f.rule, f.line, f.message) for f in findings]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rules_individually_load_bearing(rule, tmp_path):
    """With the rule deselected (≈ deleted), its bad fixture passes:
    no other rule shadows it."""
    bad, _ = FIXTURES[rule]
    others = [r for r in RULES if r != rule]
    findings, _ = _run_on(tmp_path, bad, select=others)
    assert not findings, [(f.rule, f.line, f.message) for f in findings]


def test_every_module_rule_has_a_fixture():
    assert {r.name for r in module_rules()} == set(FIXTURES)


# --------------------------------------------------------------------------
# suppression + baseline machinery
# --------------------------------------------------------------------------

def test_inline_suppression_same_line(tmp_path):
    bad, _ = FIXTURES["host-sync-in-jit"]
    src = bad.replace(
        "return float(x) + np.asarray(x).sum()",
        "return float(x) + np.asarray(x).sum()  "
        "# tpu-lint: disable=host-sync-in-jit -- test justification")
    findings, suppressed = _run_on(tmp_path, src)
    assert not findings
    assert suppressed == 2      # float() and np.asarray on the same line


def test_inline_suppression_comment_line_above(tmp_path):
    bad, _ = FIXTURES["host-sync-in-jit"]
    src = bad.replace(
        "            return float(x) + np.asarray(x).sum()",
        "            # tpu-lint: disable=host-sync-in-jit\n"
        "            return float(x) + np.asarray(x).sum()")
    findings, _ = _run_on(tmp_path, src)
    assert not findings


def test_suppression_of_other_rule_does_not_apply(tmp_path):
    bad, _ = FIXTURES["host-sync-in-jit"]
    src = bad.replace(
        "return float(x) + np.asarray(x).sum()",
        "return float(x) + np.asarray(x).sum()  "
        "# tpu-lint: disable=pallas-block-tiling")
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_baseline_workflow(tmp_path, capsys):
    bad, _ = FIXTURES["jit-donated-reuse"]
    f = tmp_path / "legacy.py"
    f.write_text(textwrap.dedent(bad))
    args = [str(f), "--root", str(tmp_path)]

    assert cli.main(args) == 1
    assert cli.main(args + ["--write-baseline"]) == 0
    assert (tmp_path / "tpu_lint_baseline.json").exists()
    # baselined finding no longer fails the run ...
    assert cli.main(args) == 0
    # ... but a NEW finding of the same rule in another scope does
    f.write_text(textwrap.dedent(bad) + textwrap.dedent("""
        def run2(buf):
            out = g(buf)
            return out + buf.sum()
    """))
    capsys.readouterr()
    assert cli.main(args) == 1
    out = capsys.readouterr().out
    assert "run2" in out or "jit-donated-reuse" in out


def test_json_format(tmp_path, capsys):
    bad, _ = FIXTURES["pallas-dtype-drift"]
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(bad))
    rc = cli.main([str(f), "--root", str(tmp_path), "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["counts"]["new"] == 1
    (finding,) = data["findings"]
    assert finding["rule"] == "pallas-dtype-drift"
    assert finding["path"].endswith("snippet.py")
    assert finding["line"] > 0


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def oops(:\n")
    findings, _ = cli.analyze_paths([str(f)], root=tmp_path,
                                    with_project_rules=False)
    assert [f.rule for f in findings] == ["parse-error"]


def test_unknown_rule_is_usage_error(tmp_path, capsys):
    assert cli.main(["--root", str(tmp_path),
                     "--select", "no-such-rule"]) == 2


def test_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in RULES:
        assert name in out


# --------------------------------------------------------------------------
# aot-case-drift project rule
# --------------------------------------------------------------------------

_AOT_STUB = """\
def kernel_cases():
    yield ("layer_norm_bwd", None, [])
    yield ("flash_bwd_seq512", None, [])
"""


def _drift_tree(tmp_path, case_names):
    (tmp_path / "tpu_aot.py").write_text(_AOT_STUB)
    tests = tmp_path / "tests"
    tests.mkdir()
    names = ", ".join(repr(n) for n in case_names)
    (tests / "test_aot_mosaic.py").write_text(f"CASE_NAMES = [{names}]\n")


def test_aot_case_drift_detects_stale_name(tmp_path):
    _drift_tree(tmp_path, ["layer_norm_bwd", "renamed_case"])
    findings, _ = cli.analyze_paths([], root=tmp_path,
                                    select=["aot-case-drift"])
    assert len(findings) == 1
    assert "renamed_case" in findings[0].message


def test_aot_case_drift_clean_when_in_sync(tmp_path):
    _drift_tree(tmp_path, ["layer_norm_bwd", "flash_bwd_seq512"])
    findings, _ = cli.analyze_paths([], root=tmp_path,
                                    select=["aot-case-drift"])
    assert not findings


# --------------------------------------------------------------------------
# end-to-end: the repo itself is clean (the run_tpu_round.sh gate, tier-1)
# --------------------------------------------------------------------------

def test_repo_is_clean_at_current_baseline(capsys):
    rc = cli.main(["--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, f"tpu-lint found new issues in the repo:\n{out}"


def test_repo_case_names_in_sync():
    """Direct tier-1 pin of the drift pair, independent of the CLI."""
    findings, _ = cli.analyze_paths([], root=REPO,
                                    select=["aot-case-drift"])
    assert not findings, [f.message for f in findings]


# --------------------------------------------------------------------------
# jit-entry marking regressions (code-review repros)
# --------------------------------------------------------------------------

def test_switch_branch_list_is_traced(tmp_path):
    """lax.switch branches arrive as ONE list argument; each element is a
    traced body and must be reachable for the host-sync rule."""
    src = """\
        import jax
        import numpy as np
        from jax import lax

        def branch_a(x):
            return np.asarray(x).sum()

        def branch_b(x):
            return x

        @jax.jit
        def step(i, x):
            return lax.switch(i, [branch_a, branch_b], x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}
    assert any("branch_a" in f.message for f in findings)


def test_cond_operand_is_not_marked_traced(tmp_path):
    """cond(pred, true_fun, false_fun, *operands): an operand that happens
    to be a host-side function must NOT be marked as a traced body."""
    src = """\
        import jax
        import numpy as np
        from jax import lax

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(pred, v):
            return lax.cond(pred, lambda a: a + 1, lambda a: a, v)

        def host_drive(v):
            return helper(v)
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


# --------------------------------------------------------------------------
# host-sync exemption: jax.debug.callback / metrics.record (ISSUE 4)
# --------------------------------------------------------------------------
# The metrics channel (``metrics.record`` -> ``jax.debug.callback``) is
# non-blocking: the payload callable runs on the HOST with delivered
# values after the step executes. The good/bad pairs below prove the
# exemption covers exactly the callback's callable argument — the same
# host ops flagged everywhere else in jit-reachable code stay flagged.

_CB_GOOD = """\
    import jax
    import numpy as np

    def _emit(v):
        return float(np.asarray(v).sum())

    @jax.jit
    def step(x):
        jax.debug.callback(_emit, x)
        return x + 1
"""


def test_debug_callback_payload_is_exempt(tmp_path):
    """A module-level callback full of host ops, reachable ONLY through
    jax.debug.callback, is clean — instrumented jit code stays
    lint-clean."""
    findings, _ = _run_on(tmp_path, _CB_GOOD)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_debug_callback_inline_lambda_is_exempt(tmp_path):
    src = """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            jax.debug.callback(lambda v: np.asarray(v).sum(), x)
            return x + 1
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_debug_callback_exemption_is_narrow_direct_call(tmp_path):
    """The SAME callback also called directly from the jitted body is
    genuinely jit-reachable — still flagged."""
    src = _CB_GOOD.replace("return x + 1", "_emit(x)\n        return x + 1")
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}
    assert any("_emit" in f.message for f in findings)


def test_debug_callback_exemption_is_narrow_operand(tmp_path):
    """Only the CALLABLE argument is exempt: a host materialization in
    the callback's traced-operand position is a real trace-time hazard
    and stays flagged."""
    src = """\
        import jax
        import numpy as np

        def _emit(v):
            return v

        @jax.jit
        def step(x):
            jax.debug.callback(_emit, np.asarray(x))
            return x + 1
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_debug_callback_partial_callable_is_exempt(tmp_path):
    """functools.partial(fn, static) as the callback is the prescribed
    record() pattern — the partial's CALLABLE is exempt."""
    src = """\
        import functools
        import jax
        import numpy as np

        def _emit(tag, v):
            return float(np.asarray(v).sum())

        @jax.jit
        def step(x):
            jax.debug.callback(functools.partial(_emit, "loss"), x)
            return x + 1
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_debug_callback_partial_operand_stays_flagged(tmp_path):
    """partial OPERANDS evaluate at trace time — `.item()` there is a
    genuine sync and must not ride the exemption."""
    src = """\
        import functools
        import jax

        def _emit(tag, v):
            return v

        @jax.jit
        def step(x):
            jax.debug.callback(functools.partial(_emit, x.item()), x)
            return x + 1
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}
    assert any("item" in f.message for f in findings)


def test_debug_callback_factory_call_is_not_exempt(tmp_path):
    """A FACTORY call in the callable position runs at trace time —
    nothing about it is exempt, including the call itself: its callee
    stays jit-reachable and its internals stay scrutinized."""
    src = """\
        import jax

        def make_cb(x):
            x.item()
            return print

        @jax.jit
        def step(x):
            jax.debug.callback(make_cb(x), x)
            return x + 1
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}
    assert any("make_cb" in f.message for f in findings)


def test_metrics_record_in_scan_body_is_clean(tmp_path):
    """The prescribed instrumentation pattern — metrics.record on a
    traced scalar inside a scan body — lints clean end to end."""
    src = """\
        import jax
        import jax.numpy as jnp
        from jax import lax
        from apex_tpu.utils import metrics

        @jax.jit
        def run(x):
            def body(c, t):
                metrics.record("loss", c)
                return c + t, c
            return lax.scan(body, x, jnp.arange(4.0))[0]
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


# --------------------------------------------------------------------------
# suppression-parsing / baseline-write hardening (code-review repros)
# --------------------------------------------------------------------------

def test_justification_comma_does_not_leak_rules(tmp_path):
    """'disable=<other-rule> -- wrong rule, all good here' must not parse
    the prose token 'all' as a disable-everything suppression."""
    bad, _ = FIXTURES["host-sync-in-jit"]
    src = bad.replace(
        "return float(x) + np.asarray(x).sum()",
        "return float(x) + np.asarray(x).sum()  "
        "# tpu-lint: disable=pallas-block-tiling -- wrong rule, all good here")
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_pragma_inside_string_literal_is_inert(tmp_path):
    bad, _ = FIXTURES["host-sync-in-jit"]
    src = bad.replace(
        "return float(x) + np.asarray(x).sum()",
        'doc = "example: # tpu-lint: disable=all"\n'
        "            return float(x) + np.asarray(x).sum()")
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_write_baseline_refuses_select(tmp_path, capsys):
    assert cli.main(["--root", str(tmp_path), "--select",
                     "host-sync-in-jit", "--write-baseline"]) == 2


def test_scoped_write_baseline_keeps_other_files(tmp_path):
    """--write-baseline over one file must not erase another file's
    baselined legacy findings."""
    bad, _ = FIXTURES["jit-donated-reuse"]
    a = tmp_path / "legacy_a.py"
    b = tmp_path / "legacy_b.py"
    a.write_text(textwrap.dedent(bad))
    b.write_text(textwrap.dedent(bad))
    # baseline both, then re-write scoped to b only
    assert cli.main([str(a), str(b), "--root", str(tmp_path),
                     "--write-baseline"]) == 0
    assert cli.main([str(b), "--root", str(tmp_path),
                     "--write-baseline"]) == 0
    # a's legacy entry survived the scoped write
    assert cli.main([str(a), str(b), "--root", str(tmp_path)]) == 0


# --------------------------------------------------------------------------
# numpy scalar-constructor coercions (ISSUE 5 satellite)
# --------------------------------------------------------------------------

def test_np_scalar_cast_on_traced_param_is_flagged(tmp_path):
    src = """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.float32(x) + np.int32(x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert [f.rule for f in findings] == ["host-sync-in-jit"] * 2
    assert any("np.float32(x)" in f.message for f in findings)


def test_np_array_of_traced_param_is_flagged(tmp_path):
    src = """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.array(x).sum()
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_np_scalar_cast_of_literal_is_clean(tmp_path):
    """Precision: np.float32(0.5) on a CONSTANT in jitted code is a
    plain host scalar, not a sync."""
    src = """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x * np.float32(0.5)
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


# --------------------------------------------------------------------------
# report ordering (ISSUE 5 satellite)
# --------------------------------------------------------------------------

def test_text_report_sorted_by_path_line_rule_with_severity():
    from apex_tpu.analysis import report
    from apex_tpu.analysis.walker import Finding

    def f(path, line, rule, severity="error", col=1):
        return Finding(rule=rule, severity=severity, path=path,
                       line=line, col=col, message="m")

    out = report.render_text(
        [f("b.py", 3, "zz-rule"), f("a.py", 9, "b-rule"),
         f("a.py", 9, "a-rule", col=30), f("a.py", 2, "z-rule",
                                           severity="warning")],
        [f("a.py", 5, "old-rule", severity="warning")], 0,
        show_baselined=True)
    lines = out.splitlines()
    assert lines[0].startswith("a.py:2:")       # line beats rule name
    assert lines[1].startswith("a.py:9:30: [a-rule]")  # rule beats col
    assert lines[2].startswith("a.py:9:1: [b-rule]")
    assert lines[3].startswith("b.py:3:")
    assert "warning (baselined):" in lines[4]   # severity on baselined
    assert "error:" in lines[1]


# --------------------------------------------------------------------------
# interprocedural call graph (ISSUE 5 tentpole, part B)
# --------------------------------------------------------------------------

def _pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    findings, suppressed = cli.analyze_paths(
        [str(pkg)], root=tmp_path, with_project_rules=False)
    return findings, suppressed


_XMOD_UTILS = """\
    import numpy as np

    def norm(x):
        return np.asarray(x).sum()

    def host_only(x):
        return np.asarray(x)
"""


def test_host_sync_seen_through_imported_helper(tmp_path):
    """A utils/ helper full of host ops, called from a jitted scan body
    in ANOTHER module, is flagged — with the cross-module chain in the
    message. Its host-only sibling stays clean."""
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "from pkg.helpers import norm\n",
        "helpers.py": _XMOD_UTILS,
        "main.py": """\
            import jax
            from jax import lax
            from pkg import norm
            from pkg.helpers import host_only

            @jax.jit
            def step(x):
                def body(c, _):
                    return c + norm(c), None
                return lax.scan(body, x, None, length=4)

            def host_drive(x):
                return host_only(x)
        """,
    })
    assert [f.rule for f in findings] == ["host-sync-in-jit"]
    assert findings[0].path.endswith("helpers.py")
    assert findings[0].scope == "norm"
    assert "main.py" in findings[0].message


def test_jit_of_imported_function_marks_it(tmp_path):
    """jax.jit(mod.fn) marks fn in its HOME module (the scheduler's
    ``jax.jit(kv_pool.free_slot)`` pattern)."""
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "",
        "pool.py": """\
            import numpy as np

            def free_slot(cache, slot):
                return np.asarray(cache)
        """,
        "engine.py": """\
            import jax
            from pkg import pool

            _free = jax.jit(pool.free_slot)
        """,
    })
    assert [f.rule for f in findings] == ["host-sync-in-jit"]
    assert findings[0].path.endswith("pool.py")


def test_unreached_import_is_clean(tmp_path):
    """Importing a host-op-heavy module does NOT taint it: only real
    call edges from jit entries do."""
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "",
        "helpers.py": _XMOD_UTILS,
        "main.py": """\
            import jax
            from pkg.helpers import norm

            @jax.jit
            def step(x):
                return x + 1

            def host_drive(x):
                return norm(x)
        """,
    })
    assert not findings, [(f.rule, f.path, f.message) for f in findings]


def test_reexport_chain_is_followed(tmp_path):
    """__init__ re-exports resolve one more hop (the serving package's
    ``from pkg import helper`` style)."""
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "from pkg.impl import helper\n",
        "impl.py": """\
            import numpy as np

            def helper(x):
                return float(np.asarray(x).sum())
        """,
        "main.py": """\
            import jax
            from pkg import helper

            @jax.jit
            def step(x):
                return helper(x)
        """,
    })
    assert {f.rule for f in findings} == {"host-sync-in-jit"}
    assert {f.path.split("/")[-1] for f in findings} == {"impl.py"}


def test_imported_donated_wrapper_tracked(tmp_path):
    """jit-donated-reuse sees a wrapper IMPORTED from another module:
    the home module's donate_argnums travel with the name."""
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "",
        "kernels.py": """\
            import jax

            def _upd(buf):
                return buf + 1

            fused_update = jax.jit(_upd, donate_argnums=(0,))
        """,
        "train.py": """\
            from pkg.kernels import fused_update

            def run(buf):
                out = fused_update(buf)
                return out + buf.sum()
        """,
    })
    assert [f.rule for f in findings] == ["jit-donated-reuse"]
    assert findings[0].path.endswith("train.py")


def test_imported_wrapper_rebind_is_clean(tmp_path):
    findings, _ = _pkg(tmp_path, {
        "__init__.py": "",
        "kernels.py": """\
            import jax

            def _upd(buf):
                return buf + 1

            fused_update = jax.jit(_upd, donate_argnums=(0,))
        """,
        "train.py": """\
            from pkg.kernels import fused_update

            def run(buf):
                buf = fused_update(buf)
                return buf + buf.sum()
        """,
    })
    assert not findings, [(f.rule, f.message) for f in findings]


# --------------------------------------------------------------------------
# host-boundary pragma
# --------------------------------------------------------------------------

def test_host_boundary_cuts_reachability(tmp_path):
    """A declared host boundary (the engine's generate_paged pattern):
    host ops below it are host code, not jit-reachable."""
    src = """\
        import jax
        import numpy as np

        # tpu-lint: host-boundary -- drives jitted programs from the host
        def drive(x):
            return np.asarray(x).sum()

        @jax.jit
        def step(x):
            return drive(x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_without_host_boundary_same_code_is_flagged(tmp_path):
    src = """\
        import jax
        import numpy as np

        def drive(x):
            return np.asarray(x).sum()

        @jax.jit
        def step(x):
            return drive(x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert {f.rule for f in findings} == {"host-sync-in-jit"}


def test_host_boundary_pragma_in_comment_block(tmp_path):
    """The pragma may sit anywhere in the comment block directly above
    the def (real-world blocks wrap justifications over lines)."""
    src = """\
        import jax
        import numpy as np

        # this is the serving engine's host loop, and the pragma below
        # tpu-lint: host-boundary -- declared never-traced
        # (more prose after it is fine too)
        def drive(x):
            return np.asarray(x).sum()

        @jax.jit
        def step(x):
            return drive(x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


# --------------------------------------------------------------------------
# --diff mode (ISSUE 5 satellite)
# --------------------------------------------------------------------------

import subprocess  # noqa: E402


def _git(cwd, *args):
    subprocess.run(["git", "-C", str(cwd), *args], check=True,
                   capture_output=True)


_DIFF_LEGACY = """\
import jax
import numpy as np

@jax.jit
def old_step(x):
    return np.asarray(x).sum()
"""


def _diff_repo(tmp_path):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    (tmp_path / "apex_tpu").mkdir()
    (tmp_path / "apex_tpu" / "legacy.py").write_text(_DIFF_LEGACY)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "base")


def test_diff_mode_ignores_preexisting_findings(tmp_path, capsys):
    _diff_repo(tmp_path)
    assert cli.main(["--root", str(tmp_path)]) == 1       # absolute: dirty
    assert cli.main(["--root", str(tmp_path),
                     "--diff", "HEAD"]) == 0              # diff: clean


def test_diff_mode_fails_on_introduced_finding(tmp_path, capsys):
    _diff_repo(tmp_path)
    (tmp_path / "apex_tpu" / "fresh.py").write_text(_DIFF_LEGACY)
    capsys.readouterr()
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD"]) == 1
    out = capsys.readouterr().out
    assert "fresh.py" in out
    assert "legacy.py" not in out.split("NEW relative")[0]


def test_diff_mode_new_finding_in_old_scope_fails(tmp_path, capsys):
    """A SECOND finding of the same rule in the same function exceeds
    the base count and fails, mirroring baseline semantics."""
    _diff_repo(tmp_path)
    (tmp_path / "apex_tpu" / "legacy.py").write_text(
        _DIFF_LEGACY.replace(
            "return np.asarray(x).sum()",
            "return np.asarray(x).sum() + float(x)"))
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD"]) == 1


def test_diff_mode_bad_rev_is_usage_error(tmp_path, capsys):
    _diff_repo(tmp_path)
    assert cli.main(["--root", str(tmp_path),
                     "--diff", "no-such-rev"]) == 2


def test_host_boundary_on_decorated_def(tmp_path):
    """The pragma must attach through a decorator stack (the header
    span starts at the first decorator, not the def line)."""
    src = """\
        import functools
        import jax
        import numpy as np

        def deco(f):
            return f

        # tpu-lint: host-boundary -- host driver, wrapped for logging
        @deco
        @functools.wraps(print)
        def drive(x):
            return np.asarray(x).sum()

        @jax.jit
        def step(x):
            return drive(x)
    """
    findings, _ = _run_on(tmp_path, src)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_diff_refuses_baseline_flags(tmp_path, capsys):
    _diff_repo(tmp_path)
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD",
                     "--write-baseline"]) == 2
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD",
                     "--baseline", "x.json"]) == 2


def test_diff_refuses_explicit_paths(tmp_path, capsys):
    """The base side always lints the default surface; explicit paths
    would misreport off-surface pre-existing findings as new."""
    _diff_repo(tmp_path)
    assert cli.main([str(tmp_path / "apex_tpu" / "legacy.py"),
                     "--root", str(tmp_path), "--diff", "HEAD"]) == 2
