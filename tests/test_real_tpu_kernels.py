"""On-chip (Mosaic-compiled) Pallas kernel suite at bench-relevant shapes.

VERDICT round-1 weakness 4: all CPU tests run the kernels in interpret
mode, which validates numerics but not Mosaic compilation, layouts, or
VMEM limits — the bug class that bit on-chip in round 1 (M5 VMEM fixes).
This suite runs ONLY with ``APEX_TPU_REAL=1`` on a real TPU backend and
compiles every Pallas kernel at the flagship benchmark's shapes
(seq 512, hidden 1024, vocab 30528, BERT-Large-sized flat buffers),
asserting parity against pure-jnp references computed on the same chip.

    APEX_TPU_REAL=1 python -m pytest tests/test_real_tpu_kernels.py -v \
        2>&1 | tee TPU_TESTS_r02.log
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("APEX_TPU_REAL") != "1",
    reason="real-TPU kernel suite (set APEX_TPU_REAL=1 on a TPU host)")


@pytest.fixture(scope="module")
def tpu():
    dev = jax.devices()[0]
    assert dev.platform != "cpu", (
        "APEX_TPU_REAL=1 but the backend is CPU — kernels would run "
        "interpreted and prove nothing")
    return dev


@pytest.fixture
def rng():
    return np.random.default_rng(0)


SEQ, HIDDEN, VOCAB = 512, 1024, 30528


def test_layer_norm_fwd_bwd_bench_shapes(tpu, rng):
    from apex_tpu.ops.layer_norm import layer_norm

    x = jnp.asarray(rng.standard_normal((8 * SEQ, HIDDEN)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((HIDDEN,)) * 0.1 + 1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((HIDDEN,)) * 0.1, jnp.float32)

    def ref(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-12) * g + b

    y = jax.jit(lambda x: layer_norm(x, g, b, eps=1e-12))(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref(x, g, b)),
                               rtol=2e-4, atol=2e-4)

    def loss_k(x, g, b):
        return jnp.sum(layer_norm(x, g, b, eps=1e-12) ** 2)

    def loss_r(x, g, b):
        return jnp.sum(ref(x, g, b) ** 2)

    gk = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2)))(x, g, b)
    gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(x, g, b)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-2)


def test_flash_attention_fwd_bwd_seq512(tpu, rng):
    from apex_tpu.ops import flash_attention

    b, h, d = 2, 16, 64
    q = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)

    def ref(q, k, v):
        s = (q.astype(jnp.float32) @ k.astype(jnp.float32).transpose(
            0, 1, 3, 2)) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return (p @ v.astype(jnp.float32)).astype(q.dtype)

    y = jax.jit(flash_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ref(q, k, v), np.float32),
                               rtol=5e-2, atol=5e-2)

    def loss_k(q, k, v):
        return jnp.sum(flash_attention(q, k, v).astype(jnp.float32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(ref(q, k, v).astype(jnp.float32) ** 2)

    gk = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(q, k, v)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(r, np.float32),
                                   rtol=1e-1, atol=1e-1)


def test_flash_attention_causal_and_dropout_compile(tpu, rng):
    from apex_tpu.ops import flash_attention

    b, h, d = 2, 8, 64
    q = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    y = jax.jit(lambda q: flash_attention(q, q, q, causal=True,
                                          dropout_rate=0.1,
                                          dropout_seed=7))(q)
    assert np.isfinite(np.asarray(y, np.float32)).all()
    # backward through in-kernel dropout must also compile
    g = jax.jit(jax.grad(lambda q: jnp.sum(
        flash_attention(q, q, q, causal=True, dropout_rate=0.1,
                        dropout_seed=7).astype(jnp.float32))))(q)
    assert np.isfinite(np.asarray(g, np.float32)).all()


def test_xentropy_vocab30528(tpu, rng):
    from apex_tpu.ops import softmax_cross_entropy

    n = 2 * SEQ
    logits = jnp.asarray(rng.standard_normal((n, VOCAB)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, VOCAB, (n,)), jnp.int32)

    out = jax.jit(lambda l: softmax_cross_entropy(l, labels))(logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ref = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    g = jax.jit(jax.grad(lambda l: softmax_cross_entropy(l, labels).sum()))(
        logits)
    gr = jax.jit(jax.grad(
        lambda l: (-jnp.take_along_axis(jax.nn.log_softmax(l, -1),
                                        labels[:, None], 1)[:, 0]).sum()))(
        logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=1e-3, atol=1e-4)


def test_scaled_masked_softmax_seq512(tpu, rng):
    from apex_tpu.ops.scaled_softmax import (
        scaled_upper_triang_masked_softmax)

    b, h = 4, 16
    # reference API: 3D (attn_batches, sq, sk) — apex ScaledUpperTriangMaskedSoftmax
    x = jnp.asarray(rng.standard_normal((b * h, SEQ, SEQ)), jnp.bfloat16)
    y = jax.jit(lambda x: scaled_upper_triang_masked_softmax(
        x, scale=0.125))(x)
    y32 = np.asarray(y, np.float32)
    np.testing.assert_allclose(y32.sum(-1), 1.0, rtol=2e-2, atol=2e-2)
    # causal: strictly-upper triangle is zero
    iu = np.triu_indices(SEQ, 1)
    assert np.abs(y32[..., iu[0], iu[1]]).max() < 1e-3


def test_fused_optimizer_kernels_bert_large_size(tpu, rng):
    """Adam + LAMB on a BERT-Large-sized flat buffer (~340M fp32 elems is
    too big for one CPU-style test; use ~32M rows-worth which still spans
    many row tiles and VMEM windows)."""
    from apex_tpu.ops import flat_buffer, optim_kernels

    params = {
        "emb": jnp.asarray(rng.standard_normal((VOCAB, 64)), jnp.float32),
        "w1": jnp.asarray(rng.standard_normal((HIDDEN, HIDDEN)), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((4 * HIDDEN, HIDDEN)),
                          jnp.float32),
        "b": jnp.asarray(rng.standard_normal((HIDDEN,)), jnp.float32),
    }
    spec = flat_buffer.build_spec(params)
    seg = jnp.asarray(spec.segment_rows())
    p = flat_buffer.flatten(params, spec)
    g = flat_buffer.flatten(
        jax.tree.map(lambda x: 0.01 * jnp.ones_like(x), params), spec)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    p2, m2, v2 = jax.jit(lambda g, p, m, v: optim_kernels.adam_update(
        g, p, m, v, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01,
        lr=1e-3, step=1))(g, p, m, v)
    assert np.isfinite(np.asarray(p2)).all()
    # adam step-1 with bias correction: update = g/(|g|+eps) + wd*p
    expect = np.asarray(p) - 1e-3 * (
        0.01 / (0.01 + 1e-8) + 0.01 * np.asarray(p))
    np.testing.assert_allclose(np.asarray(p2), expect, rtol=1e-4, atol=1e-5)

    pl_, ml_, vl_ = jax.jit(
        lambda g, p, m, v: optim_kernels.lamb_update(
            g, p, m, v, seg, spec.num_tensors, beta1=0.9, beta2=0.999,
            eps=1e-6, weight_decay=0.01, lr=1e-3, step=1))(g, p, m, v)
    assert np.isfinite(np.asarray(pl_)).all()

    gnorm, finite, _ = jax.jit(
        lambda g: optim_kernels.global_grad_norm_and_finite(
            g, seg, spec.num_tensors))(g)
    np.testing.assert_allclose(
        float(gnorm), 0.01 * np.sqrt(spec.total_elements), rtol=1e-3)
    assert bool(finite)


def test_group_norm_kernel_path(tpu, rng):
    from apex_tpu.ops.group_norm import group_norm_nhwc, group_norm_reference

    x = jnp.asarray(rng.standard_normal((4, 16, 16, 512)), jnp.bfloat16)
    w = jnp.ones((512,), jnp.float32)
    b = jnp.zeros((512,), jnp.float32)
    y = jax.jit(lambda x: group_norm_nhwc(x, w, b, 4, 1e-5, "silu"))(x)
    ref = group_norm_reference(x, w, b, 4, 1e-5, "silu")
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_bert_large_single_train_step(tpu, rng):
    """One full BERT-Large step on-chip: every kernel at exactly the bench
    shapes in one compiled program."""
    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)
    from apex_tpu.optimizers import FusedLAMB

    cfg = bert_large_config()
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, 2, SEQ)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    step = make_pretrain_step(model)
    opt = FusedLAMB(params, lr=1e-4, weight_decay=0.01)
    loss, grads = step(params, batch, 0)
    params = opt.step(grads)
    jax.block_until_ready(params)
    assert np.isfinite(float(loss))


def test_flash_attention_with_lse_on_chip(tpu, rng):
    """Round-3: the (o, lse) variant that ring attention composes — forward
    parity, and the backward with an lse cotangent (delta_adjust path)."""
    from apex_tpu.ops import flash_attention, flash_attention_with_lse

    b, h, d = 2, 8, 64
    q = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)

    o, lse = jax.jit(flash_attention_with_lse)(q, k, v)
    o_ref = jax.jit(flash_attention)(q, k, v)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_ref, np.float32),
                               rtol=2e-2, atol=2e-2)
    assert np.isfinite(np.asarray(lse)).all()

    def f(q):
        o, lse = flash_attention_with_lse(q, k, v)
        return jnp.sum(lse) + jnp.sum(o.astype(jnp.float32))

    g = jax.jit(jax.grad(f))(q)
    assert np.isfinite(np.asarray(g, np.float32)).all()


def test_group_norm_backward_kernel_path(tpu, rng):
    """Round-3: the Pallas GroupNorm backward (one-pass slab kernel) at a
    kernel-eligible diffusion shape, vs autodiff of the jnp reference."""
    from apex_tpu.ops.group_norm import group_norm_nhwc, group_norm_reference

    x = jnp.asarray(rng.standard_normal((2, 16, 16, 512)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((512,)) * 0.1 + 1.0, jnp.float32)
    b = jnp.asarray(rng.standard_normal((512,)) * 0.1, jnp.float32)

    gk = jax.jit(jax.grad(
        lambda *a: jnp.sum(group_norm_nhwc(*a, 4, 1e-5, "silu") ** 2),
        argnums=(0, 1, 2)))(x, w, b)
    gr = jax.jit(jax.grad(
        lambda *a: jnp.sum(group_norm_reference(*a, 4, 1e-5, "silu") ** 2),
        argnums=(0, 1, 2)))(x, w, b)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=3e-3, atol=3e-3)


def test_flash_attention_tight_head_dim(tpu, rng, monkeypatch):
    """Round-3 perf lever: tight head-dim keeps head_dim 64 unpadded (block
    minor dim = full array dim) instead of zero-padding to 128 — halving
    the QK^T/PV MXU work at BERT/GPT head shapes. This proves the layout
    compiles under Mosaic and matches the padded path in BOTH forward and
    backward."""
    from apex_tpu.ops import flash_attention

    b, h, d = 2, 8, 64
    q = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)

    def loss(q):
        return jnp.sum(flash_attention(q, k, v, causal=True
                                       ).astype(jnp.float32) ** 2)

    ref = jax.jit(functools.partial(flash_attention, causal=True))(q, k, v)
    g_ref = jax.jit(jax.grad(loss))(q)

    import importlib

    # NB: `import apex_tpu.ops.flash_attention` resolves to the FUNCTION
    # (ops/__init__ re-export shadows the submodule attribute)
    fa_impl = importlib.import_module("apex_tpu.ops.flash_attention")

    monkeypatch.setattr(fa_impl, "_TIGHT_HEADDIM", True)
    try:
        jax.clear_caches()
        out = jax.jit(functools.partial(flash_attention, causal=True))(q, k, v)
        g = jax.jit(jax.grad(loss))(q)
    finally:
        monkeypatch.setattr(fa_impl, "_TIGHT_HEADDIM", False)
        jax.clear_caches()
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(g_ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_moe_dense_dispatch_compiles(tpu, rng):
    """Round-3: the MoE dispatch/combine einsums + batched expert einsums
    (apex_tpu/transformer/moe/layer.py) compile and differentiate on-chip
    at a realistic token count. Single-chip => dense-dispatch path (the
    all_to_all EP path needs a multi-device axis and is covered by the
    CPU-mesh suite + dryrun)."""
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 1024, 4096, 8, 2, 2048
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=1.25, expert_world_size=1,
                   axis_name="nope")
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.bfloat16)
    v = layer.init(jax.random.PRNGKey(0), x)

    @jax.jit
    def loss_and_grad(p, xx):
        def f(pp):
            y, aux = layer.apply({"params": pp}, xx)
            return jnp.sum(y.astype(jnp.float32) ** 2) + aux.total
        return jax.value_and_grad(f)(p)

    loss, g = loss_and_grad(v["params"], x)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss))
    assert float(jnp.sum(jnp.abs(g["router"]["weight"]))) > 0.0


def test_flash_attention_sliding_window(tpu, rng):
    """Round-3: sliding-window block skipping must compile under Mosaic
    (the extra block_live predicate) and match full-causal where the
    window covers everything."""
    from apex_tpu.ops import flash_attention

    b, h, d = 2, 8, 64
    q = jnp.asarray(rng.standard_normal((b, h, SEQ, d)), jnp.bfloat16)
    full = jax.jit(lambda q: flash_attention(q, q, q, causal=True))(q)
    wide = jax.jit(lambda q: flash_attention(q, q, q, causal=True,
                                             window=SEQ))(q)
    np.testing.assert_allclose(np.asarray(wide, np.float32),
                               np.asarray(full, np.float32),
                               rtol=2e-2, atol=2e-2)
    g = jax.jit(jax.grad(lambda q: jnp.sum(flash_attention(
        q, q, q, causal=True, window=128).astype(jnp.float32) ** 2)))(q)
    assert np.isfinite(np.asarray(g, np.float32)).all()
