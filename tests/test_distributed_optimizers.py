"""ZeRO distributed optimizer parity vs single-device fused optimizers.

Mirrors apex/contrib/test/optimizers/test_distributed_fused_adam.py — the
distributed optimizer stepping per-rank grads must match the single-device
optimizer stepping the mean grad, and its state must stay row-sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS

pytestmark = pytest.mark.slow


def make_params(rng, n_tensors=5):
    shapes = [(64, 33), (129,), (7, 5, 3), (1024,), (300, 2)][:n_tensors]
    return {f"p{i}": jnp.asarray(rng.standard_normal(s), jnp.float32)
            for i, s in enumerate(shapes)}


def make_grad_stack(rng, params, dp):
    """Per-rank grads: [dp, ...] stacked, different per rank."""
    return {k: jnp.asarray(rng.standard_normal((dp,) + v.shape), jnp.float32)
            for k, v in params.items()}


def mean_grads(gstack):
    return {k: v.mean(0) for k, v in gstack.items()}


@pytest.mark.parametrize("opt_name", ["adam", "lamb"])
def test_distributed_matches_single_device(mesh8, opt_name, rng):
    from apex_tpu.contrib.optimizers import (DistributedFusedAdam,
                                             DistributedFusedLAMB)
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    mesh = mesh8
    dp = mesh.shape[DATA_AXIS]
    params = make_params(rng)
    kw = dict(lr=1e-2, weight_decay=0.01,
              exclude_from_weight_decay=lambda n: n == "p1")
    if opt_name == "adam":
        ref = FusedAdam(params, **kw)
        dist = DistributedFusedAdam(params, mesh=mesh, **kw)
    else:
        ref = FusedLAMB(params, max_grad_norm=1.0, **kw)
        dist = DistributedFusedLAMB(params, mesh=mesh, max_grad_norm=1.0, **kw)

    p_ref = p_dist = None
    for step in range(3):
        gstack = make_grad_stack(rng, params, dp)
        p_ref = ref.step(mean_grads(gstack))

        # feed per-rank grads through shard_step inside shard_map: the
        # reduce-scatter must average them to the same mean grad
        def run(gstack, master, state, count):
            def body(g_ranked, master_s, state_s, count):
                g_local = jax.tree.map(lambda g: g[0], g_ranked)
                p, m, s, c, _ = dist.shard_step(g_local, master_s, state_s,
                                                count)
                return p, m, s, c

            row = P(DATA_AXIS, None)
            state_specs = {k: row for k in state}
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(DATA_AXIS), row, state_specs, P()),
                out_specs=(P(), row, state_specs, P()),
                check_vma=False)(gstack, master, state, count)

        p_dist, dist.master, dist.state, dist.step_count = run(
            gstack, dist.master, dist.state, dist.step_count)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_dist[k]), np.asarray(p_ref[k]),
                                   rtol=2e-5, atol=2e-6)
    assert int(dist.step_count) == 3


@pytest.mark.parametrize("opt_name", ["adam", "lamb"])
def test_facade_step_replicated_grads(mesh8, opt_name, rng):
    """Facade .step() with replicated grads == single-device optimizer."""
    from apex_tpu.contrib.optimizers import (DistributedFusedAdam,
                                             DistributedFusedLAMB)
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    params = make_params(rng)
    if opt_name == "adam":
        ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
        dist = DistributedFusedAdam(params, lr=1e-2, weight_decay=0.01,
                                    mesh=mesh8)
    else:
        ref = FusedLAMB(params, lr=1e-2, weight_decay=0.01)
        dist = DistributedFusedLAMB(params, lr=1e-2, weight_decay=0.01,
                                    mesh=mesh8)

    for step in range(2):
        g = {k: jnp.asarray(rng.standard_normal(v.shape), jnp.float32)
             for k, v in params.items()}
        p_ref = ref.step(g)
        p_dist = dist.step(g)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_dist[k]), np.asarray(p_ref[k]),
                                   rtol=2e-5, atol=2e-6)


def test_state_is_row_sharded(mesh8, rng):
    """ZeRO property: each device holds only rows/dp of master + state."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = make_params(rng)
    dist = DistributedFusedAdam(params, mesh=mesh8)
    shard_shapes = {s.data.shape for s in dist.master.addressable_shards}
    assert shard_shapes == {(dist.shard_rows, 1024)}
    for buf in dist.state.values():
        assert {s.data.shape for s in buf.addressable_shards} == \
            {(dist.shard_rows, 1024)}
    # stays sharded after a step
    g = {k: jnp.zeros_like(v) for k, v in params.items()}
    dist.step(g)
    assert {s.data.shape for s in dist.master.addressable_shards} == \
        {(dist.shard_rows, 1024)}


def test_nonfinite_grad_skips_step_all_ranks(mesh8, rng):
    """An inf on ONE rank's grads must skip the step on ALL ranks (the
    reference allreduces the noop flag across the group)."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    mesh = mesh8
    dp = mesh.shape[DATA_AXIS]
    params = make_params(rng)
    dist = DistributedFusedAdam(params, mesh=mesh, lr=1e-2)
    gstack = make_grad_stack(rng, params, dp)
    # poison rank 3's grad of one tensor
    g0 = np.array(gstack["p0"])
    g0[3, 0, 0] = np.inf
    gstack["p0"] = jnp.asarray(g0)

    def run(gstack, master, state, count):
        def body(g_ranked, master_s, state_s, count):
            g_local = jax.tree.map(lambda g: g[0], g_ranked)
            p, m, s, c, _ = dist.shard_step(g_local, master_s, state_s, count)
            return p, m, s, c

        row = P(DATA_AXIS, None)
        state_specs = {k: row for k in state}
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(DATA_AXIS), row, state_specs, P()),
            out_specs=(P(), row, state_specs, P()),
            check_vma=False)(gstack, master, state, count)

    p_new, dist.master, dist.state, dist.step_count = run(
        gstack, dist.master, dist.state, dist.step_count)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p_new[k]),
                                      np.asarray(params[k]))
    assert int(dist.step_count) == 0
