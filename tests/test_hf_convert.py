"""Cross-framework parity: transformers' LlamaForCausalLM vs our LlamaModel.

The strongest correctness evidence a model family can have — an INDEPENDENT
implementation (torch, eager attention) must produce the same logits from
the same converted weights. Covers RoPE convention, GQA head grouping,
fused kv/gate_up layouts, RMSNorm accumulation, and the attention scale in
one assertion.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

pytestmark = pytest.mark.slow


def _hf_pair(tie=False, kv_heads=2):
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=kv_heads,
                      max_position_embeddings=128,
                      tie_word_embeddings=tie,
                      attn_implementation="eager")
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, hf


@pytest.mark.parametrize("kv_heads", [2, 4])
def test_logits_match_transformers(rng, kv_heads):
    from apex_tpu.models.hf_convert import (llama_config_from_hf,
                                            llama_params_from_hf)
    from apex_tpu.models.llama import LlamaModel

    hf_cfg, hf = _hf_pair(kv_heads=kv_heads)
    cfg = llama_config_from_hf(hf_cfg)
    params = llama_params_from_hf(hf.state_dict(), cfg)
    model = LlamaModel(cfg)

    ids = rng.integers(0, hf_cfg.vocab_size, (2, 24))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(model.apply({"params": params},
                                  jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_tied_embeddings_roundtrip(rng):
    from apex_tpu.models.hf_convert import (llama_config_from_hf,
                                            llama_params_from_hf)
    from apex_tpu.models.llama import LlamaModel

    hf_cfg, hf = _hf_pair(tie=True)
    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.tie_word_embeddings
    params = llama_params_from_hf(hf.state_dict(), cfg)
    assert "lm_head" not in params
    ids = rng.integers(0, hf_cfg.vocab_size, (1, 16))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(LlamaModel(cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_mistral_sliding_window_logits_match(rng):
    """Mistral (sliding_window) vs MistralForCausalLM — the window
    semantics must agree with the HF eager mask."""
    from transformers import MistralConfig, MistralForCausalLM

    from apex_tpu.models.hf_convert import (llama_config_from_hf,
                                            llama_params_from_hf)
    from apex_tpu.models.llama import LlamaModel

    hf_cfg = MistralConfig(vocab_size=128, hidden_size=64,
                           intermediate_size=176, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=128, sliding_window=8,
                           attn_implementation="eager")
    torch.manual_seed(1)
    hf = MistralForCausalLM(hf_cfg).eval()
    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.sliding_window == 8
    params = llama_params_from_hf(hf.state_dict(), cfg)
    ids = rng.integers(0, hf_cfg.vocab_size, (2, 32))  # seq > window
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(LlamaModel(cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_unsupported_configs_fail_loud():
    from transformers import LlamaConfig as HFConfig

    from apex_tpu.models.hf_convert import llama_config_from_hf

    bad = HFConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                   num_hidden_layers=1, num_attention_heads=4,
                   rope_scaling={"rope_type": "linear", "factor": 2.0})
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        llama_config_from_hf(bad)

    bad2 = HFConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=1, num_attention_heads=4,
                    attention_bias=True)
    with pytest.raises(NotImplementedError, match="attention_bias"):
        llama_config_from_hf(bad2)


def test_gpt2_logits_match_transformers(rng):
    """GPT-2 cross-framework parity: Conv1D transposes, fused qkv order,
    learned positions, tanh-approx GELU, tied head."""
    from transformers import GPT2Config, GPT2LMHeadModel

    from apex_tpu.models.gpt import GPTModel
    from apex_tpu.models.hf_convert import (gpt2_config_from_hf,
                                            gpt2_params_from_hf)

    hf_cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=64,
                        n_layer=2, n_head=4,
                        attn_implementation="eager")
    torch.manual_seed(2)
    hf = GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2_config_from_hf(hf_cfg)
    params = gpt2_params_from_hf(hf.state_dict(), cfg)

    ids = rng.integers(0, hf_cfg.vocab_size, (2, 24))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(GPTModel(cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)


def test_bert_logits_match_transformers(rng):
    """The bench FLAGSHIP cross-checked: BertForPreTraining (ours) vs
    transformers' — MLM and NSP logits from the same converted weights
    (exact-GELU checkpoint => gelu_approximate=False)."""
    from transformers import BertConfig as HFBertConfig, BertForPreTraining

    from apex_tpu.models import BertForPreTraining as OurBert
    from apex_tpu.models.hf_convert import (bert_config_from_hf,
                                            bert_params_from_hf)

    hf_cfg = HFBertConfig(vocab_size=512, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=256,
                          max_position_embeddings=128, type_vocab_size=2,
                          attn_implementation="eager")
    torch.manual_seed(4)
    hf = BertForPreTraining(hf_cfg).eval()
    cfg = bert_config_from_hf(hf_cfg)
    assert not cfg.gelu_approximate  # HF default hidden_act='gelu' (erf)
    params = bert_params_from_hf(hf.state_dict(), cfg)

    ids = rng.integers(0, hf_cfg.vocab_size, (2, 24))
    tt = rng.integers(0, 2, (2, 24))
    mask = np.ones((2, 24), np.int32)
    mask[:, -5:] = 0  # padded tail: key masking must agree too
    with torch.no_grad():
        out = hf(torch.from_numpy(ids),
                 attention_mask=torch.from_numpy(mask),
                 token_type_ids=torch.from_numpy(tt))
    mlm, nsp = OurBert(cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32),
        jnp.asarray(tt, jnp.int32), jnp.asarray(mask, jnp.int32))
    valid = mask[:, :, None].astype(bool)
    np.testing.assert_allclose(
        np.asarray(mlm)[valid[..., 0]],
        out.prediction_logits.numpy()[valid[..., 0]],
        rtol=3e-4, atol=3e-4)
    np.testing.assert_allclose(np.asarray(nsp),
                               out.seq_relationship_logits.numpy(),
                               rtol=3e-4, atol=3e-4)


def _t5_hf_pair(ff="relu", tie=True):
    from transformers import T5Config as HFT5Config
    from transformers import T5ForConditionalGeneration

    hf_cfg = HFT5Config(vocab_size=128, d_model=64, d_kv=16, d_ff=128,
                        num_layers=2, num_heads=4,
                        relative_attention_num_buckets=32,
                        relative_attention_max_distance=128,
                        feed_forward_proj=ff, tie_word_embeddings=tie,
                        dropout_rate=0.0, decoder_start_token_id=0)
    torch.manual_seed(0)
    hf = T5ForConditionalGeneration(hf_cfg).eval()
    return hf_cfg, hf


@pytest.mark.parametrize("ff,tie", [("relu", True), ("gated-gelu", False)])
def test_t5_logits_match_transformers(rng, ff, tie):
    """v1.0 (relu, tied+rescaled head) and v1.1 (gated-gelu, untied):
    teacher-forced logits must match torch's independent implementation —
    relative-bias bucketing, unscaled attention, cross-attention, fused
    qkv/kv/wi layouts and the head convention in one assertion."""
    from apex_tpu.models.hf_convert import (t5_config_from_hf,
                                            t5_params_from_hf)
    from apex_tpu.models.t5 import T5Model

    hf_cfg, hf = _t5_hf_pair(ff=ff, tie=tie)
    cfg = t5_config_from_hf(hf_cfg)
    assert cfg.ff_act == ff and cfg.tie_word_embeddings == tie
    params = t5_params_from_hf(hf.state_dict(), cfg)
    model = T5Model(cfg)

    enc_ids = rng.integers(0, hf_cfg.vocab_size, (2, 12))
    dec_ids = rng.integers(0, hf_cfg.vocab_size, (2, 7))
    with torch.no_grad():
        ref = hf(input_ids=torch.from_numpy(enc_ids),
                 decoder_input_ids=torch.from_numpy(dec_ids)).logits.numpy()
    ours = np.asarray(model.apply({"params": params},
                                  jnp.asarray(enc_ids, jnp.int32),
                                  jnp.asarray(dec_ids, jnp.int32)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_t5_config_decode_cap_override_and_n_positions():
    """ADVICE r4: the decode cap must be overridable and derive from
    hf_config.n_positions when present instead of hard-coding 512."""
    from types import SimpleNamespace

    from apex_tpu.models.hf_convert import t5_config_from_hf

    base = dict(feed_forward_proj="relu", num_layers=2, vocab_size=32,
                d_model=16, d_ff=32, num_heads=2, d_kv=8,
                relative_attention_num_buckets=8,
                layer_norm_epsilon=1e-6, decoder_start_token_id=0,
                tie_word_embeddings=True)
    cfg = t5_config_from_hf(SimpleNamespace(**base))
    assert cfg.max_position_embeddings == 512          # default unchanged
    cfg = t5_config_from_hf(SimpleNamespace(**base, n_positions=2048))
    assert cfg.max_position_embeddings == 2048         # derived
    cfg = t5_config_from_hf(SimpleNamespace(**base, n_positions=2048),
                            max_position_embeddings=4096)
    assert cfg.max_position_embeddings == 4096         # explicit wins
