"""Quantized KV pages (docs/serving.md "Quantized KV pages"): int8/fp8
K/V in the page pool with per-(page, kv_head) symmetric f32 scales,
dequantized inside the paged-attention kernel.

Invariant tier (fast): the dtype-resolution contract and its NAMED
errors (no silent fp32 fallback), the >= 1.9x fixed-budget slot-capacity
pin (the acceptance number), the <= 0.55x per-step KV byte pin through
the cost model's own ``_kv_step_bytes_max``, kernel parity against the
dequantizing reference at s=1 and s>1, prefill/append quantization error
bounds, requantize-on-grow's full-page bit-stability (the invariant
prefix sharing and preemption spill lean on), defrag's exact scale
remap, and shared-allocation scale semantics (shared pages keep their
scales, fresh private pages reset to 0).

Engine tier (slow): greedy decode through the real engines — int8 and
fp8 pools vs the fp pool on GPT (s=1, speculative s>1, chunked prefill),
windowed Llama, TP=2 token identity vs the single-chip int8 engine, and
the frontend's preemption spill -> resume path over a quantized pool.
Token-level agreement with the fp engine is TOLERANCE-pinned (first
tokens exact — they come off the prefill forward pass, which never reads
the pool — plus a floor on fully-identical requests): quantization
legitimately perturbs logits by more than a tiny random-init model's
argmax gaps, so exact identity across dtypes is not the contract.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import (generate, layer_cache,
                                        update_paged_layer_cache)
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.ops.paged_attention import (paged_attention,
                                          paged_attention_reference)
from apex_tpu.ops.quant import (is_quantized_kv, kv_qmax, kv_quantize,
                                resolve_kv_dtype)
from apex_tpu.serving import (PagedDecodeEngine, Request,
                              alloc_slot, alloc_slot_shared,
                              init_paged_cache, prefill_into_pages,
                              release_slot)
from apex_tpu.serving.kv_pool import (defrag_map, max_slots_for_pool_bytes,
                                      page_bytes)
from apex_tpu.serving.scheduler import generate_paged

PS = 8

_HAS_FP8 = hasattr(jnp, "float8_e4m3fn")


def _dequant_layer(lc):
    """Full-precision view of a (possibly quantized) layer's pool."""
    k, v = lc["k_pages"], lc["v_pages"]
    if "k_scales" not in lc:
        return np.asarray(k, np.float32), np.asarray(v, np.float32)
    return (np.asarray(k, np.float32)
            * np.asarray(lc["k_scales"])[:, :, None, None],
            np.asarray(v, np.float32)
            * np.asarray(lc["v_scales"])[:, :, None, None])


# --- invariant tier ----------------------------------------------------------


def test_resolve_kv_dtype_contract():
    assert resolve_kv_dtype(None) is None
    dt, qmax = resolve_kv_dtype("int8")
    assert dt == jnp.int8 and qmax == 127.0
    assert resolve_kv_dtype(jnp.int8) == (jnp.int8, 127.0)
    if _HAS_FP8:
        for alias in ("fp8", "e4m3", jnp.float8_e4m3fn):
            dt, qmax = resolve_kv_dtype(alias)
            assert dt == jnp.float8_e4m3fn and qmax == 448.0
    # NAMED error, never a silent full-precision fallback
    with pytest.raises(ValueError, match="kv-dtype-unsupported"):
        resolve_kv_dtype("int4")
    with pytest.raises(ValueError, match="kv-dtype-unsupported"):
        kv_qmax(jnp.bfloat16)
    assert is_quantized_kv(jnp.int8)
    assert not is_quantized_kv(jnp.bfloat16)


def test_kv_quantize_roundtrip_bounds():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((5, 4, PS, 16)).astype(np.float32) * 3.0
    q, scale = kv_quantize(x, jnp.int8, 127.0, axes=(2, 3))
    deq = np.asarray(q, np.float32) * np.asarray(scale)
    # symmetric int8: error bounded by half an LSB of each group's grid
    assert np.all(np.abs(deq - x) <= np.asarray(scale) / 2 + 1e-7)
    # an all-zero group round-trips exactly through scale 0
    z, zscale = kv_quantize(np.zeros((1, 1, PS, 16), np.float32),
                            jnp.int8, 127.0, axes=(2, 3))
    assert float(np.abs(np.asarray(z)).max()) == 0.0
    assert float(np.asarray(zscale).max()) == 0.0


def test_named_errors_no_silent_fallback(rng):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    # kv_dtype without the paged path has no pool to quantize
    with pytest.raises(ValueError, match="kv-dtype-unsupported"):
        generate(model, v, prompt, max_new_tokens=2, kv_dtype="int8")
    # a quantized pool's page dtype IS the quantized dtype
    with pytest.raises(ValueError, match="kv-dtype-conflict"):
        init_paged_cache(cfg, num_slots=2, num_pages=8, page_size=PS,
                         dtype=jnp.bfloat16, kv_dtype="int8")
    # the engine rejects bad dtypes EAGERLY, at construction
    with pytest.raises(ValueError, match="kv-dtype-unsupported"):
        PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                          kv_dtype="int4")
    # speculative decode: the draft pool must mirror the target pool
    with pytest.raises(ValueError, match="kv-dtype-mismatch"):
        PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                          draft_model=model, draft_variables=v,
                          draft_len=2, kv_dtype="int8",
                          draft_kv_dtype=None)


def test_slot_capacity_and_page_byte_pins():
    """The acceptance numbers: at a FIXED pool-byte budget the int8 pool
    admits >= 1.9x the slots of the bf16 pool, and one int8 page (scales
    included) costs <= 0.55x a bf16 page."""
    from apex_tpu.models.gpt import gpt2_small_config

    for cfg in (gpt_tiny_config(), gpt2_small_config(dtype=jnp.bfloat16)):
        fp_page = page_bytes(cfg, 16)
        q_page = page_bytes(cfg, 16, kv_dtype="int8")
        assert q_page <= 0.55 * fp_page, (q_page, fp_page)
        pps = 32
        budget = fp_page * (64 * pps + 1)       # what 64 fp slots cost
        fp_slots = max_slots_for_pool_bytes(cfg, budget, pages_per_slot=pps)
        q_slots = max_slots_for_pool_bytes(cfg, budget, pages_per_slot=pps,
                                           kv_dtype="int8")
        assert fp_slots >= 64
        assert q_slots >= 1.9 * fp_slots, (q_slots, fp_slots)
        if _HAS_FP8:
            f8_slots = max_slots_for_pool_bytes(
                cfg, budget, pages_per_slot=pps, kv_dtype="fp8")
            assert f8_slots == q_slots          # same 1-byte pages


def test_cost_model_kv_step_bytes_ratio():
    """The ledger pin's substrate: ``obs.costs._kv_step_bytes_max`` over
    the ACTUAL pool avals prices the int8 pool's per-step KV reads
    (scale rows included) at <= 0.55x the bf16 pool's."""
    from apex_tpu.obs.costs import _kv_step_bytes_max

    cfg = gpt_tiny_config()

    def pool(kv_dtype):
        return jax.eval_shape(
            lambda: init_paged_cache(cfg, num_slots=4, num_pages=33,
                                     page_size=16, max_pages_per_seq=16,
                                     kv_dtype=kv_dtype))

    fp_bytes, _ = _kv_step_bytes_max(pool(None))
    q_bytes, _ = _kv_step_bytes_max(pool("int8"))
    assert q_bytes <= 0.55 * fp_bytes, (q_bytes, fp_bytes)


@pytest.mark.parametrize("kv_dtype,s_q",
                         [("int8", 1), ("int8", 4), ("fp8", 1)])
def test_kernel_parity_vs_dequant_reference(kv_dtype, s_q):
    """The Pallas kernel's in-VMEM dequant matches the dense reference
    that dequantizes the gathered pages in fp32 — s=1 decode and the
    s>1 spec-verify/chunked-prefill query block."""
    if kv_dtype == "fp8" and not _HAS_FP8:
        pytest.skip("no float8_e4m3fn in this build")
    dt, qmax = resolve_kv_dtype(kv_dtype)
    rng = np.random.default_rng(1)
    b, h, kv, d, npg, mp = 3, 8, 4, 64, 25, 6
    q = jnp.asarray(rng.standard_normal((b, h, s_q, d)), jnp.float32)
    kq, ks = kv_quantize(rng.standard_normal((npg, kv, 16, d)), dt, qmax,
                         axes=(2, 3))
    vq, vs = kv_quantize(rng.standard_normal((npg, kv, 16, d)), dt, qmax,
                         axes=(2, 3))
    ks, vs = ks[:, :, 0, 0], vs[:, :, 0, 0]
    bt = jnp.asarray(rng.integers(1, npg, (b, mp)), jnp.int32)
    ln = jnp.asarray([37, 80, 12], jnp.int32)
    out = paged_attention(q, kq, vq, bt, ln, k_scales=ks, v_scales=vs)
    ref = paged_attention_reference(q, kq, vq, bt, ln,
                                    k_scales=ks, v_scales=vs)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_prefill_quantizes_and_append_requantizes(rng):
    """Prefill scatters an exact per-page quantization (fresh pages have
    scale 0 = empty); the decode append requantizes-on-grow with bounded
    error; FULL pages never change under appends to other slots — the
    bit-stability invariant prefix sharing and preemption spill need."""
    cfg = gpt_tiny_config()
    kv, d = cfg.num_kv_heads if hasattr(cfg, "num_kv_heads") \
        else cfg.num_heads, cfg.head_dim
    cache = init_paged_cache(cfg, num_slots=2, num_pages=12, page_size=PS,
                             kv_dtype="int8")
    cache = alloc_slot(cache, 0, 4)              # 4th page for the spill
    s0 = 2 * PS + 3                              # 2 full pages + 3 tail
    contig = [{"k": jnp.asarray(rng.standard_normal((1, kv, 3 * PS, d)),
                                jnp.float32),
               "v": jnp.asarray(rng.standard_normal((1, kv, 3 * PS, d)),
                                jnp.float32)}
              for _ in cache["layers"]]
    cache = prefill_into_pages(cache, 0, contig, s0)
    row = np.asarray(cache["block_tables"][0])
    for li, lc0 in enumerate(cache["layers"]):
        kd, _ = _dequant_layer(lc0)
        ref = np.asarray(contig[li]["k"][0], np.float32)   # (kv, 3ps, d)
        scale = np.asarray(lc0["k_scales"])[row[:3]]       # (3, kv)
        for pg in range(3):
            n = min(s0 - pg * PS, PS)
            got = kd[row[pg], :, :n, :]
            want = ref[:, pg * PS:pg * PS + n, :].transpose(0, 1, 2)
            err = np.abs(got - want.reshape(got.shape))
            assert np.all(err <= scale[pg][:, None, None] / 2 + 1e-6)

    # decode append across the page-2 boundary (3 tail slots + spill)
    lc = layer_cache(cache, 0)
    before_full = np.asarray(lc["k_pages"])[row[:2]].copy()
    chunk_k = jnp.asarray(rng.standard_normal((2, kv, 6, d)), jnp.float32)
    chunk_v = jnp.asarray(rng.standard_normal((2, kv, 6, d)), jnp.float32)
    lc2 = update_paged_layer_cache(lc, chunk_k, chunk_v)
    kd2, _ = _dequant_layer(lc2)
    sc2 = np.asarray(lc2["k_scales"])
    # the 3 new tokens in page 2 and 3 in page 3 round-trip within their
    # page's (possibly grown) grid
    for i in range(6):
        pos = s0 + i
        pg, off = row[pos // PS], pos % PS
        err = np.abs(kd2[pg, :, off, :]
                     - np.asarray(chunk_k[0, :, i, :], np.float32))
        assert np.all(err <= sc2[pg][:, None] / 2 + 1e-6), (i, err.max())
    # slot 0's FULL pages are bit-identical after its own boundary
    # append (entries below len // ps are never members of the grow set)
    np.testing.assert_array_equal(
        np.asarray(lc2["k_pages"])[row[:2]], before_full)


def test_full_pages_bitstable_under_other_slots(rng):
    """Appending to slot 1 never perturbs slot 0's pages OR scales —
    quantized pages a prefix cache (or a preemption spill) holds are
    immutable no matter what the rest of the pool does."""
    cfg = gpt_tiny_config()
    kv, d = cfg.num_heads, cfg.head_dim
    cache = init_paged_cache(cfg, num_slots=2, num_pages=12, page_size=PS,
                             kv_dtype="int8")
    cache = alloc_slot(cache, 0, 2)
    cache = alloc_slot(cache, 1, 2)
    contig = [{"k": jnp.asarray(rng.standard_normal((1, kv, 2 * PS, d)),
                                jnp.float32),
               "v": jnp.asarray(rng.standard_normal((1, kv, 2 * PS, d)),
                                jnp.float32)}
              for _ in cache["layers"]]
    cache = prefill_into_pages(cache, 0, contig, 2 * PS)
    cache = prefill_into_pages(cache, 1, contig, PS + 1)
    row0 = np.asarray(cache["block_tables"][0])
    lc = layer_cache(cache, 0)
    pages0 = np.asarray(lc["k_pages"])[row0[:2]].copy()
    scales0 = np.asarray(lc["k_scales"])[row0[:2]].copy()

    # grow slot 1 only: mask slot 0 out by pointing its chunk at len 0
    # via a null-page table row — the engine's real masking; here simply
    # append a chunk whose slot-0 rows duplicate slot 1's (slot 0's len
    # advances but its writes land at its own tail pages, not pages0)
    chunk = jnp.asarray(rng.standard_normal((2, kv, 4, d)), jnp.float32)
    lc2 = update_paged_layer_cache(lc, chunk, chunk)
    np.testing.assert_array_equal(np.asarray(lc2["k_pages"])[row0[:2]],
                                  pages0)
    np.testing.assert_array_equal(np.asarray(lc2["k_scales"])[row0[:2]],
                                  scales0)


def test_defrag_remaps_scales_with_pages(rng):
    """defrag_map's permutation moves each page's scale with its
    contents: the dequantized pool is BIT-identical before and after
    compaction (for live pages, through the remap)."""
    cfg = gpt_tiny_config()
    kv, d = cfg.num_heads, cfg.head_dim
    cache = init_paged_cache(cfg, num_slots=2, num_pages=16, page_size=PS,
                             kv_dtype="int8")
    cache = alloc_slot(cache, 0, 3)
    contig = [{"k": jnp.asarray(rng.standard_normal((1, kv, 3 * PS, d)),
                                jnp.float32),
               "v": jnp.asarray(rng.standard_normal((1, kv, 3 * PS, d)),
                                jnp.float32)}
              for _ in cache["layers"]]
    cache = prefill_into_pages(cache, 0, contig, 3 * PS)
    row = np.asarray(cache["block_tables"][0])
    lc = layer_cache(cache, 0)
    kd_before, vd_before = _dequant_layer(lc)

    new_cache, new_idx = defrag_map(cache)
    new_idx = np.asarray(new_idx)
    new_row = np.asarray(new_cache["block_tables"][0])
    np.testing.assert_array_equal(new_row[:3], new_idx[row[:3]])
    lc2 = layer_cache(new_cache, 0)
    kd_after, vd_after = _dequant_layer(lc2)
    np.testing.assert_array_equal(kd_after[new_row[:3]], kd_before[row[:3]])
    np.testing.assert_array_equal(vd_after[new_row[:3]], vd_before[row[:3]])
    # raw pages and scales followed the same permutation
    np.testing.assert_array_equal(
        np.asarray(lc2["k_scales"])[new_row[:3]],
        np.asarray(lc["k_scales"])[row[:3]])


def test_shared_alloc_scale_semantics(rng):
    """alloc_slot_shared on a quantized pool: shared prefix pages KEEP
    their scales (shared pages are shared scales — sharing stays
    dtype-blind), fresh private pages reset to scale 0; release_slot's
    keep-mask spill leaves kept pages' contents and scales untouched, so
    a resume (re-share) reads bit-identical K/V — the preemption
    spill -> resume invariant at pool level."""
    cfg = gpt_tiny_config()
    kv, d = cfg.num_heads, cfg.head_dim
    cache = init_paged_cache(cfg, num_slots=2, num_pages=12, page_size=PS,
                             kv_dtype="int8")
    cache = alloc_slot(cache, 0, 2)
    contig = [{"k": jnp.asarray(rng.standard_normal((1, kv, 2 * PS, d)),
                                jnp.float32),
               "v": jnp.asarray(rng.standard_normal((1, kv, 2 * PS, d)),
                                jnp.float32)}
              for _ in cache["layers"]]
    cache = prefill_into_pages(cache, 0, contig, 2 * PS)
    row = np.asarray(cache["block_tables"][0])
    lc = layer_cache(cache, 0)
    pages = np.asarray(lc["k_pages"])[row[:2]].copy()
    scales = np.asarray(lc["k_scales"])[row[:2]].copy()

    # spill: keep both full pages (they become prefix-cache property)
    keep = np.zeros((cache["block_tables"].shape[1],), bool)
    keep[:2] = True
    cache = release_slot(cache, 0, jnp.asarray(keep))

    # resume: share the spilled pages back into a slot + 1 private page
    shared_row = jnp.zeros((cache["block_tables"].shape[1],), jnp.int32)
    shared_row = shared_row.at[0].set(int(row[0])).at[1].set(int(row[1]))
    cache = alloc_slot_shared(cache, 1, shared_row, 2, 1)
    assert np.asarray(cache["page_ref"])[row[:2]].tolist() == [1, 1]
    lc2 = layer_cache(cache, 0)
    np.testing.assert_array_equal(np.asarray(lc2["k_pages"])[row[:2]],
                                  pages)
    np.testing.assert_array_equal(np.asarray(lc2["k_scales"])[row[:2]],
                                  scales)
    # the fresh PRIVATE page's scale reset to 0 ("holds nothing yet")
    priv = int(np.asarray(cache["block_tables"][1])[2])
    assert float(np.abs(np.asarray(lc2["k_scales"])[priv]).max()) == 0.0


# --- engine tier -------------------------------------------------------------


def _tiny_engine_setup(rng, seed=0):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(seed),
                   jnp.zeros((1, 8), jnp.int32))
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, (n,))))
               for n in (9, 17, 5, 26)]
    return cfg, model, v, prompts


def _agreement(fp, q):
    """(all first tokens equal, count of fully-identical requests)."""
    firsts = all(int(np.asarray(a)[0]) == int(np.asarray(b)[0])
                 for a, b in zip(fp, q))
    ident = sum(bool(np.array_equal(np.asarray(a), np.asarray(b)))
                for a, b in zip(fp, q))
    return firsts, ident


@pytest.mark.slow
def test_engine_greedy_parity_tolerance(rng):
    """int8 and fp8 engines vs the fp engine on the same mixed-length
    workload: every request's FIRST token is exact (prefill logits never
    read the pool) and at least 3 of 4 requests decode token-identically
    at tiny-GPT scale — the tolerance pin, not exact identity."""
    cfg, model, v, prompts = _tiny_engine_setup(rng)
    kw = dict(max_new_tokens=12, num_slots=4, page_size=PS, num_pages=40)
    fp = generate_paged(model, v, prompts, **kw)
    for kv_dtype in ("int8",) + (("fp8",) if _HAS_FP8 else ()):
        q = generate_paged(model, v, prompts, kv_dtype=kv_dtype, **kw)
        firsts, ident = _agreement(fp, q)
        assert firsts, f"{kv_dtype}: first token flipped"
        assert ident >= 3, f"{kv_dtype}: only {ident}/4 identical"


@pytest.mark.slow
def test_engine_s_gt_1_paths_int8(rng):
    """The s>1 query-block paths over a quantized pool: in-engine
    speculative decode (self-draft) and chunked prefill, vs the plain
    int8 engine. Both share the pool dtype; outputs agree at the same
    tolerance bar (requantize-on-grow quantizes on a different chunk
    grid than monolithic prefill, so exact identity is not guaranteed)."""
    cfg, model, v, prompts = _tiny_engine_setup(rng)
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=10)
            for p in prompts]
    plain = PagedDecodeEngine(model, v, num_slots=4, page_size=PS,
                              num_pages=40, kv_dtype="int8")
    outs, _ = plain.run(reqs)

    spec = PagedDecodeEngine(model, v, num_slots=4, page_size=PS,
                             num_pages=40, kv_dtype="int8",
                             draft_model=model, draft_variables=v,
                             draft_len=2)
    s_outs, s_stats = spec.run(reqs)
    assert s_stats["spec_rounds"] >= 1
    firsts, ident = _agreement(outs, s_outs)
    assert firsts and ident >= 3, f"spec: {ident}/4"

    chunked = PagedDecodeEngine(model, v, num_slots=4, page_size=PS,
                                num_pages=40, kv_dtype="int8",
                                prefill_chunk=PS)
    c_outs, _ = chunked.run(reqs)
    firsts, ident = _agreement(outs, c_outs)
    assert firsts and ident >= 3, f"chunked: {ident}/4"


@pytest.mark.slow
def test_llama_windowed_int8(rng):
    """generate(paged=True, kv_dtype=...) through Llama's GQA + sliding
    window band: the quantized run matches the fp paged run at the
    tolerance bar on a rectangular batch."""
    from apex_tpu.models.llama import LlamaModel, llama_tiny_config

    cfg = dataclasses.replace(llama_tiny_config(), sliding_window=PS)
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (3, 12)),
                         jnp.int32)
    fp = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                             paged=True, page_size=PS))
    q8 = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                             paged=True, page_size=PS, kv_dtype="int8"))
    assert fp.shape == q8.shape
    np.testing.assert_array_equal(fp[:, :13], q8[:, :13])  # prompt+first
    ident = sum(bool(np.array_equal(a, b)) for a, b in zip(fp, q8))
    assert ident >= 2, f"windowed llama: {ident}/3 rows identical"


@pytest.mark.slow
def test_tp2_int8_token_identity(rng):
    """TP=2 over the quantized pool (scales sharded P(None, model) with
    the head-sharded pages): token-IDENTICAL to the single-chip int8
    engine — sharding must not change the numerics at all."""
    from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                     shard_model_variables, tp_mesh)

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    cfg = gpt_tiny_config()
    if cfg.num_heads % 2:
        pytest.skip("tiny config heads not divisible by 2")
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, (n,))))
               for n in (9, 17, 5)]
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=8)
            for p in prompts]
    single = PagedDecodeEngine(model, v, num_slots=3, page_size=PS,
                               num_pages=33, kv_dtype="int8")
    outs, _ = single.run(reqs)

    tp_cfg = dataclasses.replace(cfg, tensor_parallel_size=2)
    tp_model = GPTModel(tp_cfg)
    mesh = tp_mesh(2)
    tp_vars, _ = shard_model_variables(tp_model, v, mesh)
    tp_engine = TensorParallelPagedEngine(
        tp_model, tp_vars, mesh=mesh, num_slots=3, page_size=PS,
        num_pages=33, kv_dtype="int8")
    tp_outs, _ = tp_engine.run(reqs)
    for i, (a, b) in enumerate(zip(outs, tp_outs)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"request {i}")


@pytest.mark.slow
def test_prefix_cache_hit_and_evict_int8(rng):
    """The radix prefix cache over an int8 pool: cache hits skip the
    shared pages, pool pressure evicts refcount-0 quantized pages, and a
    re-populated prefix hits again — and EVERY run is token-IDENTICAL to
    the uncached int8 engine (sharing and eviction move page *ids*;
    quantized full pages are bit-stable, so same-dtype identity is
    exact, unlike the cross-dtype tolerance bar)."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    sys_p = rng.integers(0, cfg.vocab_size, (2 * PS,)).astype(np.int32)

    def _req(tail_len, max_new):
        return Request(prompt=np.concatenate(
            [sys_p, rng.integers(0, cfg.vocab_size,
                                 (tail_len,)).astype(np.int32)]),
            max_new_tokens=max_new)

    reqs = [_req(int(t), int(m))
            for t, m in zip(rng.integers(3, 12, 4), rng.integers(3, 8, 4))]
    base, _ = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                                num_pages=8, kv_dtype="int8").run(reqs)

    engine = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                               num_pages=8, prefix_cache=True,
                               kv_dtype="int8")
    outs, stats = engine.run(reqs)
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert stats["prefix_hits"] >= len(reqs) - 1
    assert stats["prefill_tokens_skipped"] >= (len(reqs) - 1) * 2 * PS

    # pool pressure: a fat distinct-prefix request must evict the cached
    # quantized pages to fit (usable pool is 7 pages)
    fat = Request(prompt=rng.integers(0, cfg.vocab_size,
                                      (5 * PS,)).astype(np.int32),
                  max_new_tokens=PS)
    (fat_base,), _ = PagedDecodeEngine(model, v, num_slots=1, page_size=PS,
                                       num_pages=8, kv_dtype="int8"
                                       ).run([fat])
    (fat_out,), s_fat = engine.run([fat])
    np.testing.assert_array_equal(np.asarray(fat_base), np.asarray(fat_out))
    assert s_fat["evicted_pages"] >= 1

    # re-populate, then hit again — still bit-identical to uncached
    _, _ = engine.run([reqs[0]])
    (out2,), s2 = engine.run([reqs[0]])
    np.testing.assert_array_equal(np.asarray(base[0]), np.asarray(out2))
    assert s2["prefix_hits"] == 1


@pytest.mark.slow
def test_frontend_preemption_over_quantized_pool(rng):
    """The preemption spill -> resume path over an int8 pool: pin every
    slot with low-priority work, land a high-priority arrival, and the
    policy must preempt-and-spill (quantized pages move INTO the prefix
    cache by page id — scales ride along, nothing is copied) and later
    resume to completion with full-length outputs."""
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.policy import PriorityDeadlinePolicy

    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = PagedDecodeEngine(model, v, num_slots=2, page_size=PS,
                               num_pages=40, prefix_cache=True,
                               kv_dtype="int8")
    low = [Request(prompt=rng.integers(0, cfg.vocab_size, 24).astype(
        np.int32), max_new_tokens=16, priority=0) for _ in range(2)]
    engine.run(low)                                    # warm the buckets
    fe = ServingFrontend(engine, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(low)]
    while fe.queue_depth:
        fe.pump()
    for _ in range(3):
        fe.pump()
    handles.append(fe.submit(
        Request(prompt=rng.integers(0, cfg.vocab_size, 24).astype(
            np.int32), max_new_tokens=4, priority=9, deadline_ms=2000.0),
        request_id=99))
    fe.drain()
    stats = fe.stats()
    assert stats["preemptions"] >= 1
    assert stats["resumes"] >= 1
    want = [16, 16, 4]
    for h, n in zip(handles, want):
        assert np.asarray(h.result()).shape == (n,)
