"""HTTP/SSE serving surface (apex_tpu/serving/http.py + aio.py) —
ISSUE 15.

The acceptance bars, each proven over a REAL localhost socket (never a
mocked transport):

- ``POST /v1/generate`` streams greedy tokens identical to lock-step
  ``generate``; the observability endpoints (healthz / metrics /
  metrics.json / costs) ride the same port.
- a reader that stalls past the frontend's ``backpressure_window``
  SPILLS its slot through the preemption path (pages parked in the
  radix cache, never pinned by a socket) and the stream still completes
  token-identically on resume — the tier-1 backpressure/leak bar.
- a client disconnect cancels at the next sync boundary and frees every
  page; bad bodies get 400; overload gets 429 + Retry-After; drain gets
  503 and a clean shutdown leaves zero serving threads.
- a :class:`ReplicaRouter` supervising two REMOTE
  :class:`HttpReplicaClient` replicas recovers a killed replica's
  in-flight requests on the survivor token-identically — the networked
  twin of test_router's kill bar.
- slow tier: ≥1k truly concurrent streams through one server, zero
  hung handles / leaked pages / dangling threads after shutdown.
"""

import contextlib
import json
import socket
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.obs.fleet import (row_from_snapshot, stitch_traces,
                                validate_flight)
from apex_tpu.serving import (PagedDecodeEngine, ReplicaRouter, Request,
                              RouterPolicy, ServingFrontend,
                              free_page_count)
from apex_tpu.serving.faults import FaultInjector, FaultSpec
from apex_tpu.serving.http import (HttpReplicaClient, HttpServingServer,
                                   _iter_sse)
from apex_tpu.utils import metrics


@pytest.fixture(scope="module")
def tiny():
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, v


def _ref(model, v, prompt, max_new):
    return np.asarray(generate(model, v, np.asarray(prompt)[None],
                               max_new_tokens=max_new)
                      )[0, np.asarray(prompt).shape[0]:]


@contextlib.contextmanager
def _serving(tiny, *, num_slots=2, num_pages=64, prefix_cache=True,
             fault_hook=None, backpressure_window=None, **server_kw):
    """A live engine + started frontend + started HTTP server, torn
    down server-first (the ownership order docs/http.md specifies)."""
    cfg, model, v = tiny
    engine = PagedDecodeEngine(model, v, num_slots=num_slots,
                               page_size=8, num_pages=num_pages,
                               prefix_cache=prefix_cache)
    fe = ServingFrontend(engine, fault_hook=fault_hook,
                         backpressure_window=backpressure_window)
    fe.start()
    srv = HttpServingServer(fe, **server_kw).start()
    try:
        yield engine, fe, srv
    finally:
        srv.shutdown(deadline_s=10.0)
        fe.shutdown(deadline_s=10.0)


def _open_stream(port, body, *, rcvbuf=None, timeout=60.0):
    """Raw POST /v1/generate; returns (sock, reader, status, headers)
    with the reader positioned at the body."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf is not None:
        # must precede connect: the TCP window scale is fixed at the
        # handshake (the backpressure test relies on a tiny window)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
    sock.settimeout(timeout)
    sock.connect(("127.0.0.1", port))
    raw = json.dumps(body).encode()
    sock.sendall((f"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                  f"Content-Length: {len(raw)}\r\n\r\n").encode() + raw)
    f = sock.makefile("rb")
    status = int(f.readline().split()[1])
    headers = {}
    while True:
        line = f.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, val = line.decode("latin-1").partition(":")
        headers[k.strip().lower()] = val.strip()
    return sock, f, status, headers


def _stream(port, body):
    sock, f, status, _ = _open_stream(port, body)
    try:
        assert status == 200
        toks, finish = [], None
        for event, data in _iter_sse(f):
            if event == "token":
                toks.append(int(data["token"]))
            elif event == "done":
                finish = data.get("finish_reason")
                break
            elif event == "error":
                raise AssertionError(data)
        return toks, finish
    finally:
        sock.close()


def _get(port, path):
    sock = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    try:
        sock.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        f = sock.makefile("rb")
        status = int(f.readline().split()[1])
        while f.readline() not in (b"\r\n", b"\n", b""):
            pass
        return status, f.read()
    finally:
        sock.close()


def _pool_settled(engine, deadline_s=10.0):
    """Poll for free + radix-cached == total pool pages (cancel retires
    at the pump's next sync boundary, so accounting may lag a moment)."""
    usable = engine.cache["free_stack"].shape[0] - 1
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        cached = len(engine.prefix) if engine.prefix is not None else 0
        if int(free_page_count(engine.cache)) + cached == usable:
            return True
        time.sleep(0.02)
    return False


# --------------------------------------------------------------------------
# the streaming contract + observability endpoints
# --------------------------------------------------------------------------

def test_stream_token_identical_and_endpoints(tiny, rng):
    cfg, model, v = tiny
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    with _serving(tiny) as (engine, fe, srv):
        toks, finish = _stream(srv.port, {"prompt": prompt.tolist(),
                                          "max_new_tokens": 6})
        np.testing.assert_array_equal(toks, _ref(model, v, prompt, 6))
        assert finish == "stop"
        # the unified port: health + metrics + costs next to generate
        status, body = _get(srv.port, "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["ok"]
        assert doc["http"]["streams"] == 1
        assert doc["http"]["streams_active"] == 0
        status, body = _get(srv.port, "/metrics")
        assert status == 200 and b"http_tokens" in body
        status, body = _get(srv.port, "/metrics.json")
        assert status == 200 and "counters" in json.loads(body)
        # no cost snapshot published in this process -> a clean 404,
        # not a crash (publish_costs flips it to 200; test_costs owns
        # that path)
        status, body = _get(srv.port, "/costs")
        assert status == 404 and b"no cost snapshot" in body
        assert srv.http_counter_deltas()["tokens"] == 6


def test_bad_request_400_and_unknown_404(tiny):
    with _serving(tiny) as (_, __, srv):
        for body in ({"prompt": []},                  # empty prompt
                     {"prompt": [1, 2], "max_new_tokens": 0},
                     {"prompt": [1, 2], "request_id": "not-an-int"}):
            sock, f, status, _ = _open_stream(srv.port, body)
            assert status == 400, body
            sock.close()
        status, _ = _get(srv.port, "/nope")
        assert status == 404
        assert srv.http_counter_deltas()["errors"] == 0


def test_overload_429_retry_after(tiny):
    with _serving(tiny, max_queue_depth=0) as (_, __, srv):
        sock, f, status, headers = _open_stream(
            srv.port, {"prompt": [1, 2], "max_new_tokens": 2})
        assert status == 429
        assert float(headers["retry-after"]) > 0.0
        sock.close()
        assert srv.http_counter_deltas()["rejected"] == 1


# --------------------------------------------------------------------------
# the robustness contract: backpressure spill, disconnect, drain
# --------------------------------------------------------------------------

def test_backpressure_spill_resume_token_identical(tiny, rng):
    """THE tier-1 backpressure/leak bar: a reader stalled past the
    window spills its slot via the preemption path (pages parked in the
    radix cache — a socket pins nothing), then resumes to a
    token-identical completion once the client reads again."""
    cfg, model, v = tiny
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    with _serving(tiny, backpressure_window=8, sse_pad_bytes=2048,
                  sndbuf=4096) as (engine, fe, srv):
        sock, f, _, _ = _open_stream(
            srv.port, {"prompt": prompt.tolist(), "max_new_tokens": 64},
            rcvbuf=2048)
        toks = []
        try:
            for event, data in _iter_sse(f):
                if event == "token":
                    toks.append(int(data["token"]))
                    if len(toks) == 2:
                        time.sleep(1.5)   # stall: socket open, unread
                elif event == "done":
                    break
        finally:
            sock.close()
        np.testing.assert_array_equal(toks, _ref(model, v, prompt, 64))
        stats = fe.stats()
        assert stats["backpressure_spills"] >= 1
        assert stats["resumes"] >= 1
        assert _pool_settled(engine), "pages pinned after spill/resume"


def test_disconnect_cancels_and_frees_pages(tiny, rng):
    cfg, model, v = tiny
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    # slow the pump so the drop always lands mid-generation
    inj = FaultInjector((FaultSpec(kind="pump_stall", at=0,
                                   count=10_000, delay_ms=5.0),))
    with _serving(tiny, fault_hook=inj) as (engine, fe, srv):
        sock, f, _, _ = _open_stream(
            srv.port, {"prompt": prompt.tolist(),
                       "max_new_tokens": 100})
        n = 0
        for event, data in _iter_sse(f):
            if event == "token":
                n += 1
                if n == 2:
                    break
        # a REAL drop: close() alone defers the FIN while the makefile
        # reader holds the fd and the server would never notice
        sock.shutdown(socket.SHUT_RDWR)
        sock.close()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            doc = json.loads(_get(srv.port, "/healthz")[1])
            if (doc["http"]["streams_active"] == 0
                    and doc["http"]["disconnects"] >= 1):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"disconnect unseen: {doc['http']}")
        assert _pool_settled(engine), "disconnect leaked pages"


def test_conn_reset_mid_request_survives(tiny):
    """A torn submit (half the bytes, then an RST) must not take the
    server down or leak a stream."""
    with _serving(tiny) as (_, __, srv):
        raw = json.dumps({"prompt": [1, 2, 3],
                          "max_new_tokens": 4}).encode()
        wire = (f"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(raw)}\r\n\r\n").encode() + raw
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=10.0)
        sock.sendall(wire[:len(wire) // 2])
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))   # close -> RST
        sock.close()
        # the retry on a fresh connection completes normally
        toks, finish = _stream(srv.port, {"prompt": [1, 2, 3],
                                          "max_new_tokens": 4})
        assert len(toks) == 4 and finish == "stop"


def test_drain_503_then_clean_shutdown(tiny, rng):
    cfg, model, v = tiny
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    with _serving(tiny) as (_, fe, srv):
        toks, _ = _stream(srv.port, {"prompt": prompt.tolist(),
                                     "max_new_tokens": 4})
        assert len(toks) == 4
        srv.drain(deadline_s=10.0)
        sock, f, status, headers = _open_stream(
            srv.port, {"prompt": [1, 2], "max_new_tokens": 2})
        assert status == 503 and "retry-after" in headers
        sock.close()
        # observability keeps serving through the drain
        assert _get(srv.port, "/healthz")[0] == 200
    names = [t.name for t in threading.enumerate()]
    assert not any(n.startswith(("serving-http-loop",
                                 "serving-frontend-pump",
                                 "http-replica-stream"))
                   for n in names), names


# --------------------------------------------------------------------------
# fleet plane over the wire: /events cursor + scrape fidelity
# --------------------------------------------------------------------------

def test_events_endpoint_since_seq_cursor(tiny):
    """GET /events?since_seq= serves the engine ring incrementally: a
    cursor past the last seq yields nothing new, a stale cursor reports
    the gap as ``dropped``, and a malformed cursor is a 400 — the wire
    half of the federation cursor contract (docs/observability.md)."""
    with _serving(tiny) as (engine, _, srv):
        for i in range(3):
            engine.events.emit("probe", i=i)
        status, body = _get(srv.port, "/events?since_seq=-1")
        assert status == 200
        doc = json.loads(body)
        assert doc["kind"] == "event_log"
        assert doc["since_seq"] == -1 and doc["dropped"] == 0
        seqs = [e["seq"] for e in doc["events"]]
        assert seqs == sorted(seqs) and len(seqs) >= 3
        # incremental scrape from the last seen seq: empty, no gap
        status, body = _get(srv.port, f"/events?since_seq={seqs[-1]}")
        assert status == 200
        tail = json.loads(body)
        assert tail["events"] == [] and tail["dropped"] == 0
        # new events land past the cursor
        engine.events.emit("probe", i=99)
        status, body = _get(srv.port, f"/events?since_seq={seqs[-1]}")
        more = json.loads(body)["events"]
        assert [e["kind"] for e in more] == ["probe"]
        assert more[0]["seq"] == seqs[-1] + 1
        # a malformed cursor is the client's fault, not a crash
        assert _get(srv.port, "/events?since_seq=abc")[0] == 400


def test_remote_scrape_fidelity(tiny, rng):
    """The federated fleet row recomputed from a REMOTE replica's
    ``/metrics.json`` scrape equals the row the replica computes from
    its own in-process registry — p95s from wire-serialized buckets,
    gauges, and queue depth all match (the scrape-fidelity bar)."""
    cfg, model, v = tiny
    metrics.clear()              # only this replica's series in play
    try:
        with _serving(tiny) as (engine, fe, srv):
            for _ in range(3):
                prompt = rng.integers(0, cfg.vocab_size,
                                      (8,)).astype(np.int32)
                toks, finish = _stream(
                    srv.port, {"prompt": prompt.tolist(),
                               "max_new_tokens": 4})
                assert len(toks) == 4 and finish == "stop"
            client = HttpReplicaClient("127.0.0.1", srv.port)
            doc = client.fleet_scrape(-1)
            remote = row_from_snapshot(doc["metrics"])
            local = row_from_snapshot(metrics.snapshot(),
                                      labels=engine.obs_labels)
            local["queue_depth"] = fe.queue_depth
            assert set(remote) == set(local)
            for key, want in local.items():
                assert remote[key] == pytest.approx(want), key
            assert remote["ttft_ms_p95"] > 0.0
            # the event half of the scrape carries the engine ring
            edoc = doc["events"]
            assert edoc["kind"] == "event_log"
            assert edoc["total"] == engine.events.total
    finally:
        metrics.clear()


# --------------------------------------------------------------------------
# router over remote HTTP replicas — the networked kill bar
# --------------------------------------------------------------------------

def test_router_over_http_replicas_kill_recovers_token_identical(
        tiny, rng):
    """Two remote HTTP replicas behind one ReplicaRouter; replica 0's
    server dies mid-stream. Its in-flight requests must re-home to the
    survivor with delivered tokens folded in — outputs token-identical
    to an unfailed run, nothing hung, both pools clean.

    The fleet-plane half of the bar rides the same run: stitching the
    two replicas' span dumps yields ONE trace per request (same
    trace_id on both replicas for every failed-over request, zero
    orphans), stitched TTFT anchors at the FIRST replica's first token,
    ``preempted_ms`` covers the failover gap, and the death dumped a
    schema-valid flight bundle naming both replicas' event rings."""
    cfg, model, v = tiny
    backends = []
    for i in range(2):
        engine = PagedDecodeEngine(model, v, num_slots=2, page_size=8,
                                   num_pages=64, prefix_cache=True)
        # replica 0 decodes slowly so the kill lands mid-generation
        inj = (FaultInjector((FaultSpec(kind="pump_stall", at=0,
                                        count=10_000, delay_ms=20.0),))
               if i == 0 else None)
        fe = ServingFrontend(engine, fault_hook=inj)
        fe.start()
        srv = HttpServingServer(fe).start()
        backends.append((engine, fe, srv))
    clients = [HttpReplicaClient("127.0.0.1", srv.port)
               for _, __, srv in backends]
    router = ReplicaRouter(clients,
                           policy=RouterPolicy(backoff_base_ms=1.0))
    router.start()
    try:
        reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (12,)
                                            ).astype(np.int32),
                        max_new_tokens=8) for _ in range(4)]
        handles = [router.submit(r, request_id=i)
                   for i, r in enumerate(reqs)]
        # wait until replica 0 has delivered a first token, so the kill
        # lands mid-generation AND the stitched trace below has a
        # pre-kill TTFT anchor (the stall spec keeps its remaining
        # decode slow enough that the stream cannot finish first)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if any(s["name"] == "first_token"
                   for s in clients[0].tracer.to_dicts()):
                break
            time.sleep(0.01)
        else:
            pytest.fail("replica 0 never delivered a first token")
        backends[0][2].close()              # kill replica 0's server
        for h, r in zip(handles, reqs):
            np.testing.assert_array_equal(
                h.result(timeout=300.0),
                _ref(model, v, r.prompt, r.max_new_tokens))
    finally:
        router.stop()
        for _, fe, srv in backends:
            srv.close()
            fe.shutdown(deadline_s=10.0)
    stats = router.stats()
    assert stats["replica_deaths"] == 1
    assert stats["failovers"] >= 1
    assert stats["failover_recovered_rate"] == 1.0
    assert stats["completed"] == 4 and stats["failed"] == 0
    assert _pool_settled(backends[1][0]), "survivor pool not clean"
    assert stats["fleet"]["replicas"] == 2

    # -- one stitched trace per request across the failover ---------------
    dumps = {f"replica{i}": client.tracer.to_dicts()
             for i, client in enumerate(clients)}
    stitched = stitch_traces(dumps)
    assert stitched["orphans"] == [], stitched["orphans"][:3]
    assert len(stitched["traces"]) == 4
    crossed = [t for t in stitched["traces"].values()
               if len(t["replicas"]) == 2]
    assert crossed, "no request failed over across replicas"
    for trace in crossed:
        # the request started on replica 0 and finished on the survivor
        assert trace["replicas"] == ["replica0", "replica1"]
        assert len(trace["failovers"]) == 1
        fo = trace["failovers"][0]
        assert (fo["from_replica"], fo["to_replica"]) == ("replica0",
                                                          "replica1")
        # the time in limbo between the kill and the re-home is
        # preempted time, and nothing else was preempted here
        assert trace["preempted_ms"] == pytest.approx(fo["gap_ms"])
        # the same trace_id binds spans on BOTH replicas' dumps
        tid = trace["trace_id"]
        rid = trace["request_ids"][0]
        for name in ("replica0", "replica1"):
            bound = [s for s in dumps[name]
                     if s["request_id"] == rid
                     and (s.get("attrs") or {}).get("trace_id") == tid]
            assert bound, f"{name} has no span bound to {tid}"
    # TTFT anchors at the FIRST replica's first token (pre-failover),
    # not at the resumed stream's first token on the survivor
    anchored = 0
    for trace in crossed:
        rid = trace["request_ids"][0]
        r0 = {s["name"]: s for s in dumps["replica0"]
              if s["request_id"] == rid}
        if "first_token" not in r0:
            continue                     # killed before its first token
        anchored += 1
        want = (r0["first_token"]["t_start"]
                - r0["enqueue"]["t_start"]) * 1e3
        assert trace["ttft_ms"] == pytest.approx(want)
        assert trace["ttft_ms"] < (trace["failovers"][0]["resume_t"]
                                   - r0["enqueue"]["t_start"]) * 1e3
    assert anchored, "no failed-over request had a pre-kill first token"

    # -- the death dumped a flight bundle naming both replicas ------------
    flight = router.last_flight
    assert flight is not None, "replica death recorded no flight"
    validate_flight(flight)
    assert flight["reason"] == "replica_dead:0"
    assert set(flight["replicas"]) == {"replica0", "replica1"}
    for entry in flight["replicas"].values():
        assert isinstance(entry["events"], list)
    assert flight["replicas"]["replica0"]["alive"] is False
    assert flight["replicas"]["replica1"]["alive"] is True
    assert any(t["trace_id"] for t in flight["traces"].values())


# --------------------------------------------------------------------------
# slow tier: the 1k-concurrent-stream load bar
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_thousand_concurrent_streams_no_leaks(tiny, rng):
    """≥1k truly concurrent streams (every socket open at once) through
    one server: all complete, zero hung client threads, zero leaked
    pages, zero serving threads after shutdown."""
    cfg, model, v = tiny
    n = 1024
    prompt = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
    ref = _ref(model, v, prompt, 2)
    with _serving(tiny, num_slots=8, num_pages=128) as (engine, fe, srv):
        results: dict = {}
        errors: list = []

        def client(i):
            try:
                toks, finish = _stream(
                    srv.port, {"prompt": prompt.tolist(),
                               "max_new_tokens": 2, "request_id": i})
                results[i] = (toks, finish)
            except BaseException as exc:   # noqa: BLE001 — re-raised
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600.0)
        assert not any(t.is_alive() for t in threads), "hung clients"
        assert not errors, errors[:3]
        assert len(results) == n
        for toks, finish in results.values():
            np.testing.assert_array_equal(toks, ref)
            assert finish == "stop"
        assert srv.http_counter_deltas()["streams"] == n
        assert _pool_settled(engine, deadline_s=30.0)
    names = [t.name for t in threading.enumerate()]
    assert not any(n_.startswith(("serving-http-loop",
                                  "serving-frontend-pump"))
                   for n_ in names), names
