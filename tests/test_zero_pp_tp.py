"""ZeRO + PP + TP composed in ONE jitted step (VERDICT r2 next-round #4).

MULTICHIP_r02 proved dp x cp x tp, ZeRO-over-dp, tp, and pp x tp separately;
this runs the real-model GPT pipeline (stage-partitioned decoder, embedding
preprocess, tied head + vocab-parallel CE) with tp=2 Megatron collectives
INSIDE each stage, dp=2 data sharding, and the DistributedFusedAdam ZeRO
update (psum_scatter grads over dp -> local row-shard Adam -> all-gather
params) — all in a single shard_map program on the 8-device CPU mesh.

Oracle: the dp-averaged stage grads fed to the single-rank FusedAdam facade
must reproduce the ZeRO-updated params on every (stage, tp) coordinate.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS
from apex_tpu.ops import flat_buffer
from apex_tpu.ops.flat_buffer import LANE

pytestmark = pytest.mark.slow


@pytest.fixture
def mesh_dp2_pp2_tp2():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(2, 2)


def _build_stacked_gpt(tp, pp):
    """[S, TP, ...] stacked pipeline+TP param layout (dryrun recipe)."""
    from __graft_entry__ import _slice_tp_tree

    from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
    from apex_tpu.models.gpt_pipeline import split_gpt_params_for_pipeline

    n_layers = 2 * pp
    cfg1 = gpt_tiny_config(tensor_parallel_size=1, num_layers=n_layers)
    cfg = gpt_tiny_config(tensor_parallel_size=tp, num_layers=n_layers)
    rng = np.random.default_rng(0)
    ids0 = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)

    v1 = GPTModel(cfg1).init(jax.random.PRNGKey(0), ids0)["params"]
    v_tp_shape = jax.eval_shape(
        lambda: GPTModel(cfg).init(jax.random.PRNGKey(0), ids0))["params"]
    per_rank = []
    for r in range(tp):
        tp_tree = _slice_tp_tree(v1, v_tp_shape, r, tp)
        per_rank.append(split_gpt_params_for_pipeline(tp_tree, pp, n_layers))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=1), *per_rank)
    stacked = {"blocks": jax.tree.map(lambda t: t[:, :, 0], stacked["blocks"]),
               "shared": stacked["shared"]}
    return cfg, stacked


def test_zero_pp_tp_one_step(mesh_dp2_pp2_tp2, rng):
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.models.gpt_pipeline import make_gpt_pipeline_fns
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd)

    mesh = mesh_dp2_pp2_tp2
    tp = pp = dp = 2
    cfg, stacked = _build_stacked_gpt(tp, pp)
    first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg)

    m, b, s = 4, 4, 16
    mbs = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (m, b, s)), jnp.int32)

    local_template = jax.tree.map(lambda t: t[0, 0], stacked)
    opt = DistributedFusedAdam(local_template, lr=1e-3, weight_decay=0.0,
                               mesh=mesh)
    shard_rows, padded_rows = opt.shard_rows, opt.padded_rows
    spec = opt.spec

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS, MODEL_AXIS), P(None, DATA_AXIS), P(None, DATA_AXIS)),
        out_specs=(P(),                                      # loss (replicated)
                   P(DATA_AXIS, STAGE_AXIS, MODEL_AXIS),     # grads per dp rank
                   P(DATA_AXIS, STAGE_AXIS, MODEL_AXIS),     # updated params
                   P(STAGE_AXIS, MODEL_AXIS, DATA_AXIS, None)),  # master shard
        check_vma=False)
    def step(p_stacked, mb, lb):
        local = jax.tree.map(lambda t: t[0, 0], p_stacked)
        loss, grads = fwd_bwd(stage_fn, loss_fn, local, mb, loss_aux=lb,
                              first_fn=first_fn, loss_with_params=True)
        # ZeRO state bootstrap for the single tested step: this rank's row
        # shard of the flat master + zero moments
        flat = flat_buffer.flatten(local, spec)
        pad = padded_rows - spec.total_rows
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad, LANE), jnp.float32)])
        r = lax.axis_index(DATA_AXIS)
        master0 = lax.dynamic_slice_in_dim(flat, r * shard_rows, shard_rows)
        zeros = jnp.zeros((shard_rows, LANE), jnp.float32)
        new_params, new_master, _, _, _ = opt.shard_step(
            grads, master0, {"m": zeros, "v": zeros}, jnp.zeros((), jnp.int32))
        loss = lax.pmean(loss, DATA_AXIS)
        expand2 = lambda t: t[None, None]       # noqa: E731
        expand3 = lambda t: t[None, None, None]  # noqa: E731
        return (loss,
                jax.tree.map(expand3, grads),
                jax.tree.map(expand3, new_params),
                new_master[None, None])

    loss, grads_dp, params_dp, master = jax.jit(step)(stacked, mbs, labels)
    jax.block_until_ready(params_dp)

    assert np.isfinite(float(loss)), float(loss)
    # master state is genuinely row-sharded: [S, TP, dp*shard_rows, LANE]
    assert master.shape == (pp, tp, padded_rows, LANE)

    # the all-gathered params must agree across the two dp ranks
    jax.tree.map(
        lambda t: np.testing.assert_allclose(
            np.asarray(t[0]), np.asarray(t[1]), rtol=1e-6, atol=1e-7),
        params_dp)

    # oracle: per (stage, tp) coordinate, FusedAdam on the dp-mean grads
    for si in range(pp):
        for ri in range(tp):
            local_p = jax.tree.map(lambda t: t[si, ri], stacked)
            g_mean = jax.tree.map(
                lambda t: (t[0, si, ri] + t[1, si, ri]) / 2.0, grads_dp)
            ref_opt = FusedAdam(local_p, lr=1e-3, weight_decay=0.0)
            ref_params = ref_opt.step(g_mean)
            got = jax.tree.map(lambda t: t[0, si, ri], params_dp)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
                got, ref_params)
