"""Extended flagship-model coverage (VERDICT round-1 weaknesses 7+8):
BERT through amp.initialize and the DDP facade, bench shapes (seq 512),
and a GPT-2-small trace-level validation at real size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models import (BertForPreTraining, bert_tiny_config,
                             make_pretrain_step, synthetic_batch)
from apex_tpu.optimizers import FusedLAMB

pytestmark = pytest.mark.slow


def test_bert_through_amp_initialize_o2(rng):
    """amp O2: params cast to bf16 (norms fp32), optimizer returns cast
    params, training still converges."""
    cfg = bert_tiny_config()
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, 4, 32)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    opt = FusedLAMB(params, lr=1e-3)
    params, opt = amp.initialize(params, opt, opt_level="O2")

    # O2 property: non-norm floats are bf16, norm params stay fp32
    from apex_tpu.amp.policy import is_norm_param_name
    from apex_tpu.optimizers.common import path_name

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for p, leaf in flat:
        name = path_name(p)
        if not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        if is_norm_param_name(name):
            assert leaf.dtype == jnp.float32, name
        else:
            assert leaf.dtype == jnp.bfloat16, name

    step = make_pretrain_step(model)
    losses = []
    for i in range(4):
        loss, grads = step(params, batch, i)
        params = opt.step(grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # optimizer hands back the policy dtypes every step
    assert params["layer_0"]["attention"]["qkv_weight"].dtype == jnp.bfloat16


def test_bert_through_ddp_facade(rng):
    """The reference integration: DDP(module) + allreduce_gradients in the
    loop (examples/simple/distributed pattern) on the flagship model."""
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(1, 1)
    cfg = bert_tiny_config()
    model = BertForPreTraining(cfg)
    ddp = DistributedDataParallel(model, message_size=10_000_000)
    batch = synthetic_batch(rng, cfg, 8, 16)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    step, place, batch_sh = make_pretrain_step(model, mesh=mesh)
    params = place(params)
    batch = jax.tree.map(jax.device_put, batch, batch_sh)
    opt = FusedLAMB(params, lr=1e-3)
    with mesh:
        losses = []
        for i in range(3):
            loss, grads = step(params, batch, i)
            grads = ddp.allreduce_gradients(grads)
            params = opt.step(grads)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_bert_remat_trains_and_matches(rng):
    """cfg.remat wraps each BertLayer in nn.remat (static deterministic):
    training still converges and the forward is bit-identical to the
    non-remat model on the same params (the bench's batch-32 escalation
    trains with this flag)."""
    import dataclasses

    # real dropout rates: the bench trains remat + dropout +
    # deterministic=False, so the nn.Dropout rng lifting through nn.remat
    # must be covered, not just the dropout-free path
    cfg = dataclasses.replace(bert_tiny_config(), remat=True,
                              hidden_dropout=0.1, attention_dropout=0.1)
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, 2, 32)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    step = make_pretrain_step(model)
    opt = FusedLAMB(params, lr=1e-3)
    losses = []
    for i in range(4):
        loss, grads = step(params, batch, i)
        params = opt.step(grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    plain = BertForPreTraining(bert_tiny_config())
    o_r = model.apply({"params": params}, batch["input_ids"],
                      batch["token_type_ids"], batch["attention_mask"])
    o_p = plain.apply({"params": params}, batch["input_ids"],
                      batch["token_type_ids"], batch["attention_mask"])
    np.testing.assert_allclose(np.asarray(o_r[0], np.float32),
                               np.asarray(o_p[0], np.float32), rtol=1e-6)


def test_bert_seq512_bench_shape_forward(rng):
    """Tiny width but BENCH sequence length: validates the seq-512 mask /
    position plumbing the benchmark runs (interpret-mode on CPU)."""
    cfg = bert_tiny_config(max_position_embeddings=512)
    model = BertForPreTraining(cfg)
    batch = synthetic_batch(rng, cfg, 1, 512)
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"],
                        batch["attention_mask"])["params"]
    mlm, nsp = model.apply({"params": params}, batch["input_ids"],
                           batch["token_type_ids"], batch["attention_mask"])
    assert mlm.shape == (1, 512, cfg.vocab_size)
    assert np.isfinite(np.asarray(mlm, np.float32)).all()


def test_gpt2_small_traces_at_real_size():
    """GPT-2-small (12L/768H/50304V) traced + lowered at real size with
    tp=4 shard shapes — catches shape/divisibility bugs that toy configs
    hide, without paying a CPU compile."""
    from apex_tpu.models.gpt import GPTModel, gpt2_small_config

    cfg = gpt2_small_config(tensor_parallel_size=4)
    model = GPTModel(cfg)
    ids = jnp.zeros((1, 1024), jnp.int32)
    var_shape = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), ids))
    p = var_shape["params"]
    # Megatron shard shapes at tp=4
    assert p["word_embeddings"]["weight"].shape == (50304 // 4, 768)
    assert p["layer_0"]["qkv"]["weight"].shape == (3 * 768 // 4, 768)
    assert p["layer_0"]["mlp_in"]["weight"].shape == (4 * 768 // 4, 768)
    assert p["layer_0"]["mlp_out"]["weight"].shape == (768, 4 * 768 // 4)
    n_params = sum(np.prod(x.shape) for x in jax.tree.leaves(p))
    assert 25e6 < n_params < 50e6  # one tp=4 shard of ~124M

    # abstract forward under a real tp=4 mesh (eval_shape of the shard_map
    # program: traces all collectives, compiles nothing)
    import functools

    from jax.sharding import PartitionSpec as P

    from apex_tpu.mesh import MODEL_AXIS
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.initialize_model_parallel(4)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P()), out_specs=P(None, None, MODEL_AXIS),
        check_vma=False)
    def fwd(v, ids):
        return model.apply(v, ids)

    # per-rank param shapes stack over the model axis for the global view
    global_vars = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), var_shape)
    out_shape = jax.eval_shape(fwd, global_vars,
                               jax.ShapeDtypeStruct((1, 1024), jnp.int32))
    assert out_shape.shape == (1, 1024, 50304)
