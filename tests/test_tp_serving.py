"""Tensor-parallel paged serving (serving/tp.py, docs/tp_serving.md).

The acceptance pins of ISSUE 10: TP=2 paged decode is greedy
TOKEN-IDENTICAL to the single-chip engine (and to lock-step
``generate``) on the forced 8-CPU-device mesh; the tp=1 TP engine
reduces to the current engine exactly; sampled decode through the TP
engine stays SCHEDULING-INVARIANT (slot count, sync_every, arrival
pacing); and the frontend/prefix-cache/scenario stack composes with the
sharded engine transparently. Also covers the variable-sharding helper
(Megatron fused-projection interleave) and the trace-only AbstractMesh
form the lint harness / cost model use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.serving.scheduler import PagedDecodeEngine, Request
from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                 abstract_tp_mesh, infer_variable_specs,
                                 shard_model_variables, tp_mesh)

EOS = 1


@pytest.fixture(scope="module")
def tp_setup():
    """One weight set, three views: the tp=1 model/variables, and the
    tp=2 model with the SAME weights sharded over a 2-device mesh."""
    cfg1 = gpt_tiny_config()
    m1 = GPTModel(cfg1)
    v1 = m1.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    cfg2 = gpt_tiny_config(tensor_parallel_size=2)
    m2 = GPTModel(cfg2)
    mesh = tp_mesh(2)
    v2, specs = shard_model_variables(m2, v1, mesh)
    return m1, v1, m2, v2, mesh, specs


def _requests(rng, n=4, eos_free=True):
    lo = 2 if eos_free else 0
    sizes = ((5, 6), (12, 4), (3, 8), (20, 5), (9, 7))[:n]
    return [Request(prompt=rng.integers(lo, 128, s).astype(np.int32),
                    max_new_tokens=m) for s, m in sizes]


def test_tp2_greedy_token_identical_to_single_chip(tp_setup, rng):
    """The acceptance pin: the tp=2 engine's greedy outputs equal the
    single-chip engine's AND lock-step ``generate``'s, request by
    request, token by token."""
    m1, v1, m2, v2, mesh, _ = tp_setup
    reqs = _requests(rng)
    e1 = PagedDecodeEngine(m1, v1, num_slots=2, page_size=8,
                           eos_token_id=EOS)
    o1, s1 = e1.run(reqs)
    e2 = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                   page_size=8, eos_token_id=EOS)
    o2, s2 = e2.run(reqs)
    assert s2["tp_world"] == 2 and s1["tp_world"] == 1
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and both match the lock-step reference for a couple of requests
    for r, out in list(zip(reqs, o2))[:2]:
        ref = np.asarray(generate(m1, v1, r.prompt[None],
                                  max_new_tokens=r.max_new_tokens,
                                  eos_token_id=EOS))
        ref_gen = ref[0, r.prompt.shape[0]:]
        n = np.asarray(out).shape[0]
        np.testing.assert_array_equal(np.asarray(out), ref_gen[:n])


@pytest.mark.slow
def test_tp2_s_gt_1_programs_token_identical(tp_setup, rng):
    """ISSUE 13: the s>1 paged programs run UNCHANGED under the TP=2
    shard_map seam — in-engine speculative decode (draft pool + s=k
    verify sharded over the same mesh) and chunked prefill both stay
    token-identical to the single-chip non-speculative engine. (Slow
    tier: three engine compiles (~25 s) don't fit the tier-1 wall
    budget; the single-chip s>1 identity pins stay in tier-1 via
    test_spec_chunked_serving.py.)"""
    m1, v1, m2, v2, mesh, _ = tp_setup
    reqs = _requests(rng)
    base, _ = PagedDecodeEngine(m1, v1, num_slots=2, page_size=8,
                                eos_token_id=EOS).run(reqs)
    # self-draft: the tp=2 model doubles as its own draft (full
    # acceptance; the point here is the shard_map seam, not speedup)
    es = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                   page_size=8, eos_token_id=EOS,
                                   draft_model=m2, draft_variables=v2,
                                   draft_len=2)
    outs, stats = es.run(reqs)
    assert stats["mean_acceptance_len"] > 1.0
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ec = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                   page_size=8, eos_token_id=EOS,
                                   prefill_chunk=8)
    outc, statc = ec.run(reqs)
    assert statc["chunked_prefills"] >= 1
    for a, b in zip(base, outc):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_tp1_engine_reduces_to_single_chip_exactly(tp_setup, rng):
    """tp=1 must reduce to the current engine token-identically: the
    size-1 mesh's collectives are identity, so the outputs are equal
    EXACTLY (same floats, same argmaxes). (Slow tier: the tp=1 engine
    compile duplicates the single-chip programs; the tier-1 wall budget
    keeps the tp=2 identity pin and the preemption composition.)"""
    m1, v1, _, _, _, _ = tp_setup
    reqs = _requests(rng)
    mesh1 = tp_mesh(1)
    v1s, _ = shard_model_variables(m1, v1, mesh1)
    er = TensorParallelPagedEngine(m1, v1s, mesh=mesh1, num_slots=2,
                                   page_size=8, eos_token_id=EOS)
    outs_r, _ = er.run(reqs)
    e1 = PagedDecodeEngine(m1, v1, num_slots=2, page_size=8,
                           eos_token_id=EOS)
    outs_1, _ = e1.run(reqs)
    for a, b in zip(outs_r, outs_1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_tp2_sampled_scheduling_invariance(tp_setup, rng):
    """Sampled decode through the TP engine draws from per-request key
    streams — outputs must not depend on slot count or chunk size.
    (Slow tier: two extra TP engine compiles; the single-chip sampled
    invariance pin stays tier-1.)"""
    _, _, m2, v2, mesh, _ = tp_setup
    reqs = _requests(rng, n=3)
    key = jax.random.PRNGKey(7)
    ea = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                   page_size=8, eos_token_id=EOS,
                                   temperature=0.9, top_k=16, rng=key,
                                   sync_every=1)
    eb = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=3,
                                   page_size=8, eos_token_id=EOS,
                                   temperature=0.9, top_k=16, rng=key,
                                   sync_every=3)
    oa, _ = ea.run(reqs)
    ob, _ = eb.run(reqs)
    for a, b in zip(oa, ob):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_tp2_prefix_cache_hits_and_identity(tp_setup, rng):
    """The radix prefix cache shares head-SHARDED pages: warm-cache
    admissions hit, skip the shared-header prefill, and stay
    token-identical to the cache-off single-chip engine. (Slow tier:
    heavy composition variant; the single-chip prefix-cache pins and
    the tp2 greedy identity stay tier-1.)"""
    m1, v1, m2, v2, mesh, _ = tp_setup
    hdr = rng.integers(2, 128, 16).astype(np.int32)
    reqs = [Request(prompt=np.concatenate(
        [hdr, rng.integers(2, 128, 4).astype(np.int32)]),
        max_new_tokens=5) for _ in range(4)]
    ec = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                   page_size=8, eos_token_id=EOS,
                                   prefix_cache=True)
    ec.run(reqs)                       # cold: populate the tree
    outs, stats = ec.run(reqs)         # warm: every admission hits
    assert stats["prefix_hits"] >= len(reqs)
    assert stats["prefill_tokens_skipped"] > 0
    ref_engine = PagedDecodeEngine(m1, v1, num_slots=2, page_size=8,
                                   eos_token_id=EOS)
    ref, _ = ref_engine.run(reqs)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_tp_shared_prefix_scenario_checks(rng):
    """The catalogued ``tp-shared-prefix`` scenario replays the
    multi-tenant radix workload through the tp=2 engine via the
    FRONTEND (streaming submit, policy, pump) with both amplifiers on:
    per-request greedy identity vs tp=1 lock-step ``generate``, and
    scheduling invariance at a different ``sync_every``."""
    from apex_tpu.serving.scenarios import run_scenario, scenario_spec

    spec = scenario_spec("tp-shared-prefix", seed=5, n_requests=8)
    assert spec.engine.tensor_parallel == 2
    res = run_scenario(spec, check=True)
    assert res.report["checks"]["scheduling_invariance"] is True
    assert res.report["checks"]["greedy_identity_requests"] >= 1
    assert res.stats["tp_world"] == 2
    assert res.stats["retired"] == 8


def test_shard_model_variables_layout(tp_setup):
    """Sharded-variable layout: a rank's shard of the fused qkv weight
    is ITS heads' q,k,v (Megatron interleave), plain column/vocab splits
    are contiguous, and replicated leaves are whole on every device."""
    m1, v1, m2, v2, mesh, specs = tp_setup
    p2 = v2["params"]
    p1 = v1["params"]
    qkv2 = p2["layer_0"]["qkv"]["weight"]
    qkv1 = np.asarray(p1["layer_0"]["qkv"]["weight"])
    e = qkv1.shape[0] // 3
    per = e // 2
    q, k, v = qkv1[:e], qkv1[e:2 * e], qkv1[2 * e:]
    for r in range(2):
        shard = np.asarray(
            [s.data for s in qkv2.addressable_shards
             if s.device == mesh.devices.flat[r]][0])
        expect = np.concatenate([q[r * per:(r + 1) * per],
                                 k[r * per:(r + 1) * per],
                                 v[r * per:(r + 1) * per]])
        np.testing.assert_array_equal(shard, expect)
    # vocab-parallel embedding: contiguous row split
    emb2 = p2["word_embeddings"]["weight"]
    emb1 = np.asarray(p1["word_embeddings"]["weight"])
    half = emb1.shape[0] // 2
    shard0 = np.asarray(
        [s.data for s in emb2.addressable_shards
         if s.device == mesh.devices.flat[0]][0])
    np.testing.assert_array_equal(shard0, emb1[:half])
    # replicated leaf (final norm): full copy, P() spec
    spec = specs["params"]["final_norm"]["weight"]
    assert not any(s is not None for s in spec)
    np.testing.assert_array_equal(
        np.asarray(p2["final_norm"]["weight"]),
        np.asarray(p1["final_norm"]["weight"]))


def test_tp_engine_validates_mesh_and_checkpoint(tp_setup):
    """Misconfigurations fail loudly: a mesh whose axis size disagrees
    with ``tensor_parallel_size``, and a pre-sharded (local-shape)
    checkpoint passed where the full one is expected."""
    m1, v1, m2, _, mesh, _ = tp_setup
    with pytest.raises(ValueError, match="tensor_parallel_size"):
        TensorParallelPagedEngine(m1, v1, mesh=mesh, num_slots=2,
                                  page_size=8)
    local = jax.tree.map(
        lambda s: np.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: m2.init(jax.random.PRNGKey(0),
                                       jnp.zeros((1, 8), jnp.int32))))
    with pytest.raises(ValueError, match="FULL shape"):
        shard_model_variables(m2, local, mesh)


def test_abstract_mesh_engine_is_trace_only(tp_setup):
    """An ``AbstractMesh`` engine (the lint-harness/cost-model form)
    builds ShapeDtypeStruct state and traces its programs devicelessly
    — the TP cases must lint on any host, any device count."""
    _, _, m2, _, _, _ = tp_setup
    eng = TensorParallelPagedEngine(
        m2, None, mesh=abstract_tp_mesh(2), num_slots=2, page_size=8,
        num_pages=17, max_pages_per_seq=8, sync_every=2)
    assert eng.abstract
    assert isinstance(eng.cache["layers"][0]["k_pages"],
                      jax.ShapeDtypeStruct)
    # the GLOBAL pool holds the full head count; the spec shards dim 1
    kv_heads = m2.config.num_heads
    assert eng.cache["layers"][0]["k_pages"].shape[1] == kv_heads
    dvars, _ = infer_variable_specs(m2)
    i32 = jnp.int32
    jx = jax.make_jaxpr(eng._step_fn())(
        eng.cache, dvars, jax.ShapeDtypeStruct((2,), i32),
        jax.ShapeDtypeStruct((2,), jnp.bool_),
        jax.ShapeDtypeStruct((2,), i32),
        jax.ShapeDtypeStruct((2, 2), jnp.uint32),
        jax.ShapeDtypeStruct((2,), i32))
    assert jx.eqns, "decode chunk failed to stage"


def test_tp2_frontend_preemption_composes(tp_setup, rng):
    """Preempt-and-spill through the TP engine: pin every slot with
    low-priority work, land a high-priority arrival, and require the
    preemption/resume path to fire with all results intact."""
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.policy import PriorityDeadlinePolicy

    _, _, m2, v2, mesh, _ = tp_setup
    eng = TensorParallelPagedEngine(m2, v2, mesh=mesh, num_slots=2,
                                    page_size=8, eos_token_id=EOS,
                                    prefix_cache=True)
    fe = ServingFrontend(eng, policy=PriorityDeadlinePolicy(
        preempt_on_priority=True))
    low = [fe.submit(Request(prompt=rng.integers(2, 128, 12).astype(
        np.int32), max_new_tokens=12, priority=0)) for _ in range(2)]
    for _ in range(3):
        fe.pump()
    hi = fe.submit(Request(prompt=rng.integers(2, 128, 6).astype(
        np.int32), max_new_tokens=3, priority=9))
    fe.drain()
    stats = fe.stats()
    assert stats["preemptions"] >= 1 and stats["resumes"] >= 1
    assert hi.result(timeout=0).shape[0] >= 1
    for h in low:
        assert h.result(timeout=0).shape[0] >= 1
