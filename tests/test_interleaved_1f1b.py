"""Lock-step interleaved (VPP) 1F1B: parity + memory + genuine bubble math.

VERDICT r2 weak #2: the round-2 VPP schedule delivered the API while
conceding a LARGER bubble than non-interleaved. The lock-step
implementation (schedules._fwd_bwd_interleaved_1f1b) does one chunk-forward
and one chunk-backward per device per tick, giving fill/drain of
S + (S-1)/V full-stage units vs non-interleaved 1F1B's 2(S-1) — a real
reduction for S >= 4. These tests pin (a) exact grad/loss parity vs the
sequential reference AND vs the autodiff oracle on an M % S == 0 case that
takes the new path, and (b) O(V*S) activation memory (flat in M).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import STAGE_AXIS

pytestmark = pytest.mark.slow

S, V, D = 4, 2, 8


@pytest.fixture
def pp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(1, 4)


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def loss_fn(y, lb):
    return jnp.mean((y - lb) ** 2)


def make_virtual_params(rng):
    """[S, V, ...] layout: virtual stage v*S + s at [s, v]."""
    w_virt = rng.standard_normal((V * S, D, D)).astype(np.float32) / np.sqrt(D)
    b_virt = (rng.standard_normal((V * S, D)) * 0.1).astype(np.float32)
    w = np.zeros((S, V, D, D), np.float32)
    bb = np.zeros((S, V, D), np.float32)
    for v in range(V):
        for s in range(S):
            w[s, v] = w_virt[v * S + s]
            bb[s, v] = b_virt[v * S + s]
    return ({"w": jnp.asarray(w), "b": jnp.asarray(bb)},
            jnp.asarray(w_virt), jnp.asarray(b_virt))


def build_run(mesh, implementation):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving as fwd_bwd)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        p = jax.tree.map(lambda t: t[0], p_stacked)  # [V, ...] chunks
        loss, grads = fwd_bwd(stage_fn, loss_fn, p, mb, loss_aux=lb,
                              implementation=implementation)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)

    return run


def test_interleaved_1f1b_matches_sequential_and_oracle(pp4_mesh, rng):
    m = 8  # divisible by S -> takes the lock-step path
    params, w_virt, b_virt = make_virtual_params(rng)
    mbs = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    labels = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)

    def ref(pw, pb):
        def per_mb(mb, lb):
            x = mb
            for i in range(V * S):
                x = jnp.tanh(x @ pw[i] + pb[i])
            return jnp.mean((x - lb) ** 2)

        return jax.vmap(per_mb)(mbs, labels).mean()

    ref_l, (ref_gw, ref_gb) = jax.value_and_grad(ref, argnums=(0, 1))(
        w_virt, b_virt)

    loss_e, grads_e = jax.jit(build_run(pp4_mesh, "1f1b"))(
        params, mbs, labels)
    loss_a, grads_a = jax.jit(build_run(pp4_mesh, "autodiff"))(
        params, mbs, labels)

    np.testing.assert_allclose(np.asarray(loss_e), float(ref_l),
                               rtol=1e-5, atol=1e-6)
    gw, gb = np.asarray(grads_e["w"]), np.asarray(grads_e["b"])
    for v in range(V):
        for s in range(S):
            np.testing.assert_allclose(gw[s, v], np.asarray(ref_gw)[v * S + s],
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gb[s, v], np.asarray(ref_gb)[v * S + s],
                                       rtol=1e-4, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads_e, grads_a)


def _peak_temp_bytes(mesh, m, width=128):
    run = build_run(mesh, "1f1b")
    params = {"w": jnp.zeros((S, V, width, width), jnp.float32),
              "b": jnp.zeros((S, V, width), jnp.float32)}
    mbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    lbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    compiled = (jax.jit(run)
                .lower(jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
                    mbs, lbs)
                .compile())
    ma = compiled.memory_analysis()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend does not report memory analysis")
    return ma.temp_size_in_bytes


def test_interleaved_1f1b_memory_flat_in_microbatch_count(pp4_mesh):
    small = _peak_temp_bytes(pp4_mesh, m=8)
    big = _peak_temp_bytes(pp4_mesh, m=32)
    assert big <= small * 1.35 + (1 << 20), (small, big)


def test_indivisible_microbatches_warn_on_autodiff_fallback(pp4_mesh, rng,
                                                            caplog):
    """VERDICT r3 weak #7: M % S != 0 silently dropped VPP to the autodiff
    schedule; the reference raises on its divisibility constraint, we warn
    (and still train correctly)."""
    import logging

    m = 6  # not divisible by S=4
    params, w_virt, b_virt = make_virtual_params(rng)
    mbs = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    labels = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    run = build_run(pp4_mesh, "1f1b")
    with caplog.at_level(logging.WARNING):
        loss, grads = run(params, mbs, labels)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss[0]))
    assert any("num_microbatches" in r.message and "autodiff" in r.message
               for r in caplog.records), caplog.records


def test_probe_failure_warns_on_autodiff_fallback(caplog):
    """VERDICT r3 weak #4: a crashing dispatch probe must not downgrade to
    the O(M)-memory autodiff path without a signal."""
    import logging

    from apex_tpu.transformer.pipeline_parallel import schedules

    def broken_stage(p, x):
        raise ValueError("stage bug")

    with caplog.at_level(logging.WARNING):
        use = schedules._use_explicit_schedule(
            broken_stage, {"w": jnp.ones((2, 2))}, None,
            lambda y: jnp.sum(y), None, False,
            jnp.ones((4, 2, 2), jnp.float32))
    assert use is False
    assert any("probe failed" in r.message and "stage bug" in r.message
               for r in caplog.records), caplog.records


def test_bubble_accounting_beats_noninterleaved():
    """The schedule's own tick arithmetic: fill/drain in full-stage units is
    S + (S-1)/V for lock-step VPP vs 2(S-1) non-interleaved — smaller for
    S >= 4 (this is the claim the round-2 docstring had to withdraw)."""
    for s_, v_ in [(4, 2), (4, 4), (8, 2)]:
        interleaved = s_ + (s_ - 1) / v_
        non_interleaved = 2 * (s_ - 1)
        assert interleaved < non_interleaved, (s_, v_)
