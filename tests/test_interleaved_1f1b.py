"""Lock-step interleaved (VPP) 1F1B: parity + memory + genuine bubble math.

VERDICT r2 weak #2: the round-2 VPP schedule delivered the API while
conceding a LARGER bubble than non-interleaved. The lock-step
implementation (schedules._fwd_bwd_interleaved_1f1b) does one chunk-forward
and one chunk-backward per device per tick, giving fill/drain of
S + (S-1)/V full-stage units vs non-interleaved 1F1B's 2(S-1) — a real
reduction for S >= 4. These tests pin (a) exact grad/loss parity vs the
sequential reference AND vs the autodiff oracle on an M % S == 0 case that
takes the new path, and (b) O(V*S) activation memory (flat in M).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import STAGE_AXIS

pytestmark = pytest.mark.slow

S, V, D = 4, 2, 8


@pytest.fixture
def pp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(1, 4)


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def loss_fn(y, lb):
    return jnp.mean((y - lb) ** 2)


def make_virtual_params(rng):
    """[S, V, ...] layout: virtual stage v*S + s at [s, v]."""
    w_virt = rng.standard_normal((V * S, D, D)).astype(np.float32) / np.sqrt(D)
    b_virt = (rng.standard_normal((V * S, D)) * 0.1).astype(np.float32)
    w = np.zeros((S, V, D, D), np.float32)
    bb = np.zeros((S, V, D), np.float32)
    for v in range(V):
        for s in range(S):
            w[s, v] = w_virt[v * S + s]
            bb[s, v] = b_virt[v * S + s]
    return ({"w": jnp.asarray(w), "b": jnp.asarray(bb)},
            jnp.asarray(w_virt), jnp.asarray(b_virt))


def build_run(mesh, implementation):
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving as fwd_bwd)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(STAGE_AXIS), P(), P()),
        out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
        check_vma=False)
    def run(p_stacked, mb, lb):
        p = jax.tree.map(lambda t: t[0], p_stacked)  # [V, ...] chunks
        loss, grads = fwd_bwd(stage_fn, loss_fn, p, mb, loss_aux=lb,
                              implementation=implementation)
        return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)

    return run


def test_interleaved_1f1b_matches_sequential_and_oracle(pp4_mesh, rng):
    m = 8  # divisible by S -> takes the lock-step path
    params, w_virt, b_virt = make_virtual_params(rng)
    mbs = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    labels = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)

    def ref(pw, pb):
        def per_mb(mb, lb):
            x = mb
            for i in range(V * S):
                x = jnp.tanh(x @ pw[i] + pb[i])
            return jnp.mean((x - lb) ** 2)

        return jax.vmap(per_mb)(mbs, labels).mean()

    ref_l, (ref_gw, ref_gb) = jax.value_and_grad(ref, argnums=(0, 1))(
        w_virt, b_virt)

    loss_e, grads_e = jax.jit(build_run(pp4_mesh, "1f1b"))(
        params, mbs, labels)
    loss_a, grads_a = jax.jit(build_run(pp4_mesh, "autodiff"))(
        params, mbs, labels)

    np.testing.assert_allclose(np.asarray(loss_e), float(ref_l),
                               rtol=1e-5, atol=1e-6)
    gw, gb = np.asarray(grads_e["w"]), np.asarray(grads_e["b"])
    for v in range(V):
        for s in range(S):
            np.testing.assert_allclose(gw[s, v], np.asarray(ref_gw)[v * S + s],
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(gb[s, v], np.asarray(ref_gb)[v * S + s],
                                       rtol=1e-4, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        grads_e, grads_a)


def _peak_temp_bytes(mesh, m, width=128):
    run = build_run(mesh, "1f1b")
    params = {"w": jnp.zeros((S, V, width, width), jnp.float32),
              "b": jnp.zeros((S, V, width), jnp.float32)}
    mbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    lbs = jax.ShapeDtypeStruct((m, 4, width), jnp.float32)
    compiled = (jax.jit(run)
                .lower(jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
                    mbs, lbs)
                .compile())
    ma = compiled.memory_analysis()
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend does not report memory analysis")
    return ma.temp_size_in_bytes


def test_interleaved_1f1b_memory_flat_in_microbatch_count(pp4_mesh):
    small = _peak_temp_bytes(pp4_mesh, m=8)
    big = _peak_temp_bytes(pp4_mesh, m=32)
    assert big <= small * 1.35 + (1 << 20), (small, big)


def test_indivisible_microbatches_warn_on_autodiff_fallback(pp4_mesh, rng,
                                                            caplog):
    """VERDICT r3 weak #7: M % S != 0 silently dropped VPP to the autodiff
    schedule; the reference raises on its divisibility constraint, we warn
    (and still train correctly)."""
    import logging

    m = 6  # not divisible by S=4
    params, w_virt, b_virt = make_virtual_params(rng)
    mbs = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    labels = jnp.asarray(rng.standard_normal((m, 2, D)), jnp.float32)
    run = build_run(pp4_mesh, "1f1b")
    with caplog.at_level(logging.WARNING):
        loss, grads = run(params, mbs, labels)
    jax.block_until_ready(loss)
    assert np.isfinite(float(loss[0]))
    assert any("num_microbatches" in r.message and "autodiff" in r.message
               for r in caplog.records), caplog.records


def test_probe_failure_warns_on_autodiff_fallback(caplog):
    """VERDICT r3 weak #4: a crashing dispatch probe must not downgrade to
    the O(M)-memory autodiff path without a signal."""
    import logging

    from apex_tpu.transformer.pipeline_parallel import schedules

    def broken_stage(p, x):
        raise ValueError("stage bug")

    with caplog.at_level(logging.WARNING):
        use = schedules._use_explicit_schedule(
            broken_stage, {"w": jnp.ones((2, 2))}, None,
            lambda y: jnp.sum(y), None, False,
            jnp.ones((4, 2, 2), jnp.float32))
    assert use is False
    assert any("probe failed" in r.message and "stage bug" in r.message
               for r in caplog.records), caplog.records


def _collect_scan_lengths(jaxpr, out):
    """All lax.scan trip counts anywhere in a (closed) jaxpr."""
    from jax.extend import core as jex_core

    jaxpr_types = (jex_core.ClosedJaxpr, jex_core.Jaxpr)

    def as_jaxpr(v):
        return v.jaxpr if isinstance(v, jex_core.ClosedJaxpr) else v

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(int(eqn.params["length"]))
        for val in eqn.params.values():
            subs = []
            if isinstance(val, jaxpr_types):
                subs = [as_jaxpr(val)]
            elif isinstance(val, (tuple, list)):
                subs = [as_jaxpr(v) for v in val if isinstance(v, jaxpr_types)]
            for sub in subs:
                _collect_scan_lengths(sub, out)
    return out


@pytest.mark.slow
def test_bubble_measured_from_compiled_schedule(pp4_mesh, rng):
    """VERDICT r3 weak #2 closed with measurement, not arithmetic: (a) the
    tick loop the schedules actually COMPILE (the lax.scan trip count in
    the lowered program) realizes the claimed lengths — V*M + V*S + S - 1
    interleaved vs M + 2(S-1) non-interleaved — and (b) runtime
    host-callback counts of stage-body executions per device confirm the
    dead slots really are skipped (lax.cond), so per-tick cost is 1/V of a
    full stage and measured time-units are

        interleaved (S=4, V=2, M=8): 27 ticks / V = 13.5 full-stage units
        non-interleaved:             14 ticks     = 14.0 full-stage units

    i.e. fill/drain 5.5 = S + (S-1)/V beats 6 = 2(S-1)."""
    import collections

    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_without_interleaving as fwd_bwd_flat)
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving as fwd_bwd_vpp)

    params, w_virt, b_virt = make_virtual_params(rng)
    flat_params = {"w": jnp.asarray(np.asarray(params["w"])[:, 0]),
                   "b": jnp.asarray(np.asarray(params["b"])[:, 0])}

    calls = []

    def counting_stage(p, x):
        jax.debug.callback(
            lambda dev: calls.append(int(dev)),
            jax.lax.axis_index(STAGE_AXIS))
        return stage_fn(p, x)

    def build(fwd_bwd):
        @functools.partial(
            jax.shard_map, mesh=pp4_mesh,
            in_specs=(P(STAGE_AXIS), P(), P()),
            out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
            check_vma=False)
        def run(p_stacked, mb, lb):
            p = jax.tree.map(lambda t: t[0], p_stacked)
            loss, grads = fwd_bwd(counting_stage, loss_fn, p, mb,
                                  loss_aux=lb)
            return loss.reshape(1), jax.tree.map(lambda t: t[None], grads)
        return run

    run_vpp = build(fwd_bwd_vpp)
    run_flat = build(fwd_bwd_flat)

    def data(m_):
        mbs = jnp.asarray(rng.standard_normal((m_, 2, D)), jnp.float32)
        lbs = jnp.asarray(rng.standard_normal((m_, 2, D)), jnp.float32)
        return mbs, lbs

    def ticks(run, p, m_):
        return max(_collect_scan_lengths(
            jax.make_jaxpr(run)(p, *data(m_)).jaxpr, []))

    # (a) compiled tick counts (the scan the schedule actually builds) at
    # two microbatch counts: the M-linear work term and the CONSTANT
    # fill/drain overhead are measured, not derived from the formula
    t_vpp8, t_vpp16 = ticks(run_vpp, params, 8), ticks(run_vpp, params, 16)
    t_flat8, t_flat16 = (ticks(run_flat, flat_params, 8),
                         ticks(run_flat, flat_params, 16))
    assert t_vpp16 - t_vpp8 == V * 8, (t_vpp8, t_vpp16)   # V ticks per mb
    assert t_flat16 - t_flat8 == 8, (t_flat8, t_flat16)   # 1 tick per mb
    fill_drain_vpp = t_vpp8 - V * 8     # measured constant overhead, ticks
    fill_drain_flat = t_flat8 - 8
    assert fill_drain_vpp == V * S + S - 1 == 11, t_vpp8
    assert fill_drain_flat == 2 * (S - 1) == 6, t_flat8
    # a VPP tick costs 1/V of a full stage (one chunk fwd + one chunk bwd;
    # confirmed in (b)): measured fill/drain 11/2 = 5.5 < 6 full-stage units
    assert fill_drain_vpp / V < fill_drain_flat

    # (b) runtime stage-body executions: work scales EXACTLY linearly in M
    # (the extra ticks of (a) carry no hidden work — the bubble is dead
    # time), devices are uniformly loaded (the lock-step balance), and the
    # last device runs fewer standalone forwards (its last-chunk forward is
    # folded into the bwd vjp). Counts are compared per-device between M=8
    # and M=16 so any fixed per-slot callback multiplicity (vjp + remat
    # replay) cancels.
    def measure(m_):
        calls.clear()
        loss, _ = run_vpp(params, *data(m_))
        jax.block_until_ready(loss)
        jax.effects_barrier()  # debug callbacks land on a separate thread
        return collections.Counter(calls)

    c8, c16 = measure(8), measure(16)
    for dev in range(S):
        assert c16[dev] == 2 * c8[dev], (dev, c8, c16)
    assert c8[0] == c8[1] == c8[2], c8
    assert 0 < c8[S - 1] < c8[0], c8
