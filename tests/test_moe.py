"""MoE / expert parallelism: dispatch parity + all_to_all EP vs single rank.

No reference analog (apex has no MoE — beyond-reference extension); the test
strategy mirrors the TP suites: sharded execution on the 8-device CPU mesh
must reproduce a single-device ground truth bit-for-bit up to dtype noise.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import DATA_AXIS


def _dense_moe_reference(x, router_w, w1, b1, w2, b2, k, normalize):
    """Ground truth: every token through its top-k experts, no capacity."""
    logits = x.astype(np.float32) @ router_w.T
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)
    top_vals = np.asarray(top_vals)
    if normalize:
        top_vals = top_vals / top_vals.sum(-1, keepdims=True)
    out = np.zeros_like(x, dtype=np.float32)
    for t in range(x.shape[0]):
        for i in range(k):
            e = int(top_idx[t, i])
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                x[t] @ w1[e] + b1[e])))
            out[t] += top_vals[t, i] * (h @ w2[e] + b2[e])
    return out


def _ample_capacity(num_experts, k):
    # capacity = cf * k * T / E >= T  <=>  cf >= E / k: dropless
    return float(num_experts) / k + 1.0


def test_single_rank_moe_matches_dense_reference(rng):
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 8, 16, 4, 2, 12
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=_ample_capacity(e, k),
                   expert_world_size=1, axis_name="nope")
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    v = layer.init(jax.random.PRNGKey(0), x)
    y, aux = layer.apply(v, x)

    p = v["params"]
    ref = _dense_moe_reference(
        np.asarray(x), np.asarray(p["router"]["weight"]),
        np.asarray(p["w1"]), np.asarray(p["b1"]),
        np.asarray(p["w2"]), np.asarray(p["b2"]), k, True)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)
    assert float(aux.load_balance) >= 1.0 - 1e-5  # lower bound at uniform
    assert np.isfinite(float(aux.z_loss))


def test_capacity_drops_tokens(rng):
    """With capacity 1 slot/expert most assignments drop; output shrinks."""
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, t = 8, 16, 4, 32
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    ample = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=1,
                   capacity_factor=_ample_capacity(e, 1),
                   expert_world_size=1, axis_name="nope")
    v = ample.init(jax.random.PRNGKey(0), x)
    y_full, _ = ample.apply(v, x)
    tight = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=1,
                   capacity_factor=e / t,  # 1 slot per expert
                   expert_world_size=1, axis_name="nope")
    y_tight, _ = tight.apply(v, x)
    dropped = np.sum(np.all(np.asarray(y_tight) == 0.0, axis=-1))
    assert dropped >= t - 2 * e  # at most 2*... only e slots survive...
    assert not np.allclose(np.asarray(y_full), np.asarray(y_tight))


@pytest.mark.slow
@pytest.mark.parametrize("ep", [2, 4])
def test_expert_parallel_matches_single_rank(rng, ep):
    """ep-way all_to_all MoE == single-rank MoE with the same stacked params."""
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k = 8, 16, 8, 2
    t_per = 8                      # tokens per rank
    t = t_per * ep
    cf = _ample_capacity(e, k)

    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)

    # ground truth on one rank, full expert stack
    single = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                    capacity_factor=cf, expert_world_size=1, axis_name="nope")
    v = single.init(jax.random.PRNGKey(1), x)
    y_ref, aux_ref = single.apply(v, x)

    # shard the same params: rank r owns experts [r*e/ep, (r+1)*e/ep)
    p = v["params"]
    e_loc = e // ep
    sharded_params = {
        "router": {"weight": p["router"]["weight"]},   # replicated
        "w1": p["w1"].reshape(ep, e_loc, d, ff),
        "b1": p["b1"].reshape(ep, e_loc, ff),
        "w2": p["w2"].reshape(ep, e_loc, ff, d),
        "b2": p["b2"].reshape(ep, e_loc, d),
    }
    par = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                 capacity_factor=cf, expert_world_size=ep,
                 axis_name=DATA_AXIS)

    # an ep-sized mesh so the data axis IS the expert-parallel group
    devs = jax.devices()[:ep]
    from jax.sharding import Mesh
    small = Mesh(np.asarray(devs).reshape(ep, 1, 1, 1),
                 ("data", "stage", "context", "model"))

    @functools.partial(
        jax.shard_map, mesh=small,
        in_specs=(P("data"), P("data"), P()), out_specs=(P("data"), P()),
        check_vma=False)
    def run(xx, wstack, rw):
        variables = {"params": {
            "router": {"weight": rw},
            "w1": wstack["w1"][0], "b1": wstack["b1"][0],
            "w2": wstack["w2"][0], "b2": wstack["b2"][0]}}
        y, aux = par.apply(variables, xx)
        return y, aux.load_balance

    wstack = {kk: sharded_params[kk] for kk in ("w1", "b1", "w2", "b2")}
    y_par, lb_par = run(x, wstack, p["router"]["weight"])

    np.testing.assert_allclose(np.asarray(y_par), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_grads_flow_and_balance_loss_differentiable(rng):
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 8, 16, 4, 2, 16
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=_ample_capacity(e, k),
                   expert_world_size=1, axis_name="nope",
                   aux_loss_coeff=1e-2, z_loss_coeff=1e-3)
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    v = layer.init(jax.random.PRNGKey(0), x)

    def loss(params, xx):
        y, aux = layer.apply({"params": params}, xx)
        return jnp.sum(y * y) + aux.total

    g = jax.grad(loss)(v["params"], x)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in flat)
    # router weight must receive gradient (through gates AND aux losses)
    assert float(jnp.sum(jnp.abs(g["router"]["weight"]))) > 0.0
    # every expert weight tensor must receive gradient
    assert float(jnp.sum(jnp.abs(g["w1"]))) > 0.0


def test_swiglu_experts_match_manual(rng):
    """activation='swiglu' experts: dropless MoE output == manual top-k
    routing through silu(x@gate)*(x@up) @ down per expert."""
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 8, 16, 4, 2, 12
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=_ample_capacity(e, k),
                   activation="swiglu", expert_world_size=1,
                   axis_name="nope")
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    v = layer.init(jax.random.PRNGKey(0), x)
    y, _ = layer.apply(v, x)

    p = v["params"]
    assert "b1" not in p and "b2" not in p  # bias-free like Mixtral w1/w3/w2
    logits = np.asarray(x, np.float32) @ np.asarray(
        p["router"]["weight"]).T
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))
    top_idx = np.argsort(-probs, axis=-1)[:, :k]
    out = np.zeros((t, d), np.float32)
    w1 = np.asarray(p["w1"])
    w2 = np.asarray(p["w2"])
    for ti in range(t):
        gates = probs[ti, top_idx[ti]]
        gates = gates / gates.sum()
        for gi, ei in zip(gates, top_idx[ti]):
            hh = np.asarray(x[ti]) @ w1[ei]
            gate_h, up_h = hh[:ff], hh[ff:]
            act = np.asarray(jax.nn.silu(jnp.asarray(gate_h))) * up_h
            out[ti] += gi * (act @ w2[ei])
    np.testing.assert_allclose(np.asarray(y), out, rtol=2e-4, atol=2e-4)


def test_moe_under_gspmd_jit_sharded_experts(rng):
    """Dense-dispatch MoEMLP under plain jit with the expert stacks sharded
    over ``data`` via NamedSharding: GSPMD partitions the dispatch/expert
    einsums itself (inserting the all_to_alls), and the result must match
    the unsharded single-device module — the pjit-trainer consumption path
    (no shard_map)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 8, 16, 8, 2, 32
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=_ample_capacity(e, k),
                   expert_world_size=1, axis_name="nope")
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    v = layer.init(jax.random.PRNGKey(0), x)
    y_ref, _ = layer.apply(v, x)

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("data",))
    exp_sh = NamedSharding(mesh, P("data"))      # experts split over data
    rep_sh = NamedSharding(mesh, P())
    p = v["params"]
    p_sharded = {
        "router": jax.device_put(p["router"], rep_sh),
        "w1": jax.device_put(p["w1"], exp_sh),
        "b1": jax.device_put(p["b1"], exp_sh),
        "w2": jax.device_put(p["w2"], exp_sh),
        "b2": jax.device_put(p["b2"], exp_sh),
    }
    x_sh = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def f(params, xx):
        y, aux = layer.apply({"params": params}, xx)
        return y, aux.total

    with mesh:
        y, aux = f(p_sharded, x_sh)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(aux))


@pytest.mark.slow
@pytest.mark.parametrize("activation", ["gelu", "swiglu"])
def test_expert_tensor_parallel_matches_single_rank(rng, activation):
    """EP x expert-TP: (data=2, model=2) mesh — experts split over data AND
    their FFN dim over model (w2 partials psum'd) == the single-rank
    full-expert module."""
    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k = 8, 16, 4, 2
    ep, tp = 2, 2
    t_per = 8
    t = t_per * ep
    cf = _ample_capacity(e, k)
    ffl = ff // tp
    e_loc = e // ep

    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
    single = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                    capacity_factor=cf, activation=activation,
                    expert_world_size=1, axis_name="nope")
    v = single.init(jax.random.PRNGKey(2), x)
    y_ref, _ = single.apply(v, x)
    p = v["params"]

    # slice: expert rows over ep; FFN cols over tp ([gate_r|up_r] for swiglu)
    def w1_slice(er, tr):
        w = np.asarray(p["w1"])[er * e_loc:(er + 1) * e_loc]
        if activation == "swiglu":
            gate, up = w[..., :ff], w[..., ff:]
            return np.concatenate([gate[..., tr * ffl:(tr + 1) * ffl],
                                   up[..., tr * ffl:(tr + 1) * ffl]], -1)
        return w[..., tr * ffl:(tr + 1) * ffl]

    def w2_slice(er, tr):
        return np.asarray(p["w2"])[er * e_loc:(er + 1) * e_loc,
                                   tr * ffl:(tr + 1) * ffl]

    stacked = {
        "w1": np.stack([[w1_slice(er, tr) for tr in range(tp)]
                        for er in range(ep)]),
        "w2": np.stack([[w2_slice(er, tr) for tr in range(tp)]
                        for er in range(ep)]),
    }
    if activation == "gelu":
        b1 = np.asarray(p["b1"])
        stacked["b1"] = np.stack(
            [[b1[er * e_loc:(er + 1) * e_loc, tr * ffl:(tr + 1) * ffl]
              for tr in range(tp)] for er in range(ep)])
        # b2 replicated over tp (added after the psum)
        stacked["b2"] = np.stack(
            [[np.asarray(p["b2"])[er * e_loc:(er + 1) * e_loc]
              for _ in range(tp)] for er in range(ep)])

    par = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                 capacity_factor=cf, activation=activation,
                 expert_world_size=ep, axis_name="data",
                 tensor_world_size=tp, tensor_parallel_axis="model")

    from jax.sharding import Mesh
    devs = jax.devices()[:ep * tp]
    mesh = Mesh(np.asarray(devs).reshape(ep, 1, 1, tp),
                ("data", "stage", "context", "model"))

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("data"), P("data", "model"), P()),
        out_specs=P("data"), check_vma=False)
    def run(xx, ws, rw):
        variables = {"params": dict(
            {"router": {"weight": rw}},
            **{kk: ws[kk][0, 0] for kk in ws})}
        y, _ = par.apply(variables, xx)
        return y

    ws = {kk: jnp.asarray(vv) for kk, vv in stacked.items()}
    y_par = run(x, ws, p["router"]["weight"])
    np.testing.assert_allclose(np.asarray(y_par), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)
