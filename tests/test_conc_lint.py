"""tpu-lint concurrency tier (apex_tpu.analysis.conc) coverage.

Mirrors the PR 3/5 load-bearing pattern for the third tier, per ISSUE 7:

1. per-rule fixture pairs — a bad module that triggers EXACTLY its rule
   (and passes with the rule deselected), and a good twin that is clean;
2. machinery — thread coloring, GuardedBy inference, inline suppression,
   the tier-partitioned baseline, CLI usage errors, ``--diff`` coverage;
3. seeded mutations against the LIVE frontend: removing one
   ``with self._lock:`` fires ``conc-unguarded-shared-field``, and an
   inverted acquisition order fires ``conc-lock-order-cycle``;
4. end-to-end — ``--conc`` over the repo itself exits 0 at HEAD: the
   tier-1 twin of the ``run_tpu_round.sh`` conc gate.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.analysis import cli                              # noqa: E402
from apex_tpu.analysis.conc import (CONC_RULES,                # noqa: E402
                                    analyze_conc_sources, build_model)
from apex_tpu.analysis.tiers import tier_of, tier_of_key       # noqa: E402

# --------------------------------------------------------------------------
# per-rule fixture pairs
# --------------------------------------------------------------------------

FIXTURES = {
    "conc-unguarded-shared-field": (
        """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def worker(self):
                self._items.append(1)

            def read(self):
                with self._lock:
                    return list(self._items)

            def also(self):
                with self._lock:
                    self._items.append(2)

            def spawn(self):
                threading.Thread(target=self.worker, name="w",
                                 daemon=True).start()
        """,
        """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def worker(self):
                with self._lock:
                    self._items.append(1)

            def read(self):
                with self._lock:
                    return list(self._items)

            def also(self):
                with self._lock:
                    self._items.append(2)

            def spawn(self):
                threading.Thread(target=self.worker, name="w",
                                 daemon=True).start()
        """,
    ),
    "conc-lock-order-cycle": (
        """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        return 1

            def rev(self):
                with self._b:
                    with self._a:
                        return 2
        """,
        """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        return 1

            def rev(self):
                with self._a:
                    with self._b:
                        return 2
        """,
    ),
    "conc-blocking-under-lock": (
        """\
        import queue
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def poll(self):
                with self._lock:
                    return self._q.get()
        """,
        """\
        import queue
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def poll(self):
                item = self._q.get()
                with self._lock:
                    return item
        """,
    ),
    "conc-resource-leak": (
        """\
        from apex_tpu.serving import kv_pool

        def grab(cache, slot, n, ok):
            cache = kv_pool.alloc_slot(cache, slot, n)
            if not ok:
                raise RuntimeError("boom")
            return kv_pool.free_slot(cache, slot)
        """,
        """\
        from apex_tpu.serving import kv_pool

        def grab(cache, slot, n, ok):
            cache = kv_pool.alloc_slot(cache, slot, n)
            try:
                if not ok:
                    raise RuntimeError("boom")
            finally:
                cache = kv_pool.free_slot(cache, slot)
            return cache
        """,
    ),
    "conc-unreleased-lock": (
        """\
        import threading

        class M:
            def __init__(self):
                self._lock = threading.Lock()

            def step(self, fail):
                self._lock.acquire()
                if fail:
                    return None
                self._lock.release()
                return 1
        """,
        """\
        import threading

        class M:
            def __init__(self):
                self._lock = threading.Lock()

            def step(self, fail):
                with self._lock:
                    if fail:
                        return None
                    return 1
        """,
    ),
    "conc-double-acquire": (
        """\
        import threading

        class D:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    return self.inner()

            def inner(self):
                with self._lock:
                    return 1
        """,
        """\
        import threading

        class D:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    return self.inner()

            def inner(self):
                with self._lock:
                    return 1
        """,
    ),
    "conc-thread-leak": (
        """\
        import threading

        def fire(fn):
            t = threading.Thread(target=fn)
            t.start()
        """,
        """\
        import threading

        def fire(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            u = threading.Thread(target=fn)
            u.start()
            u.join()
        """,
    ),
    "conc-useless-local-lock": (
        """\
        import threading

        def guard(x):
            lock = threading.Lock()
            with lock:
                return x + 1
        """,
        """\
        import threading

        _LOCK = threading.Lock()

        def guard(x):
            with _LOCK:
                return x + 1
        """,
    ),
    "conc-await-under-lock": (
        """\
        import asyncio
        import threading

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            async def step(self):
                with self._lock:
                    await asyncio.sleep(0)
        """,
        # the good twin is ALSO the asyncio-primitive discrimination
        # test: `async with asyncio.Lock()` suspends instead of
        # blocking and must never register as a threading lock (if it
        # did, the await under it would fire)
        """\
        import asyncio
        import threading

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()
                self._alock = asyncio.Lock()

            async def step(self):
                with self._lock:
                    n = 1
                async with self._alock:
                    await asyncio.sleep(0)
                return n
        """,
    ),
}


def _run(src, select=None):
    findings, suppressed = analyze_conc_sources(
        {"apex_tpu/mod.py": textwrap.dedent(src)}, select=select)
    return findings, suppressed


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_bad_module_triggers_exactly_its_rule(rule):
    findings, _ = _run(FIXTURES[rule][0])
    fired = [f.rule for f in findings]
    assert fired, f"bad module for {rule} produced no findings"
    assert set(fired) == {rule}, fired


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_good_module_is_clean(rule):
    findings, _ = _run(FIXTURES[rule][1])
    assert not findings, [(f.rule, f.message) for f in findings]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_conc_rules_individually_load_bearing(rule):
    """With the rule deselected (≈ deleted), its bad module passes: no
    other conc rule shadows it."""
    others = [r for r in CONC_RULES if r != rule]
    findings, _ = _run(FIXTURES[rule][0], select=others)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_every_conc_rule_has_a_fixture():
    assert set(CONC_RULES) == set(FIXTURES)


# --------------------------------------------------------------------------
# machinery: coloring, inference, suppression, tiers, CLI
# --------------------------------------------------------------------------

def _surface_sources():
    root = Path(REPO)
    return {cli._rel(root, p): p.read_text()
            for p in cli.discover(root, ())}


def test_pump_thread_coloring_on_live_frontend():
    """The background pump thread is discovered by its literal name and
    colors the whole pump-side call chain, including the handle-push
    path another thread consumes."""
    model, _ = build_model(_surface_sources())
    colored = {k.qualname for k, v in model.colors.items()
               if "serving-frontend-pump" in v}
    for fn in ("ServingFrontend.pump", "ServingFrontend._harvest",
               "ServingFrontend._try_admit", "StreamHandle._push"):
        assert fn in colored, sorted(colored)
    # ISSUE 17: the host-tier copy drain rides the pump's host-work
    # slot — no new thread, so the demote/promote chain must inherit
    # the pump color (the field rule sees tier-adjacent engine state
    # as pump-confined, same as the admission path)
    for fn in ("ServingFrontend._demote", "ServingFrontend._try_promote"):
        assert fn in colored, sorted(colored)
    # the /metrics endpoint's handler colors the exporter/registry reads
    http = {k.qualname for k, v in model.colors.items()
            if "http-handler" in v}
    assert "prometheus_text" in http and "snapshot" in http


def test_guardedby_inference_on_live_frontend():
    """The inference recovers the intended lock discipline of the
    serving stack (the docs/frontend.md thread-safety contract)."""
    model, _ = build_model(_surface_sources())
    guards = {(f[1], f[2]): lock.display()
              for f, (lock, _, _) in model.inferred_guards().items()}
    assert guards[("StreamHandle", "_tokens")] == "StreamHandle._lock"
    assert guards[("ServingFrontend", "_ingest")] == \
        "ServingFrontend._ingest_lock"
    assert guards[("ServingFrontend", "_failure")] == \
        "ServingFrontend._ingest_lock"
    assert guards[("SpanTracer", "_spans")] == "SpanTracer._lock"
    assert guards[("EventLog", "_buf")] == "EventLog._lock"
    assert guards[("Counter", "_value")] == "_LOCK"
    # ISSUE 8: the pump timing / SLO-window fields are pump-confined by
    # design — never locked anywhere, so the inference must NOT claim a
    # guard for them (a half-locked access pattern would fire the rule)
    for field in ("_last_ready", "_wait_s", "_slo_window",
                  "_storm_seen"):
        assert ("ServingFrontend", field) not in guards
    # the compile watcher's tables ARE locked everywhere
    assert guards[("CompileWatcher", "_compiles")] == \
        "CompileWatcher._lock"


def test_router_supervisor_thread_coloring():
    """ISSUE 11: the replica router's supervisor thread is discovered
    by its literal name and colors the whole supervision chain —
    failure detection, token forwarding, failover resubmission — so the
    field rule sees every router-state access as multi-thread."""
    model, _ = build_model(_surface_sources())
    colored = {k.qualname for k, v in model.colors.items()
               if "serving-router-supervisor" in v}
    for fn in ("ReplicaRouter._tick", "ReplicaRouter._service_locked",
               "ReplicaRouter._failover_locked", "ReplicaRouter._place",
               "ReplicaRouter._route_due",
               "ReplicaRouter._mark_dead_locked"):
        assert fn in colored, sorted(colored)


def test_router_guardedby_map_pinned():
    """ISSUE 11: the router's lock discipline is a CHECKED contract —
    the inference must recover exactly the intended GuardedBy map for
    the router's shared state and the fault injector's trigger
    counters (and the frontend's new shutdown flag)."""
    model, _ = build_model(_surface_sources())
    guards = {(f[1], f[2]): lock.display()
              for f, (lock, _, _) in model.inferred_guards().items()}
    for field in ("_entries", "_queued", "_records", "_accepting",
                  "_rr_next", "_sup_thread"):
        assert guards[("ReplicaRouter", field)] == \
            "ReplicaRouter._lock", (field, guards.get(
                ("ReplicaRouter", field)))
    for field in ("_pumps", "_submits", "_rejected", "fired"):
        assert guards[("FaultInjector", field)] == \
            "FaultInjector._lock"
    assert guards[("ServingFrontend", "_accepting")] == \
        "ServingFrontend._ingest_lock"


def test_host_tier_guardedby_map_pinned():
    """ISSUE 17: the host spill tier is one single-lock object shared
    between the pump (demote / drain / promote) and arbitrary caller
    threads reading ``stats()`` — the inference must recover
    ``HostPageTier._lock`` for every piece of tier state."""
    model, _ = build_model(_surface_sources())
    guards = {(f[1], f[2]): lock.display()
              for f, (lock, _, _) in model.inferred_guards().items()}
    for field in ("_entries", "_pending", "_resident_bytes"):
        assert guards[("HostPageTier", field)] == \
            "HostPageTier._lock", (field, guards.get(
                ("HostPageTier", field)))


def test_fleet_guardedby_map_pinned():
    """ISSUE 19: the fleet plane's lock discipline is a CHECKED
    contract. The collector merges scrape results under its OWN lock
    (never the router's — the scrape I/O itself runs lock-free), the
    alerter's sample window is single-lock, and the router's flight /
    tick bookkeeping joined the router-lock family."""
    model, _ = build_model(_surface_sources())
    guards = {(f[1], f[2]): lock.display()
              for f, (lock, _, _) in model.inferred_guards().items()}
    for field in ("_order", "_rows", "_tails", "_cursors",
                  "_scraped_at", "_storms", "_dropped", "_alive"):
        assert guards[("FleetCollector", field)] == \
            "FleetCollector._lock", (field, guards.get(
                ("FleetCollector", field)))
    for field in ("_samples", "_firing", "_fired"):
        assert guards[("BurnRateAlerter", field)] == \
            "BurnRateAlerter._lock", (field, guards.get(
                ("BurnRateAlerter", field)))
    for field in ("_flight_reason", "_last_tick_t", "last_flight"):
        assert guards[("ReplicaRouter", field)] == \
            "ReplicaRouter._lock", (field, guards.get(
                ("ReplicaRouter", field)))
    # the collector's read side is reachable from the supervisor color
    # (the flight path), so the field rule treats its state as shared
    colored = {k.qualname for k, v in model.colors.items()
               if "serving-router-supervisor" in v}
    for fn in ("FleetCollector.block", "FleetCollector.events_tail",
               "FleetCollector.scrape_ages"):
        assert fn in colored, sorted(c for c in colored if "Fleet" in c)


def test_fleet_collector_tick_coloring_fixture():
    """ISSUE 19: ``router._tick_impl`` invokes ``self.fleet.tick()``
    across a module boundary the call-graph cannot resolve, so the
    supervisor-coloring of the collector's tick is pinned on an inline
    fixture instead: a literal-named supervisor thread drives a mini
    collector whose tick scrapes LOCK-FREE and merges under its own
    lock. The good twin is clean; dropping the merge lock fires
    ``conc-unguarded-shared-field``."""
    good = """\
        import threading

        def scrape(fe):
            return fe.row()              # pure I/O — no lock held

        class MiniCollector:
            def __init__(self, targets):
                self._lock = threading.Lock()
                self._targets = targets
                self._rows = {}

            def tick(self):
                got = {n: scrape(fe) for n, fe in self._targets}
                with self._lock:
                    for name, row in got.items():
                        self._rows[name] = row

            def block(self):
                with self._lock:
                    return dict(self._rows)

        class Sup:
            def __init__(self, collector):
                self.fleet = collector

            def _loop(self):
                self.fleet.tick()

            def start(self):
                threading.Thread(target=self._loop,
                                 name="mini-fleet-supervisor",
                                 daemon=True).start()
    """
    findings, _ = _run(good)
    assert not findings, [(f.rule, f.message) for f in findings]
    src = {"apex_tpu/mod.py": textwrap.dedent(good)}
    model, _ = build_model(src)
    colored = {k.qualname for k, v in model.colors.items()
               if "mini-fleet-supervisor" in v}
    # the supervisor color reaches the tick AND its lock-free scrape
    for fn in ("Sup._loop", "MiniCollector.tick", "scrape"):
        assert fn in colored, sorted(colored)
    guards = {(f[1], f[2]): lock.display()
              for f, (lock, _, _) in model.inferred_guards().items()}
    assert guards[("MiniCollector", "_rows")] == "MiniCollector._lock"
    bad = good.replace("""\
                with self._lock:
                    for name, row in got.items():
                        self._rows[name] = row
""", """\
                for name, row in got.items():
                    self._rows[name] = row
""")
    assert bad != good, "mutation did not apply"
    findings, _ = _run(bad)
    assert "conc-unguarded-shared-field" in [f.rule for f in findings], \
        [(f.rule, f.message) for f in findings]


def test_promote_pairing_catches_dropped_promotion():
    """ISSUE 17: ``promote_pages`` pops device pages off the free stack
    exactly like an allocation; the obligation discharges when
    ``insert_promoted`` grafts the page into the radix tree. A path
    that promotes but exits before the graft silently leaks device
    pages — the conc-resource-leak pairing table must catch it."""
    bad = """\
        from apex_tpu.serving import kv_pool

        def promote(cache, tree, nodes, key, pages, n, tiles, ok):
            cache = kv_pool.promote_pages(cache, pages, n, tiles)
            if not ok:
                return cache
            tree.insert_promoted(nodes, key, int(pages[0]))
            return cache
    """
    findings, _ = _run(bad)
    assert [f.rule for f in findings] == ["conc-resource-leak"], \
        [(f.rule, f.message) for f in findings]
    good = """\
        from apex_tpu.serving import kv_pool

        def promote(cache, tree, nodes, key, pages, n, tiles):
            cache = kv_pool.promote_pages(cache, pages, n, tiles)
            tree.insert_promoted(nodes, key, int(pages[0]))
            return cache
    """
    findings, _ = _run(good)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_asyncio_task_coloring_on_live_http_server():
    """ISSUE 15: asyncio tasks are a thread color. The HTTP server's
    per-connection callback (handed to ``asyncio.start_server``) roots
    the ``asyncio`` color and it propagates through the whole
    connection-handling chain, including the disconnect watcher spawned
    via ``loop.create_task(...)``; the loop's own host thread keeps its
    literal-name color."""
    model, _ = build_model(_surface_sources())
    colored = {k.qualname for k, v in model.colors.items()
               if "asyncio" in v}
    for fn in ("HttpServingServer._handle", "HttpServingServer._dispatch",
               "HttpServingServer._generate",
               "HttpServingServer._stream_tokens",
               "HttpServingServer._watch_disconnect",
               "HttpServingServer._sse"):
        assert fn in colored, sorted(colored)
    loop_thread = {k.qualname for k, v in model.colors.items()
                   if "serving-http-loop" in v}
    assert "HttpServingServer._run" in loop_thread, sorted(loop_thread)
    # the client's per-request reader threads color the SSE parse chain
    reader = {k.qualname for k, v in model.colors.items()
              if "_stream" in v}
    assert "HttpReplicaClient._stream" in reader
    assert "_iter_sse" in reader


def test_docs_thread_safety_contract_matches_inference():
    """docs/frontend.md's contract table rows are cross-checked against
    the inferred GuardedBy map — the doc cannot drift from the code."""
    doc = Path(REPO, "docs", "frontend.md").read_text()
    rows = [line for line in doc.splitlines()
            if line.startswith("| `") and "`" in line[3:]]
    claimed = {}
    for line in rows:
        cells = [c.strip().strip("`") for c in line.strip("|").split("|")]
        if len(cells) >= 2 and "." in cells[0] and cells[1] != "—":
            claimed[cells[0]] = cells[1]
    assert claimed, "docs/frontend.md lost its thread-safety table"
    model, _ = build_model(_surface_sources())
    inferred = {f"{f[1]}.{f[2]}": lock.display()
                for f, (lock, _, _) in model.inferred_guards().items()}
    for field, lock in claimed.items():
        assert inferred.get(field) == lock, (
            f"doc claims {field} is guarded by {lock}; inference says "
            f"{inferred.get(field)}")


def test_blocking_in_nested_thread_target_not_flagged():
    """A nested def created under a lock runs when CALLED — on its own
    thread, lock-free. Its body must not inherit the enclosing
    function's lockset (code-review repro)."""
    src = """\
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def helper(self):
                with self._lock:
                    def cb():
                        return self._q.get()
                    t = threading.Thread(target=cb, daemon=True)
                    t.start()
                    return t
    """
    findings, _ = _run(src)
    assert not findings, [(f.rule, f.message) for f in findings]


def test_conc_finding_is_inline_suppressible():
    src = FIXTURES["conc-useless-local-lock"][0].replace(
        "lock = threading.Lock()",
        "lock = threading.Lock()  "
        "# tpu-lint: disable=conc-useless-local-lock -- test")
    findings, suppressed = _run(src)
    assert not findings
    assert suppressed == 1


def test_tier_registry():
    assert tier_of("conc-lock-order-cycle") == "conc"
    assert tier_of("ir-x64-leak") == "ir"
    assert tier_of("host-sync-in-jit") == "ast"
    assert tier_of_key("a.py::conc-resource-leak::fn") == "conc"
    assert tier_of_key("a.py::host-sync-in-jit::fn") == "ast"
    assert tier_of_key("legacy-shape") == "ast"


def test_conc_write_baseline_keeps_other_tiers(tmp_path, monkeypatch):
    """--conc --write-baseline replaces only conc-* entries; AST and IR
    debt survives (the shared prefix registry, not string checks)."""
    from apex_tpu.analysis.walker import Finding

    baseline = tmp_path / "tpu_lint_baseline.json"
    baseline.write_text(json.dumps({"version": 1, "findings": {
        "x.py::conc-blocking-under-lock::old": 1,
        "y.py::ir-dead-output::case_b": 2,
        "z.py::host-sync-in-jit::fn": 3,
    }}))
    fresh = Finding(rule="conc-resource-leak", severity="error",
                    path="x.py", line=1, col=1, message="m", scope="fn")
    import apex_tpu.analysis.conc as conc_pkg
    monkeypatch.setattr(conc_pkg, "analyze_conc",
                        lambda root, select=None: ([fresh], 0))
    assert cli.main(["--root", str(tmp_path), "--conc",
                     "--write-baseline"]) == 0
    counts = json.loads(baseline.read_text())["findings"]
    assert counts == {
        "x.py::conc-resource-leak::fn": 1,     # conc tier replaced
        "y.py::ir-dead-output::case_b": 2,     # IR kept
        "z.py::host-sync-in-jit::fn": 3,       # AST kept
    }


def test_conc_cli_usage_errors(capsys):
    assert cli.main(["--root", REPO, "--conc",
                     "--select", "no-such-conc-rule"]) == 2
    # AST rule names are not valid in conc mode
    assert cli.main(["--root", REPO, "--conc",
                     "--select", "host-sync-in-jit"]) == 2
    assert cli.main(["apex_tpu", "--root", REPO, "--conc"]) == 2
    assert cli.main(["--root", REPO, "--conc", "--ir"]) == 2
    assert cli.main(["--root", REPO, "--conc", "--diff", "HEAD"]) == 2


def test_list_rules_shows_all_tiers(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "conc:host" in out
    assert "conc-lock-order-cycle" in out
    assert "ir:jaxpr" in out


# --------------------------------------------------------------------------
# --diff covers the conc tier
# --------------------------------------------------------------------------

_DIFF_BASE = """\
import threading

def guard(x):
    lock = threading.Lock()
    with lock:
        return x + 1
"""

_DIFF_NEW = _DIFF_BASE + """\

def guard2(x):
    lock2 = threading.Lock()
    with lock2:
        return x + 2
"""


def _git(repo, *args):
    subprocess.run(["git", "-C", str(repo), *args], check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_diff_covers_conc_tier(tmp_path, capsys):
    """A pre-existing conc finding at the base rev is absorbed; the one
    introduced since fails the diff gate."""
    _git(tmp_path, "init", "-q")
    mod = tmp_path / "tpu_scratch.py"
    mod.write_text(_DIFF_BASE)
    _git(tmp_path, "add", "tpu_scratch.py")
    _git(tmp_path, "commit", "-qm", "base")
    # unchanged tree: diff-clean even though the absolute gate would fire
    assert cli.main(["--root", str(tmp_path), "--diff", "HEAD"]) == 0
    capsys.readouterr()
    mod.write_text(_DIFF_NEW)
    rc = cli.main(["--root", str(tmp_path), "--diff", "HEAD"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "conc-useless-local-lock" in out
    assert "guard2" in out           # only the NEW finding is reported


# --------------------------------------------------------------------------
# seeded mutations against the live frontend
# --------------------------------------------------------------------------

_FE = "apex_tpu/serving/frontend.py"
_PUSH_LOCKED = ("    def _push(self, tok: int) -> None:\n"
                "        with self._lock:")
_INIT_ANCHOR = "        self._ingest_lock = threading.Lock()"
_INVERTED_METHODS = '''

    def _mut_fwd(self):
        with self._ingest_lock:
            with self._order_lock:
                return None

    def _mut_rev(self):
        with self._order_lock:
            with self._ingest_lock:
                return None
'''


def test_mutation_removed_lock_is_caught():
    """ISSUE 7 acceptance: deleting one ``with self._lock:`` from the
    live frontend fires conc-unguarded-shared-field on the lock-free
    site."""
    sources = _surface_sources()
    src = sources[_FE]
    assert src.count(_PUSH_LOCKED) == 1, "frontend._push anchor moved"
    sources[_FE] = src.replace(
        _PUSH_LOCKED, _PUSH_LOCKED.replace("with self._lock:", "if True:"))
    findings, _ = analyze_conc_sources(sources)
    hits = [f for f in findings
            if f.rule == "conc-unguarded-shared-field"
            and f.scope == "StreamHandle._push"]
    assert hits, [(f.rule, f.scope) for f in findings]
    # the unlocked _push body touches several guarded fields now
    # (_tokens plus ISSUE 15's consumption-listener seam) — every one
    # must be reported against the handle's lock
    msgs = " ".join(h.message for h in hits)
    assert "_tokens" in msgs
    assert "StreamHandle._lock" in msgs


def test_mutation_inverted_lock_order_is_caught():
    """ISSUE 7 acceptance: seeding an inverted acquisition order into
    the live frontend fires conc-lock-order-cycle naming both locks."""
    sources = _surface_sources()
    src = sources[_FE]
    assert _INIT_ANCHOR in src, "frontend __init__ anchor moved"
    sources[_FE] = src.replace(
        _INIT_ANCHOR,
        _INIT_ANCHOR + "\n        self._order_lock = threading.Lock()"
    ) + _INVERTED_METHODS
    findings, _ = analyze_conc_sources(sources)
    cycles = [f for f in findings if f.rule == "conc-lock-order-cycle"]
    assert cycles, [(f.rule, f.scope) for f in findings]
    assert "_ingest_lock" in cycles[0].message
    assert "_order_lock" in cycles[0].message


_HTTP = "apex_tpu/serving/http.py"
_GEN_ANCHOR = ("        with self._lock:\n"
               "            draining = self._draining\n")


def test_mutation_await_under_lock_is_caught():
    """ISSUE 15 acceptance: moving an ``await`` under the HTTP server's
    connection lock in the live source fires conc-await-under-lock on
    the coroutine — the rule is load-bearing against the real asyncio
    surface, not just the fixture."""
    sources = _surface_sources()
    src = sources[_HTTP]
    assert src.count(_GEN_ANCHOR) == 1, "http._generate anchor moved"
    sources[_HTTP] = src.replace(
        _GEN_ANCHOR, _GEN_ANCHOR + "            await asyncio.sleep(0)\n")
    findings, _ = analyze_conc_sources(sources)
    hits = [f for f in findings if f.rule == "conc-await-under-lock"
            and f.scope == "HttpServingServer._generate"]
    assert hits, [(f.rule, f.scope) for f in findings]
    assert "HttpServingServer._lock" in hits[0].message


def test_unmutated_frontend_scheduler_pair_is_clean():
    """The live frontend/scheduler pair carries no lock-order cycles or
    unguarded fields beyond the inline-suppressed intentional ones."""
    findings, suppressed = analyze_conc_sources(_surface_sources())
    assert not findings, [(f.rule, f.path, f.line) for f in findings]
    assert suppressed >= 1           # the _failure double-checked read


# --------------------------------------------------------------------------
# end-to-end: the repo is conc-clean at HEAD (tier-1 conc-gate twin)
# --------------------------------------------------------------------------

def test_repo_conc_is_clean_at_head(capsys):
    rc = cli.main(["--root", REPO, "--conc"])
    out = capsys.readouterr().out
    assert rc == 0, f"tpu-lint --conc found new issues in the repo:\n{out}"
