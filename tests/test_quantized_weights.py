"""Quantized weight streaming (docs/serving.md "Quantized weight
streaming"): int8/fp8 per-channel and int4-grouped weight buffers for
the block linears, dequantized inside the fused dequant-matmul Pallas
kernel, selected per layer CLASS by ``WeightPrecisionPolicy`` (the
``apex.amp`` opt-level analog — embeddings/norms/biases/head stay fp).

Invariant tier (fast): the dtype-resolution and policy contracts with
their NAMED errors (no silent fp fallback, no silent legacy-flag pick),
the group-local int4 pack/unpack round trip and its TP-sharding slice
invariant, quantization error bounds per kind, fused-kernel parity
against the dequantizing reference for all three kinds, the policy
round trip leaving fp leaves untouched (bit-identical embeddings/norms/
biases), and the per-step weight-byte ratio pins at real gpt2-small
shapes (w8 <= 0.55x fp, w4 <= 0.35x fp — scale reads included), the
substrate of ``cost.decode.w8.weight_bytes_ratio_vs_bf16``.

Engine tier (slow): greedy decode through the real engines — int8, fp8
and int4-grouped weight trees vs the fp tree on GPT and windowed Llama,
TP=2 w8 token identity vs the single-chip w8 engine (group-local
packing makes contiguous shard slices exact, so sharding must not
change the numerics), speculative decode with a MORE aggressively
quantized draft (int4 draft / int8 target), and the frontend path over
a quantized tree. Unlike KV quantization, prefill itself runs the
quantized weights, so even first tokens are an empirical fixed-seed pin
rather than a structural guarantee — at tiny-GPT scale they hold, and
full streams are pinned per kind (identity counts + greedy
common-prefix floors): EVERY matmul is perturbed here, so the
tests/test_quantized_kv.py identity bar does not transfer.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.generation import generate
from apex_tpu.models.gpt import GPTModel, gpt_tiny_config
from apex_tpu.models.quantize import (assert_quantized_loaded,
                                      quantize_model_params)
from apex_tpu.ops.quant import (WeightPrecisionPolicy, dequantize_weight,
                                fused_dequant_matmul, pack_int4,
                                quantize_weight, quantize_weight_fp8,
                                quantize_weight_int4, resolve_weight_dtype,
                                unpack_int4, validate_int4_group,
                                weight_storage_dtype)
from apex_tpu.serving import PagedDecodeEngine, Request
from apex_tpu.serving.scheduler import generate_paged

PS = 8

_HAS_FP8 = hasattr(jnp, "float8_e4m3fn")

# tiny-GPT block linears have in_features 64 and 256 — group 8 divides
# both (the gpt2s default 128 does not divide 64)
TINY_GS = 8


# --- invariant tier ----------------------------------------------------------


def test_resolve_weight_dtype_contract():
    assert resolve_weight_dtype(None) is None
    assert resolve_weight_dtype(False) is None
    assert resolve_weight_dtype(True) == "int8"      # quantize_int8 alias
    assert resolve_weight_dtype("int8") == "int8"
    assert resolve_weight_dtype(jnp.int8) == "int8"
    assert resolve_weight_dtype("int4") == "int4"
    if _HAS_FP8:
        for alias in ("fp8", "e4m3", jnp.float8_e4m3fn):
            assert resolve_weight_dtype(alias) == "fp8"
        assert weight_storage_dtype("fp8") == jnp.float8_e4m3fn
    assert weight_storage_dtype("int8") == jnp.int8
    assert weight_storage_dtype("int4") == jnp.uint8   # packed nibbles
    # NAMED error, never a silent full-precision fallback
    with pytest.raises(ValueError, match="weight-dtype-unsupported"):
        resolve_weight_dtype("int2")
    with pytest.raises(ValueError, match="weight-dtype-unsupported"):
        resolve_weight_dtype(jnp.bfloat16)


def test_weight_policy_contract():
    pol = WeightPrecisionPolicy()
    assert pol.linears == "int8" and pol.group_size == 128
    assert WeightPrecisionPolicy(None).linears is None
    assert WeightPrecisionPolicy(True).linears == "int8"
    assert WeightPrecisionPolicy("int4", group_size=8).linears == "int4"
    with pytest.raises(ValueError, match="weight-dtype-unsupported"):
        WeightPrecisionPolicy("int2")
    with pytest.raises(ValueError, match="int4-group-invalid"):
        WeightPrecisionPolicy("int4", group_size=12)
    # the ONE resolution rule for policy x legacy quantize_int8 flag
    assert WeightPrecisionPolicy.resolve(None, False) is None
    assert WeightPrecisionPolicy.resolve(None, True).linears == "int8"
    assert WeightPrecisionPolicy.resolve(
        WeightPrecisionPolicy(None), True).linears == "int8"
    kept = WeightPrecisionPolicy.resolve(WeightPrecisionPolicy("int8"), True)
    assert kept.linears == "int8"
    with pytest.raises(ValueError, match="weight-policy-conflict"):
        WeightPrecisionPolicy.resolve(
            WeightPrecisionPolicy("int4", group_size=8), True)


def test_validate_int4_group_named_errors():
    validate_int4_group(64, 8)
    with pytest.raises(ValueError, match="int4-group-invalid"):
        validate_int4_group(64, 12)            # not a power of two
    with pytest.raises(ValueError, match="int4-group-invalid"):
        validate_int4_group(64, 1)             # too small
    with pytest.raises(ValueError, match="int4-group-invalid"):
        validate_int4_group(60, 8)             # not a multiple


def test_pack_int4_roundtrip_and_shard_slice_invariant(rng):
    q = rng.integers(-8, 8, (6, 64)).astype(np.int8)
    gs = 16
    packed = pack_int4(jnp.asarray(q), group_size=gs)
    assert packed.shape == (6, 32) and packed.dtype == jnp.uint8
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(packed, group_size=gs)), q)
    # GROUP-LOCAL packing: a contiguous slice of whole groups along the
    # packed axis IS the packed form of those groups — the invariant
    # that lets tensor-parallel row-sharding slice packed weights
    # (and their contiguous scale rows) with zero repacking
    half = 32 // 2                              # 2 of 4 groups
    np.testing.assert_array_equal(
        np.asarray(packed[:, :half]),
        np.asarray(pack_int4(jnp.asarray(q[:, :32]), group_size=gs)))
    np.testing.assert_array_equal(
        np.asarray(packed[:, half:]),
        np.asarray(pack_int4(jnp.asarray(q[:, 32:]), group_size=gs)))


def test_quantize_roundtrip_bounds(rng):
    w = rng.standard_normal((12, 64)).astype(np.float32) * 3.0
    q, s = quantize_weight(jnp.asarray(w))
    err = np.abs(np.asarray(dequantize_weight(q, s)) - w)
    assert np.all(err <= np.asarray(s)[:, None] / 2 + 1e-7)

    qp, sg = quantize_weight_int4(jnp.asarray(w), group_size=16)
    assert qp.shape == (12, 32) and sg.shape == (4, 12)
    err4 = np.abs(np.asarray(dequantize_weight(qp, sg)) - w)
    # per-(channel, group) grid: half an LSB of each group's scale
    bound = np.asarray(sg).T.repeat(16, axis=1) / 2 + 1e-6
    assert np.all(err4 <= bound)

    if _HAS_FP8:
        q8, s8 = quantize_weight_fp8(jnp.asarray(w))
        assert q8.dtype == jnp.float8_e4m3fn
        deq = np.asarray(dequantize_weight(q8, s8))
        # e4m3 keeps ~2-3 mantissa bits: relative error under ~1/8 of
        # each channel's amax-normalized grid
        assert np.all(np.abs(deq - w)
                      <= np.abs(w) * 0.13 + np.asarray(s8)[:, None])


@pytest.mark.parametrize("kind", ["int8", "fp8", "int4"])
def test_fused_kernel_parity_vs_dequant_reference(kind, rng):
    """The Pallas kernel's in-VMEM dequant + contraction matches
    ``x @ dequant(qw).T`` to f32 dot accuracy — no activation
    quantization roundtrip (weight-only, W8A16-style)."""
    if kind == "fp8" and not _HAS_FP8:
        pytest.skip("no float8_e4m3fn in this build")
    w = rng.standard_normal((128, 64)).astype(np.float32)
    x = rng.standard_normal((5, 64)).astype(np.float32)
    if kind == "int8":
        qw, s = quantize_weight(jnp.asarray(w))
    elif kind == "fp8":
        qw, s = quantize_weight_fp8(jnp.asarray(w))
    else:
        qw, s = quantize_weight_int4(jnp.asarray(w), group_size=16)
    got = np.asarray(fused_dequant_matmul(jnp.asarray(x), qw, s))
    want = x @ np.asarray(dequantize_weight(qw, s)).T
    assert got.shape == (5, 128)
    assert float(np.abs(got - want).max()) < 1e-4
    # leading-dims flattening: (b, t, in) agrees with the 2D path
    got3 = np.asarray(fused_dequant_matmul(
        jnp.asarray(x.reshape(5, 1, 64)), qw, s))
    np.testing.assert_allclose(got3.reshape(5, 128), got, atol=1e-5)


def test_policy_roundtrip_leaves_fp_untouched(rng):
    """quantize_model_params under a policy: block-linear weights land
    narrow with sibling scales; embeddings, norms, biases and every
    other fp leaf pass through BIT-identical."""
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    for pol in (WeightPrecisionPolicy("int8"),
                WeightPrecisionPolicy("int4", group_size=TINY_GS)):
        qmodel = GPTModel(dataclasses.replace(cfg, weight_policy=pol))
        qparams = quantize_model_params(qmodel, v, jnp.zeros((1, 8),
                                                            jnp.int32))
        assert_quantized_loaded(qparams)       # narrow leaves, non-zero
        flat_fp = dict(jax.tree_util.tree_flatten_with_path(v["params"])[0])
        flat_q = dict(jax.tree_util.tree_flatten_with_path(qparams)[0])
        narrow = {jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8)}
        n_narrow = n_fp = 0
        for path, leaf in flat_q.items():
            if jnp.dtype(leaf.dtype) in narrow:
                n_narrow += 1
                continue
            if path not in flat_fp:
                assert path[-1].key == "scale"     # produced with weight
                continue
            n_fp += 1
            np.testing.assert_array_equal(np.asarray(leaf),
                                          np.asarray(flat_fp[path]))
        assert n_narrow == 4 * cfg.num_layers      # qkv/out/mlp_in/mlp_out
        assert n_fp > 0                            # embeddings et al.


def test_weight_bytes_ratio_pins():
    """The acceptance numbers at REAL gpt2-small shapes, straight off
    the abstract param trees the cost model prices (per-LEAF dtype
    bytes, scale reads included): int8 policy <= 0.55x the fp tree,
    int4 policy (+ bf16 fp leaves, the documented aggressive pairing)
    <= 0.35x — ``cost.decode.w8/w4.weight_bytes_ratio_vs_bf16``."""
    from apex_tpu.models.gpt import gpt2_small_config

    def tree_bytes(cfg):
        model = GPTModel(cfg)
        tree = jax.eval_shape(lambda: model.init(
            jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree.leaves(tree))

    base = gpt2_small_config(dtype=jnp.bfloat16)
    fp = tree_bytes(base)
    w8 = tree_bytes(dataclasses.replace(
        base, weight_policy=WeightPrecisionPolicy("int8")))
    w4 = tree_bytes(dataclasses.replace(
        base, weight_policy=WeightPrecisionPolicy("int4"),
        param_dtype=jnp.bfloat16))
    assert w8 <= 0.55 * fp, (w8, fp)
    assert w4 <= 0.35 * fp, (w4, fp)


def test_assert_quantized_loaded_named_errors():
    cfg = gpt_tiny_config(
        weight_policy=WeightPrecisionPolicy("int4", group_size=TINY_GS))
    qmodel = GPTModel(cfg)
    placeholders = qmodel.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="all zeros"):
        assert_quantized_loaded(placeholders)   # init() placeholders
    fp_model = GPTModel(gpt_tiny_config())
    fp = fp_model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="no int8"):
        assert_quantized_loaded(fp)             # not a quantized tree


# --- engine tier -------------------------------------------------------------


def _tiny_quantized_setup(rng, pol):
    cfg = gpt_tiny_config()
    model = GPTModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, (n,))))
               for n in (9, 17, 5, 26)]
    qmodel = GPTModel(dataclasses.replace(cfg, weight_policy=pol))
    qv = {"params": quantize_model_params(qmodel, v,
                                          jnp.zeros((1, 8), jnp.int32))}
    return cfg, model, v, qmodel, qv, prompts


def _agreement(fp, q):
    """(all first tokens equal, count of fully-identical requests)."""
    firsts = all(int(np.asarray(a)[0]) == int(np.asarray(b)[0])
                 for a, b in zip(fp, q))
    ident = sum(bool(np.array_equal(np.asarray(a), np.asarray(b)))
                for a, b in zip(fp, q))
    return firsts, ident


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["int8", "fp8", "int4"])
def test_engine_greedy_parity_tolerance(kind, rng):
    """Quantized-weight engines vs the fp engine on the same
    mixed-length workload. Every request's FIRST token matches (the
    fixed-seed pin — prefill runs the quantized weights, so this is
    empirical, not structural). Full streams diverge once a perturbed
    logit crosses an argmax gap, and unlike KV quantization EVERY
    matmul is perturbed — so the bar is per-kind: int8/fp8 keep >= 2/4
    requests fully identical, and every kind keeps a mean greedy
    common-prefix of generated tokens above its pin (int4-grouped at
    group 8 is the aggressive end and diverges earliest)."""
    if kind == "fp8" and not _HAS_FP8:
        pytest.skip("no float8_e4m3fn in this build")
    pol = WeightPrecisionPolicy(kind, group_size=TINY_GS)
    cfg, model, v, qmodel, qv, prompts = _tiny_quantized_setup(rng, pol)
    kw = dict(max_new_tokens=12, num_slots=4, page_size=PS, num_pages=40)
    fp = generate_paged(model, v, prompts, **kw)
    q = generate_paged(qmodel, qv, prompts, **kw)
    firsts, ident = _agreement(fp, q)
    assert firsts, f"{kind}: first token flipped"
    gen_prefix = []
    for p, a, b in zip(prompts, fp, q):
        a, b = np.asarray(a), np.asarray(b)
        n = 0
        while n < len(a) and n < len(b) and a[n] == b[n]:
            n += 1
        gen_prefix.append(n - len(p))          # agreed GENERATED tokens
    min_ident = {"int8": 2, "fp8": 2, "int4": 0}[kind]
    min_mean_prefix = {"int8": 4.0, "fp8": 4.0, "int4": 2.0}[kind]
    assert ident >= min_ident, f"{kind}: only {ident}/4 identical"
    mean_prefix = sum(gen_prefix) / len(gen_prefix)
    assert all(n >= 1 for n in gen_prefix), (kind, gen_prefix)
    assert mean_prefix >= min_mean_prefix, (kind, gen_prefix)


@pytest.mark.slow
def test_llama_windowed_w8(rng):
    """generate(paged=True) through Llama's GQA + sliding-window band
    with the int8 weight policy: matches the fp paged run at the
    tolerance bar on a rectangular batch."""
    from apex_tpu.models.llama import LlamaModel, llama_tiny_config

    cfg = dataclasses.replace(llama_tiny_config(), sliding_window=PS)
    model = LlamaModel(cfg)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    qmodel = LlamaModel(dataclasses.replace(
        cfg, weight_policy=WeightPrecisionPolicy("int8")))
    qv = {"params": quantize_model_params(qmodel, v,
                                          jnp.zeros((1, 8), jnp.int32))}
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (3, 12)),
                         jnp.int32)
    fp = np.asarray(generate(model, v, prompt, max_new_tokens=6,
                             paged=True, page_size=PS))
    q8 = np.asarray(generate(qmodel, qv, prompt, max_new_tokens=6,
                             paged=True, page_size=PS))
    assert fp.shape == q8.shape
    np.testing.assert_array_equal(fp[:, :13], q8[:, :13])  # prompt+first
    ident = sum(bool(np.array_equal(a, b)) for a, b in zip(fp, q8))
    assert ident >= 2, f"windowed llama w8: {ident}/3 rows identical"


@pytest.mark.slow
def test_tp2_w8_token_identity(rng):
    """TP=2 over the int8 weight tree: token-IDENTICAL to the
    single-chip w8 engine. Column shards slice int8 channels exactly;
    the row-parallel per-channel scale is replicated — so the sharded
    dequantized weights are bit-identical to the unsharded ones and
    greedy argmax cannot move (the group-local-packing design claim of
    serving/tp.py, exercised end to end)."""
    from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                     shard_model_variables, tp_mesh)

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    pol = WeightPrecisionPolicy("int8")
    cfg, model, v, qmodel, qv, prompts = _tiny_quantized_setup(rng, pol)
    if cfg.num_heads % 2:
        pytest.skip("tiny config heads not divisible by 2")
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=8)
            for p in prompts[:3]]
    single = PagedDecodeEngine(qmodel, qv, num_slots=3, page_size=PS,
                               num_pages=33)
    outs, _ = single.run(reqs)

    tp_cfg = dataclasses.replace(cfg, tensor_parallel_size=2,
                                 weight_policy=pol)
    tp_model = GPTModel(tp_cfg)
    mesh = tp_mesh(2)
    tp_vars, _ = shard_model_variables(tp_model, qv, mesh)
    tp_engine = TensorParallelPagedEngine(
        tp_model, tp_vars, mesh=mesh, num_slots=3, page_size=PS,
        num_pages=33)
    tp_outs, _ = tp_engine.run(reqs)
    for i, (a, b) in enumerate(zip(outs, tp_outs)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"request {i}")


@pytest.mark.slow
def test_spec_decode_int4_draft_int8_target(rng):
    """Speculative decode composes with an at-least-as-aggressive draft:
    int4-grouped draft weights proposing for an int8 target. Outputs
    agree with the plain int8 engine at the tolerance bar and the
    acceptance telemetry is live (a cross-precision draft accepts less
    than the self-draft ceiling but must still draft usefully)."""
    pol8 = WeightPrecisionPolicy("int8")
    cfg, model, v, qmodel, qv, prompts = _tiny_quantized_setup(rng, pol8)
    d_model = GPTModel(dataclasses.replace(
        cfg, weight_policy=WeightPrecisionPolicy("int4",
                                                 group_size=TINY_GS)))
    dv = {"params": quantize_model_params(d_model, v,
                                          jnp.zeros((1, 8), jnp.int32))}
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=10)
            for p in prompts]
    plain = PagedDecodeEngine(qmodel, qv, num_slots=4, page_size=PS,
                              num_pages=40)
    outs, _ = plain.run(reqs)

    spec = PagedDecodeEngine(qmodel, qv, num_slots=4, page_size=PS,
                             num_pages=40, draft_model=d_model,
                             draft_variables=dv, draft_len=2)
    s_outs, s_stats = spec.run(reqs)
    assert s_stats["spec_rounds"] >= 1
    assert s_stats["mean_acceptance_len"] >= 1.0
    firsts, ident = _agreement(outs, s_outs)
    assert firsts and ident >= 3, f"spec int4-draft: {ident}/4"


@pytest.mark.slow
def test_frontend_over_quantized_weights(rng):
    """The async frontend path over a w8 engine: submit/pump/drain
    completes with full-length outputs identical to the engine's
    batch run — the serving surface accepts quantized trees whole."""
    from apex_tpu.serving.frontend import ServingFrontend

    pol = WeightPrecisionPolicy("int8")
    cfg, model, v, qmodel, qv, prompts = _tiny_quantized_setup(rng, pol)
    reqs = [Request(prompt=np.asarray(p, np.int32), max_new_tokens=6)
            for p in prompts]
    engine = PagedDecodeEngine(qmodel, qv, num_slots=4, page_size=PS,
                               num_pages=40)
    base, _ = engine.run(reqs)
    fe = ServingFrontend(engine)
    handles = [fe.submit(r, request_id=i) for i, r in enumerate(reqs)]
    fe.drain()
    for h, b in zip(handles, base):
        np.testing.assert_array_equal(np.asarray(h.result()),
                                      np.asarray(b))
