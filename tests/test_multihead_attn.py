"""SelfMultiheadAttn / EncdecMultiheadAttn: fast impl vs default impl.

Mirrors apex/contrib/test/multihead_attn/test_self_multihead_attn.py — the
reference validates impl='fast' against impl='default' (the pure-framework
path) on identical weights, incl. norm_add variants and padding masks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn


def _mk(module_cls, rng_key, x, **kwargs):
    m = module_cls(embed_dim=64, num_heads=4, impl="fast", **kwargs)
    variables = m.init(rng_key, x, x, x, is_training=False)
    return m, variables


@pytest.mark.parametrize("bias", [False, True])
@pytest.mark.parametrize("include_norm_add", [False, True])
@pytest.mark.slow
def test_self_fast_vs_default(rng, bias, include_norm_add):
    s, b, e = 24, 3, 64
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    key = jax.random.PRNGKey(0)
    fast = SelfMultiheadAttn(embed_dim=e, num_heads=4, bias=bias,
                             include_norm_add=include_norm_add, impl="fast")
    variables = fast.init(key, x, is_training=False)
    default = SelfMultiheadAttn(embed_dim=e, num_heads=4, bias=bias,
                                include_norm_add=include_norm_add,
                                impl="default")
    out_f, _ = fast.apply(variables, x, is_training=False)
    out_d, _ = default.apply(variables, x, is_training=False)
    np.testing.assert_allclose(out_f, out_d, atol=2e-5, rtol=2e-5)
    assert out_f.shape == (s, b, e)

    gf = jax.grad(lambda x: (fast.apply(variables, x, is_training=False)[0] ** 2).sum())(x)
    gd = jax.grad(lambda x: (default.apply(variables, x, is_training=False)[0] ** 2).sum())(x)
    np.testing.assert_allclose(gf, gd, atol=5e-5, rtol=5e-4)


def test_self_key_padding_mask(rng):
    s, b, e = 16, 2, 64
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    pad = jnp.zeros((b, s), bool).at[:, -4:].set(True)
    fast = SelfMultiheadAttn(embed_dim=e, num_heads=4, impl="fast")
    variables = fast.init(jax.random.PRNGKey(0), x, is_training=False)
    default = SelfMultiheadAttn(embed_dim=e, num_heads=4, impl="default")
    out_f, _ = fast.apply(variables, x, key_padding_mask=pad, is_training=False)
    out_d, _ = default.apply(variables, x, key_padding_mask=pad,
                             is_training=False)
    np.testing.assert_allclose(out_f, out_d, atol=2e-5, rtol=2e-5)


def test_self_separate_qkv(rng):
    s, b, e = 12, 2, 64
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=e, num_heads=4, separate_qkv_params=True,
                          impl="fast")
    variables = m.init(jax.random.PRNGKey(0), x, is_training=False)
    params = variables["params"]
    assert set(params) >= {"q_weight", "k_weight", "v_weight",
                           "out_proj_weight"}
    out, _ = m.apply(variables, x, is_training=False)
    assert out.shape == (s, b, e)


def test_self_dropout_training(rng):
    s, b, e = 16, 2, 64
    x = jnp.asarray(rng.standard_normal((s, b, e)), jnp.float32)
    m = SelfMultiheadAttn(embed_dim=e, num_heads=4, dropout=0.5, impl="fast")
    variables = m.init(jax.random.PRNGKey(0), x, is_training=False)
    o1, _ = m.apply(variables, x, is_training=True,
                    rngs={"dropout": jax.random.PRNGKey(1)})
    o2, _ = m.apply(variables, x, is_training=True,
                    rngs={"dropout": jax.random.PRNGKey(2)})
    o3, _ = m.apply(variables, x, is_training=False)
    assert not jnp.array_equal(o1, o2)
    # eval mode is deterministic and needs no rng
    o4, _ = m.apply(variables, x, is_training=False)
    assert jnp.array_equal(o3, o4)


@pytest.mark.parametrize("include_norm_add", [False, True])
def test_encdec_fast_vs_default(rng, include_norm_add):
    sq, sk, b, e = 12, 20, 2, 64
    q = jnp.asarray(rng.standard_normal((sq, b, e)), jnp.float32)
    kv = jnp.asarray(rng.standard_normal((sk, b, e)), jnp.float32)
    fast = EncdecMultiheadAttn(embed_dim=e, num_heads=4,
                               include_norm_add=include_norm_add, impl="fast")
    variables = fast.init(jax.random.PRNGKey(0), q, kv, kv, is_training=False)
    default = EncdecMultiheadAttn(embed_dim=e, num_heads=4,
                                  include_norm_add=include_norm_add,
                                  impl="default")
    out_f, _ = fast.apply(variables, q, kv, kv, is_training=False)
    out_d, _ = default.apply(variables, q, kv, kv, is_training=False)
    np.testing.assert_allclose(out_f, out_d, atol=2e-5, rtol=2e-5)
    assert out_f.shape == (sq, b, e)


def test_encdec_rejects_bias():
    with pytest.raises(ValueError):
        EncdecMultiheadAttn(embed_dim=64, num_heads=4, bias=True).init(
            jax.random.PRNGKey(0), jnp.zeros((4, 1, 64)), jnp.zeros((4, 1, 64)),
            jnp.zeros((4, 1, 64)))
