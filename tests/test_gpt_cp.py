"""GPT with context parallelism: sequence sharded over the ``context`` axis.

The decoder's attention dispatches to ring_attention when the context axis is
bound (apex_tpu/models/gpt.py), position embeddings use global offsets, and
the loss pmean-combines chunk means — so the cp-sharded loss and grads must
match the single-device model exactly.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.mesh import CONTEXT_AXIS
from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

pytestmark = pytest.mark.slow


@pytest.fixture
def cp4_mesh():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(
        1, 1, context_parallel_size_=4)


@pytest.mark.parametrize("layout", ["ring", "zigzag"])
def test_gpt_cp_loss_and_grads_match_single_device(cp4_mesh, rng, layout):
    cfg = gpt_tiny_config(context_parallel=True,
                          context_parallel_zigzag=layout == "zigzag")
    model = GPTModel(cfg)
    b, s = 2, 64
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)
    labels = jnp.roll(ids, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def ref_loss(p):
        return gpt_loss(model, {"params": p}, ids, labels)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)

    if layout == "zigzag":
        # the model consumes the zigzag-permuted sequence (position
        # embeddings follow); the mean loss is permutation-invariant
        from apex_tpu.ops import to_zigzag

        ids = to_zigzag(ids, 4, axis=1)
        labels = to_zigzag(labels, 4, axis=1)

    seq_sh = P(None, CONTEXT_AXIS)

    @functools.partial(
        jax.shard_map, mesh=cp4_mesh,
        in_specs=(P(), seq_sh, seq_sh), out_specs=P(), check_vma=False)
    def cp_forward(p, ii, ll):
        return gpt_loss(model, {"params": p}, ii, ll)

    def cp_loss(p):
        return cp_forward(p, ids, labels)

    cp_l, cp_g = jax.value_and_grad(cp_loss)(params)

    np.testing.assert_allclose(float(cp_l), float(ref_l), rtol=2e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        cp_g, ref_g)
