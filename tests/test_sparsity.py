"""ASP 2:4 sparsity (BASELINE config #5; reference:
apex/contrib/test/sparsity/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _reset_asp():
    yield
    from apex_tpu.contrib.sparsity import ASP

    ASP.reset()


def _check_2_4(mask: np.ndarray):
    g = mask.reshape(-1, 4)
    np.testing.assert_array_equal(g.sum(-1), 2 * np.ones(g.shape[0]))


def test_create_mask_is_2_4_and_keeps_top2(rng):
    from apex_tpu.contrib.sparsity import create_mask

    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    m = np.asarray(create_mask(w))
    _check_2_4(m)
    # kept entries are the 2 largest |values| of each group
    wg = np.abs(np.asarray(w)).reshape(-1, 4)
    mg = m.reshape(-1, 4)
    for row_w, row_m in zip(wg, mg):
        kept = np.sort(row_w[row_m])
        dropped = row_w[~row_m]
        assert kept.min() >= dropped.max() - 1e-7


@pytest.mark.slow
def test_masks_on_bert_param_tree():
    """Masks verified 2:4 on a BERT param tree (VERDICT done-criterion)."""
    from apex_tpu.contrib.sparsity import ASP
    from apex_tpu.models import BertForPreTraining, bert_tiny_config

    cfg = bert_tiny_config()
    model = BertForPreTraining(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    ASP.init_model_for_pruning(params)
    masks, masked = ASP.compute_sparse_masks(params)

    n_pruned = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(masks,
                                                   is_leaf=lambda x: x is None)
    for path, mask in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if mask is None:
            continue
        n_pruned += 1
        _check_2_4(np.asarray(mask).reshape(-1, 4))
        assert "emb" not in name.lower() and "norm" not in name.lower()
    assert n_pruned >= 2 * cfg.num_layers  # at least qkv/out/mlp weights
    # masked params actually zeroed
    mw = np.asarray(masked["layer_0"]["attention"]["qkv_weight"])
    assert (np.count_nonzero(mw.reshape(-1, 4), axis=1) <= 2).all()


def test_masked_finetune_smoke(rng):
    """prune_trained_model: optimizer hook keeps weights 2:4 through
    fine-tune steps and the loss still decreases."""
    from apex_tpu.contrib.sparsity import ASP
    from apex_tpu.optimizers import FusedAdam

    w_true = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
    y = x @ w_true.T

    params = {"dense_weight": jnp.asarray(
        rng.standard_normal((16, 16)) * 0.1, jnp.float32)}
    opt = FusedAdam(params, lr=5e-2)
    params, opt = ASP.prune_trained_model(params, opt)
    _check_2_4(np.asarray(ASP.masks()["dense_weight"]).reshape(-1, 4))

    def loss_fn(p):
        return jnp.mean((x @ p["dense_weight"].T - y) ** 2)

    losses = []
    for _ in range(12):
        loss, g = jax.value_and_grad(loss_fn)(params)
        params = opt.step(g)
        losses.append(float(loss))
        # sparsity enforced after every step
        nz = np.count_nonzero(
            np.asarray(params["dense_weight"]).reshape(-1, 4), axis=1)
        assert (nz <= 2).all()
    assert losses[-1] < losses[0]


def test_permutation_search_improves_retention(rng):
    from apex_tpu.contrib.sparsity import (apply_permutation_and_mask,
                                           magnitude_retained, mn_1d_mask,
                                           search_permutation)

    # adversarial layout: large magnitudes clustered so plain 2:4 drops them
    w = np.abs(rng.standard_normal((8, 16))).astype(np.float32) * 0.1
    w[:, 0:4] *= 100.0   # one group holds all the big values
    w = jnp.asarray(w)

    base = float(magnitude_retained(w, mn_1d_mask(w)))
    perm, _ = search_permutation(jnp.abs(w))
    mask_p = apply_permutation_and_mask(w, perm)
    after = float(magnitude_retained(w, mask_p))
    # the returned mask is in ORIGINAL column order; 2:4 holds under the
    # permuted grouping (the reference folds the permutation upstream)
    _check_2_4(np.asarray(mask_p[:, np.asarray(perm)]).reshape(-1, 4))
    assert after >= base - 1e-6
    assert after > base + 0.01  # the adversarial case must actually improve


def test_asp_state_dict_roundtrip(rng):
    from apex_tpu.contrib.sparsity import ASP

    params = {"w": jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)}
    ASP.init_model_for_pruning(params)
    ASP.compute_sparse_masks(params)
    sd = ASP.state_dict()
    ASP.reset()
    ASP.load_state_dict(sd)
    assert ASP.is_sparsity_enabled()
    _check_2_4(np.asarray(ASP.masks()["w"]).reshape(-1, 4))
