"""Parity tests for the Pallas LayerNorm/RMSNorm kernels vs pure-jnp reference.

Mirrors tests/L0/run_fused_layer_norm/test_fused_layer_norm.py from the
reference: fused module vs framework-native reference across
dtype × shape × affine × memory_efficient grids, fwd and bwd.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import layer_norm, rms_norm


def ref_layer_norm(x, w=None, b=None, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def ref_rms_norm(x, w=None, eps=1e-5):
    xf = x.astype(jnp.float32)
    ms = (xf * xf).mean(-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    return y.astype(x.dtype)


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=2e-5, atol=2e-5)


SHAPES = [(4, 64), (3, 5, 128), (16, 1024), (13, 257)]  # incl. ragged/row-odd
DTYPES = [jnp.float32, jnp.bfloat16]


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("memory_efficient", [False, True])
def test_layer_norm_affine_forward(shape, dtype, memory_efficient):
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, shape, dtype)
    w = jax.random.normal(jax.random.PRNGKey(1), shape[-1:], jnp.float32) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), shape[-1:], jnp.float32)
    got = layer_norm(x, w, b, 1e-5, memory_efficient)
    want = ref_layer_norm(x, w, b)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **_tol(dtype)
    )


@pytest.mark.parametrize("shape", [(4, 64), (13, 257)])
@pytest.mark.parametrize("memory_efficient", [False, True])
def test_layer_norm_affine_grads(shape, memory_efficient):
    x = jax.random.normal(jax.random.PRNGKey(0), shape)
    w = jax.random.normal(jax.random.PRNGKey(1), shape[-1:]) + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), shape[-1:])

    def loss_fused(x, w, b):
        return (layer_norm(x, w, b, 1e-5, memory_efficient) ** 2).sum()

    def loss_ref(x, w, b):
        return (ref_layer_norm(x, w, b) ** 2).sum()

    g = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, e, name in zip(g, gr, "x w b".split()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-4, err_msg=f"d{name}"
        )


def test_layer_norm_no_affine():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 96))
    got = layer_norm(x)
    want = ref_layer_norm(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda v: (layer_norm(v) ** 2).sum())(x)
    gr = jax.grad(lambda v: (ref_layer_norm(v) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("shape", [(4, 64), (13, 257)])
@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("memory_efficient", [False, True])
def test_rms_norm_affine(shape, dtype, memory_efficient):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype)
    w = jax.random.normal(jax.random.PRNGKey(1), shape[-1:], jnp.float32) + 1.0
    got = rms_norm(x, w, 1e-5, memory_efficient)
    want = ref_rms_norm(x, w)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), **_tol(dtype)
    )


@pytest.mark.parametrize("memory_efficient", [False, True])
def test_rms_norm_grads(memory_efficient):
    x = jax.random.normal(jax.random.PRNGKey(0), (6, 128))
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)) + 1.0
    g = jax.grad(lambda x, w: (rms_norm(x, w, 1e-5, memory_efficient) ** 2).sum(), (0, 1))(x, w)
    gr = jax.grad(lambda x, w: (ref_rms_norm(x, w) ** 2).sum(), (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gr[0]), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gr[1]), rtol=1e-4, atol=1e-4)


def test_module_api():
    """FusedLayerNorm / FusedRMSNorm flax modules (reference class API)."""
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 7, 64))
    m = FusedLayerNorm(normalized_shape=64)
    params = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(params, x)
    want = ref_layer_norm(x, params["params"]["weight"], params["params"]["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=2e-5, atol=2e-5)

    r = FusedRMSNorm(normalized_shape=64, elementwise_affine=False)
    yr = r.apply(r.init(jax.random.PRNGKey(2), x), x)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(ref_rms_norm(x)), rtol=2e-5, atol=2e-5)


def test_multidim_normalized_shape():
    """apex supports normalized_shape spanning multiple trailing dims."""
    from apex_tpu.normalization import fused_layer_norm_affine

    x = jax.random.normal(jax.random.PRNGKey(0), (3, 4, 8))
    w = jnp.ones((4, 8))
    b = jnp.zeros((4, 8))
    y = fused_layer_norm_affine(x, w, b, (4, 8))
    want = ref_layer_norm(x.reshape(3, 32)).reshape(3, 4, 8)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=2e-5, atol=2e-5)
