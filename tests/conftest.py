"""Test harness: 8 virtual CPU devices so mesh/collective tests run anywhere.

This replaces the reference's MultiProcessTestCase/NCCL-over-localhost trick
(apex/transformer/testing/distributed_test_base.py) with XLA's host-platform
device-count override — strictly better: no accelerator needed at all
(SURVEY.md §4 closing note).

Must run before jax initializes its backends, hence module-level env mutation
in conftest (pytest imports conftest before test modules).
"""

import os

# Force CPU even when the session env pins a TPU platform (JAX_PLATFORMS=axon):
# unit tests exercise numerics + mesh semantics on 8 virtual CPU devices;
# bench.py is what runs on the real chip. The env may import jax before this
# file runs (sitecustomize), so set jax.config directly rather than env vars.
#
# APEX_TPU_REAL=1 keeps the ambient TPU backend instead: the on-chip kernel
# suite (tests/test_real_tpu_kernels.py) then compiles every Pallas kernel
# via Mosaic at bench-relevant shapes — closing the interpret-mode blind
# spot (VERDICT round-1 weakness 4). Run it as:
#   APEX_TPU_REAL=1 python -m pytest tests/test_real_tpu_kernels.py -v
REAL_TPU = os.environ.get("APEX_TPU_REAL") == "1"

import jax  # noqa: E402

if not REAL_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: no jax_num_cpu_devices option — the XLA_FLAGS route
        # works as long as the host backend has not been initialized yet
        # (conftest runs before any test touches a device)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


# On-chip suite ordered by information value (VERDICT r3 next-round #1c):
# never-run-post-fix kernels first, long-compiling full train step last, so a
# short tunnel window proves the most. Names not listed keep collection order
# after the listed ones.
_ONCHIP_PRIORITY = [
    # r5: tight-head-dim first — its compile half is proven offline
    # (AOT_r05.json) and a runtime pass + autotune timing flips the
    # default to the 2x-less-MXU-work layout (run_tpu_round.sh marker)
    "test_flash_attention_tight_head_dim",
    "test_fused_optimizer_kernels_bert_large_size",  # held the 86 GB bug
    "test_group_norm_backward_kernel_path",
    "test_group_norm_kernel_path",
    "test_flash_attention_sliding_window",
    "test_moe_dense_dispatch_compiles",
    "test_flash_attention_with_lse_on_chip",
    "test_scaled_masked_softmax_seq512",
    "test_layer_norm_fwd_bwd_bench_shapes",
    "test_flash_attention_fwd_bwd_seq512",
    "test_flash_attention_causal_and_dropout_compile",
    "test_xentropy_vocab30528",
    "test_bert_large_single_train_step",  # 15+ min compile — always last
]


# r5 tier rebalance (VERDICT r4 weak #5): tests measured >4 s on the 1-core
# box (smoke_durations.log) move to the slow tier by name — the smoke tier
# is a fast sanity pass, and every one of these still runs in the full
# tier. Names, not marks, so the measurement stays reviewable in one place.
_SMOKE_EXCLUDED = {
    "test_llama_remat_same_loss_and_grads",          # 27.6s
    "test_llama_moe_resume_roundtrip",               # 15.1s
    "test_assert_quantized_loaded_guards_placeholders",  # 12.2s
    # test_gpt_prefill_matches_full_forward (12.2s) stays in smoke ON
    # PURPOSE: it is the tier's one real decode-parity check (see its
    # in-code comment) — a KV-cache regression must not survive the
    # dev loop
    "test_gpt_moe_pipeline_rejects_bad_stride",      # 11.8s
    "test_moe_under_gspmd_jit_sharded_experts",      # 11.2s
    "test_moe_grads_flow_and_balance_loss_differentiable",  # 9.6s
    "test_gpt_moe_aux_loss_included",                # 8.7s
    "test_direct_apply_bounds_raise_at_trace_time",  # 8.4s
    "test_single_rank_moe_matches_dense_reference",  # 7.5s
    "test_column_parallel_linear_matches_dense",     # 7.3s
    "test_pipeline_forward_only",                    # 6.9s
    "test_gqa_native_kv_heads",                      # 6.0s/5.6s
    "test_self_dropout_training",                    # 6.0s
    "test_generate_validates_lengths",               # 5.0s
    "test_restore_preserves_sharding",               # 4.7s
    "test_with_lse_grad_includes_lse_cotangent",     # 4.7s
    "test_self_key_padding_mask",                    # 4.6s
    "test_fused_adam_matches_optax_adamw",           # 4.5s
    "test_ring_gqa_kv_heads",                        # 4.4s
    "test_upper_triang",                             # 4.4s
    "test_fully_masked_rows_output_zero",            # 4.1s
}


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: anything not marked ``slow`` is the smoke tier, so
    both ``-m smoke`` and ``-m "not slow"`` select the fast sanity set
    (VERDICT r2 weakness: 20-min suite with no fast tier; r5: measured
    >4s tests reclassified via _SMOKE_EXCLUDED)."""
    for item in items:
        if item.name.split("[")[0] in _SMOKE_EXCLUDED:
            item.add_marker(pytest.mark.slow)
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.smoke)
    if REAL_TPU:
        rank = {n: i for i, n in enumerate(_ONCHIP_PRIORITY)}
        items.sort(key=lambda it: rank.get(it.name.split("[")[0],
                                           len(_ONCHIP_PRIORITY)))


def pytest_runtest_logreport(report):
    """Per-test artifact checkpointing for the on-chip suite (VERDICT r3
    weak #3): append one JSON line the moment a test finishes, so a tunnel
    window that dies mid-suite still banks every completed test."""
    if not REAL_TPU:
        return
    if report.when != "call" and not (report.when == "setup"
                                      and report.outcome != "passed"):
        return
    import json
    import subprocess
    import time

    global _GIT_SHA
    if _GIT_SHA is None:
        try:
            _GIT_SHA = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or "unknown"
        except Exception:
            _GIT_SHA = "unknown"
    tag = os.environ.get("APEX_TPU_TAG", "session")
    line = {
        "test": report.nodeid.split("::")[-1],
        "outcome": report.outcome,
        "when": report.when,
        "duration_s": round(report.duration, 1),
        "sha": _GIT_SHA,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    with open(f"TPU_TESTS_{tag}.jsonl", "a") as f:
        f.write(json.dumps(line) + "\n")
        f.flush()


_GIT_SHA = None


@pytest.fixture(autouse=True)
def _fresh_parallel_state():
    """Tear down global mesh state between tests (reference:
    destroy_model_parallel in test teardowns)."""
    yield
    from apex_tpu.transformer import parallel_state

    parallel_state.destroy_model_parallel()


@pytest.fixture(autouse=True)
def _fresh_amp_state():
    """Reset the global amp policy/scalers between tests — modules consult
    amp.current_policy() for compute dtypes, so leakage would silently flip
    other tests' dtypes."""
    yield
    from apex_tpu import amp

    amp._current_policy = None
    amp._loss_scalers = []


@pytest.fixture
def mesh8():
    """data=8 mesh."""
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(1, 1)


@pytest.fixture
def mesh_tp2_pp2_dp2():
    from apex_tpu.transformer import parallel_state

    return parallel_state.initialize_model_parallel(2, 2)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_sched_adapters(schedule: str, vpp: int):
    """(fwd_bwd, to_sched_tree, from_sched_tree) for a pipeline parity
    test over {"1f1b", "interleaved"} — shared by the GPT and Llama
    pipeline suites (the stage-local tree has a leading [V] chunk axis on
    blocks; interleaved wants shared params broadcast across V, 1f1b wants
    the V=1 axis dropped)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving)

    if schedule == "interleaved":
        def to_sched_tree(local):
            return {"blocks": local["blocks"],
                    "shared": jax.tree.map(
                        lambda x: jnp.broadcast_to(x[None],
                                                   (vpp,) + x.shape),
                        local["shared"])}

        def from_sched_tree(g):
            return {"blocks": g["blocks"],
                    "shared": jax.tree.map(lambda x: x.sum(0), g["shared"])}

        return (forward_backward_pipelining_with_interleaving,
                to_sched_tree, from_sched_tree)

    def to_sched_tree(local):
        return {"blocks": jax.tree.map(lambda t: t[0], local["blocks"]),
                "shared": local["shared"]}

    def from_sched_tree(g):
        return {"blocks": jax.tree.map(lambda t: t[None], g["blocks"]),
                "shared": g["shared"]}

    return (forward_backward_pipelining_without_interleaving,
            to_sched_tree, from_sched_tree)
