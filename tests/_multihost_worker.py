"""Worker for tests/test_multihost.py — one simulated host.

Joins a 2-process jax.distributed cluster (Gloo over localhost, the CPU
stand-in for DCN), contributes 4 virtual CPU devices to the 8-device
global mesh, and runs a Megatron-TP GPT grad step over the apex_tpu
parallel_state mesh spanning BOTH processes. Prints PASS lines the parent
asserts on.
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid)
    import functools

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sys.path.insert(0, ".")
    from apex_tpu.mesh import DATA_AXIS, MODEL_AXIS
    from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config
    from apex_tpu.transformer import parallel_state

    assert jax.device_count() == 8 and jax.local_device_count() == 4
    # tp=2 -> dp=4: the data axis SPANS the process boundary (the DCN story)
    mesh = parallel_state.initialize_model_parallel(2, 1)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert sizes == {"data": 4, "stage": 1, "context": 1, "model": 2}, sizes
    print(f"PASS mesh pid={pid} {sizes}")

    # hybrid ICI-inner/DCN-outer placement (VERDICT r4 missing #4): with
    # dcn_data_parallel_size_=2 over the two processes, every model pair is
    # process-LOCAL and the data axis crosses the process boundary exactly
    # once (ranks 0-1 on process 0, ranks 2-3 on process 1). The device
    # list is deliberately INTERLEAVED across processes — jax.devices() is
    # process-major, so the plain reshape would pass these asserts
    # vacuously; alternating processes makes them discriminate the
    # grouping logic (code-review r5 finding).
    devs = jax.devices()
    by_proc = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    interleaved = [g[i] for i in range(4) for g in by_proc.values()]
    hybrid = parallel_state.initialize_model_parallel(
        2, 1, devices=interleaved, dcn_data_parallel_size_=2)
    for dd in range(4):
        tp_pair = hybrid.devices[dd, 0, 0, :]
        assert tp_pair[0].process_index == tp_pair[1].process_index, (
            "model axis crossed the process (DCN) boundary")
    procs_by_dp = [hybrid.devices[dd, 0, 0, 0].process_index
                   for dd in range(4)]
    assert procs_by_dp == [0, 0, 1, 1], procs_by_dp
    print(f"PASS hybrid pid={pid} data_procs={procs_by_dp}")
    # reinstall the plain mesh for the TP step below
    mesh = parallel_state.initialize_model_parallel(2, 1)

    cfg = gpt_tiny_config(tensor_parallel_size=2)
    model = GPTModel(cfg)
    rng = np.random.default_rng(0)  # identical data on both processes
    ids_np = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    labels_np = np.roll(ids_np, -1, axis=1)

    def replicated(x_np):
        sh = NamedSharding(mesh, P())
        return jax.make_array_from_callback(
            x_np.shape, sh, lambda idx: x_np[idx])

    ids, labels = replicated(ids_np), replicated(labels_np)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(MODEL_AXIS), P(MODEL_AXIS)), check_vma=False)
    def tp_step(ii, ll):
        v = model.init(jax.random.PRNGKey(0), ii)["params"]

        def f(p):
            # shard the batch over the cross-process data axis by slicing
            # per data rank — grads then pmean over ``data``, which rides
            # the simulated DCN between the two hosts
            r = jax.lax.axis_index(DATA_AXIS)
            my_ii = jax.lax.dynamic_slice_in_dim(ii, r * 2, 2)
            my_ll = jax.lax.dynamic_slice_in_dim(ll, r * 2, 2)
            return gpt_loss(model, {"params": p}, my_ii, my_ll)

        loss, grads = jax.value_and_grad(f)(v)
        loss = jax.lax.pmean(loss, DATA_AXIS)
        gnorm = jnp.sqrt(sum(
            jnp.sum(jax.lax.pmean(g, DATA_AXIS).astype(jnp.float32) ** 2)
            for g in jax.tree.leaves(grads)))
        return loss.reshape(1), gnorm.reshape(1)

    with mesh:
        loss, gnorm = jax.jit(tp_step)(ids, labels)
    loss_local = float(loss.addressable_shards[0].data[0])
    gnorm_local = float(gnorm.addressable_shards[0].data[0])
    assert np.isfinite(loss_local) and np.isfinite(gnorm_local)
    print(f"PASS step pid={pid} loss={loss_local:.6f} gnorm={gnorm_local:.6f}")


if __name__ == "__main__":
    main()
