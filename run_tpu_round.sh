#!/bin/bash
# One-shot real-TPU validation for a round: probe the tunnel, run the
# on-chip Pallas kernel suite (committing its log), then the benchmark.
# Safe to re-run; everything is retried/timeboxed. Usage:
#   bash run_tpu_round.sh [round_tag]   # e.g. r03
set -u
TAG="${1:-r03}"
cd "$(dirname "$0")"

echo "[$(date +%H:%M:%S)] probing TPU tunnel..."
timeout 300 python - << 'EOF'
import subprocess, sys
r = subprocess.run([sys.executable, "-c",
                    "import jax; ds=jax.devices(); "
                    "print('PROBE_OK', len(ds), ds[0].device_kind)"],
                   capture_output=True, text=True, timeout=280)
print(r.stdout.strip() or r.stderr.strip()[-300:])
sys.exit(0 if "PROBE_OK" in r.stdout else 1)
EOF
if [ $? -ne 0 ]; then
  echo "[$(date +%H:%M:%S)] tunnel down; nothing run"
  exit 1
fi

echo "[$(date +%H:%M:%S)] on-chip kernel suite (Mosaic compile of every Pallas kernel)..."
APEX_TPU_REAL=1 timeout 3000 python -m pytest tests/test_real_tpu_kernels.py -v \
  2>&1 | tee "TPU_TESTS_${TAG}.log" | tail -15

echo "[$(date +%H:%M:%S)] benchmark..."
timeout 5400 python bench.py 2> "bench_${TAG}.stderr.log" | tee "BENCH_${TAG}.json.local"
tail -5 "bench_${TAG}.stderr.log"
echo "[$(date +%H:%M:%S)] done — commit TPU_TESTS_${TAG}.log + BENCH_${TAG}.json.local if nonzero"
