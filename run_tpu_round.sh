#!/bin/bash
# One-shot real-TPU validation for a round: probe the tunnel (with
# retries — it flaps on minute timescales), run the BENCHMARK first (the
# round's gate artifact; bench.py has its own init+compile retry
# machinery), then the on-chip Pallas kernel suite, whose log only
# replaces a previous one if it reached a pytest summary. Usage:
#   bash run_tpu_round.sh [round_tag]   # e.g. r03
set -u
TAG="${1:-r04}"
cd "$(dirname "$0")"

bench_done() { python bench_ok.py "BENCH_${TAG}.json.local"; }

# FAIL-FAST static-analysis gate (docs/static_analysis.md): a host sync in
# the decode scan or a Pallas contract violation should die here, on the
# CI box, not after burning a tunnel window on chip
echo "[$(date +%H:%M:%S)] tpu-lint static-analysis gate (AST tier)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis; then
  echo "[$(date +%H:%M:%S)] tpu-lint found new hazards; fix, suppress with"
  echo "  justification, or baseline them (docs/static_analysis.md) first"
  exit 1
fi
# IR tier: trace every registered entry point (tpu_aot kernel cases + the
# serving engine programs) on CPU and lint the STAGED jaxprs — dtype
# promotion drift, dead scan state, ineffective donation, compile-key
# cardinality. Same no-TPU-needed contract as the AST tier.
echo "[$(date +%H:%M:%S)] tpu-lint static-analysis gate (IR tier)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --ir; then
  echo "[$(date +%H:%M:%S)] tpu-lint --ir found new jaxpr-level hazards;"
  echo "  fix or suppress with justification (docs/static_analysis.md)"
  exit 1
fi
# Concurrency tier: thread coloring + lockset/GuardedBy inference +
# lock-order + resource-lifecycle pairing over the host side of the
# serving stack (the pump thread, /metrics exporter, callback threads).
# A race or ABBA inversion should die here, not as a wedged pump on chip.
echo "[$(date +%H:%M:%S)] tpu-lint static-analysis gate (conc tier)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --conc; then
  echo "[$(date +%H:%M:%S)] tpu-lint --conc found new host-concurrency"
  echo "  hazards; fix or suppress with justification (docs/static_analysis.md)"
  exit 1
fi
# Memory tier: trace the same registry (plus the AOT acceptance meshes)
# on CPU and prove every program FITS — per-chip padded-liveness peak vs
# its declared HBM budget (scan-carry double-buffering priced in), every
# pallas_call's VMEM residency vs the 16 MiB scoped budget, and the
# sharding contracts (indivisible specs, collective-free replicated
# outputs, donation/spec aliasing, scale/weight shard drift). The PR 10
# d=64 padding OOM and the PR 14 VMEM overflow both die here now, on
# the CI box, before a tunnel window sees the compile.
echo "[$(date +%H:%M:%S)] tpu-lint static-analysis gate (mem tier)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --mem; then
  echo "[$(date +%H:%M:%S)] tpu-lint --mem found memory-budget/sharding"
  echo "  hazards; fix or suppress with justification (docs/static_analysis.md)"
  exit 1
fi
# Contract tier: producer/consumer drift proofs for the string-keyed
# observability surface — metric families vs the docs catalog and the
# golden exposition, event kinds vs their readers, HTTP routes + SSE
# frames vs both sides of the socket, schema pins vs their validators,
# ledger extraction vs gating classes. A renamed gauge or a dropped
# frame kind should die here, not as a flat dashboard weeks later.
echo "[$(date +%H:%M:%S)] tpu-lint static-analysis gate (contract tier)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --contract; then
  echo "[$(date +%H:%M:%S)] tpu-lint --contract found wire/observability"
  echo "  drift; fix or suppress with justification (docs/static_analysis.md)"
  exit 1
fi
# diff-aware gate: when CI exports LINT_DIFF_BASE (e.g. the PR merge
# base), ALSO fail on AST + conc + contract findings introduced relative
# to it — catches regressions even if someone grows the baseline file in
# the same PR (all three tiers are source-only, so the base rev is
# analyzable)
if [ -n "${LINT_DIFF_BASE:-}" ]; then
  echo "[$(date +%H:%M:%S)] tpu-lint diff gate vs ${LINT_DIFF_BASE}..."
  if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --diff "$LINT_DIFF_BASE"; then
    echo "[$(date +%H:%M:%S)] tpu-lint: findings introduced since ${LINT_DIFF_BASE}"
    exit 1
  fi
  # the mem tier diffs too — its base side runs in a throwaway worktree
  # (traced programs need real code, not git blobs); a base rev that
  # predates the tier counts every mem finding as new
  echo "[$(date +%H:%M:%S)] tpu-lint mem diff gate vs ${LINT_DIFF_BASE}..."
  if ! JAX_PLATFORMS=cpu python -m apex_tpu.analysis --diff "$LINT_DIFF_BASE" --mem; then
    echo "[$(date +%H:%M:%S)] tpu-lint: mem findings introduced since ${LINT_DIFF_BASE}"
    exit 1
  fi
fi

# PERF-ATTRIBUTION gates (docs/observability.md "Cost attribution & perf
# ledger"): the deviceless roofline cost report over every lint-harness
# program, banked as a round artifact, then the perf-ledger regression
# gate — deterministic cost.* metrics must match the last committed
# entry exactly (an intentional change is appended + committed, i.e.
# reviewed), wall-time metrics get a tolerance band.
echo "[$(date +%H:%M:%S)] cost-model report (deviceless roofline)..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.costs --json "COSTS_${TAG}.json"; then
  echo "[$(date +%H:%M:%S)] cost model failed to trace a registered case;"
  echo "  fix the entry point (or its harness registration) first"
  exit 1
fi
echo "[$(date +%H:%M:%S)] perf-ledger regression gate..."
if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --check --costs "COSTS_${TAG}.json"; then
  echo "[$(date +%H:%M:%S)] perf ledger: HEAD drifted/regressed vs"
  echo "  PERF_LEDGER.jsonl; if intentional, append + commit:"
  echo "  python -m apex_tpu.obs.ledger --append --tag ${TAG}"
  exit 1
fi
# append this round's deterministic entry NOW — before the tunnel probe
# can exit the script — so a dead tunnel never leaves the round's perf
# trajectory empty again (the r03–r05 failure mode)
JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --append --tag "$TAG" \
  --costs "COSTS_${TAG}.json"

# SCENARIO smoke (docs/scenarios.md): replay two catalog scenarios on
# CPU (tiny model — workload/SLO mechanics, not throughput) and bank the
# pinned-schema report; runs BEFORE the tunnel probe so a dead tunnel
# still leaves the round's scenario evidence. The per-scenario SLO
# fields (scenario.<name>.ttft_ms_p95 / tpot_ms_p95 /
# deadline_miss_rate) band-gate against the trajectory like the other
# wall-time metrics, and host-tier-churn's host_tier block banks the
# tier-on-vs-off hit-rate A/B (scenario.host-tier-churn.
# tier_delta_hit_rate — the strictly-positive proof the spill tier
# earns its copies, docs/serving.md "Tiered KV pool") — check BEFORE append (checking after would compare
# the round to itself); a regression marks the round failed at exit
# with the entry still banked.
if [ ! -f "SCENARIOS_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] scenario smoke (CPU, tiny model)..."
  # tp-shared-prefix replays through the tp=2 TensorParallelPagedEngine
  # (docs/tp_serving.md) — force 8 virtual CPU devices so its 2-device
  # mesh exists on this box
  if ! JAX_PLATFORMS=cpu \
      XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
      timeout 1800 python -m apex_tpu.serving.scenarios \
      --scenario steady-poisson --scenario multi-tenant-shared-prefix \
      --scenario tp-shared-prefix --scenario host-tier-churn \
      --json "SCENARIOS_${TAG}.json" --seed 0; then
    echo "[$(date +%H:%M:%S)] scenario smoke failed; the workload layer"
    echo "  is broken — fix before burning a tunnel window"
    exit 1
  fi
fi
# check + append run even when a leftover artifact skipped the smoke
# (a round that died between smoke and append must not silently skip
# the gate on re-run — the empty-trajectory failure mode again)
if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --check \
    --costs "COSTS_${TAG}.json" --bench "SCENARIOS_${TAG}.json"; then
  echo "[$(date +%H:%M:%S)] perf ledger: scenario SLO regression vs the"
  echo "  trajectory; round marked failed — entry still appended so the"
  echo "  regression itself is on record"
  LEDGER_BENCH_RC=1
fi
JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --append --tag "$TAG" \
  --bench "SCENARIOS_${TAG}.json"

# CHAOS smoke (docs/router.md, docs/http.md): replicated serving through
# an injected mid-decode replica kill + the affinity-vs-round-robin A/B,
# plus the two NETWORK chaos entries — chaos-slow-reader (stalled SSE
# readers cross the backpressure window: slot spills, stream resumes
# token-identical) and chaos-disconnect-storm (real socket drops + torn
# submits: pages freed, survivors identical) — both replayed over real
# localhost HTTP (EngineSpec(http=True)), on CPU before the tunnel
# probe. --check is on: the greedy-identity amplifier proves neither
# failover nor the wire corrupted tokens. The banked router fields
# (scenario.<name>.failover_recovered_rate, affinity_hit_rate /
# round_robin_hit_rate / affinity_delta_hit_rate) band-gate against
# the trajectory like the other rates (absolute ±0.25); the network
# scenarios' SLO percentiles band-gate too, while their
# scenario.<name>.http_* counters (backpressure_spills, disconnects,
# conn_reset_retries, ...) land as informational trajectory.
#
# The round also banks the FLEET plane (docs/observability.md "Fleet
# plane"): FLEET_${TAG}.json holds every routed scenario's federated
# fleet block (the ledger band-gates scenario.<name>.fleet_ttft_ms_p95
# / fleet_tpot_ms_p95; burn / depth / alerts_fired ride as
# informational trajectory), and the replica kill dumps the
# schema-validated postmortem FLIGHT_${TAG}.json — the --flight write
# refuses a malformed bundle, so a banked flight is always readable.
if [ ! -f "CHAOS_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] chaos smoke (replica kill + affinity A/B + network chaos, CPU)..."
  if ! JAX_PLATFORMS=cpu timeout 1800 python -m apex_tpu.serving.scenarios \
      --scenario chaos-replica-kill --scenario router-affinity-ab \
      --scenario chaos-slow-reader --scenario chaos-disconnect-storm \
      --check --json "CHAOS_${TAG}.json" --seed 0 \
      --fleet "FLEET_${TAG}.json" --flight "FLIGHT_${TAG}.json"; then
    echo "[$(date +%H:%M:%S)] chaos smoke failed; replica failover or the"
    echo "  HTTP surface is broken — fix before burning a tunnel window"
    exit 1
  fi
  if [ ! -f "FLIGHT_${TAG}.json" ]; then
    echo "[$(date +%H:%M:%S)] chaos round killed a replica but recorded no"
    echo "  flight bundle — the postmortem recorder is broken"
    exit 1
  fi
fi
if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --check \
    --costs "COSTS_${TAG}.json" --bench "CHAOS_${TAG}.json"; then
  echo "[$(date +%H:%M:%S)] perf ledger: chaos/router regression vs the"
  echo "  trajectory; round marked failed — entry still appended so the"
  echo "  regression itself is on record"
  LEDGER_BENCH_RC=1
fi
JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --append --tag "$TAG" \
  --bench "CHAOS_${TAG}.json"

# HTTP smoke (docs/http.md): boot the asyncio HTTP/SSE server and drive
# one catalogued scenario through the HTTP client driver (--http forces
# EngineSpec(http=True): every request is a real POST /v1/generate SSE
# stream over localhost), on CPU before the tunnel probe. --check is on
# — greedy identity over the wire proves the transport corrupts no
# tokens. bench-shared-prefix is banked NOWHERE else, so its
# scenario.bench-shared-prefix.ttft_ms_p95 / tpot_ms_p95 /
# deadline_miss_rate band-gate a transport-inclusive trajectory without
# colliding with the in-process SCENARIOS_ baselines; the http_* stream/
# disconnect counters ride along as informational trajectory.
if [ ! -f "HTTP_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] HTTP smoke (bench-shared-prefix over SSE, CPU)..."
  if ! JAX_PLATFORMS=cpu timeout 1800 python -m apex_tpu.serving.scenarios \
      --scenario bench-shared-prefix --http \
      --check --json "HTTP_${TAG}.json" --seed 0; then
    echo "[$(date +%H:%M:%S)] HTTP smoke failed; the network serving"
    echo "  surface is broken — fix before burning a tunnel window"
    exit 1
  fi
fi
if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --check \
    --costs "COSTS_${TAG}.json" --bench "HTTP_${TAG}.json"; then
  echo "[$(date +%H:%M:%S)] perf ledger: HTTP-path SLO regression vs the"
  echo "  trajectory; round marked failed — entry still appended so the"
  echo "  regression itself is on record"
  LEDGER_BENCH_RC=1
fi
JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --append --tag "$TAG" \
  --bench "HTTP_${TAG}.json"

# persistent XLA compilation cache: a window that dies after the 15-min
# BERT-Large compile still banks the executable for the next window
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"

# TP-SERVING compile pin (docs/tp_serving.md): the tensor-parallel
# sharded admit/decode programs must AOT-compile for the deviceless
# v5e:2x4 topology at a pool shape one chip cannot hold — banked BEFORE the
# tunnel probe (like the cost entry) so a dead tunnel still keeps the
# round's TP compile evidence, and gated: a broken TP program fails the
# round here, on the CI box.
if [ ! -f "AOT_${TAG}_tp.json" ]; then
  echo "[$(date +%H:%M:%S)] deviceless TP-serving compile pin..."
  APEX_TPU_TAG="${TAG}_tp" timeout 2700 python tpu_aot.py \
    --only tp4_paged_engine_admit tp4_paged_engine_decode_chunk \
    --skip-autotune --skip-overlap 2> "aot_tp_${TAG}.stderr.log" || true
  tail -2 "aot_tp_${TAG}.stderr.log"
fi
python - "$TAG" <<'EOF' || exit 1
import json, sys
tag = sys.argv[1]
try:
    doc = json.load(open(f"AOT_{tag}_tp.json"))
except Exception as e:  # noqa: BLE001
    raise SystemExit(f"[tp-aot] missing/corrupt AOT_{tag}_tp.json: {e}")
mc = doc.get("multichip", {})
bad = [n for n in ("tp4_paged_engine_admit", "tp4_paged_engine_decode_chunk")
       if not (mc.get(n, {}).get("ok")
               and mc.get(n, {}).get("under_16gib_budget"))]
if bad:
    for n in bad:
        print(f"[tp-aot] {n}: {json.dumps(mc.get(n, {}))[:400]}")
    raise SystemExit(f"[tp-aot] TP serving programs failed the deviceless "
                     f"compile pin: {bad}")
print("[tp-aot] tp4 admit+decode compile for the v5e topology under the "
      "per-chip budget")
EOF

# TUNNEL-INDEPENDENT tier first (VERDICT r4 weak #2: the probe must not
# gate evidence the tunnel does not actually gate): the offline AOT-Mosaic
# sweep compiles every Pallas kernel + the BERT-Large step against a
# device-less v5e topology. Runs once per tag; a dead-tunnel round still
# banks AOT_${TAG}.json.
if [ ! -f "AOT_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] offline AOT-Mosaic sweep (no tunnel needed)..."
  APEX_TPU_TAG="$TAG" timeout 3600 python tpu_aot.py \
    2> "aot_${TAG}.stderr.log" || true
  tail -2 "aot_${TAG}.stderr.log"
fi

PROBE_ERR="probe_${TAG}.stderr"
probe() {
  timeout 130 python -c \
    "import jax; ds=jax.devices(); print('PROBE_OK', len(ds), ds[0].device_kind)" \
    2>"$PROBE_ERR" | grep -q PROBE_OK
}

ok=0
for attempt in 1 2 3 4 5 6; do
  echo "[$(date +%H:%M:%S)] probe attempt $attempt/6..."
  if probe; then ok=1; echo "[$(date +%H:%M:%S)] tunnel up"; break; fi
  [ "$attempt" -lt 6 ] && sleep 45
done
if [ "$ok" != 1 ]; then
  echo "[$(date +%H:%M:%S)] tunnel down after 6 probes; last probe stderr:"
  tail -c 400 "$PROBE_ERR" 2>/dev/null   # env breakage vs tunnel-down triage
  exit 1
fi
rm -f "$PROBE_ERR"

if bench_done; then
  echo "[$(date +%H:%M:%S)] bench already nonzero for ${TAG}; skipping to suite"
else
  echo "[$(date +%H:%M:%S)] benchmark (bench.py retries init+compile itself)..."
  timeout 5400 python bench.py 2> "bench_${TAG}.stderr.log" | tee "BENCH_${TAG}.json.local"
  tail -3 "bench_${TAG}.stderr.log"
fi

echo "[$(date +%H:%M:%S)] on-chip kernel suite (Mosaic compile of every Pallas kernel)..."
# APEX_TPU_TAG: conftest appends one JSON line per finished test to
# TPU_TESTS_${TAG}.jsonl — a 30-second tunnel window banks whatever ran
APEX_TPU_REAL=1 APEX_TPU_TAG="$TAG" timeout 3600 \
  python -m pytest tests/test_real_tpu_kernels.py -v \
  2>&1 | tee "TPU_TESTS_${TAG}.log.tmp" | tail -8
# any completed pytest summary (passed/failed/errors/skipped/no tests)
# replaces the previous log; only a TRUNCATED run (timeout mid-suite, no
# summary line) keeps it
if tail -3 "TPU_TESTS_${TAG}.log.tmp" \
    | grep -qE "[0-9]+ (passed|failed|errors?|skipped)|no tests ran"; then
  mv "TPU_TESTS_${TAG}.log.tmp" "TPU_TESTS_${TAG}.log"
  echo "[$(date +%H:%M:%S)] kernel-suite log saved"
else
  echo "[$(date +%H:%M:%S)] suite truncated; keeping previous log (tmp retained)"
fi
# post-suite window harvest (best-effort, each time-bounded; skipped once
# their artifact exists so retry loops don't redo finished work)
if [ ! -f "apex_tpu/ops/_flash_block_table.json" ]; then
  echo "[$(date +%H:%M:%S)] flash block-size autotune..."
  timeout 3600 python tpu_autotune.py \
    > "AUTOTUNE_${TAG}.json.local" 2> "autotune_${TAG}.stderr.log" || true
  tail -2 "autotune_${TAG}.stderr.log"
fi
# tight-head-dim default flip (r5 pre-staged): enable the unpadded d=64
# layout for future runs ONLY once (a) the on-chip parity test passed and
# (b) the autotuner timed it faster than the 128-padded default on chip.
# flash_attention._tight_default() consults the marker at import.
if [ ! -f "apex_tpu/ops/_flash_tight_ok.json" ]; then
  python - "$TAG" <<'EOF'
import glob, json, sys
tag = sys.argv[1]
passed = False
for path in glob.glob("TPU_TESTS_*.jsonl"):
    for line in open(path):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (rec.get("test") == "test_flash_attention_tight_head_dim"
                and rec.get("outcome") == "passed"
                and rec.get("when") == "call"):
            passed = True
# consult EVERY round's autotune artifact: the parity pass and the timing
# may land in different windows/rounds (autotune is skipped once the block
# table exists), and both proofs remain valid across rounds
speedup = None
for path in sorted(glob.glob("AUTOTUNE_*.json.local")):
    try:
        with open(path) as f:
            data = json.loads(f.read().strip().splitlines()[-1])
    except Exception:
        continue
    if data.get("device") != "tpu":
        continue
    speedups = [s.get("tight_speedup") for s in data.get("shapes", {}).values()
                if isinstance(s, dict) and s.get("tight_speedup")]
    if speedups:
        speedup = min(speedups) if speedup is None else min(speedup, min(speedups))
if passed and speedup and speedup > 1.0:
    # key the marker to the revision + shape set it proves: _tight_default()
    # ignores markers from other revisions / shape sets (ADVICE r5), so a
    # later flash-kernel change can't serve a stale proof
    from apex_tpu.ops.flash_attention import TIGHT_PROOF_SHAPES, _git_rev
    rev = _git_rev() or ""
    if not rev or rev.endswith("-dirty"):
        # _tight_default() only accepts clean-tree proofs (a dirty rev
        # names no reproducible code state) — don't write a dead marker
        print(f"[tight-headdim] proof held but tree not clean (rev={rev!r});"
              " commit first, then re-run")
        raise SystemExit(0)
    with open("apex_tpu/ops/_flash_tight_ok.json", "w") as f:
        json.dump({"ok": True, "min_speedup": speedup, "rev": rev,
                   "shapes": [list(s) for s in TIGHT_PROOF_SHAPES],
                   "proof": "on-chip parity test + autotune timing"}, f)
    print(f"[tight-headdim] ENABLED (min speedup {speedup:.2f}x, rev {rev[:12]})")
else:
    print(f"[tight-headdim] not enabled (passed={passed}, speedup={speedup})")
EOF
fi
if [ ! -f "PROFILE_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] profiler trace + overlap check..."
  APEX_TPU_TAG="$TAG" timeout 3600 python tpu_profile.py \
    2> "profile_${TAG}.stderr.log" || true
  tail -2 "profile_${TAG}.stderr.log"
fi
# batch escalation (one-time, only after the gate artifacts exist): MFU at
# batch 8/chip may leave the MXU underfed — measure 16 and 32, persist the
# winner so the driver's own plain `python bench.py` run uses it
if bench_done && [ -f "TPU_TESTS_${TAG}.log" ] \
    && [ ! -f "bench_batch.json" ]; then
  for B in 16 32; do
    # 32/chip needs remat headroom on 16 GB HBM (activations ~8 GB w/o it)
    R=0; [ "$B" -ge 32 ] && R=1
    echo "[$(date +%H:%M:%S)] bench at batch ${B}/chip (remat=$R)..."
    echo "$R" > "bench_${TAG}_b${B}.remat"   # record what was measured
    APEX_TPU_BENCH_BATCH=$B APEX_TPU_BENCH_REMAT=$R timeout 5400 \
      python bench.py 2> "bench_${TAG}_b${B}.stderr.log" \
      | tee "BENCH_${TAG}_b${B}.json.local"
  done
  python - "$TAG" <<'EOF'
import json, sys
tag = sys.argv[1]
best_b, best_v = 8, 0.0
try:
    with open(f"BENCH_{tag}.json.local") as f:
        best_v = json.load(f).get("value", 0.0)
except Exception:
    pass
for b in (16, 32):
    try:
        with open(f"BENCH_{tag}_b{b}.json.local") as f:
            v = json.load(f).get("value", 0.0)
    except Exception:
        continue
    if v > best_v:
        best_b, best_v = b, v


def measured_remat(b):
    # the sidecar written next to each escalated run — the single source
    # of truth for how the winner was actually measured (batch 8 = no
    # sidecar = no remat)
    try:
        with open(f"bench_{tag}_b{b}.remat") as f:
            return f.read().strip() == "1"
    except Exception:
        return False


with open("bench_batch.json", "w") as f:
    json.dump({"batch_per_chip": best_b, "remat": measured_remat(best_b),
               "tokens_per_sec_per_chip": best_v}, f)
if best_b != 8:
    # the committed .local artifact should carry the best measurement
    import shutil
    shutil.copy(f"BENCH_{tag}_b{best_b}.json.local",
                f"BENCH_{tag}.json.local")
print(f"[batch escalation] winner: {best_b}/chip at {best_v:.0f} tok/s")
EOF
fi
# decode-throughput harvest (beyond reference — no gate dependency beyond
# the suite's flash/xentropy compiles; cheap: one small-model compile).
# Emits five metrics: lock-step decode, paged continuous batching, the
# tp=2 TENSOR-PARALLEL paged engine (gpt2_tp2_paged_decode_* per-chip
# throughput + TTFT/TPOT fields; skipped->0.0 on a 1-device window;
# docs/tp_serving.md), prefix-cached serving (shared-system-prompt
# workload), and the async serving FRONT-END under an open-loop Poisson
# arrival stream with priorities/deadlines + a forced
# preemption/spill/resume burst (gpt2_frontend_* TTFT/TPOT/deadline-miss
# fields; docs/frontend.md).
# The offline AOT sweep above covers the matching compile evidence via
# the gpt2s_prefix_cached_admit + paged_attention_gpt2s_decode cases,
# and the IR lint registry traces the frontend's admission/decode-chunk
# programs (gpt2s_frontend_*)
if bench_done && [ ! -f "DECODE_${TAG}.json" ]; then
  echo "[$(date +%H:%M:%S)] decode-throughput bench (GPT-2 small KV cache)..."
  # APEX_TPU_METRICS_OUT: the bench dumps the full instrument registry
  # (serving latency histograms, pool gauges — docs/observability.md) as
  # a round artifact next to the headline JSON
  APEX_TPU_METRICS_OUT="METRICS_${TAG}.json" timeout 3600 \
    python tpu_decode_bench.py \
    > "DECODE_${TAG}.json.tmp" 2> "decode_${TAG}.stderr.log" \
    && mv "DECODE_${TAG}.json.tmp" "DECODE_${TAG}.json" || true
  tail -2 "decode_${TAG}.stderr.log"
  [ -f "METRICS_${TAG}.json" ] && \
    echo "[$(date +%H:%M:%S)] metrics snapshot banked: METRICS_${TAG}.json"
  # band-gate THIS round's wall-time numbers against the trajectory
  # (the pre-probe gate only covers the deterministic cost metrics —
  # bench fields exist only once the chip has spoken), then bank them.
  # Check BEFORE append: checking after would compare the round to
  # itself. A regression fails the round at exit, after all evidence
  # is banked.
  if [ -f "DECODE_${TAG}.json" ]; then
    if ! JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --check \
        --costs "COSTS_${TAG}.json" --bench "DECODE_${TAG}.json"; then
      echo "[$(date +%H:%M:%S)] perf ledger: WALL-TIME regression vs the"
      echo "  trajectory (see above); round marked failed — the entry is"
      echo "  still appended so the regression itself is on record"
      LEDGER_BENCH_RC=1
    fi
    JAX_PLATFORMS=cpu python -m apex_tpu.obs.ledger --append \
      --tag "$TAG" --bench "DECODE_${TAG}.json"
  fi
fi
echo "[$(date +%H:%M:%S)] done — commit TPU_TESTS_${TAG}.log + BENCH_${TAG}.json.local if nonzero"
exit "${LEDGER_BENCH_RC:-0}"
